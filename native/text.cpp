// Native text pipeline: corpus tokenize + vocab count + index in C++.
//
// TPU-native analogue of the reference's host-side NLP hot path (vocab
// build + sentence indexing feeding Word2Vec training,
// ref: models/word2vec/Word2Vec.java fit() vocab phase + VocabActor /
// wordstore InMemoryLookupCache): the host tokenization/counting work the
// reference spreads across a JVM actor pool runs here as two tight passes
// over one contiguous buffer.
//
// Contract (mirrors deeplearning4j_tpu/text/vocab.py exactly, for ASCII
// input — the Python binding gates on bytes.isascii() so byte-wise
// tokenizing and sorting coincide with Python str semantics):
//   - sentences separated by '\n'; tokens split on ASCII whitespace
//   - vocab = words with count >= min_count, ordered by (-count, word)
//   - corpus index = per-sentence vocab hits; sentences with < 2 kept
//     tokens are dropped (word2vec.py build_vocab)
//
// Exported with the same C ABI / error-reporting pattern as dataloader.cpp.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

inline bool is_ws(unsigned char c) {
  // exactly the ASCII chars Python str.split() treats as whitespace:
  // \t \v \f \r space and the \x1c-\x1f separator controls ('\n' is the
  // sentence delimiter, handled by scan())
  return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f' ||
         (c >= 0x1c && c <= 0x1f);
}

struct Corpus {
  // vocab, sorted by (-count, word)
  std::vector<std::string> words;
  std::vector<int64_t> counts;
  // '\n'-joined byte length of words (for export sizing)
  int64_t words_bytes = 0;
  // corpus index
  std::vector<int32_t> flat;
  std::vector<int32_t> sids;
};

// Walk [buf, buf+len) calling sent_end() at each '\n' (and once at EOF)
// and tok(tokens_view) per whitespace-delimited token.
template <typename TokFn, typename SentFn>
void scan(const char *buf, int64_t len, TokFn &&tok, SentFn &&sent_end) {
  int64_t i = 0;
  while (i <= len) {
    int64_t start = i;
    while (i < len && !is_ws(buf[i]) && buf[i] != '\n') i++;
    if (i > start) tok(std::string_view(buf + start, size_t(i - start)));
    if (i >= len) {
      sent_end();
      break;
    }
    if (buf[i] == '\n') sent_end();
    i++;
  }
}

}  // namespace

extern "C" {

Corpus *dl4j_corpus_index(const char *buf, int64_t len, int min_count) {
  if (buf == nullptr || len < 0) return nullptr;  // caller falls back
  auto *c = new Corpus();
  // pass 1: count tokens
  std::unordered_map<std::string_view, int64_t> count;
  count.reserve(1 << 16);
  scan(buf, len, [&](std::string_view t) { count[t]++; }, [] {});
  // vocab: prune + sort by (-count, word) — identical to VocabCache.finish
  std::vector<std::pair<std::string_view, int64_t>> kept;
  kept.reserve(count.size());
  for (auto &kv : count)
    if (kv.second >= min_count) kept.emplace_back(kv.first, kv.second);
  std::sort(kept.begin(), kept.end(), [](const auto &a, const auto &b) {
    if (a.second != b.second) return a.second > b.second;
    return a.first < b.first;
  });
  std::unordered_map<std::string_view, int32_t> index;
  index.reserve(kept.size());
  c->words.reserve(kept.size());
  c->counts.reserve(kept.size());
  for (size_t i = 0; i < kept.size(); i++) {
    index.emplace(kept[i].first, int32_t(i));
    c->words.emplace_back(kept[i].first);
    c->counts.push_back(kept[i].second);
    c->words_bytes += int64_t(kept[i].first.size()) + 1;  // + '\n'
  }
  // pass 2: index sentences (>= 2 kept tokens, as word2vec.py build_vocab)
  std::vector<int32_t> sent;
  int32_t sid = 0;
  scan(
      buf, len,
      [&](std::string_view t) {
        auto it = index.find(t);
        if (it != index.end()) sent.push_back(it->second);
      },
      [&] {
        if (sent.size() >= 2) {
          c->flat.insert(c->flat.end(), sent.begin(), sent.end());
          c->sids.insert(c->sids.end(), sent.size(), sid);
          sid++;
        }
        sent.clear();
      });
  return c;
}

int64_t dl4j_corpus_vocab_size(Corpus *c) { return int64_t(c->words.size()); }

int64_t dl4j_corpus_words_bytes(Corpus *c) { return c->words_bytes; }

// words_out: words_bytes chars, '\n' after every word; counts_out: vocab_size
void dl4j_corpus_export_vocab(Corpus *c, char *words_out, int64_t *counts_out) {
  char *p = words_out;
  for (size_t i = 0; i < c->words.size(); i++) {
    std::memcpy(p, c->words[i].data(), c->words[i].size());
    p += c->words[i].size();
    *p++ = '\n';
    counts_out[i] = c->counts[i];
  }
}

int64_t dl4j_corpus_n_tokens(Corpus *c) { return int64_t(c->flat.size()); }

int64_t dl4j_corpus_n_sentences(Corpus *c) {
  return c->sids.empty() ? 0 : int64_t(c->sids.back()) + 1;
}

void dl4j_corpus_export_index(Corpus *c, int32_t *flat, int32_t *sids) {
  std::memcpy(flat, c->flat.data(), c->flat.size() * sizeof(int32_t));
  std::memcpy(sids, c->sids.data(), c->sids.size() * sizeof(int32_t));
}

void dl4j_corpus_free(Corpus *c) { delete c; }

}  // extern "C"
