// Native data-loading runtime for deeplearning4j_tpu.
//
// The reference delegates its performance-critical native work to the
// external ND4J backend (SURVEY.md §2.4); on TPU the device math belongs to
// XLA, so the native seam that remains host-side is the input pipeline:
// parsing, batching, and double-buffered prefetch feeding device infeed.
// This file implements that seam as a small C API consumed via ctypes
// (deeplearning4j_tpu/native/).
//
// Components:
//  - CSV parser: mmap'd single-pass float parser (no per-field malloc)
//  - aligned buffer pool: reusable page-aligned host staging buffers
//  - prefetch loader: background thread parses + batches ahead of the
//    consumer through a bounded queue (the Canova-equivalent async path)

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- csv ----

// Parse a delimited numeric text file. Returns a malloc'd row-major float
// buffer (caller frees with dl4j_free); *out_rows/*out_cols receive the
// shape. Returns nullptr on error (errno-style message via dl4j_last_error).
static thread_local std::string g_last_error;

const char* dl4j_last_error() { return g_last_error.c_str(); }

void dl4j_free(void* p) { std::free(p); }

// Locale-free float scanner for the common decimal forms the data files use
// (sign, digits, fraction, exponent). ~4x faster than strtof, which pays
// locale + errno machinery per call. Falls back to strtof for anything
// exotic (hex floats, inf/nan).
static inline float parse_float(const char* p, const char* end,
                                const char** out) {
  const char* q = p;
  bool neg = false;
  if (q < end && (*q == '-' || *q == '+')) neg = (*q++ == '-');
  double mantissa = 0.0;
  int digits = 0;
  while (q < end && *q >= '0' && *q <= '9') {
    mantissa = mantissa * 10.0 + (*q++ - '0');
    ++digits;
  }
  int frac_digits = 0;
  if (q < end && *q == '.') {
    ++q;
    while (q < end && *q >= '0' && *q <= '9') {
      mantissa = mantissa * 10.0 + (*q++ - '0');
      ++frac_digits;
      ++digits;
    }
  }
  if (digits == 0) {  // not a plain number (inf/nan/hex/garbage)
    // strtof needs NUL termination the mmap'd buffer doesn't guarantee, and
    // would happily scan past `end`; copy the token into a bounded stack
    // buffer first.
    char tok[64];
    size_t len = static_cast<size_t>(end - p);
    if (len > sizeof(tok) - 1) len = sizeof(tok) - 1;
    std::memcpy(tok, p, len);
    tok[len] = '\0';
    char* next = nullptr;
    float v = strtof(tok, &next);
    *out = p + (next - tok);
    return v;
  }
  int exponent = -frac_digits;
  if (q < end && (*q == 'e' || *q == 'E')) {
    const char* exp_start = q++;
    bool eneg = false;
    if (q < end && (*q == '-' || *q == '+')) eneg = (*q++ == '-');
    int ev = 0;
    if (q < end && *q >= '0' && *q <= '9') {
      while (q < end && *q >= '0' && *q <= '9') ev = ev * 10 + (*q++ - '0');
      exponent += eneg ? -ev : ev;
    } else {
      q = exp_start;  // bare 'e' belongs to the next token
    }
  }
  static const double pow10[] = {1e0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7,
                                 1e8, 1e9, 1e10, 1e11, 1e12, 1e13, 1e14, 1e15};
  double v = mantissa;
  int e = exponent;
  if (e > 0) {
    while (e >= 16) { v *= 1e16; e -= 16; }
    v *= pow10[e];
  } else if (e < 0) {
    e = -e;
    while (e >= 16) { v /= 1e16; e -= 16; }
    v /= pow10[e];
  }
  *out = q;
  return static_cast<float>(neg ? -v : v);
}

float* dl4j_csv_load(const char* path, char delimiter, int skip_lines,
                     int64_t* out_rows, int64_t* out_cols) {
  *out_rows = 0;
  *out_cols = 0;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) {
    g_last_error = std::string("open failed: ") + std::strerror(errno);
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    g_last_error = "empty or unstatable file";
    ::close(fd);
    return nullptr;
  }
  size_t size = static_cast<size_t>(st.st_size);
  const char* data =
      static_cast<const char*>(mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0));
  ::close(fd);
  if (data == MAP_FAILED) {
    g_last_error = std::string("mmap failed: ") + std::strerror(errno);
    return nullptr;
  }

  std::vector<float> values;
  values.reserve(size / 4);  // rough guess: ~4 chars per numeric field
  int64_t cols = -1, rows = 0;
  int64_t line_no = 0;
  const char* p = data;
  const char* end = data + size;
  bool error = false;
  while (p < end && !error) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!line_end) line_end = end;
    if (line_no++ < skip_lines || line_end == p) {
      p = line_end + 1;
      continue;
    }
    int64_t field_count = 0;
    const char* q = p;
    while (q < line_end) {
      const char* next = nullptr;
      float v = parse_float(q, line_end, &next);
      if (next == q) {
        g_last_error = "parse error at line " + std::to_string(line_no);
        error = true;
        break;
      }
      values.push_back(v);
      ++field_count;
      q = next;
      while (q < line_end && (*q == delimiter || *q == ' ' || *q == '\r')) ++q;
    }
    if (error) break;
    if (cols < 0) {
      cols = field_count;
    } else if (field_count != cols) {
      g_last_error = "ragged row at line " + std::to_string(line_no) + ": " +
                     std::to_string(field_count) + " fields, expected " +
                     std::to_string(cols);
      error = true;
      break;
    }
    ++rows;
    p = line_end + 1;
  }
  munmap(const_cast<char*>(data), size);
  if (error || rows == 0) {
    if (rows == 0 && !error) g_last_error = "no data rows";
    return nullptr;
  }
  float* out = static_cast<float*>(std::malloc(values.size() * sizeof(float)));
  if (!out) {
    g_last_error = "oom";
    return nullptr;
  }
  std::memcpy(out, values.data(), values.size() * sizeof(float));
  *out_rows = rows;
  *out_cols = cols;
  return out;
}

// --------------------------------------------------------- buffer pool ----

// Page-aligned reusable staging buffers. The pool hands out raw pointers;
// release returns a buffer to the freelist. Thread-safe.
struct Dl4jPool {
  size_t buffer_bytes;
  std::mutex mu;
  std::vector<void*> free_list;
  std::vector<void*> all;
};

void* dl4j_pool_create(size_t buffer_bytes, int count) {
  auto* pool = new Dl4jPool();
  pool->buffer_bytes = buffer_bytes;
  for (int i = 0; i < count; ++i) {
    void* buf = nullptr;
    if (posix_memalign(&buf, 4096, buffer_bytes) != 0) {
      for (void* b : pool->all) std::free(b);
      delete pool;
      g_last_error = "posix_memalign failed";
      return nullptr;
    }
    pool->free_list.push_back(buf);
    pool->all.push_back(buf);
  }
  return pool;
}

void* dl4j_pool_acquire(void* handle) {
  auto* pool = static_cast<Dl4jPool*>(handle);
  std::lock_guard<std::mutex> lock(pool->mu);
  if (pool->free_list.empty()) return nullptr;
  void* buf = pool->free_list.back();
  pool->free_list.pop_back();
  return buf;
}

void dl4j_pool_release(void* handle, void* buf) {
  auto* pool = static_cast<Dl4jPool*>(handle);
  std::lock_guard<std::mutex> lock(pool->mu);
  pool->free_list.push_back(buf);
}

int dl4j_pool_available(void* handle) {
  auto* pool = static_cast<Dl4jPool*>(handle);
  std::lock_guard<std::mutex> lock(pool->mu);
  return static_cast<int>(pool->free_list.size());
}

void dl4j_pool_destroy(void* handle) {
  auto* pool = static_cast<Dl4jPool*>(handle);
  for (void* b : pool->all) std::free(b);
  delete pool;
}

// ----------------------------------------------------- prefetch loader ----

// Background-thread CSV batch loader: parses the whole file once, then a
// producer thread stages shuffled epoch batches into a bounded queue while
// the consumer (python / device infeed) drains. Parity target: the
// reference's actor-based batch feeding (BatchActor) and Canova record
// iteration, redesigned as a double-buffered host pipeline.
struct Dl4jLoader {
  std::vector<float> data;  // row-major parsed file
  int64_t rows = 0, cols = 0;
  int64_t batch = 0;
  bool drop_last = false;

  std::deque<std::vector<float>> queue;
  size_t capacity = 4;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::atomic<bool> done{false}, stop{false};
  std::thread producer;
};

void* dl4j_loader_open(const char* path, char delimiter, int skip_lines,
                       int64_t batch, int queue_capacity, int drop_last,
                       uint64_t shuffle_seed) {
  int64_t rows = 0, cols = 0;
  float* parsed = dl4j_csv_load(path, delimiter, skip_lines, &rows, &cols);
  if (!parsed) return nullptr;
  auto* ld = new Dl4jLoader();
  ld->data.assign(parsed, parsed + rows * cols);
  dl4j_free(parsed);
  ld->rows = rows;
  ld->cols = cols;
  ld->batch = batch;
  ld->drop_last = drop_last != 0;
  ld->capacity = queue_capacity > 0 ? queue_capacity : 4;

  ld->producer = std::thread([ld, shuffle_seed]() {
    // xorshift64 permutation for shuffling without <random> allocations
    std::vector<int64_t> order(ld->rows);
    for (int64_t i = 0; i < ld->rows; ++i) order[i] = i;
    uint64_t state = shuffle_seed ? shuffle_seed : 0x9e3779b97f4a7c15ull;
    auto next_rand = [&state]() {
      state ^= state << 13;
      state ^= state >> 7;
      state ^= state << 17;
      return state;
    };
    if (shuffle_seed) {
      for (int64_t i = ld->rows - 1; i > 0; --i) {
        int64_t j = static_cast<int64_t>(next_rand() % (i + 1));
        std::swap(order[i], order[j]);
      }
    }
    for (int64_t start = 0; start < ld->rows; start += ld->batch) {
      if (ld->stop.load()) break;
      int64_t count = std::min(ld->batch, ld->rows - start);
      if (count < ld->batch && ld->drop_last) break;
      std::vector<float> buf(count * ld->cols);
      for (int64_t r = 0; r < count; ++r) {
        std::memcpy(buf.data() + r * ld->cols,
                    ld->data.data() + order[start + r] * ld->cols,
                    ld->cols * sizeof(float));
      }
      std::unique_lock<std::mutex> lock(ld->mu);
      ld->not_full.wait(lock, [ld] {
        return ld->queue.size() < ld->capacity || ld->stop.load();
      });
      if (ld->stop.load()) break;
      ld->queue.push_back(std::move(buf));
      ld->not_empty.notify_one();
    }
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->done.store(true);
    ld->not_empty.notify_all();
  });
  return ld;
}

int64_t dl4j_loader_cols(void* handle) {
  return static_cast<Dl4jLoader*>(handle)->cols;
}

int64_t dl4j_loader_rows(void* handle) {
  return static_cast<Dl4jLoader*>(handle)->rows;
}

// Copies the next batch into out (size out_capacity floats). Returns the
// number of ROWS copied, 0 at end of epoch, -1 if out_capacity too small.
int64_t dl4j_loader_next(void* handle, float* out, int64_t out_capacity) {
  auto* ld = static_cast<Dl4jLoader*>(handle);
  std::unique_lock<std::mutex> lock(ld->mu);
  ld->not_empty.wait(lock, [ld] { return !ld->queue.empty() || ld->done.load(); });
  if (ld->queue.empty()) return 0;
  std::vector<float>& front = ld->queue.front();
  int64_t n = static_cast<int64_t>(front.size());
  if (n > out_capacity) {
    g_last_error = "out_capacity " + std::to_string(out_capacity) +
                   " too small for batch of " + std::to_string(n) + " floats";
    return -1;
  }
  std::memcpy(out, front.data(), n * sizeof(float));
  ld->queue.pop_front();
  ld->not_full.notify_one();
  return n / ld->cols;
}

void dl4j_loader_close(void* handle) {
  auto* ld = static_cast<Dl4jLoader*>(handle);
  ld->stop.store(true);
  {
    std::lock_guard<std::mutex> lock(ld->mu);
    ld->not_full.notify_all();
    ld->not_empty.notify_all();
  }
  if (ld->producer.joinable()) ld->producer.join();
  delete ld;
}

}  // extern "C"
