"""Corpora pipeline tests (ref: text/corpora/treeparser/* + annotator/PoStagger
+ sentiwordnet/SWN3). End goal: RNTN trains on trees built from RAW TEXT."""

import pytest

from deeplearning4j_tpu.text.corpora import (
    SWN3,
    ConstituencyTree,
    HeadWordFinder,
    PennTreeReader,
    PosTagger,
    TreeIterator,
    TreeParser,
    TreeVectorizer,
    binarize,
    collapse_unaries,
    to_rntn_tree,
)


class TestPennTreeReader:
    def test_round_trip(self):
        s = "(S (NP (DT the) (NN cat)) (VP (VBD sat) (PP (IN on) (NP (DT the) (NN mat)))))"
        t = PennTreeReader.parse(s)
        assert t.to_sexpr() == s
        assert t.yield_words() == ["the", "cat", "sat", "on", "the", "mat"]

    def test_multiple_trees_and_root_unwrap(self):
        text = "(ROOT (S (NP (NN dogs)) (VP (VBP bark))))\n(S (NP (NN cats)) (VP (VBP meow)))"
        trees = list(PennTreeReader(text))
        assert len(trees) == 2
        assert trees[0].tag == "S"  # ROOT unwrapped
        assert trees[1].yield_words() == ["cats", "meow"]

    def test_malformed_raises(self):
        with pytest.raises((AssertionError, IndexError, ValueError)):
            list(PennTreeReader("(S (NP"))


class TestTransformers:
    def test_collapse_unaries(self):
        # X -> Y -> (leaves) collapses to X -> (leaves)
        t = PennTreeReader.parse("(S (NP (NX (DT the) (NN cat))) (VP (VBD sat)))")
        c = collapse_unaries(t)
        assert c.children[0].tag == "NP"
        assert [k.tag for k in c.children[0].children] == ["DT", "NN"]
        # pre-terminal chains keep top tag
        assert c.yield_words() == t.yield_words()

    def test_binarize_left_factored(self):
        t = PennTreeReader.parse("(NP (DT the) (JJ big) (JJ red) (NN dog))")
        b = binarize(t)

        def check(n):
            assert len(n.children) in (0, 2)
            for c in n.children:
                check(c)

        check(b)
        assert b.yield_words() == ["the", "big", "red", "dog"]
        assert b.tag == "NP"
        # fabricated inner labels are marked
        assert any(n
                   for n in b.children if n.tag.startswith("@NP"))

    def test_binarize_leaves_binary_tree_alone(self):
        t = PennTreeReader.parse("(S (NP (NN x)) (VP (VBP y)))")
        b = binarize(t)
        assert b.to_sexpr() == t.to_sexpr()


class TestHeadWordFinder:
    def test_np_head_is_noun(self):
        t = PennTreeReader.parse("(NP (DT the) (JJ big) (NN dog))")
        head = HeadWordFinder().find_head(t)
        assert head.word == "dog"

    def test_s_head_through_vp(self):
        t = PennTreeReader.parse(
            "(S (NP (DT the) (NN cat)) (VP (VBD sat) (PP (IN on) (NP (NN mat)))))")
        head = HeadWordFinder().find_head(t)
        assert head.word == "sat"


class TestPosTagger:
    def test_basic_sentence(self):
        tags = PosTagger().tag_sentence("the cat sat on the mat .")
        assert tags == ["DT", "NN", "VBD", "IN", "DT", "NN", "."]

    def test_suffix_and_shape_rules(self):
        tagger = PosTagger()
        tags = tagger.tag(["she", "quickly", "painted", "3", "beautiful", "houses"])
        assert tags == ["PRP", "RB", "VBD", "CD", "JJ", "NNS"]

    def test_capitalized_mid_sentence_is_nnp(self):
        tags = PosTagger().tag(["then", "Alice", "spoke"])
        assert tags[1] == "NNP"


class TestSWN3:
    def test_polarity_signs(self):
        swn = SWN3()
        assert swn.score("an excellent wonderful movie") > 0.5
        assert swn.score("a terrible awful mess") < -0.5
        assert swn.score("the chair is wooden") == 0.0

    def test_negation_flips(self):
        swn = SWN3()
        assert swn.score("not good") < 0
        assert swn.score("never boring") > 0

    def test_buckets_partition(self):
        swn = SWN3()
        assert swn.class_for_score(0.9) == "strong_positive"
        assert swn.class_for_score(0.4) == "positive"
        assert swn.class_for_score(0.1) == "weak_positive"
        assert swn.class_for_score(0.0) == "neutral"
        assert swn.class_for_score(-0.1) == "weak_negative"
        assert swn.class_for_score(-0.4) == "negative"
        assert swn.class_for_score(-0.9) == "strong_negative"
        assert swn.classify("an excellent superb masterpiece") == "strong_positive"

    def test_sentiment_class_5way(self):
        swn = SWN3()
        assert swn.sentiment_class(-0.9) == 0
        assert swn.sentiment_class(0.0) == 2
        assert swn.sentiment_class(0.9) == 4


class TestTreeParser:
    def test_parse_structure(self):
        t = TreeParser().get_trees("the cat sat on the mat .")[0]
        assert t.tag == "S"
        assert t.yield_words() == ["the", "cat", "sat", "on", "the", "mat", "."]
        tags = {n.tag for n in _all_nodes(t)}
        assert "NP" in tags and "VP" in tags  # real structure, not a chain

    def test_sentence_splitting(self):
        trees = TreeParser().get_trees("dogs bark . cats meow .")
        assert len(trees) == 2
        assert trees[1].yield_words() == ["cats", "meow", "."]


def _all_nodes(t):
    out = [t]
    for c in t.children:
        out.extend(_all_nodes(c))
    return out


class TestTreeVectorizer:
    def test_labeled_binary_trees(self):
        vec = TreeVectorizer()
        trees = vec.get_trees_with_labels("this movie is an excellent masterpiece .")
        assert len(trees) == 1
        t = trees[0]
        for n in t.preorder():
            assert len(n.children) in (0, 2)
            assert 0 <= n.label <= 4
        assert t.label >= 3  # positive sentence at the root

    def test_rntn_trains_from_raw_text(self):
        """The full pipeline the reference builds from UIMA+treebank parts:
        raw sentences → trees → RNTN.fit (ref: rntn/RNTN.java + TreeVectorizer)."""
        from deeplearning4j_tpu.models.rntn import RNTN

        sents = ("an excellent wonderful movie . a terrible awful mess . "
                 "a brilliant amazing film . a boring dull disaster .")
        trees = TreeVectorizer().get_trees_with_labels(sents)
        assert len(trees) == 4
        model = RNTN(num_hidden=8, iterations=8, lr=0.05, seed=3)
        model.fit(trees)
        assert model.losses[-1] < model.losses[0]

    def test_tree_iterator_batches(self):
        from deeplearning4j_tpu.text.sentence_iterator import (
            CollectionSentenceIterator,
        )

        it = TreeIterator(
            CollectionSentenceIterator(["good movie .", "bad movie .",
                                        "great fun ."]),
            TreeVectorizer(), batch_size=2)
        batches = list(it)
        assert sum(len(b) for b in batches) == 3
        assert all(hasattr(t, "preorder") for b in batches for t in b)


class TestAdviceRegressions:
    def test_penn_reader_empty_label_wrapper(self):
        """Standard PTB '( (S ...) )' form (ADVICE r02)."""
        from deeplearning4j_tpu.text.corpora.treeparser import PennTreeReader

        t = PennTreeReader.parse("( (S (NP (DT the) (NN cat)) (VP (VBD sat))) )")
        assert t.tag == "S"
        assert t.yield_words() == ["the", "cat", "sat"]

    def test_binarized_tree_sexpr_reparses(self):
        """binarize() labels must stay paren-free so to_sexpr round-trips
        (ADVICE r02: '@X-(' labels broke PennTreeReader)."""
        from deeplearning4j_tpu.text.corpora.treeparser import (
            PennTreeReader, binarize)

        t = PennTreeReader.parse(
            "(NP (DT the) (JJ big) (JJ red) (NN cat))")
        b = binarize(t)
        rt = PennTreeReader.parse(b.to_sexpr())
        assert rt.yield_words() == ["the", "big", "red", "cat"]

    def test_to_infinitive_tagged_verb(self):
        from deeplearning4j_tpu.text.corpora.pos import PosTagger

        tags = PosTagger().tag(["to", "walk"])  # out-of-lexicon fallback
        assert tags == ["TO", "VB"]
        tags = PosTagger().tag(["to", "run"])  # lexicon-tagged verb
        assert tags == ["TO", "VB"]
        # prepositional "to" + suffix-rule noun must NOT be retagged VB
        tags = PosTagger().tag(["to", "perfection"])
        assert tags == ["TO", "NN"]

    def test_head_finder_through_binarized_nodes(self):
        """Fabricated '@X|ctx' labels must still match head-priority rules."""
        from deeplearning4j_tpu.text.corpora.treeparser import (
            HeadWordFinder, PennTreeReader, binarize)

        t = binarize(PennTreeReader.parse(
            "(VP (RB quickly) (VB run) (RB away))"))
        head = HeadWordFinder().find_head(t)
        assert head.word == "run"
