"""Layer-level tests (ref test models: RBMTests, AutoEncoderTest,
TestConvolutionLayer, SubsampleTests, LSTMTest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.api import ConvolutionType, HiddenUnit, VisibleUnit
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import autoencoder as ae
from deeplearning4j_tpu.nn.layers import convolution, lstm, rbm, subsampling
from deeplearning4j_tpu.nn.params import init_layer_params
from deeplearning4j_tpu.optimize.solver import Solver


# ----------------------------------------------------------------- conv ----

def conv_conf(**kw):
    kw.setdefault("layer_type", "CONVOLUTION")
    kw.setdefault("n_in", 1)
    kw.setdefault("n_out", 6)
    kw.setdefault("filter_size", (5, 5))
    kw.setdefault("activation_function", "relu")
    return NeuralNetConfiguration(**kw)


def test_conv_output_shape():
    conf = conv_conf()
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    assert params["convweights"].shape == (6, 1, 5, 5)
    x = jnp.zeros((4, 1, 28, 28))
    out = convolution.forward(conf, params, x)
    assert out.shape == (4, 6, 24, 24)  # VALID 5x5 conv


def test_conv_emitter_matches_im2col():
    """conv2d (the lax.conv emitter core, round-5 switch) must match the
    legacy im2col formulation in forward AND both gradients — im2col is the
    pads-and-matmuls parity oracle."""
    import numpy as np

    w = jax.random.normal(jax.random.PRNGKey(0), (8, 3, 5, 5)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 12, 12))

    convolution.set_conv_emitter(True)  # (3*5*5=75 would auto-gate to im2col)
    try:
        np.testing.assert_allclose(
            np.asarray(convolution.conv2d(x, w)),
            np.asarray(convolution.im2col_conv(x, w)), atol=1e-5)

        def loss_emitter(w, x):
            return jnp.sum(convolution.conv2d(x, w) ** 2)

        def loss_im2col(w, x):
            return jnp.sum(convolution.im2col_conv(x, w) ** 2)

        gw_e, gx_e = jax.grad(loss_emitter, argnums=(0, 1))(w, x)
        gw_i, gx_i = jax.grad(loss_im2col, argnums=(0, 1))(w, x)
        np.testing.assert_allclose(np.asarray(gw_e), np.asarray(gw_i),
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(gx_e), np.asarray(gx_i),
                                   atol=1e-4)
    finally:
        convolution.set_conv_emitter(None)

    # auto gate: narrow contraction routes to the im2col core exactly
    np.testing.assert_array_equal(
        np.asarray(convolution.conv2d(x, w)),
        np.asarray(convolution.im2col_conv(x, w)))


def test_subsampling_max_pool():
    conf = NeuralNetConfiguration(layer_type="SUBSAMPLING", stride=(2, 2),
                                  convolution_type=ConvolutionType.MAX)
    x = jnp.arange(16.0).reshape(1, 1, 4, 4)
    out = subsampling.forward(conf, {}, x)
    np.testing.assert_allclose(np.asarray(out[0, 0]), [[5, 7], [13, 15]])


def test_subsampling_avg_pool():
    conf = NeuralNetConfiguration(layer_type="SUBSAMPLING", stride=(2, 2),
                                  convolution_type=ConvolutionType.AVG)
    x = jnp.ones((1, 1, 4, 4))
    out = subsampling.forward(conf, {}, x)
    np.testing.assert_allclose(np.asarray(out), np.ones((1, 1, 2, 2)))


def test_lenet_trains_on_synthetic_mnist():
    """BASELINE config #2 smoke: score decreases and accuracy beats chance."""
    from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
    from deeplearning4j_tpu.models.zoo import lenet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    xs, ys = synthetic_mnist(256)
    labels = np.eye(10, dtype=np.float32)[ys]
    net = MultiLayerNetwork(lenet(num_iterations=1)).init()
    from deeplearning4j_tpu.datasets.dataset import DataSet

    data = DataSet(xs, labels)
    before = net.score(data)
    net.fit_epochs(data, num_epochs=30, batch_size=256)
    after = net.score(data)
    assert after < before * 0.6, (before, after)
    acc = (net.predict(xs) == ys).mean()
    assert acc > 0.5, acc


# ------------------------------------------------------------------ RBM ----

def rbm_conf(**kw):
    kw.setdefault("layer_type", "RBM")
    kw.setdefault("n_in", 6)
    kw.setdefault("n_out", 4)
    kw.setdefault("lr", 0.1)
    kw.setdefault("k", 1)
    return NeuralNetConfiguration(**kw)


def test_rbm_prop_up_down_shapes():
    conf = rbm_conf()
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    v = jnp.ones((3, 6))
    h = rbm.prop_up(conf, params, v)
    assert h.shape == (3, 4)
    v2 = rbm.prop_down(conf, params, h)
    assert v2.shape == (3, 6)
    assert float(h.min()) >= 0.0 and float(h.max()) <= 1.0  # binary units


@pytest.mark.parametrize("hidden", [HiddenUnit.BINARY, HiddenUnit.RECTIFIED,
                                    HiddenUnit.GAUSSIAN, HiddenUnit.SOFTMAX])
def test_rbm_hidden_unit_types(hidden):
    conf = rbm_conf(hidden_unit=hidden)
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    mean, sample = rbm.sample_hidden_given_visible(
        conf, params, jnp.ones((2, 6)), jax.random.PRNGKey(1)
    )
    assert mean.shape == sample.shape == (2, 4)
    assert np.isfinite(np.asarray(sample)).all()


@pytest.mark.parametrize("visible", [VisibleUnit.BINARY, VisibleUnit.GAUSSIAN,
                                     VisibleUnit.LINEAR, VisibleUnit.SOFTMAX])
def test_rbm_visible_unit_types(visible):
    conf = rbm_conf(visible_unit=visible)
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    mean, sample = rbm.sample_visible_given_hidden(
        conf, params, jnp.ones((2, 4)), jax.random.PRNGKey(1)
    )
    assert mean.shape == sample.shape == (2, 6)


def test_rbm_cd_learns_patterns():
    """CD-k lowers reconstruction error on a small binary pattern set
    (ref test model: RBMTests.testBasic)."""
    rng = np.random.default_rng(0)
    base = np.array([[1, 1, 1, 0, 0, 0], [0, 0, 0, 1, 1, 1]], np.float32)
    x = jnp.asarray(np.repeat(base, 10, axis=0))
    conf = rbm_conf(lr=0.5, k=1, num_iterations=150, use_ada_grad=False, momentum=0.0)
    params = init_layer_params(jax.random.PRNGKey(0), conf)

    before = float(rbm.reconstruction_error(conf, params, x))

    def score_fn(p, key):
        return rbm.reconstruction_error(conf, p, x)

    def grad_fn(p, key):
        return rbm.contrastive_divergence(conf, p, x, key)

    solver = Solver(conf, score_fn, grad_fn=grad_fn)
    from deeplearning4j_tpu.nn.api import OptimizationAlgorithm
    params = solver.optimize(params, jax.random.PRNGKey(2),
                             algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    after = float(rbm.reconstruction_error(conf, params, x))
    assert after < before * 0.7, (before, after)


def test_rbm_cd_k_multiple_gibbs_steps():
    conf = rbm_conf(k=3)
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    g = rbm.contrastive_divergence(conf, params, jnp.ones((4, 6)), jax.random.PRNGKey(1))
    assert set(g) == {"W", "b", "vb"}
    assert g["W"].shape == (6, 4)


# ----------------------------------------------------------- AutoEncoder ----

def test_autoencoder_denoising_learns():
    """ref test model: AutoEncoderTest — reconstruction improves."""
    rng = np.random.default_rng(1)
    x = jnp.asarray((rng.random((40, 12)) > 0.5).astype(np.float32))
    conf = NeuralNetConfiguration(layer_type="AUTOENCODER", n_in=12, n_out=6,
                                  lr=0.5, corruption_level=0.3,
                                  num_iterations=200, use_ada_grad=True,
                                  activation_function="sigmoid")
    params = init_layer_params(jax.random.PRNGKey(0), conf)

    def recon_err(p):
        recon = ae.decode(conf, p, ae.encode(conf, p, x))
        return float(jnp.mean((x - recon) ** 2))

    before = recon_err(params)

    def score_fn(p, key):
        return ae.pretrain_loss(conf, p, x, key)

    solver = Solver(conf, score_fn)
    params = solver.optimize(params, jax.random.PRNGKey(3))
    after = recon_err(params)
    assert after < before * 0.8, (before, after)


def test_corruption_masks_fraction():
    x = jnp.ones((1000, 10))
    corrupted = ae.get_corrupted_input(jax.random.PRNGKey(0), x, 0.3)
    frac = float(corrupted.mean())
    assert 0.65 < frac < 0.75  # ~70% kept


# ------------------------------------------------------------------ LSTM ----

def test_lstm_shapes_and_scan():
    conf = NeuralNetConfiguration(layer_type="LSTM", n_in=10, n_out=16)
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    assert params["recurrentweights"].shape == (1 + 10 + 16, 64)
    x = jnp.zeros((2, 7, 10))  # (batch, time, features)
    out = lstm.forward(conf, params, x)
    assert out.shape == (2, 7, 16)


def test_lstm_learns_echo():
    """Predict the previous input token (1-step memory)."""
    rng = np.random.default_rng(0)
    vocab = 8
    seq = rng.integers(0, vocab, size=(16, 20))
    x = np.eye(vocab, dtype=np.float32)[seq]
    # target: previous timestep's input
    y = np.concatenate([np.zeros_like(x[:, :1]), x[:, :-1]], axis=1)

    conf = NeuralNetConfiguration(layer_type="LSTM", n_in=vocab, n_out=vocab,
                                  lr=0.05, num_iterations=150,
                                  use_ada_grad=True, momentum=0.0)
    params = init_layer_params(jax.random.PRNGKey(0), conf)
    xj, yj = jnp.asarray(x), jnp.asarray(y)

    def score_fn(p, key):
        logits = lstm.forward(conf, p, xj)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.sum(yj * logp, axis=-1))

    solver = Solver(conf, score_fn)
    before = float(score_fn(params, None))
    params = solver.optimize(params, jax.random.PRNGKey(1))
    after = float(score_fn(params, None))
    assert after < before * 0.6, (before, after)


# ------------------------------------------------------------ attention ----

class TestAttentionLayer:
    """Multi-head causal self-attention block (beyond-reference long-context
    layer; sequence-head contract mirrors the LSTM decoder)."""

    def _conf(self, d=16, heads=4, out=11, causal=True):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        return NeuralNetConfiguration(
            layer_type="ATTENTION", n_in=d, n_out=out, n_heads=heads,
            causal=causal, weight_init="VI", seed=5)

    def test_output_shape_and_params(self):
        import jax

        from deeplearning4j_tpu.nn.layers import attention
        from deeplearning4j_tpu.nn.params import init_layer_params

        conf = self._conf()
        params = init_layer_params(jax.random.PRNGKey(0), conf)
        assert params["wq"].shape == (16, 16)
        assert params["decoderweights"].shape == (16, 11)
        x = jax.random.normal(jax.random.PRNGKey(1), (3, 10, 16))
        out = attention.forward(conf, params, x)
        assert out.shape == (3, 10, 11)

    def test_heads_must_divide(self):
        import jax
        import pytest as _pytest

        from deeplearning4j_tpu.nn.params import init_layer_params

        with _pytest.raises(ValueError, match="divisible"):
            init_layer_params(jax.random.PRNGKey(0), self._conf(d=16, heads=3))

    def test_causal_masking(self):
        """With causal=True, output at position t must not depend on
        positions > t."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.layers import attention
        from deeplearning4j_tpu.nn.params import init_layer_params

        conf = self._conf()
        params = init_layer_params(jax.random.PRNGKey(0), conf)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16))
        base = attention.forward(conf, params, x)
        x2 = x.at[:, -1].set(99.0)  # perturb the LAST position only
        pert = attention.forward(conf, params, x2)
        assert jnp.allclose(base[:, :-1], pert[:, :-1], atol=1e-5)
        # and a non-causal block does leak it backward
        nconf = self._conf(causal=False)
        nbase = attention.forward(nconf, params, x)
        npert = attention.forward(nconf, params, x2)
        assert not jnp.allclose(nbase[:, :-1], npert[:, :-1], atol=1e-3)

    def test_ring_forward_matches_dense(self):
        """forward_ring (sequence sharded over 8 devices, ring attention)
        reproduces the dense block."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.layers import attention
        from deeplearning4j_tpu.nn.params import init_layer_params
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

        conf = self._conf()
        params = init_layer_params(jax.random.PRNGKey(0), conf)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))  # 8 | 32
        mesh = data_parallel_mesh(8)
        dense_out = attention.forward(conf, params, x)
        ring_out = attention.forward_ring(conf, params, x, mesh, "data")
        assert jnp.allclose(dense_out, ring_out, atol=1e-4), float(
            jnp.max(jnp.abs(dense_out - ring_out)))

    def test_ring_gradients_match_dense(self):
        """Sequence-parallel TRAINING: gradients through forward_ring (loss
        on the ring-attention path, sequence sharded over 8 devices) equal
        the dense block's gradients — ppermute transposes correctly."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from deeplearning4j_tpu.nn.layers import attention
        from deeplearning4j_tpu.nn.params import init_layer_params
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

        conf = self._conf()
        params = init_layer_params(jax.random.PRNGKey(0), conf)
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 16))
        tgt = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 11))
        mesh = data_parallel_mesh(8)

        def dense_loss(p):
            return jnp.mean((attention.forward(conf, p, x) - tgt) ** 2)

        def ring_loss(p):
            return jnp.mean(
                (attention.forward_ring(conf, p, x, mesh, "data") - tgt) ** 2)

        gd = jax.grad(dense_loss)(params)
        gr = jax.grad(ring_loss)(params)
        for k in gd:
            err = float(jnp.max(jnp.abs(jnp.asarray(gd[k]) - jnp.asarray(gr[k]))))
            assert err < 1e-4, (k, err)

    def test_char_lm_trains(self):
        """char_attention_lm fits a repeating sequence: loss decreases and
        next-char prediction on the pattern becomes exact."""
        import jax
        import numpy as np

        from deeplearning4j_tpu.models.zoo import char_attention_lm
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        vocab, T, B = 8, 16, 16
        conf = char_attention_lm(vocab=vocab, d_model=16, n_heads=4, lr=0.3,
                                 num_iterations=100)
        rng = np.random.RandomState(0)
        starts = rng.randint(0, vocab, B)
        toks = (starts[:, None] + np.arange(T + 1)[None]) % vocab  # cyclic
        x = np.eye(vocab, dtype=np.float32)[toks[:, :-1]]
        y = np.eye(vocab, dtype=np.float32)[toks[:, 1:]]
        net = MultiLayerNetwork(conf).init()
        net.fit(x, y)
        first = net.score(x, y)
        for _ in range(5):
            net.fit(x, y)
        last = net.score(x, y)
        assert last < first * 0.5, (first, last)
        logits = np.asarray(net.output(x))
        acc = (logits.argmax(-1) == toks[:, 1:]).mean()
        assert acc > 0.9, acc
