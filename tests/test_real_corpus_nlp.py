"""Real-corpus NLP gates (VERDICT r02 missing #4/#5).

Uses the reference's mounted test fixtures as DATA (no egress needed):
- raw_sentences.txt — 757k words of real English (the classic restricted-
  vocabulary LM corpus the reference's Word2Vec tests train on).
- vec.bin — the reference's golden word2vec-C binary file; loading it
  proves serializer compatibility with the ref's WordVectorSerializer
  format (ref: models/embeddings/loader/WordVectorSerializer.java).
"""

import os

import numpy as np
import pytest

RES = "/root/reference/dl4j-test-resources/src/main/resources"

needs_fixtures = pytest.mark.skipif(
    not os.path.isdir(RES), reason="reference fixtures not mounted")


@needs_fixtures
def test_load_reference_golden_vec_bin():
    from deeplearning4j_tpu.models.embeddings import load_word_vectors_binary

    vocab, mat = load_word_vectors_binary(os.path.join(RES, "vec.bin"))
    assert mat.shape == (4, 100)
    assert [vocab.word_at(i) for i in range(4)] == \
        ["</s>", "Adam", "is", "awesome."]
    assert np.isfinite(mat).all()
    assert (np.linalg.norm(mat, axis=1) > 0).all()


@needs_fixtures
def test_binary_round_trip_matches_reference_format():
    """Write with our serializer, read back, and byte-compare the header
    discipline against the ref file's layout (word SP floats NL)."""
    import io
    import tempfile

    from deeplearning4j_tpu.models.embeddings import (
        load_word_vectors_binary, write_word_vectors_binary)

    vocab, mat = load_word_vectors_binary(os.path.join(RES, "vec.bin"))

    class _T:  # minimal table shim for the writer
        pass

    t = _T()
    t.syn0 = mat
    t.vocab = vocab
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "out.bin")
        write_word_vectors_binary(t, p)
        vocab2, mat2 = load_word_vectors_binary(p)
    assert [vocab2.word_at(i) for i in range(4)] == \
        [vocab.word_at(i) for i in range(4)]
    np.testing.assert_allclose(mat2, mat, rtol=0, atol=0)


@needs_fixtures
def test_word2vec_on_real_english_corpus():
    """Train on a slice of raw_sentences.txt and assert semantic structure:
    number words cluster, day relates to time words — rank-based, robust to
    the absolute-cosine drift of short trainings."""
    from deeplearning4j_tpu.models.word2vec import Word2Vec
    from deeplearning4j_tpu.text.sentence_iterator import (
        CollectionSentenceIterator,
    )

    with open(os.path.join(RES, "raw_sentences.txt")) as f:
        sents = [line.strip() for line in f][:20000]
    vec = Word2Vec(sentence_iterator=CollectionSentenceIterator(sents),
                   layer_size=64, window=5, negative=5, iterations=3,
                   min_word_frequency=5, sample=1e-3, batch_size=2048,
                   lr=0.05, seed=7)
    vec.build_vocab()
    assert vec.vocab.num_words() > 200  # real vocabulary came through
    vec.fit()
    # the number cluster is the most robust signal at this corpus-slice size;
    # the full-corpus gate (accuracy_gates.gate_word2vec_real_corpus) also
    # asserts the day/night/week time cluster
    near_two = set(vec.words_nearest("two", 10))
    assert near_two & {"three", "four", "five", "six", "ten", "Two", "Three"}, \
        near_two
