"""StringGrid / FingerPrintKeyer / SloppyMath tests
(ref: util/StringGrid.java, util/FingerPrintKeyer.java,
berkeley/SloppyMath.java)."""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.utils.sloppy_math import (
    is_dangerous,
    is_discrete_prob,
    lambert,
    log_add,
    log_add_all,
    log_normalize,
    relative_difference,
)
from deeplearning4j_tpu.utils.string_grid import FingerPrintKeyer, StringGrid


class TestFingerPrintKeyer:
    def test_normalizes_case_punct_order(self):
        k = FingerPrintKeyer()
        assert k.key("Hello, World!") == k.key("world hello")
        assert k.key("  Acme Corp. ") == k.key("acme corp")

    def test_accents_stripped(self):
        k = FingerPrintKeyer()
        assert k.key("café") == k.key("cafe")

    def test_dedup_tokens(self):
        assert FingerPrintKeyer().key("a a b") == "a b"


class TestStringGrid:
    def _grid(self):
        return StringGrid(sep=",", data=[
            "Acme Corp,NY,100",
            "acme corp.,NY,200",
            "Beta LLC,SF,300",
        ])

    def test_columns(self):
        g = self._grid()
        assert g.get_num_columns() == 3
        assert g.get_column(1) == ["NY", "NY", "SF"]

    def test_ragged_row_rejected(self):
        g = self._grid()
        with pytest.raises(ValueError):
            g.append_line("only,two")

    def test_dedupe_by_cluster(self):
        g = self._grid()
        g.dedupe_by_cluster(0)  # Acme Corp ≡ acme corp. by fingerprint
        assert len(g) == 2
        assert g[0][0] == "Acme Corp" and g[1][0] == "Beta LLC"

    def test_cluster_column(self):
        clusters = self._grid().cluster_column(0)
        assert sorted(map(len, clusters.values())) == [1, 2]

    def test_select_and_filter(self):
        g = self._grid()
        assert len(g.select(1, "NY")) == 2
        assert g.filter_rows_by_column(1, {"SF"}) == [2]

    def test_remove_columns_and_merge(self):
        g = self._grid()
        g.merge(0, 1)
        assert g[0][0] == "Acme Corp NY" and g.get_num_columns() == 2

    def test_split_column(self):
        g = StringGrid(sep="|", data=["a b|x", "c|y"])
        g.split(0, " ")
        assert g[0] == ["a", "b", "x"] and g[1] == ["c", "", "y"]

    def test_similarity_filter(self):
        g = StringGrid(sep=",", data=["Acme Corp,acme corp", "Acme Corp,zebra"])
        similar = g.get_all_with_similarity(0.9, 0, 1)
        assert len(similar) == 1 and similar[0][1] == "acme corp"
        g.filter_by_similarity(0.9, 0, 1)
        assert len(g) == 1 and g[0][1] == "zebra"

    def test_file_round_trip(self, tmp_path):
        g = self._grid()
        p = str(tmp_path / "g.csv")
        g.write_lines_to(p)
        g2 = StringGrid.from_file(p, sep=",")
        assert list(g2) == list(g)


class TestSloppyMath:
    def test_log_add_matches_naive(self):
        for lx, ly in [(-1.0, -2.0), (0.0, 0.0), (-700.0, -701.0), (5.0, -40.0)]:
            assert log_add(lx, ly) == pytest.approx(
                math.log(math.exp(lx) + math.exp(ly)), rel=1e-9)

    def test_log_add_extremes(self):
        assert log_add(float("-inf"), float("-inf")) == float("-inf")
        assert log_add(-1000.0, 0.0) == 0.0  # tolerance early-out
        # overflow-free where naive exp would blow up
        assert log_add(800.0, 800.0) == pytest.approx(800.0 + math.log(2))

    def test_log_add_all_and_normalize(self):
        v = [-1.0, -2.0, -3.0]
        assert log_add_all(v) == pytest.approx(
            math.log(sum(math.exp(x) for x in v)))
        assert np.exp(log_normalize(v)).sum() == pytest.approx(1.0)
        assert log_add_all([]) == float("-inf")

    def test_predicates(self):
        assert is_dangerous(0.0) and is_dangerous(float("nan"))
        assert not is_dangerous(1.0)
        assert is_discrete_prob(1.0) and not is_discrete_prob(1.1)
        assert relative_difference(1.0, 2.0) == pytest.approx(0.5)

    def test_lambert(self):
        # w e^w = v e^u
        v, u = 1.0, 0.5
        w = lambert(v, u)
        assert w * math.exp(w) == pytest.approx(v * math.exp(u), rel=1e-9)
