"""ISSUE 17 — runtime profiling layer (telemetry/runprof.py).

Pins the measured step-phase model end to end: the ``runprof=`` seam and
its env knob, phase timings + streaming gauges on a real jitted step,
arm-time gauge pre-creation (with ``runprof_measured_mfu`` deliberately
UNBORN until a profiled step supplies FLOPs — the "<"-op pre-arm trap),
the DecodeEngine scheduler seam, the tier-1 measured-MFU cross-check
against wall-clock arithmetic, on-demand session lifecycle (including
kill -9 write-ahead reconstruction and torn-tail tolerance), the UI
``/api/profiling`` control route, report rendering (silent-when-absent
pinned both ways, meta-test off live registry names), and lock hygiene
under the lockwatch watchdog.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry, flat_record
from deeplearning4j_tpu.telemetry.runprof import (
    _ARM_GAUGES,
    RunProfiledStep,
    RunProfiler,
    StepTiming,
    chrome_trace_events,
    find_sessions,
    load_session,
    maybe_runprof,
    resolve_runprof,
    set_runprof,
    summarize_session,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _timing(label="s", wall=2.0, host=0.1, dispatch=0.5, device=1.5,
            flops=None, t_unix=None, trace_id=None):
    return StepTiming(label=label, t_unix=time.time() if t_unix is None
                      else t_unix, wall_ms=wall, host_ms=host,
                      dispatch_ms=dispatch, device_ms=device,
                      flops=flops, trace_id=trace_id)


def _registry_names(registry, prefix="runprof_"):
    snap = registry.snapshot()
    return {r["name"] for kind in ("counters", "gauges", "histograms")
            for r in snap[kind] if r["name"].startswith(prefix)}


@pytest.fixture
def clean_default(monkeypatch):
    """Isolate the process-default profiler and the env knob."""
    monkeypatch.delenv("DL4J_TPU_RUNPROF", raising=False)
    monkeypatch.delenv("DL4J_TPU_RUNPROF_DIR", raising=False)
    set_runprof(None)
    yield monkeypatch
    set_runprof(None)


# ------------------------------------------------------------- seam resolution ----

class TestSeamResolution:
    def test_default_off_without_env(self, clean_default):
        assert resolve_runprof(None) is None
        fn = lambda x: x  # noqa: E731
        assert maybe_runprof(fn, None, "lbl") is fn

    def test_env_knob_arms_the_default(self, clean_default):
        clean_default.setenv("DL4J_TPU_RUNPROF", "1")
        prof = resolve_runprof(None)
        assert isinstance(prof, RunProfiler)
        assert prof is resolve_runprof(None)  # one process default
        assert not prof.session_active  # "1" = gauges only, no session

    def test_env_off_spellings(self, clean_default):
        for off in ("0", "false", "off", "no", ""):
            clean_default.setenv("DL4J_TPU_RUNPROF", off)
            assert resolve_runprof(None) is None, off

    def test_false_always_opts_out(self, clean_default):
        clean_default.setenv("DL4J_TPU_RUNPROF", "1")
        assert resolve_runprof(False) is None
        fn = lambda x: x  # noqa: E731
        assert maybe_runprof(fn, False, "lbl") is fn

    def test_explicit_profiler_used_as_is(self, clean_default):
        prof = RunProfiler(registry=MetricsRegistry())
        assert resolve_runprof(prof) is prof

    def test_env_auto_session(self, clean_default, tmp_path):
        """DL4J_TPU_RUNPROF=<N>, N > 1: the default profiler is born with
        an N-step capture already open."""
        clean_default.setenv("DL4J_TPU_RUNPROF", "5")
        clean_default.setenv("DL4J_TPU_RUNPROF_DIR", str(tmp_path))
        prof = resolve_runprof(None)
        assert prof.session_active
        for _ in range(5):
            prof.record(_timing())
        assert not prof.session_active  # auto-stopped at N steps
        assert len(prof.sessions_completed) == 1
        assert prof.sessions_completed[0].startswith(str(tmp_path))


# ------------------------------------------------- phase timings on a real step ----

class TestPhaseTimings:
    def test_profiled_jitted_step(self):
        """RunProfiledStep on a real jitted fn: phases measured, gauges
        streamed, FLOPs inherited from the composed ProfiledStep."""
        import jax
        import jax.numpy as jnp

        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=2)
        step = RunProfiledStep(jax.jit(lambda x: (x @ x).sum()),
                               label="unit", profiler=prof)
        x = jnp.ones((32, 32))
        for _ in range(4):
            step(x)
        timings = prof.timings("unit")
        assert len(timings) == 4
        for t in timings:
            assert t.wall_ms >= t.device_ms >= 0.0
            assert t.dispatch_ms >= 0.0
            assert t.flops and t.flops > 0  # ProfiledStep composed in
        # host gap only measurable from the second step on
        assert timings[0].host_ms == 0.0
        assert all(t.host_ms > 0.0 for t in timings[1:])
        flat = flat_record(reg, prefixes=("runprof_",))
        assert flat["runprof_steps_total"] == 4.0
        assert flat["runprof_step_ms"] > 0.0
        assert flat["runprof_steps_per_s"] > 0.0
        assert 0.0 <= flat["runprof_host_fraction"] <= 1.0
        assert flat["runprof_measured_mfu"] > 0.0  # born: FLOPs known

    def test_step_profile_and_lower_passthrough(self):
        import jax
        import jax.numpy as jnp

        prof = RunProfiler(registry=MetricsRegistry())
        step = RunProfiledStep(jax.jit(lambda x: x + 1), label="p",
                               profiler=prof)
        step(jnp.ones((2,)))  # profile populated on first call (AOT)
        assert step.step_profile is not None
        assert step.step_profile.flops >= 0
        assert step.lower(jnp.ones((2,))) is not None

    def test_input_wait_hook_feeds_fraction_gauge(self):
        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=2)
        prof.note_input_wait(0.010, "loader")
        prof.record(_timing(label="loader", wall=10.0, host=1.0))
        prof.record(_timing(label="loader", wall=10.0, host=1.0))
        assert prof.timings("loader")[0].input_wait_ms == pytest.approx(10.0)
        flat = flat_record(reg, prefixes=("runprof_",))
        assert flat["runprof_input_wait_fraction"] > 0.0


# ------------------------------------------------------ arm-time pre-creation ----

class TestPreArm:
    def test_arm_pre_creates_watched_instruments(self):
        """ISSUE 17 satellite (a): every watched runprof gauge exists at
        arm time on a FRESH registry — except ``runprof_measured_mfu``,
        which must stay unborn until a step supplies FLOPs (pre-creating
        it at 0.0 would make the "<"-op mfu_collapse rule page on an
        idle process)."""
        reg = MetricsRegistry()
        RunProfiler(registry=reg).arm("train")
        names = _registry_names(reg)
        assert "runprof_steps_total" in names
        for g in _ARM_GAUGES:
            assert g in names, g
        assert "runprof_measured_mfu" not in names

    def test_engine_arms_at_construction(self):
        """The DecodeEngine pre-creates its runprof instruments when the
        seam is armed — before any step runs."""
        import jax

        from deeplearning4j_tpu.models.transformer_lm import init_lm_params
        from deeplearning4j_tpu.serve import DecodeEngine

        reg = MetricsRegistry()
        prof = RunProfiler()  # no registry: adopts the engine's
        params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                                n_layers=1)
        DecodeEngine(params, 2, n_slots=1, max_len=16, serve_dtype=None,
                     registry=reg, runprof=prof)
        names = _registry_names(reg)
        for g in _ARM_GAUGES:
            assert g in names, g
        assert "runprof_measured_mfu" not in names


# ----------------------------------------------------------- DecodeEngine seam ----

class TestEngineSeam:
    def test_scheduler_loop_records_timings(self):
        import jax

        from deeplearning4j_tpu.models.transformer_lm import init_lm_params
        from deeplearning4j_tpu.serve import DecodeEngine

        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=1)
        params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                                n_layers=1)
        eng = DecodeEngine(params, 2, n_slots=1, max_len=16,
                           serve_dtype=None, registry=reg, runprof=prof)
        toks = eng.generate([1, 2, 3], max_new_tokens=4)
        assert len(toks) == 4
        timings = prof.timings("serve_decode")
        assert timings
        for t in timings:
            assert t.wall_ms > 0.0
            assert t.host_ms >= 0.0  # scheduler time around the decode
        flat = flat_record(reg, prefixes=("runprof_",))
        assert flat["runprof_steps_total"] >= len(timings)
        assert flat["runprof_step_ms"] > 0.0


# ----------------------------------------------------- measured-MFU cross-check ----

class TestMeasuredMfuCrossCheck:
    def test_composed_lm_step_measured_vs_wall_mfu(self):
        """Tier-1 acceptance: ``runprof_measured_mfu`` on the composed-LM
        single-device step agrees with wall-clock MFU arithmetic.

        measured_mfu = FLOPs / fenced-device-seconds / peak;
        wall_mfu = FLOPs / wall-seconds / peak. Fenced device time is a
        subset of wall time, so measured/wall >= ~1 by construction; the
        documented band [0.8, 8.0] allows timer jitter below and Python
        dispatch overhead on tiny CPU steps above (bench observes ~1.2
        on this model)."""
        import jax

        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_single_device_train_step,
        )
        from deeplearning4j_tpu.telemetry.xprofile import DEFAULT_PEAK_FLOPS

        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=4)
        step = make_single_device_train_step(2, runprof=prof)
        assert isinstance(step, RunProfiledStep)
        params = init_lm_params(jax.random.PRNGKey(0), 64, 32, 2, 2, 64,
                                n_layers=1)
        k = jax.random.PRNGKey(1)
        toks = jax.random.randint(k, (8, 33), 0, 64)
        x, y = toks[:, :-1], toks[:, 1:]
        params, _ = step(params, x, y)  # warmup: compile
        n = 8
        t0 = time.perf_counter()
        for _ in range(n):
            params, loss = step(params, x, y)
        jax.block_until_ready(loss)
        wall_step_s = (time.perf_counter() - t0) / n
        flat = flat_record(reg, prefixes=("runprof_",))
        measured = flat["runprof_measured_mfu"]
        assert measured > 0.0
        flops = step.step_profile.flops
        assert flops and flops > 0
        wall_mfu = flops / wall_step_s / DEFAULT_PEAK_FLOPS
        ratio = measured / wall_mfu
        assert 0.8 <= ratio <= 8.0, (measured, wall_mfu, ratio)


# -------------------------------------------------------------------- sessions ----

class TestSessions:
    def test_lifecycle_final_dump_and_chrome_trace(self, tmp_path):
        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path))
        sid = prof.start_session()
        assert prof.session_active
        for i in range(3):
            prof.record(_timing(flops=1e9 if i == 2 else None))
        with pytest.raises(RuntimeError):
            prof.start_session()  # one at a time
        final = prof.stop_session()
        assert final and final.endswith(f"runprof_{sid}.json")
        assert prof.stop_session() is None  # idempotent
        sess = load_session(final)
        assert sess["partial"] is False
        assert len(sess["steps"]) == 3
        assert sess["summary"]["steps"] == 3
        assert sess["summary"]["measured_mfu"] > 0.0
        phases = {e["name"] for e in sess["chrome_trace"]}
        assert {"s.host", "s.dispatch", "s.device"} <= phases
        # write-ahead sidecar kept as crash evidence
        assert os.path.isfile(final[:-len(".json")] + ".jsonl")
        assert find_sessions(str(tmp_path))[0]["session"] == sid

    def test_auto_stop_after_n_steps(self, tmp_path):
        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path))
        prof.start_session(steps=2)
        prof.record(_timing())
        assert prof.session_active
        prof.record(_timing())
        assert not prof.session_active
        assert load_session(prof.sessions_completed[0])["summary"][
            "steps"] == 2

    def test_repeated_start_stop_no_thread_leak(self, tmp_path):
        """ISSUE 17 satellite (c): sessions spawn no threads — active
        count is stable across repeated start/stop cycles."""
        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path))
        before = threading.active_count()
        for _ in range(10):
            prof.start_session()
            prof.record(_timing())
            prof.stop_session()
        assert threading.active_count() == before
        assert len(prof.sessions_completed) == 10

    def test_trace_id_linkage(self, tmp_path):
        """Steps recorded inside a tracer span carry its trace id into
        both the StepTiming and the Chrome event args — the PR 7/12
        linkage."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.telemetry import trace as trace_mod

        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path))
        step = RunProfiledStep(jax.jit(lambda x: x * 2), label="tr",
                               profiler=prof)
        tracer = trace_mod.Tracer("test", trace_dir=str(tmp_path / "tr"))
        old = trace_mod.set_tracer(tracer)
        try:
            prof.start_session()
            with trace_mod.maybe_span("train.loop") as sp:
                step(jnp.ones((4,)))
                want = sp.trace_id
        finally:
            trace_mod.set_tracer(old)
        final = prof.stop_session()
        assert prof.timings("tr")[0].trace_id == want
        sess = load_session(final)
        assert sess["steps"][0]["trace_id"] == want
        assert any(e["args"].get("trace_id") == want
                   for e in sess["chrome_trace"])

    def test_torn_tail_tolerated_and_counted(self, tmp_path):
        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path))
        prof.start_session()
        prof.record(_timing())
        prof.record(_timing())
        prof.stop_session()
        jsonl = glob.glob(str(tmp_path / "*.jsonl"))[0]
        with open(jsonl, "a") as fh:
            fh.write('{"ev": "step", "wall_')  # kill -9 mid-write
        sess = load_session(jsonl)
        assert sess["partial"] is True
        assert sess["torn_lines"] == 1
        assert len(sess["steps"]) == 2

    def test_kill_minus_nine_reconstructs_partial(self, tmp_path):
        """ISSUE 17 satellite (c): SIGKILL mid-session leaves a
        write-ahead JSONL the readers reconstruct — steps survive, the
        dump is flagged partial, and the report renders it."""
        child = tmp_path / "child.py"
        child.write_text(
            "import os, signal, sys\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from deeplearning4j_tpu.telemetry.registry import "
            "MetricsRegistry\n"
            "from deeplearning4j_tpu.telemetry.runprof import "
            "RunProfiler, StepTiming\n"
            "prof = RunProfiler(registry=MetricsRegistry(), "
            "session_dir=sys.argv[1])\n"
            "prof.start_session()\n"
            "for i in range(5):\n"
            "    prof.record(StepTiming(label='s', t_unix=1000.0 + i,\n"
            "        wall_ms=2.0, host_ms=0.1, dispatch_ms=0.5,\n"
            "        device_ms=1.5, flops=1e9))\n"
            "os.kill(os.getpid(), signal.SIGKILL)\n")
        sess_dir = tmp_path / "sessions"
        out = subprocess.run([sys.executable, str(child), str(sess_dir)],
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == -signal.SIGKILL, out.stderr
        assert not glob.glob(str(sess_dir / "*.json"))  # no final dump
        sessions = find_sessions(str(sess_dir))
        assert len(sessions) == 1
        sess = sessions[0]
        assert sess["partial"] is True
        assert len(sess["steps"]) == 5  # line-buffered write-ahead
        assert sess["summary"]["measured_mfu"] > 0.0
        # the report chain renders the reconstructed partial
        rep = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "profile_report.py"),
             "--dir", REPO, "--runtime", str(sess_dir)],
            capture_output=True, text=True, timeout=60)
        assert rep.returncode == 0, rep.stderr
        assert "runtime sessions" in rep.stdout
        assert "PARTIAL" in rep.stdout


# ------------------------------------------------------------- UI control route ----

class TestUiProfilingRoute:
    @pytest.fixture
    def server(self, tmp_path, clean_default):
        from deeplearning4j_tpu.ui import UiServer

        s = UiServer(artifact_dir=str(tmp_path))
        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path / "sessions"))
        s.attach_runprof(prof)
        s.start(port=0)
        yield s, prof
        s.stop()

    def _req(self, server, path, body=None):
        url = f"http://127.0.0.1:{server.port}{path}"
        req = urllib.request.Request(
            url, data=body,
            headers={"Content-Type": "application/json"} if body else {})
        try:
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    def test_start_stop_round_trip(self, server):
        ui, prof = server
        status, out = self._req(
            ui, "/api/profiling",
            json.dumps({"action": "start", "steps": 2}).encode())
        assert status == 200 and out["steps"] == 2
        assert prof.session_active
        status, _ = self._req(
            ui, "/api/profiling",
            json.dumps({"action": "start"}).encode())
        assert status == 409  # one session at a time
        prof.record(_timing())
        prof.record(_timing())  # auto-stop at steps=2
        status, out = self._req(ui, "/api/profiling")  # GET snapshot
        assert status == 200
        assert out["session"] is None
        assert len(out["sessions_completed"]) == 1
        assert out["labels"]["s"]["steps_total"] == 2
        status, out = self._req(
            ui, "/api/profiling",
            json.dumps({"action": "stop"}).encode())
        assert status == 200 and out["stopped"] is None  # already closed

    def test_bad_action_rejected(self, server):
        ui, _ = server
        status, _ = self._req(
            ui, "/api/profiling",
            json.dumps({"action": "dance"}).encode())
        assert status == 400


# -------------------------------------------------------------- report rendering ----

class TestRunprofReport:
    """ISSUE 17 satellite (d) + meta-test: every live ``runprof_*``
    registry name renders through summarize_step_log and
    tools/telemetry_report.py, silent-when-absent pinned both ways."""

    def _run_report(self, path):
        out = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "telemetry_report.py"), path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        return out.stdout

    def test_meta_every_runprof_metric_rendered(self, tmp_path):
        from deeplearning4j_tpu.telemetry.step_log import (
            StepLogWriter,
            read_step_log,
            summarize_step_log,
        )

        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=1)
        prof.arm("train")
        prof.note_input_wait(0.002, "train")
        for i in range(3):
            prof.record(_timing(label="train", flops=1e9))
        names = _registry_names(reg)
        assert "runprof_measured_mfu" in names  # FLOPs supplied: born
        rec = flat_record(reg, prefixes=("runprof_",))
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0, **rec)
        summary = summarize_step_log(read_step_log(path))
        text = self._run_report(path)
        assert "runprof metrics (registry)" in text
        for name in sorted(names):
            assert (name in summary["runprof"]
                    or f"{name}_count" in summary["runprof"]), name
            assert name in text, f"{name} not rendered"

    def test_silent_when_absent_both_ways(self, tmp_path):
        from deeplearning4j_tpu.telemetry.step_log import (
            StepLogWriter,
            read_step_log,
            summarize_step_log,
        )

        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0, wall_ms=2.0)
        assert "runprof" not in summarize_step_log(read_step_log(path))
        assert "runprof metrics" not in self._run_report(path)

    def test_profile_report_runtime_section(self, tmp_path):
        prof = RunProfiler(registry=MetricsRegistry(),
                           session_dir=str(tmp_path))
        sid = prof.start_session()
        for _ in range(4):
            prof.record(_timing(flops=1e9))
        prof.stop_session()
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "profile_report.py"),
             "--dir", REPO, "--runtime", str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "runtime sessions" in out.stdout
        assert sid in out.stdout
        assert "PARTIAL" not in out.stdout  # clean final dump
        js = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "profile_report.py"),
             "--dir", REPO, "--runtime", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=60)
        assert js.returncode == 0, js.stderr
        rep = json.loads(js.stdout)
        assert rep["runtime_sessions"][0]["session"] == sid


# ------------------------------------------------------------------ lock hygiene ----

class TestLockHygiene:
    def test_runprof_lock_watched_no_cycles(self, lockwatch, tmp_path):
        """ISSUE 17 satellite (c): the profiler's lock is lockwatch-
        instrumented; a record+session workout acquires it cleanly with
        no lock-order cycles (the engine->runprof order is one-way)."""
        import jax

        from deeplearning4j_tpu.models.transformer_lm import init_lm_params
        from deeplearning4j_tpu.serve import DecodeEngine

        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=1,
                           session_dir=str(tmp_path))
        params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                                n_layers=1)
        eng = DecodeEngine(params, 2, n_slots=1, max_len=16,
                           serve_dtype=None, registry=reg, runprof=prof)
        prof.start_session()
        eng.generate([1, 2, 3], max_new_tokens=3)
        prof.stop_session()
        s = lockwatch.summary()
        assert s["locks"]["telemetry.runprof"]["acquires"] > 0
        assert s["cycles"] == 0


# ----------------------------------------------------------- elastic worker seam ----

class TestElasticSeam:
    def test_synthetic_worker_records_steps(self):
        from deeplearning4j_tpu.scaleout.elastic import (
            SyntheticRegressionModel,
        )

        reg = MetricsRegistry()
        prof = RunProfiler(registry=reg, update_every=1)
        model = SyntheticRegressionModel(d_in=4, d_hidden=8, batch=8,
                                         lr=0.05, mesh_devices=1,
                                         runprof=prof)
        p, loss = model.run_steps(model.init_params(), 0, 3,
                                  worker_seed=0)
        assert loss == loss  # finite training ran
        timings = prof.timings("elastic_worker")
        assert len(timings) == 3
        assert all(t.wall_ms > 0.0 for t in timings)
        flat = flat_record(reg, prefixes=("runprof_",))
        assert flat["runprof_steps_total"] == 3.0


# --------------------------------------------------------------- reader details ----

class TestReaders:
    def test_summarize_empty_and_percentiles(self):
        assert summarize_session([]) == {"steps": 0}
        recs = [_timing(wall=float(i + 1), t_unix=1000.0 + i).to_dict()
                for i in range(100)]
        for r in recs:
            r["ev"] = "step"
        s = summarize_session(recs)
        assert s["wall_ms"]["p50"] == 50.0
        assert s["wall_ms"]["p95"] == 95.0
        assert s["steps_per_s"] == pytest.approx(1.0)

    def test_chrome_events_skip_zero_phases(self):
        t = _timing(host=0.0, dispatch=0.5, device=1.5, t_unix=1000.0)
        d = t.to_dict()
        d["ev"] = "step"
        names = {e["name"] for e in chrome_trace_events([d])}
        assert names == {"s.dispatch", "s.device"}  # no zero-width host
