"""ISSUE 8 numerical-fault guardrails: guarded steps are bit-identical to
unguarded ones on clean batches (every composed path, 0-compile retrace
budget), a NaN batch/param is skipped with params carried unchanged, the
divergence watchdog rolls back to the ``last_good`` checkpoint, and the
replay-bundle → ``tools/step_replay.py`` forensic chain reproduces the
faulting step deterministically."""

import contextlib
import json
import math
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.models.transformer_lm import (
    init_lm_params,
    make_composed_train_step,
    make_single_device_train_step,
    shard_lm_batch,
    shard_lm_params,
)
from deeplearning4j_tpu.optimize.guardrails import (
    DivergenceWatchdog,
    GuardConfig,
    dump_replay_bundle,
    guarded_sgd_update,
    load_replay_bundle,
    nonfinite_report,
    tree_all_finite,
)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.utils.retrace_guard import retrace_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, D, H, E, DFF = 32, 16, 2, 4, 32
B, T = 4, 16


def _bits_equal(a, b):
    return np.asarray(a).tobytes() == np.asarray(b).tobytes()


def _tree_bits_equal(ta, tb):
    la = jax.tree_util.tree_leaves(jax.device_get(ta))
    lb = jax.tree_util.tree_leaves(jax.device_get(tb))
    assert len(la) == len(lb)
    return all(_bits_equal(a, b) for a, b in zip(la, lb))


def _params(n_layers=2):
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                          n_layers=n_layers)


def _data(seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (B, T + 1), 0, V)
    return toks[:, :-1], toks[:, 1:]


def _poison(params, leaf="embed"):
    host = jax.device_get(params)
    arr = np.asarray(host[leaf]).copy()
    arr.flat[0] = np.nan
    host[leaf] = arr
    return jax.tree_util.tree_map(jnp.asarray, host)


# ------------------------------------------------- clean-batch bit parity ----

class TestCleanBatchBitParity:
    """The acceptance pin: guard=True must be invisible on clean batches —
    loss AND params bit-identical to the unguarded step, across every
    composed path, with a 0-compile steady-state retrace budget."""

    def _run(self, plain, guarded, p0, p1, args, steps=3):
        for i in range(steps):
            guard_ctx = (contextlib.nullcontext() if i == 0 else
                         retrace_guard(0, label=f"guarded step {i}"))
            with guard_ctx:
                p0, l0 = plain(p0, *args)
                jax.block_until_ready(l0)
                p1, l1, gm = guarded(p1, *args)
                jax.block_until_ready(l1)
            assert _bits_equal(l0, l1), i
        assert _tree_bits_equal(p0, p1)
        gm = jax.device_get(gm)
        assert float(gm["nonfinite"]) == 0.0
        assert float(gm["clipped"]) == 0.0
        assert float(gm["guard_grad_norm"]) > 0

    def test_single_device(self):
        params = _params()
        tk, tg = _data()
        plain = make_single_device_train_step(H, attn_impl="dense")
        guarded = make_single_device_train_step(H, attn_impl="dense",
                                                guard=True)
        self._run(plain, guarded, params, params, (tk, tg))

    def test_single_device_with_generous_clip(self):
        """A clip threshold far above the actual grad norm yields an
        exactly-1.0 scale — still bit-identical."""
        params = _params()
        tk, tg = _data()
        plain = make_single_device_train_step(H, attn_impl="dense")
        guarded = make_single_device_train_step(
            H, attn_impl="dense", guard=GuardConfig(clip_norm=1e6))
        self._run(plain, guarded, params, params, (tk, tg))

    def test_dp_ep(self):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                    ("data", "expert"))
        params = shard_lm_params(_params(), mesh)
        tk, tg = shard_lm_batch(*_data(), mesh)
        cap = (B // 2) * T
        plain = make_composed_train_step(mesh, H, cap)
        guarded = make_composed_train_step(mesh, H, cap, guard=True)
        self._run(plain, guarded, params, params, (tk, tg))

    def test_dp_sp_ep(self):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                    ("data", "sp", "expert"))
        params = shard_lm_params(_params(), mesh)
        tk, tg = shard_lm_batch(*_data(), mesh)
        cap = (B // 2) * (T // 2)
        plain = make_composed_train_step(mesh, H, cap)
        guarded = make_composed_train_step(mesh, H, cap, guard=True)
        self._run(plain, guarded, params, params, (tk, tg))

    def test_dp_pp(self):
        from deeplearning4j_tpu.models.transformer_lm import make_pp_stages
        from deeplearning4j_tpu.parallel.pipeline import (
            make_pipeline_train_step,
            shard_stage_params,
            stack_stage_params,
        )

        params = _params(n_layers=2)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "pipe"))
        per_stage, stage_fn = make_pp_stages(params, H, n_stages=2,
                                             attn_impl="dense")
        stacked = shard_stage_params(stack_stage_params(per_stage), mesh,
                                     "pipe")
        n_micro, mb = 4, 2
        toks = jax.random.randint(jax.random.PRNGKey(3),
                                  (n_micro, mb, T + 1), 0, V)
        tk, tg = toks[..., :-1], toks[..., 1:]

        def pp_loss(y, tgt_mb):
            logits = y @ params["dec_w"] + params["dec_b"]
            logp = jax.nn.log_softmax(logits, axis=-1)
            return jnp.mean(
                -jnp.take_along_axis(logp, tgt_mb[..., None], -1)[..., 0])

        def copy(t):
            return jax.tree_util.tree_map(jnp.array, t)

        plain = make_pipeline_train_step(stage_fn, pp_loss, mesh, "pipe",
                                         batch_axis="data")
        guarded = make_pipeline_train_step(stage_fn, pp_loss, mesh, "pipe",
                                           batch_axis="data", guard=True)
        emb = params["embed"][tk]
        p0, l0 = plain(copy(stacked), emb, tg)
        p1, l1, gm = guarded(copy(stacked), emb, tg)
        assert _bits_equal(l0, l1)
        assert _tree_bits_equal(p0, p1)
        assert float(jax.device_get(gm)["nonfinite"]) == 0.0

    def test_trainer_sync_step(self):
        from deeplearning4j_tpu.nn import functional as F
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
        from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .num_iterations(1).seed(0).list(2)
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax",
                          loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        mesh = data_parallel_mesh(8)
        params = F.init_params(conf, jax.random.PRNGKey(0))
        states = F.init_train_state(conf, params)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(16, 4).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
        w = jnp.ones((16,), jnp.float32)
        key = jax.random.PRNGKey(7)

        def copy(t):
            return jax.tree_util.tree_map(jnp.array, t)

        plain = make_sync_train_step(conf, mesh)
        guarded = make_sync_train_step(conf, mesh, guard=True)
        p0, s0, sc0 = plain(copy(params), copy(states), jnp.asarray(0),
                            x, y, w, key)
        p1, s1, sc1, gm = guarded(copy(params), copy(states), jnp.asarray(0),
                                  x, y, w, key)
        assert _bits_equal(sc0, sc1)
        assert _tree_bits_equal(p0, p1)
        assert _tree_bits_equal(s0, s1)
        gm = jax.device_get(gm)
        assert float(gm["nonfinite"]) == 0.0
        # metrics-threaded twin merges the guard block into the dict
        both = make_sync_train_step(conf, mesh, with_metrics=True,
                                    guard=True)
        p2, s2, sc2, metrics = both(copy(params), copy(states),
                                    jnp.asarray(0), x, y, w, key)
        assert _bits_equal(sc0, sc2)
        assert _tree_bits_equal(p0, p2)
        m = jax.device_get(metrics)
        for k in ("loss", "grad_norm", "nonfinite", "clipped",
                  "guard_grad_norm"):
            assert k in m


# --------------------------------------------------------- skip semantics ----

class TestSkipOnNonfinite:
    def test_poisoned_lm_params_skip(self):
        """A NaN anywhere in the params poisons loss + grads; the guarded
        step carries the incoming params bitwise (skipped_steps==1 via
        the guard flag) instead of spraying NaN into every leaf."""
        poisoned = _poison(_params())
        tk, tg = _data()
        guarded = make_single_device_train_step(H, attn_impl="dense",
                                                guard=True)
        p2, loss, gm = guarded(poisoned, tk, tg)
        assert not math.isfinite(float(loss))
        assert float(jax.device_get(gm)["nonfinite"]) == 1.0
        assert _tree_bits_equal(p2, poisoned)
        # the UNGUARDED twin really would have poisoned everything —
        # the guard is load-bearing, not vacuous
        plain = make_single_device_train_step(H, attn_impl="dense")
        p3, _ = plain(_poison(_params()), tk, tg)
        assert not tree_all_finite(p3)

    def test_poisoned_batch_trainer_sync_step(self):
        """A NaN in the float features (the realistic corrupt-input case)
        freezes params AND updater state through the step."""
        from deeplearning4j_tpu.nn import functional as F
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
        from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .num_iterations(1).seed(0).list(2)
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax",
                          loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        mesh = data_parallel_mesh(8)
        params = F.init_params(conf, jax.random.PRNGKey(0))
        states = F.init_train_state(conf, params)
        rng = np.random.RandomState(0)
        x = rng.rand(16, 4).astype(np.float32)
        x[3, 1] = np.nan
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
        w = jnp.ones((16,), jnp.float32)

        def copy(t):
            return jax.tree_util.tree_map(jnp.array, t)

        guarded = make_sync_train_step(conf, mesh, guard=True)
        p1, s1, score, gm = guarded(copy(params), copy(states),
                                    jnp.asarray(0), jnp.asarray(x), y, w,
                                    jax.random.PRNGKey(7))
        assert float(jax.device_get(gm)["nonfinite"]) == 1.0
        assert _tree_bits_equal(p1, params)
        assert _tree_bits_equal(s1, states)

    def test_clip_engages_above_threshold(self):
        """clip_norm below the actual grad norm scales the update (params
        move LESS than unclipped) and sets the clipped flag; the loss is
        untouched (clipping is post-grad)."""
        params = _params()
        tk, tg = _data()
        ref = make_single_device_train_step(H, attn_impl="dense",
                                            guard=True)
        _, _, gm = ref(params, tk, tg)
        gn = float(jax.device_get(gm)["guard_grad_norm"])
        clipping = make_single_device_train_step(
            H, attn_impl="dense", guard=GuardConfig(clip_norm=gn / 2))
        p1, loss, gm1 = clipping(params, tk, tg)
        gm1 = jax.device_get(gm1)
        assert float(gm1["clipped"]) == 1.0
        assert float(gm1["nonfinite"]) == 0.0
        # the clipped update is exactly half the unguarded one
        plain = make_single_device_train_step(H, attn_impl="dense")
        p0, loss0 = plain(params, tk, tg)
        assert _bits_equal(loss, loss0)  # loss precedes the clip
        d_plain = jax.tree_util.tree_map(lambda a, b: np.asarray(a - b),
                                         jax.device_get(p0),
                                         jax.device_get(params))
        d_clip = jax.tree_util.tree_map(lambda a, b: np.asarray(a - b),
                                        jax.device_get(p1),
                                        jax.device_get(params))
        for a, b in zip(jax.tree_util.tree_leaves(d_plain),
                        jax.tree_util.tree_leaves(d_clip)):
            np.testing.assert_allclose(b, a * 0.5, rtol=1e-5, atol=1e-7)

    def test_guarded_sgd_update_direct(self):
        params = {"w": jnp.ones((3,))}
        grads = {"w": jnp.full((3,), jnp.inf)}
        new, gm = jax.jit(guarded_sgd_update, static_argnums=(3, 4))(
            params, grads, jnp.float32(1.0), 0.1, GuardConfig())
        assert float(gm["nonfinite"]) == 1.0
        assert _tree_bits_equal(new, params)

    def test_coerce(self):
        assert GuardConfig.coerce(None) is None
        assert GuardConfig.coerce(False) is None
        assert GuardConfig.coerce(True) == GuardConfig()
        cfg = GuardConfig(clip_norm=2.0)
        assert GuardConfig.coerce(cfg) is cfg
        with pytest.raises(TypeError, match="guard="):
            GuardConfig.coerce("yes")


# --------------------------------------------------------------- watchdog ----

class TestWatchdog:
    def test_consecutive_skips_declare_divergence(self):
        reg = MetricsRegistry()
        wd = DivergenceWatchdog(registry=reg, max_consecutive_skips=3)
        assert wd.observe(0, 1.0) == "ok"
        assert wd.observe(1, float("nan"), {"nonfinite": 1.0}) == "skipped"
        assert wd.observe(2, float("nan"), {"nonfinite": 1.0}) == "skipped"
        assert wd.observe(3, float("nan"), {"nonfinite": 1.0}) == "diverged"
        assert wd.diverged and "consecutive" in wd.divergence_reason
        assert reg.counter("guard_skipped_steps_total").value == 3
        assert reg.counter("guard_divergence_total").value == 1

    def test_finite_step_resets_the_burst(self):
        wd = DivergenceWatchdog(registry=MetricsRegistry(),
                                max_consecutive_skips=2)
        wd.observe(0, float("nan"), {"nonfinite": 1.0})
        assert wd.observe(1, 1.0) == "ok"
        assert wd.observe(2, float("nan"), {"nonfinite": 1.0}) == "skipped"
        assert not wd.diverged

    def test_ema_spike_declares_divergence(self):
        reg = MetricsRegistry()
        wd = DivergenceWatchdog(registry=reg, spike_factor=5.0,
                                warmup_steps=4)
        for i in range(4):
            assert wd.observe(i, 1.0 + 0.01 * i) == "ok"
        # 3x the EMA is loud but tolerated...
        assert wd.observe(4, 3.0) == "ok"
        # ...5x+ is divergence (EMA moved a little from the 3.0 reading)
        assert wd.observe(5, 50.0) == "diverged"
        assert "spiked" in wd.divergence_reason
        assert reg.gauge("guard_last_finite_loss").value == 50.0

    def test_clipped_counter_and_registry(self):
        reg = MetricsRegistry()
        wd = DivergenceWatchdog(registry=reg)
        assert wd.observe(0, 1.0, {"clipped": 1.0}) == "clipped"
        assert wd.observe(1, 1.0, {"clipped": 0.0}) == "ok"
        assert reg.counter("guard_clipped_steps_total").value == 1
        assert wd.clipped_steps == 1

    def test_note_checkpoint_tags_only_while_healthy(self, tmp_path):
        from deeplearning4j_tpu.scaleout.ckpt import Checkpointer

        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        wd = DivergenceWatchdog(checkpointer=ck,
                                registry=MetricsRegistry(),
                                max_consecutive_skips=5)
        wd.observe(0, 1.0)
        wd.note_checkpoint(1)
        assert ck.last_good_step() == 1
        wd.observe(1, float("nan"), {"nonfinite": 1.0})
        wd.note_checkpoint(2)  # mid-burst: must NOT move the tag
        assert ck.last_good_step() == 1

    def test_rollback_restores_last_good_with_resume_parity(self, tmp_path):
        """The acceptance rollback: healthy steps checkpointed, step 2
        tagged last_good, params poisoned, K skips → diverged, rollback
        restores the step-2 state exactly (kill/resume-grade: the restored
        tree matches the saved one bitwise, and training continues from it
        identically to an uninterrupted twin)."""
        from deeplearning4j_tpu.scaleout.ckpt import Checkpointer

        reg = MetricsRegistry()
        ck = Checkpointer(str(tmp_path), keep_last=5, registry=reg)
        wd = DivergenceWatchdog(checkpointer=ck, registry=reg,
                                max_consecutive_skips=2,
                                replay_dir=str(tmp_path / "replay"))
        params = _params()
        tk, tg = _data()
        step = make_single_device_train_step(H, attn_impl="dense",
                                             guard=True)
        for i in range(1, 3):
            params, loss, gm = step(params, tk, tg)
            assert wd.observe(i, loss, jax.device_get(gm)) == "ok"
            ck.save(i, {"params": params})
            wd.note_checkpoint(i)
        saved = jax.device_get(params)  # the step-2 state
        assert ck.last_good_step() == 2
        # poison and diverge
        params = _poison(params)
        verdict = None
        for i in range(3, 6):
            params, loss, gm = step(params, tk, tg)
            verdict = wd.observe(i, loss, jax.device_get(gm),
                                 params=params,
                                 batch={"tokens": tk, "targets": tg})
            if verdict == "diverged":
                break
        assert verdict == "diverged"
        assert wd.bundles and os.path.exists(wd.bundles[0])
        state, got, _meta = wd.rollback({"params": _params()})
        assert got == 2
        assert _tree_bits_equal(state["params"], saved)
        assert reg.counter("guard_rollbacks_total").value == 1
        assert not wd.diverged
        # resume-grade: two post-rollback steps equal the uninterrupted twin
        a = jax.tree_util.tree_map(jnp.asarray, state["params"])
        b = jax.tree_util.tree_map(jnp.asarray, saved)
        ref = make_single_device_train_step(H, attn_impl="dense")
        for i in range(2):
            a, la, _ = step(a, tk, tg)
            b, lb = ref(b, tk, tg)
            assert abs(float(la) - float(lb)) <= 1e-6
        for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                        jax.tree_util.tree_leaves(jax.device_get(b))):
            assert float(np.max(np.abs(x - y))) <= 1e-6


# ------------------------------------------------------ replay forensics ----

class TestReplayBundles:
    def _nan_model(self):
        from deeplearning4j_tpu.scaleout.elastic import (
            SyntheticRegressionModel,
        )

        return SyntheticRegressionModel(d_in=4, d_hidden=8, batch=8,
                                        lr=0.05, mesh_devices=1,
                                        guard=True, nan_at_step=2)

    def test_bundle_roundtrip_and_forensics(self, tmp_path):
        model = self._nan_model()
        p, _ = model.run_steps(model.init_params(), 0, 2, worker_seed=0)
        x, y = model._batch_for(0, 2)
        path = dump_replay_bundle(
            str(tmp_path), 2, {"params": p, "batch": {"x": x, "y": y}},
            {"worker": "w0", "rng_key": [0, 2]})
        payload, meta = load_replay_bundle(path)
        assert meta["step"] == 2 and meta["worker"] == "w0"
        assert meta["rng_key"] == [0, 2]
        np.testing.assert_array_equal(payload["batch"]["x"], x)
        bad = [e for e in nonfinite_report(payload) if e["nonfinite"]]
        assert [e["path"] for e in bad] == ["['batch']['x']"]
        assert bad[0]["nonfinite"] == 1

    def test_step_replay_cli_reproduces_nonfinite(self, tmp_path):
        model = self._nan_model()
        p, _ = model.run_steps(model.init_params(), 0, 2, worker_seed=0)
        x, y = model._batch_for(0, 2)
        path = dump_replay_bundle(
            str(tmp_path), 2, {"params": p, "batch": {"x": x, "y": y}}, {})
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "step_replay.py"),
             path, "--factory",
             "deeplearning4j_tpu.scaleout.elastic:synthetic_replay",
             "--kwargs-json",
             json.dumps({"d_in": 4, "d_hidden": 8, "batch": 8,
                         "lr": 0.05}),
             "--expect-nonfinite", "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert out.returncode == 0, out.stderr[-800:]
        rep = json.loads(out.stdout)
        assert rep["reproduced"] is True
        assert rep["result"]["loss"] == "nan"
        assert any(e["nonfinite"] for e in rep["forensics"])

    def test_step_replay_cli_clean_bundle_fails_expectation(self, tmp_path):
        """A finite replay under --expect-nonfinite is exit 1 — the gate
        the fault tests rely on cannot pass vacuously."""
        model = self._nan_model()
        p = model.init_params()
        x, y = model._batch_for(0, 0)  # step 0 is clean
        path = dump_replay_bundle(
            str(tmp_path), 0, {"params": p, "batch": {"x": x, "y": y}}, {})
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "step_replay.py"),
             path, "--factory",
             "deeplearning4j_tpu.scaleout.elastic:synthetic_replay",
             "--kwargs-json",
             json.dumps({"d_in": 4, "d_hidden": 8, "batch": 8,
                         "lr": 0.05}),
             "--expect-nonfinite"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert out.returncode == 1

    def test_step_replay_cli_missing_bundle(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "step_replay.py"),
             str(tmp_path / "nope.npz")],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 2

    def test_lm_replay_factory(self, tmp_path):
        """The flagship-LM replay factory reproduces a poisoned-params
        non-finite loss from its bundle."""
        from deeplearning4j_tpu.models.transformer_lm import lm_replay

        poisoned = jax.device_get(_poison(_params()))
        tk, tg = _data()
        path = dump_replay_bundle(
            str(tmp_path), 7,
            {"params": poisoned,
             "batch": {"tokens": np.asarray(tk), "targets": np.asarray(tg)}},
            {})
        payload, meta = load_replay_bundle(path)
        result = lm_replay(H, attn_impl="dense")(payload)
        assert not math.isfinite(result["loss"])

    def test_watchdog_bundle_retention(self, tmp_path):
        wd = DivergenceWatchdog(registry=MetricsRegistry(),
                                max_consecutive_skips=100,
                                replay_dir=str(tmp_path), max_bundles=2)
        batch = {"x": np.ones((2, 2), np.float32)}
        for i in range(4):
            wd.observe(i, float("nan"), {"nonfinite": 1.0}, batch=batch)
            wd.observe(100 + i, 1.0)  # close the burst so each skip dumps
        assert len(wd.bundles) == 2
        assert all(os.path.exists(p) for p in wd.bundles)
        assert len(os.listdir(tmp_path)) == 2  # stale bundles deleted
