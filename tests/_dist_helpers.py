"""Importable performer factories for distributed-runner worker processes
(the worker CLI resolves "--performer module:factory" by import, so test
performers must live in a real module, not a test function)."""

import os
import time

import numpy as np

from deeplearning4j_tpu.scaleout.job import Job
from deeplearning4j_tpu.scaleout.perform import (
    MultiLayerNetworkWorkPerformer,
    WorkerPerformer,
)


def iris_performer(conf_json: str) -> MultiLayerNetworkWorkPerformer:
    return MultiLayerNetworkWorkPerformer(conf_json)


class AveragingPerformer(WorkerPerformer):
    """Toy performer: result = work + current/10 — cheap, deterministic,
    and parameter-coupled enough to prove replication round-trips."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self._current = 0.0

    def perform(self, job: Job) -> None:
        if self.delay_s:
            time.sleep(self.delay_s)
        job.result = np.asarray([float(job.work) + self._current / 10.0])
        job.score = abs(float(job.work))

    def update(self, *args) -> None:
        if args:
            self._current = float(np.asarray(args[0]).reshape(-1)[0])


def averaging_performer(delay_s: float = 0.0) -> AveragingPerformer:
    return AveragingPerformer(delay_s)


class CrashAfterOnePerformer(AveragingPerformer):
    """Performs exactly one job, then kills its own PROCESS without
    cleanup (os._exit — no atexit, no socket close): the hard-crash case
    the master's heartbeat fault detection must recover from."""

    def perform(self, job: Job) -> None:
        super().perform(job)
        # publish nothing: the crash must cost the cluster this job
        os._exit(17)


def crashing_performer() -> CrashAfterOnePerformer:
    return CrashAfterOnePerformer()
