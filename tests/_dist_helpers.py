"""Importable performer factories for distributed-runner worker processes
(the worker CLI resolves "--performer module:factory" by import, so test
performers must live in a real module, not a test function), plus the
ISSUE-6 fault-injection harness: elastic model factories and the
``FaultyTrackerProxy`` that delays / cuts / blackholes tracker frames."""

import os
import socket
import struct
import threading
import time

import numpy as np

from deeplearning4j_tpu.scaleout.job import Job
from deeplearning4j_tpu.scaleout.perform import (
    MultiLayerNetworkWorkPerformer,
    WorkerPerformer,
)


def iris_performer(conf_json: str) -> MultiLayerNetworkWorkPerformer:
    return MultiLayerNetworkWorkPerformer(conf_json)


class AveragingPerformer(WorkerPerformer):
    """Toy performer: result = work + current/10 — cheap, deterministic,
    and parameter-coupled enough to prove replication round-trips."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self._current = 0.0

    def perform(self, job: Job) -> None:
        if self.delay_s:
            time.sleep(self.delay_s)
        job.result = np.asarray([float(job.work) + self._current / 10.0])
        job.score = abs(float(job.work))

    def update(self, *args) -> None:
        if args:
            self._current = float(np.asarray(args[0]).reshape(-1)[0])


def averaging_performer(delay_s: float = 0.0) -> AveragingPerformer:
    return AveragingPerformer(delay_s)


class CrashAfterOnePerformer(AveragingPerformer):
    """Performs exactly one job, then kills its own PROCESS without
    cleanup (os._exit — no atexit, no socket close): the hard-crash case
    the master's heartbeat fault detection must recover from."""

    def perform(self, job: Job) -> None:
        super().perform(job)
        # publish nothing: the crash must cost the cluster this job
        os._exit(17)


def crashing_performer() -> CrashAfterOnePerformer:
    return CrashAfterOnePerformer()


# ------------------------------------------------------------- elastic ----

def elastic_toy_model(**kwargs):
    """Small deterministic ElasticModel for multi-process elastic tests —
    resolvable by the elastic worker CLI as ``_dist_helpers:
    elastic_toy_model``. Kwargs override the tiny defaults."""
    from deeplearning4j_tpu.scaleout.elastic import SyntheticRegressionModel

    defaults = dict(d_in=4, d_hidden=8, batch=8, lr=0.05, seed=0,
                    mesh_devices=2)
    defaults.update(kwargs)
    return SyntheticRegressionModel(**defaults)


# ------------------------------------------------------ fault injection ----

_HDR = struct.Struct(">I")


def _read_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _read_frame_bytes(sock):
    hdr = _read_exact(sock, _HDR.size)
    (n,) = _HDR.unpack(hdr)
    return hdr + _read_exact(sock, n)


class FaultyTrackerProxy:
    """A frame-aware TCP proxy between ``StateTrackerClient``s and a real
    ``StateTrackerServer`` — the deterministic fault injector for the
    transport layer. Per request/response exchange it can:

    - ``delay_s``: sleep before forwarding each request frame (latency).
    - ``cut_response_after``: forward that many exchanges normally, then
      send only HALF of the next response frame and close both sockets —
      the client sees a broken frame mid-read and must reconnect
      (one-shot: subsequent connections pass through cleanly).
    - ``blackhole=True``: forward nothing and never respond — the client's
      request timeout is the only way out.

    Connect clients to ``proxy.address``; the proxy dials ``target``
    per client connection.
    """

    def __init__(self, target_address: str, delay_s: float = 0.0,
                 cut_response_after: int = None, blackhole: bool = False):
        host, _, port = target_address.rpartition(":")
        self._target = (host, int(port))
        self.delay_s = delay_s
        self.blackhole = blackhole
        self._cut_remaining = cut_response_after
        self._lock = threading.Lock()
        self.exchanges = 0
        self.cuts = 0
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(16)
        self._closing = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        host, port = self._listener.getsockname()
        return f"{host}:{port}"

    def _accept_loop(self):
        while not self._closing:
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._pump, args=(client,),
                             daemon=True).start()

    def _pump(self, client):
        try:
            upstream = socket.create_connection(self._target, timeout=10)
        except OSError:
            client.close()
            return
        try:
            while True:
                request = _read_frame_bytes(client)
                if self.delay_s:
                    time.sleep(self.delay_s)
                if self.blackhole:
                    continue  # swallow: the client request times out
                upstream.sendall(request)
                response = _read_frame_bytes(upstream)
                cut = False
                with self._lock:
                    self.exchanges += 1
                    if self._cut_remaining is not None:
                        if self._cut_remaining <= 0:
                            self._cut_remaining = None
                            self.cuts += 1
                            cut = True
                        else:
                            self._cut_remaining -= 1
                if cut:
                    client.sendall(response[: max(1, len(response) // 2)])
                    return  # broken frame: close both mid-response
                client.sendall(response)
        except (ConnectionError, OSError):
            return
        finally:
            client.close()
            upstream.close()

    def close(self):
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
