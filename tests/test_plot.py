"""t-SNE + renderer tests (ref: plot/TsneTest.java, BarnesHutTsneTest.java —
embed a small labeled set, assert shapes/finiteness and that same-class
points end up closer than cross-class)."""

import json
import os

import numpy as np

from deeplearning4j_tpu.plot import BarnesHutTsne, FilterRenderer, NeuralNetPlotter, Tsne


def _clusters(n_per=25, d=10, seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(n_per, d) * 0.3
    b = rng.randn(n_per, d) * 0.3 + 5.0
    x = np.concatenate([a, b]).astype(np.float32)
    labels = np.array([0] * n_per + [1] * n_per)
    return x, labels


def _separation(y, labels):
    same = np.mean([np.linalg.norm(y[i] - y[j])
                    for i in range(len(y)) for j in range(i + 1, len(y))
                    if labels[i] == labels[j]])
    cross = np.mean([np.linalg.norm(y[i] - y[j])
                     for i in range(len(y)) for j in range(i + 1, len(y))
                     if labels[i] != labels[j]])
    return same, cross


def test_exact_tsne_separates_clusters():
    x, labels = _clusters()
    # 500 iters: at 300 the layout can sit mid-swing (cross/same ~1.96,
    # just under the 2x bar) depending on the accelerator's reduction
    # order; by 500 it is decisively separated (~4.8x)
    tsne = Tsne(max_iter=500, perplexity=10.0, learning_rate=100.0, seed=7)
    y = tsne.calculate(x)
    assert y.shape == (50, 2)
    assert np.all(np.isfinite(y))
    same, cross = _separation(y, labels)
    assert cross > 2 * same, (same, cross)
    # KL cost decreased after the early-exaggeration phase
    assert tsne.costs[-1] < tsne.costs[260]


def test_tsne_plot_writes_coords(tmp_path):
    x, labels = _clusters(n_per=10)
    path = str(tmp_path / "coords.csv")
    y = Tsne(max_iter=50).plot(x, 2, labels, path)
    lines = open(path).read().strip().split("\n")
    assert len(lines) == 20
    assert len(lines[0].split(",")) == 3  # x, y, label
    assert y.shape == (20, 2)


def test_barnes_hut_tsne_separates_clusters():
    x, labels = _clusters(n_per=20)
    bh = BarnesHutTsne(theta=0.5, perplexity=8.0, max_iter=300,
                       learning_rate=100.0, seed=7)
    y = bh.fit_transform(x)
    assert y.shape == (40, 2)
    assert np.all(np.isfinite(y))
    same, cross = _separation(y, labels)
    assert cross > 1.5 * same, (same, cross)


def test_barnes_hut_theta_zero_matches_exact_gradient():
    """theta=0 disables approximation: BH gradient == dense gradient on the
    same sparse P (repulsion exact over all pairs)."""
    rng = np.random.RandomState(1)
    y = rng.randn(15, 2)
    # dense symmetric P restricted to a k-NN pattern
    from deeplearning4j_tpu.plot.barnes_hut_tsne import _knn_affinities
    x = rng.randn(15, 4)
    rows, cols, vals = _knn_affinities(x, k=5, perplexity=3.0)
    bh = BarnesHutTsne(theta=0.0)
    g = bh.gradient(rows, cols, vals, y)
    # dense computation
    n = len(y)
    p = np.zeros((n, n))
    for i in range(n):
        for ptr in range(rows[i], rows[i + 1]):
            p[i, cols[ptr]] = vals[ptr]
    d = ((y[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    num = 1.0 / (1.0 + d)
    np.fill_diagonal(num, 0.0)
    z = num.sum()
    pos = np.zeros_like(y)
    neg = np.zeros_like(y)
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            pos[i] += p[i, j] * num[i, j] * (y[i] - y[j])
            neg[i] += num[i, j] ** 2 * (y[i] - y[j]) / z
    np.testing.assert_allclose(g, pos - neg, atol=1e-8)


def test_neural_net_plotter(tmp_path):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).num_iterations(1).list(2)
        .override(0, layer_type="DENSE")
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    plotter = NeuralNetPlotter(out_dir=str(tmp_path))
    html = plotter.plot_weight_histograms(net)
    assert os.path.exists(html)
    data = json.load(open(html.replace(".html", ".json")))
    assert "layer0_W" in data and "counts" in data["layer0_W"]
    act_path = plotter.plot_activations(net, np.zeros((5, 4), np.float32))
    assert "activation_layer0" in json.load(open(act_path))


def test_filter_renderer(tmp_path):
    rng = np.random.RandomState(0)
    w = rng.randn(16, 6)  # 4x4 patches, 6 filters
    path = str(tmp_path / "filters.svg")
    FilterRenderer().render_filters(w, path, 4, 4, cols=3)
    svg = open(path).read()
    assert svg.startswith("<svg") and svg.count("<rect") == 16 * 6
