"""Pipeline parallelism tests (beyond-reference axis — SURVEY.md §2.5: the
reference's only axis is DP; pp completes dp/tp/sp/pp)."""

import contextlib as _contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_noop_ctx = _contextlib.nullcontext

from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
from deeplearning4j_tpu.parallel.pipeline import (
    PIPE_AXIS,
    make_pipeline_train_step,
    pipeline_apply,
    shard_stage_params,
    stack_stage_params,
)
from deeplearning4j_tpu.utils.retrace_guard import retrace_guard
from jax.sharding import Mesh

D = 16
N_STAGES = 4
N_MICRO = 8
MB = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:N_STAGES]), (PIPE_AXIS,))


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_STAGES)
    return [
        {"w": jax.random.normal(k, (D, D)) / np.sqrt(D),
         "b": jnp.zeros((D,))}
        for k in ks
    ]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


def test_pipeline_matches_sequential():
    """The (M + S − 1)-tick ppermute schedule reproduces applying the four
    stages in order to every microbatch."""
    per_stage = _stages()
    mesh = _mesh()
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    x = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, D))
    out = pipeline_apply(stacked, x, _stage_fn, mesh)
    ref = jax.vmap(lambda m: _sequential(per_stage, m))(x)
    assert jnp.allclose(out, ref, atol=1e-5), float(
        jnp.max(jnp.abs(out - ref)))


def test_pipeline_gradients_exact():
    """jax.grad through the schedule (reverse ppermute) equals the
    sequential model's gradients for EVERY stage's params."""
    per_stage = _stages(3)
    mesh = _mesh()
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    x = jax.random.normal(jax.random.PRNGKey(2), (N_MICRO, MB, D))
    tgt = jax.random.normal(jax.random.PRNGKey(3), (N_MICRO, MB, D))

    def pipe_loss(params):
        out = pipeline_apply(params, x, _stage_fn, mesh)
        return jnp.mean((out - tgt) ** 2)

    def seq_loss(per_stage_list):
        out = jax.vmap(lambda m: _sequential(per_stage_list, m))(x)
        return jnp.mean((out - tgt) ** 2)

    g_pipe = jax.grad(pipe_loss)(stacked)
    g_seq = jax.grad(seq_loss)(per_stage)
    for s in range(N_STAGES):
        for k in ("w", "b"):
            a = np.asarray(g_pipe[k][s])
            b = np.asarray(g_seq[s][k])
            err = float(np.max(np.abs(a - b)))
            assert err < 1e-5, (s, k, err)


def test_pipeline_training_reduces_loss():
    per_stage = _stages(5)
    mesh = _mesh()
    params = shard_stage_params(stack_stage_params(per_stage), mesh)
    x = jax.random.normal(jax.random.PRNGKey(4), (N_MICRO, MB, D))
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(5), (N_MICRO, MB, D)))

    step = make_pipeline_train_step(
        _stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh, lr=0.2)
    _, first = step(jax.tree_util.tree_map(jnp.array, params), x, tgt)
    for i in range(30):
        # steps 0-1 may compile (first trace + committed-sharding
        # specialization); a warmed pipeline step must never retrace
        guard = (retrace_guard(0, label=f"pipeline step {i}") if i >= 2
                 else _noop_ctx())
        with guard:
            params, loss = step(params, x, tgt)
            # serialize dispatch: piled-up async multi-device executions can
            # starve an XLA CPU collective rendezvous on a single-core host
            jax.block_until_ready(loss)
    assert float(loss) < float(first) * 0.7, (float(first), float(loss))


def test_overlap_schedule_bit_identical_forward():
    """ISSUE 14: the double-buffered handoff schedule (rotate issued for
    the previous tick's output while this tick computes) produces
    BIT-identical pipeline outputs — same (stage, microbatch) inputs, the
    extra ticks contribute exact zeros — including at M not divisible by
    S and at M < S (all-bubble)."""
    per_stage = _stages(11)
    mesh = _mesh()
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    for n_micro in (N_MICRO, 6, 2):
        x = jax.random.normal(jax.random.PRNGKey(9), (n_micro, MB, D))
        strict = pipeline_apply(stacked, x, _stage_fn, mesh, overlap=False)
        overlap = pipeline_apply(stacked, x, _stage_fn, mesh, overlap=True)
        assert jnp.array_equal(strict, overlap), n_micro


def test_overlap_train_step_bit_identical_and_steady(retrace_budget):
    """The overlapped train step is bit-identical (loss AND params) to the
    strict-tick oracle over several updates — dp×pp composed — and holds
    the same 0-compile steady retrace budget."""
    per_stage = _stages(12)
    mesh = Mesh(np.array(jax.devices()).reshape(2, N_STAGES),
                ("data", PIPE_AXIS))
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    x = jax.random.normal(jax.random.PRNGKey(13), (N_MICRO, MB, D))
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(14),
                                     (N_MICRO, MB, D)))
    loss_fn = lambda y, t: jnp.mean((y - t) ** 2)  # noqa: E731
    strict = make_pipeline_train_step(_stage_fn, loss_fn, mesh, lr=0.2,
                                      batch_axis="data")
    overlap = make_pipeline_train_step(_stage_fn, loss_fn, mesh, lr=0.2,
                                       batch_axis="data", overlap=True)
    p_s = jax.tree_util.tree_map(jnp.array, stacked)
    p_o = jax.tree_util.tree_map(jnp.array, stacked)
    for _ in range(2):  # compile + committed-sharding warmup
        p_s, l_s = strict(p_s, x, tgt)
        p_o, l_o = overlap(p_o, x, tgt)
        jax.block_until_ready((l_s, l_o))
    with retrace_budget(0, label="overlapped pipeline steady state"):
        for _ in range(3):
            p_s, l_s = strict(p_s, x, tgt)
            p_o, l_o = overlap(p_o, x, tgt)
            jax.block_until_ready((l_s, l_o))
    assert float(l_s) == float(l_o)
    for a, b in zip(jax.tree_util.tree_leaves(p_s),
                    jax.tree_util.tree_leaves(p_o)):
        assert jnp.array_equal(a, b)


def test_microbatch_count_not_divisible_by_stages():
    """M and S need not be related: 6 microbatches over 4 stages."""
    per_stage = _stages(7)
    mesh = _mesh()
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    x = jax.random.normal(jax.random.PRNGKey(8), (6, MB, D))
    out = pipeline_apply(stacked, x, _stage_fn, mesh)
    ref = jax.vmap(lambda m: _sequential(per_stage, m))(x)
    assert jnp.allclose(out, ref, atol=1e-5)


def test_pipeline_from_conf_matches_network_forward():
    """The conf/param bridge: a MultiLayerConfiguration's uniform DENSE
    segment staged over the pipe mesh reproduces applying those layers
    sequentially through the framework's own layer forward."""
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.nn import layers as layer_ops
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.parallel.pipeline import pipeline_from_conf

    d = 16
    conf = (NeuralNetConfiguration.Builder()
            .n_in(d).n_out(d).activation_function("tanh").seed(3)
            .weight_init("VI").list(5)
            .override(4, layer_type="OUTPUT", n_in=d, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build())
    params = F.init_params(conf, jax.random.PRNGKey(0))
    mesh = _mesh()  # 4 devices; layers 0-3 are the uniform dense segment

    stacked, stage_fn = pipeline_from_conf(conf, params, mesh)
    x = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, d))
    out = pipeline_apply(stacked, x, stage_fn, mesh)

    def seq(m):
        for i in range(4):
            m = layer_ops.forward(conf.conf(i), params[i], m)
        return m

    ref = jax.vmap(seq)(x)
    assert jnp.allclose(out, ref, atol=1e-5), float(
        jnp.max(jnp.abs(out - ref)))


def test_pipeline_from_conf_validates_stage_count():
    import pytest as _pytest

    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.parallel.pipeline import pipeline_from_conf

    conf = (NeuralNetConfiguration.Builder()
            .n_in(8).n_out(8).activation_function("tanh").list(3)
            .override(2, layer_type="OUTPUT", n_in=8, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build())
    params = F.init_params(conf, jax.random.PRNGKey(0))
    with _pytest.raises(ValueError, match="pipe axis"):
        pipeline_from_conf(conf, params, _mesh())  # 2 dense != 4 devices


# ---------------------------------------------------------------------------
# Heterogeneous (non-uniform width) staging of real zoo models


def test_heterogeneous_pipeline_zoo_forward_parity():
    """digits_mlp (64→32→10, DENSE+OUTPUT) staged one-layer-per-device:
    pipeline output == the sequential full-network forward."""
    from deeplearning4j_tpu.models.zoo import digits_mlp
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.parallel.pipeline import (
        heterogeneous_pipeline_from_conf,
    )

    conf = digits_mlp(hidden=32)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    mesh = Mesh(np.array(jax.devices()[:2]), (PIPE_AXIS,))
    stacked, stage_fn, out_w = heterogeneous_pipeline_from_conf(
        conf, params, mesh)
    assert out_w == 10

    x = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, 64))
    dmax = stacked["W"].shape[-1]
    x_pad = jnp.pad(x, ((0, 0), (0, 0), (0, dmax - 64)))
    out = pipeline_apply(stacked, x_pad, stage_fn, mesh)[..., :out_w]

    ref = jax.vmap(lambda xb: F.output(conf, params, xb))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)
    # padding lanes carry exact zeros
    assert float(jnp.max(jnp.abs(
        pipeline_apply(stacked, x_pad, stage_fn, mesh)[..., out_w:]))) == 0.0


def test_heterogeneous_pipeline_zoo_trains_with_parity():
    """SGD through the staged digits_mlp matches the identical SGD on the
    sequential model step-for-step (padded params receive zero grads)."""
    from deeplearning4j_tpu.models.zoo import digits_mlp
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.parallel.pipeline import (
        heterogeneous_pipeline_from_conf,
    )

    conf = digits_mlp(hidden=32)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    mesh = Mesh(np.array(jax.devices()[:2]), (PIPE_AXIS,))
    stacked, stage_fn, out_w = heterogeneous_pipeline_from_conf(
        conf, params, mesh)
    dmax = stacked["W"].shape[-1]

    x = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, 64))
    y = jax.nn.one_hot(
        jax.random.randint(jax.random.PRNGKey(2), (N_MICRO, MB), 0, 10), 10)
    x_pad = jnp.pad(x, ((0, 0), (0, 0), (0, dmax - 64)))
    y_pad = jnp.pad(y, ((0, 0), (0, 0), (0, dmax - 10)))

    eps = 1e-8

    def loss_fn(probs, labels):  # MCXENT on the unpadded slice
        return -jnp.mean(jnp.sum(
            labels[..., :out_w] * jnp.log(probs[..., :out_w] + eps), -1))

    lr = 0.5
    step = make_pipeline_train_step(stage_fn, loss_fn, mesh, lr=lr)
    jax.block_until_ready(pipeline_apply(stacked, x_pad, stage_fn, mesh))

    # sequential twin: same forward, same loss, same SGD
    def seq_loss(ps):
        outs = jax.vmap(lambda xb: F.output(conf, ps, xb))(x)
        return -jnp.mean(jnp.sum(y * jnp.log(outs + eps), -1))

    seq_params = params
    losses_pipe, losses_seq = [], []
    for _ in range(5):
        stacked, lp = step(stacked, x_pad, y_pad)
        jax.block_until_ready(lp)
        ls, gs = jax.value_and_grad(seq_loss)(seq_params)
        seq_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, seq_params, gs)
        losses_pipe.append(float(lp))
        losses_seq.append(float(ls))
    np.testing.assert_allclose(losses_pipe, losses_seq, atol=1e-5, rtol=1e-5)
    assert losses_pipe[-1] < losses_pipe[0]


def test_heterogeneous_pipeline_validation():
    from deeplearning4j_tpu.models.zoo import digits_mlp, lenet
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.parallel.pipeline import (
        heterogeneous_pipeline_from_conf,
    )

    conf = digits_mlp(hidden=32)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="pipe axis"):
        heterogeneous_pipeline_from_conf(conf, params, _mesh())  # 2 != 4
    lconf = lenet()
    lparams = F.init_params(lconf, jax.random.PRNGKey(0))
    mesh7 = Mesh(np.array(jax.devices()[:7]), (PIPE_AXIS,))
    with pytest.raises(ValueError, match="DENSE/OUTPUT"):
        heterogeneous_pipeline_from_conf(lconf, lparams, mesh7)
