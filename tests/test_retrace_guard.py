"""Retrace guard: compile budgets pinned for the flagship steps.

The acceptance surface: a deliberately shape-unstable step FAILS the
guard, while the composed-LM, pipeline, and DP-sync steady states each
run under a ZERO-compile budget after warmup — shape/weak-type drift can
never silently recompile a train step per call again."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.utils.retrace_guard import (
    RetraceBudgetExceeded,
    compiles_so_far,
    recent_compiles,
    retrace_guard,
    signature_diff,
)

V, D, H, E, DFF = 32, 16, 2, 2, 32
B, T = 2, 16


def test_counter_counts_real_compiles():
    before = compiles_so_far()
    f = jax.jit(lambda x: x * 3 + 1)
    f(jnp.ones((5,)))  # at least the jitted program compiles
    assert compiles_so_far() > before


def test_shape_unstable_step_fails_the_guard():
    f = jax.jit(lambda x: (x * 2).sum())
    f(jnp.ones((3,)))  # warm one shape
    with pytest.raises(RetraceBudgetExceeded, match="retrace budget"):
        with retrace_guard(1, label="shape-unstable"):
            for n in range(4, 9):  # every call a fresh shape -> recompiles
                f(jnp.ones((n,)))


def test_guard_does_not_mask_inner_exceptions():
    with pytest.raises(ValueError, match="boom"):
        with retrace_guard(0):
            raise ValueError("boom")


def test_weak_type_drift_is_caught():
    """The classic silent retrace: a python scalar where an array was
    traced gives a weak-typed tracer and a second program."""
    f = jax.jit(lambda x, s: x * s)
    x = jnp.ones((4,))
    f(x, jnp.float32(2.0))  # warm the strong-typed program
    with pytest.raises(RetraceBudgetExceeded):
        with retrace_guard(0, label="weak-type drift"):
            f(x, 2.0)  # python float -> weak type -> retrace


def test_blown_budget_reports_what_recompiled():
    """ISSUE 9: the error names the recompiled program with its abstract
    signature AND diffs it against the program's previous compile —
    'arg 1 went weak' instead of a bare count."""

    def distinctly_named_step(x, s):
        return x * s

    f = jax.jit(distinctly_named_step)
    x = jnp.ones((7,))
    compiles_so_far()  # ensure the signature recorder is installed
    f(x, jnp.float32(2.0))  # warm: strong-typed signature recorded
    with pytest.raises(RetraceBudgetExceeded) as ei:
        with retrace_guard(0, label="forensics"):
            f(x, 2.0)  # weak-type drift
    msg = str(ei.value)
    assert "compiled in this region:" in msg
    assert "distinctly_named_step" in msg
    assert "weak_type=True" in msg
    # the diff vs the warm compile pinpoints the drifted argument
    assert "vs previous compile:" in msg
    assert "arg 1:" in msg and "->" in msg


def test_guard_records_signatures_even_under_budget():
    """Signatures are forensics, not failures: a region whose compiles
    fit the budget still exposes them on guard.compiled."""
    f = jax.jit(lambda x: x + 3)
    compiles_so_far()  # install the recorder before the compile
    with retrace_guard(2, label="cold region") as guard:
        f(jnp.ones((9,)))
    assert guard.count >= 1
    assert any("float32[9]" in rec["signature"] for rec in guard.compiled)
    assert recent_compiles()  # process-wide ring retains them


def test_signature_diff_is_positional():
    a = "ShapedArray(float32[4]), ShapedArray(float32[])"
    b = "ShapedArray(float32[4]), ShapedArray(float32[], weak_type=True)"
    d = signature_diff(a, b)
    assert d == ("arg 1: ShapedArray(float32[]) -> "
                 "ShapedArray(float32[], weak_type=True)")
    assert signature_diff(a, a) == "signatures identical"
    assert "arg count changed: 2 -> 1" == signature_diff(
        a, "ShapedArray(float32[4])")


def test_lm_composed_single_device_budget(retrace_budget):
    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_single_device_train_step,
    )

    params = init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                            n_layers=2)
    step = make_single_device_train_step(H)
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T + 1), 0, V)
    tk, tg = toks[:, :-1], toks[:, 1:]
    params, loss = step(params, tk, tg)  # warmup compile
    jax.block_until_ready(loss)
    with retrace_budget(0, label="lm_composed steady state"):
        for _ in range(3):
            params, loss = step(params, tk, tg)
        jax.block_until_ready(loss)
    assert np.isfinite(float(loss))


def test_lm_composed_dp_ep_budget(retrace_budget):
    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_composed_train_step,
        shard_lm_batch,
        shard_lm_params,
    )

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "expert"))
    params = shard_lm_params(
        init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF), mesh)
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T + 1), 0, V)
    stoks, stgts = shard_lm_batch(toks[:, :-1], toks[:, 1:], mesh)
    step = make_composed_train_step(mesh, H, capacity=B * T)
    params, loss = step(params, stoks, stgts)  # warmup compile
    jax.block_until_ready(loss)
    with retrace_budget(0, label="dp×ep composed steady state"):
        for _ in range(3):
            params, loss = step(params, stoks, stgts)
            jax.block_until_ready(loss)
    assert np.isfinite(float(loss))


def test_pipeline_step_budget(retrace_budget):
    from deeplearning4j_tpu.parallel.pipeline import (
        PIPE_AXIS,
        make_pipeline_train_step,
        shard_stage_params,
        stack_stage_params,
    )

    mesh = Mesh(np.array(jax.devices()[:4]), (PIPE_AXIS,))
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    per_stage = [{"w": jax.random.normal(k, (D, D)) / np.sqrt(D),
                  "b": jnp.zeros((D,))} for k in ks]
    stage_fn = lambda p, x: jnp.tanh(x @ p["w"] + p["b"])  # noqa: E731
    params = shard_stage_params(stack_stage_params(per_stage), mesh)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, D))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (4, 2, D))
    step = make_pipeline_train_step(
        stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh, lr=0.1)
    params, loss = step(params, x, tgt)  # warmup compile
    jax.block_until_ready(loss)
    with retrace_budget(0, label="pipeline steady state"):
        for _ in range(3):
            params, loss = step(params, x, tgt)
            jax.block_until_ready(loss)
    assert np.isfinite(float(loss))


def test_dp_sync_step_budget(retrace_budget):
    from deeplearning4j_tpu.models.zoo import mnist_mlp
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

    conf = mnist_mlp(32, 16)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    mesh = data_parallel_mesh(4)
    step = make_sync_train_step(conf, mesh)
    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.uniform(kx, (16, 784), jnp.float32)
    y = jax.nn.one_hot(jax.random.randint(ky, (16,), 0, 10), 10,
                       dtype=jnp.float32)
    w = jnp.ones((16,), jnp.float32)
    key = jax.random.PRNGKey(2)
    # TWO warmup calls: the first traces against the host-placed inputs,
    # the second compiles once more against the committed output shardings
    # the sharded step produces. From there the program is pinned stable.
    for i in range(2):
        params, states, score = step(params, states, jnp.asarray(i), x, y, w,
                                     key)
    jax.block_until_ready(score)
    with retrace_budget(0, label="DP-sync steady state"):
        for i in range(2, 5):
            # graftlint-style discipline: same dtypes/shapes every call
            params, states, score = step(params, states, jnp.asarray(i), x,
                                         y, w, key)
            jax.block_until_ready(score)
    assert np.isfinite(float(score))
