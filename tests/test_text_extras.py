"""Inverted index + moving-window text tests (ref: LuceneInvertedIndex
usage, text/movingwindow WindowsTest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.text.inverted_index import InvertedIndex
from deeplearning4j_tpu.text.movingwindow import (
    PAD,
    Window,
    WindowConverter,
    windows,
)


class TestInvertedIndex:
    def _index(self):
        idx = InvertedIndex()
        idx.add_document("the cat sat".split())
        idx.add_document("the dog ran".split())
        idx.add_document("cat and dog".split())
        return idx

    def test_postings(self):
        idx = self._index()
        assert idx.documents("cat") == [0, 2]
        assert idx.documents("the") == [0, 1]
        assert idx.documents("zzz") == []
        assert idx.doc_frequency("dog") == 2
        assert idx.num_documents() == 3

    def test_duplicate_tokens_counted_once(self):
        idx = InvertedIndex()
        idx.add_document(["a", "a", "b"])
        assert idx.documents("a") == [0]

    def test_batch_iter_covers_all(self):
        idx = self._index()
        docs = [d for batch in idx.batch_iter(2, seed=1) for d in batch]
        assert len(docs) == 3
        assert sorted(map(tuple, docs)) == sorted(
            map(tuple, [idx.document(i) for i in range(3)])
        )

    def test_sample(self):
        idx = self._index()
        s = idx.sample(2, seed=0)
        assert len(s) == 2


class TestWindows:
    def test_padding_and_focus(self):
        ws = windows("a b c".split(), window_size=3)
        assert len(ws) == 3
        assert ws[0].tokens == [PAD, "a", "b"]
        assert ws[0].focus_word == "a"
        assert ws[2].tokens == ["b", "c", PAD]

    def test_even_window_rejected(self):
        with pytest.raises(ValueError):
            windows(["a"], window_size=4)

    def test_converter_concatenates_vectors(self):
        class Lookup:
            layer_size = 2

            def vector(self, w):
                return {"a": np.array([1.0, 2.0]),
                        "b": np.array([3.0, 4.0])}.get(w)

        ws = windows(["a", "b"], window_size=3)
        conv = WindowConverter(Lookup())
        m = conv.as_matrix(ws)
        assert m.shape == (2, 6)
        # first window: PAD a b -> zeros + [1,2] + [3,4]
        np.testing.assert_array_equal(m[0], [0, 0, 1, 2, 3, 4])
