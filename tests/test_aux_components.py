"""Auxiliary component parity tests: vectorizers + dataset persistence
(ref: datasets/vectorizer/, datasets/creator/), document iterators
(ref: text/documentiterator/), the plotting iteration listener
(ref: plot/iterationlistener/), distributed word counting
(ref: scaleout/perform/text/), and CLI blob-URI model IO
(ref: cli/api/schemes/)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.vectorizer import (
    DirectoryImageVectorizer,
    ImageVectorizer,
    load_dataset,
    save_dataset,
)


def _write_pgm(path, value: int, side: int = 4):
    img = np.full((side, side), value, np.uint8)
    with open(path, "wb") as f:
        f.write(b"P5\n%d %d\n255\n" % (side, side) + img.tobytes())


class TestVectorizers:
    def test_image_vectorizer_one_row(self, tmp_path):
        p = str(tmp_path / "img.pgm")
        _write_pgm(p, 128)
        ds = ImageVectorizer(p, num_labels=3, label=1).vectorize()
        assert ds.features.shape == (1, 16)
        assert ds.labels.tolist() == [[0.0, 1.0, 0.0]]
        assert ds.features[0, 0] == pytest.approx(128 / 255)

    def test_image_vectorizer_resize(self, tmp_path):
        p = str(tmp_path / "img.pgm")
        _write_pgm(p, 10, side=8)
        ds = ImageVectorizer(p, num_labels=2, label=0, width=4, height=4).vectorize()
        assert ds.features.shape == (1, 16)

    def test_directory_vectorizer(self, tmp_path):
        for label in ("cat", "dog"):
            os.makedirs(tmp_path / label)
            for i in range(2):
                _write_pgm(str(tmp_path / label / f"{i}.pgm"), 50 + i)
        ds = DirectoryImageVectorizer(str(tmp_path)).vectorize()
        assert ds.features.shape == (4, 16)
        assert ds.labels.shape == (4, 2)
        assert ds.labels.sum() == 4.0

    def test_dataset_save_load_round_trip(self, tmp_path):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        ds = DataSet(np.ones((3, 2), np.float32), np.eye(3, dtype=np.float32))
        path = save_dataset(str(tmp_path / "mnist-ds"), ds)
        assert path.endswith(".npz")
        back = load_dataset(path)
        np.testing.assert_array_equal(back.features, ds.features)
        np.testing.assert_array_equal(back.labels, ds.labels)


class TestDocumentIterator:
    def test_file_documents(self, tmp_path):
        from deeplearning4j_tpu.text.document_iterator import FileDocumentIterator

        (tmp_path / "a.txt").write_text("first doc")
        sub = tmp_path / "sub"
        sub.mkdir()
        (sub / "b.txt").write_text("second doc")
        docs = list(FileDocumentIterator(str(tmp_path)))
        assert docs == ["first doc", "second doc"]

    def test_document_to_sentence_adapter(self):
        from deeplearning4j_tpu.text.document_iterator import (
            CollectionDocumentIterator,
            DocumentSentenceIterator,
        )

        it = DocumentSentenceIterator(
            CollectionDocumentIterator(["line one\nline two", "line three"]))
        sents = []
        while it.has_next():
            sents.append(it.next_sentence())
        assert sents == ["line one", "line two", "line three"]
        it.reset()
        assert it.has_next()


class TestPlotterIterationListener:
    def test_renders_on_frequency(self, tmp_path):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.plot.iteration_listener import (
            PlotterIterationListener,
        )

        conf = (
            NeuralNetConfiguration.Builder()
            .n_in(4).n_out(3).activation_function("tanh").lr(0.1)
            .num_iterations(7).list(1)
            .override(0, layer_type="OUTPUT", activation_function="softmax",
                      loss_function="MCXENT")
            .pretrain(False).backward(True).build()
        )
        net = MultiLayerNetwork(conf).init()
        listener = PlotterIterationListener(frequency=3,
                                            out_dir=str(tmp_path / "plots"))
        net.set_listeners([listener])
        x = np.random.default_rng(0).random((12, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.arange(12) % 3]
        net.fit(x, labels=y)  # 7 iterations → renders at 3 and 6
        assert len(listener.paths) == 2
        for p in listener.paths:
            assert os.path.exists(p + ".json") or os.path.exists(p)

    def test_bad_frequency_rejected(self):
        from deeplearning4j_tpu.plot.iteration_listener import (
            PlotterIterationListener,
        )

        with pytest.raises(ValueError):
            PlotterIterationListener(frequency=0)


class TestWordCount:
    def test_performer_and_aggregator(self):
        from deeplearning4j_tpu.scaleout.job import Job
        from deeplearning4j_tpu.scaleout.nlp_perform import (
            WordCountJobAggregator,
            WordCountWorkPerformer,
        )

        performer = WordCountWorkPerformer()
        agg = WordCountJobAggregator()
        for chunk in (["the cat sat", "the dog"], ["the end"]):
            job = Job(chunk, "w0")
            performer.perform(job)
            agg.accumulate(job)
        merged = agg.aggregate()
        assert merged.get_count("the") == 3.0
        assert merged.get_count("cat") == 1.0


class TestCliBlobUri:
    def test_model_round_trip_through_file_uri(self, tmp_path):
        from deeplearning4j_tpu.cli.driver import main
        from deeplearning4j_tpu.datasets.fetchers import iris_data
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        conf = (
            NeuralNetConfiguration.Builder()
            .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
            .momentum(0.9).use_ada_grad(True).num_iterations(40).seed(42)
            .weight_init("VI").list(2)
            .override(0, layer_type="DENSE")
            .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build()
        )
        conf_path = tmp_path / "model.json"
        conf_path.write_text(conf.to_json())
        x, y = iris_data()
        csv = tmp_path / "iris.csv"
        csv.write_text("\n".join(
            ",".join(f"{v:.4f}" for v in row) + f",{int(lab)}"
            for row, lab in zip(x, y)) + "\n")

        store_dir = tmp_path / "store"
        uri = f"file://{store_dir}/params.npz"
        assert main(["train", "--conf", str(conf_path), "--input", str(csv),
                     "--model", uri, "--labels", "3", "--batch", "150"]) == 0
        assert (store_dir / "params.npz").exists()
        assert main(["test", "--conf", str(conf_path), "--input", str(csv),
                     "--model", uri, "--labels", "3", "--batch", "150"]) == 0
