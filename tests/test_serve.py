"""Serving subsystem (ISSUE 10): KV-cached decode correctness against the
recompute-per-token full-forward oracle (dense AND blockwise prefill,
multi-block, MoE layers), cache eviction/readmission parity under
mid-stream turnover, the 0-compile steady-state decode retrace budget,
the serve_dtype quantization seam, the open-loop load generator, and the
template-free checkpoint restore behind ``DecodeEngine.from_checkpoint``.
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer_lm import (
    dense_moe,
    init_kv_cache,
    init_lm_params,
    lm_checkpoint_meta,
    lm_dims,
    lm_forward,
    lm_prefill,
)
from deeplearning4j_tpu.ops.flash_attention import attention_core
from deeplearning4j_tpu.serve import (
    DecodeEngine,
    PrefixPageCache,
    QuantTensor,
    SpeculativeConfig,
    accept_longest_prefix,
    arrival_schedule,
    params_nbytes,
    prepare_serve_params,
    resolve_speculative,
    run_open_loop,
)

V, D, H, E, DFF, L = 61, 16, 2, 4, 32, 2
MAXLEN = 32


@pytest.fixture(scope="module")
def params():
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                          n_layers=L)


def _prompts(n, seed=1, lo=3, hi=12):
    rng = np.random.RandomState(seed)
    return [list(map(int, rng.randint(0, V, rng.randint(lo, hi))))
            for _ in range(n)]


@functools.lru_cache(maxsize=None)
def _oracle_fwd(attn_impl):
    """The full-forward logits fn the oracle recomputes per token — the
    EXACT training forward (lm_forward) with the dense MoE and the given
    attention core; jit-cached per (impl, length) across tests."""
    core = lambda q, k, v: attention_core(q, k, v, causal=True,  # noqa: E731
                                          impl=attn_impl)
    moe = lambda rw, ex, x: dense_moe(rw, ex, x, 2)  # noqa: E731
    return jax.jit(lambda p, t: lm_forward(p, t, H, core, moe)[0],
                   donate_argnums=())


def _oracle_greedy(params, prompt, max_new, attn_impl=None):
    """Recompute-per-token: at every step the FULL sequence so far runs
    through the training forward and the last position's argmax extends
    it — the O(t)-per-token reference the decode engine must reproduce
    token-for-token."""
    fwd = _oracle_fwd(attn_impl)
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = fwd(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    return out


# ------------------------------------------------------- decode parity ----

def test_prefill_logits_bit_identical_to_training_forward(params):
    """lm_prefill IS the training forward plus K/V outputs: logits must be
    bit-identical (not just close) for both attention cores."""
    toks = jnp.asarray([_prompts(1, seed=7, lo=8, hi=9)[0]], jnp.int32)
    for impl in ("dense", "blockwise"):
        fwd = _oracle_fwd(impl)
        want = np.asarray(fwd(params, toks))
        logits, ks, vs = jax.jit(
            lambda p, t, i=impl: lm_prefill(p, t, H, attn_impl=i),
            donate_argnums=())(params, toks)
        assert np.array_equal(np.asarray(logits), want), impl
        assert ks.shape == (L, 1, H, toks.shape[1], D // H)
        assert vs.shape == ks.shape


@pytest.mark.parametrize("attn_impl", ["dense", "blockwise"])
def test_greedy_decode_matches_full_forward_oracle(params, attn_impl):
    """Acceptance criterion: the engine's greedy token sequence is
    bit-identical to the recompute-per-token oracle — multi-block (L=2),
    MoE FFNs, both prefill cores, varying prompt lengths (so both prefill
    buckets and the padded-cache attention mask are exercised)."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None, attn_impl=attn_impl)
    for prompt in _prompts(3, seed=2):
        got = eng.generate(prompt, max_new_tokens=6)
        want = _oracle_greedy(params, prompt, 6, attn_impl)
        assert got == want, (prompt, got, want)


def test_eviction_readmission_parity_under_turnover(params):
    """2 slots, 7 requests submitted up front: every request's output must
    match its isolated oracle even though slots are freed and reused
    mid-stream (stale cache pages from evicted requests must never leak
    into a readmitted one)."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    prompts = _prompts(7, seed=3)
    reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    eng.run_until_idle()
    assert all(r.done.is_set() for r in reqs)
    # turnover really happened: more requests than slots, all completed
    assert eng.stats()["requests_total"] == 7
    for p, r in zip(prompts, reqs):
        want = _oracle_greedy(params, p, 4)
        assert r.generated == want, (p, r.generated, want)
    # occupancy was shared: the scheduler interleaved, not serialized
    assert eng.stats()["occupancy_mean"] > 1.0


def test_decode_steady_state_zero_retrace(params, retrace_budget):
    """ISSUE 10 satellite: with prefill buckets warmed, the decode loop
    holds a 0-compile budget across admissions, occupancy changes, and
    slot turnover — the continuous-batching scheduler can never pay a
    retrace for a varying active-request count."""
    eng = DecodeEngine(params, H, n_slots=3, max_len=MAXLEN,
                       serve_dtype=None)
    # warm both buckets the traffic below hits (8 and 16) + the decode step
    eng.generate([1] * 5, max_new_tokens=2)
    eng.generate([1] * 12, max_new_tokens=2)
    p = _prompts(6, seed=4)  # lengths 3..11 → buckets {8, 16}
    with retrace_budget(0, label="serve steady-state decode"):
        r1 = eng.submit(p[0], max_new_tokens=4)
        eng.step()  # occupancy 1
        r2 = eng.submit(p[1], max_new_tokens=6)
        r3 = eng.submit(p[2], max_new_tokens=3)
        eng.run_until_idle()  # occupancy up to 3, then draining
        # readmission wave into freed slots
        r4 = eng.submit(p[3], max_new_tokens=5)
        r5 = eng.submit(p[4], max_new_tokens=2)
        eng.run_until_idle()
    for r in (r1, r2, r3, r4, r5):
        assert r.done.is_set() and r.finish_reason == "max_new_tokens"


def test_mixed_greedy_and_sampled_slots_one_executable(params):
    """Greedy and temperature requests ride the SAME decode executable
    (in-graph select on the per-slot temperature vector): a greedy request
    batched next to a sampling one still matches the oracle."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    prompt_g, prompt_s = _prompts(2, seed=5)
    rg = eng.submit(prompt_g, max_new_tokens=5, temperature=0.0)
    rs = eng.submit(prompt_s, max_new_tokens=5, temperature=1.0)
    eng.run_until_idle()
    assert rg.generated == _oracle_greedy(params, prompt_g, 5)
    assert len(rs.generated) == 5
    assert all(0 <= t < V for t in rs.generated)


def test_sampling_reproducible_per_engine_seed(params):
    """Same seed + same submission order → identical sampled streams;
    different seed → (overwhelmingly) different."""
    prompt = _prompts(1, seed=6)[0]

    def run(seed):
        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None, seed=seed)
        return eng.generate(prompt, max_new_tokens=8, temperature=1.0)

    assert run(0) == run(0)
    assert run(0) != run(123)


def test_eos_retires_slot_and_excludes_token(params):
    """EOS eviction: pick the token the greedy oracle emits mid-stream as
    the EOS id — the engine must stop there, exclude it, and free the
    slot for the queue."""
    prompt = _prompts(1, seed=2)[0]
    oracle = _oracle_greedy(params, prompt, 6)
    eos = oracle[2]
    cut = oracle.index(eos)  # greedy streams repeat tokens: first hit wins
    eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                       serve_dtype=None, eos_id=eos)
    out = eng.generate(prompt, max_new_tokens=6)
    assert out == oracle[:cut]
    assert eos not in out
    st = eng.stats()
    assert st["active_slots"] == 0 and st["queue_depth"] == 0


def test_max_len_evicts_at_cache_capacity(params):
    """A request that would outrun its cache page retires with
    finish_reason="max_len" instead of writing out of bounds."""
    eng = DecodeEngine(params, H, n_slots=1, max_len=16, serve_dtype=None)
    prompt = [1] * 12
    req = eng.submit(prompt, max_new_tokens=50)
    eng.run_until_idle()
    assert req.finish_reason == "max_len"
    # cache positions 12..15 hold generated tokens; the final sample (from
    # position 15's logits) needs no write, so capacity yields
    # max_len - len(prompt) + 1 tokens
    assert len(req.generated) == 16 - 12 + 1


def test_submit_validation(params):
    eng = DecodeEngine(params, H, n_slots=1, max_len=16, serve_dtype=None)
    with pytest.raises(ValueError):
        eng.submit([])
    with pytest.raises(ValueError):
        eng.submit([V + 5])
    with pytest.raises(ValueError):
        eng.submit([1] * 16)  # needs one free position to generate
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=0)
    with pytest.raises(ValueError):
        DecodeEngine(params, H, serve_dtype="fp7")


# ------------------------------------------------------ serve_dtype seam ----

def test_serve_dtype_twins_and_quant_error(params):
    f32b = params_nbytes(prepare_serve_params(params, None))
    bf16b = params_nbytes(prepare_serve_params(params, "bf16"))
    q = prepare_serve_params(params, "int8")
    int8b = params_nbytes(q)
    assert int8b < bf16b < f32b
    # every matmul weight got quantized; dequant error bounded by the
    # per-channel step size
    w = np.asarray(params["blocks"]["wq"], np.float32)
    qt = q["blocks"]["wq"]
    assert isinstance(qt, QuantTensor)
    deq = np.asarray(qt.dequantize(), np.float32)
    step = np.asarray(qt.scale, np.float32)
    assert np.all(np.abs(deq - w) <= step + 1e-2 * np.abs(w) + 1e-6)
    # biases/norm gains stay unquantized
    assert not isinstance(q["blocks"]["ln_g"], QuantTensor)
    # both twins actually decode
    for dt in ("bf16", "int8"):
        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=dt)
        out = eng.generate(_prompts(1)[0], max_new_tokens=4)
        assert len(out) == 4 and all(0 <= t < V for t in out)


def test_serve_metrics_flow_through_registry(params):
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None, registry=reg)
    eng.generate(_prompts(1)[0], max_new_tokens=3)
    assert reg.counter("serve_requests_total").value == 1
    assert reg.counter("serve_tokens_total").value == 3
    assert reg.counter("serve_completed_total",
                       {"reason": "max_new_tokens"}).value == 1
    assert reg.histogram("serve_prefill_ms").count >= 1
    assert reg.histogram("serve_decode_step_ms").count >= 1
    assert reg.histogram("serve_request_ms").count == 1


# ------------------------------------------------------------- loadgen ----

def test_arrival_schedule_deterministic():
    a = arrival_schedule(16, 10.0, seed=3)
    b = arrival_schedule(16, 10.0, seed=3)
    assert a == b and len(a) == 16
    assert all(x < y for x, y in zip(a, a[1:]))
    with pytest.raises(ValueError):
        arrival_schedule(4, 0.0)


def test_open_loop_drives_engine_to_completion(params):
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    eng.generate([1] * 5, max_new_tokens=2)  # warm
    prompts = _prompts(6, seed=8)
    rep = run_open_loop(eng, prompts, rate_rps=300.0, max_new_tokens=4)
    assert rep.completed == rep.n_requests == 6
    assert rep.tokens_out == 6 * 4
    assert rep.tokens_per_sec > 0
    assert rep.latency_p95_ms >= rep.latency_p50_ms > 0
    assert rep.latency_mean_ms > 0
    d = rep.to_dict()
    assert d["offered_rps"] == 300.0
    # without an SLO the goodput fields are explicitly absent-as-None
    assert d["slo_ms"] is None and d["goodput_rps"] is None


def test_open_loop_inter_token_percentiles(params):
    """ISSUE 16: the report carries decode-token inter-arrival
    percentiles (gaps between consecutive tokens within a request) — the
    stream-smoothness number the chunked-prefill twin is measured on."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    eng.generate([1] * 5, max_new_tokens=2)  # warm
    rep = run_open_loop(eng, _prompts(4, seed=19), rate_rps=300.0,
                        max_new_tokens=4)
    assert rep.completed == 4
    assert rep.inter_token_p99_ms >= rep.inter_token_p50_ms > 0
    d = rep.to_dict()
    assert d["inter_token_p50_ms"] == rep.inter_token_p50_ms
    # single-token requests produce no gaps: fields stay None
    eng2 = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                        serve_dtype=None)
    rep1 = run_open_loop(eng2, _prompts(2, seed=20), rate_rps=300.0,
                         max_new_tokens=1)
    assert rep1.inter_token_p50_ms is None


def test_open_loop_goodput_under_slo(params):
    """ISSUE 15 satellite: ``slo_ms`` turns the open-loop run into a
    goodput measurement — requests completing WITHIN the SLO per second,
    with attainment the matching fraction. Pinned at the two boundary
    SLOs (impossible → 0 goodput, generous → all requests count) so the
    accounting can't drift from the latency percentiles."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    eng.generate([1] * 5, max_new_tokens=2)  # warm
    prompts = _prompts(6, seed=8)
    tight = run_open_loop(eng, prompts, rate_rps=300.0,
                          max_new_tokens=4, slo_ms=1e-9)
    assert tight.slo_attainment == 0.0 and tight.goodput_rps == 0.0
    loose = run_open_loop(eng, prompts, rate_rps=300.0,
                          max_new_tokens=4, slo_ms=1e9)
    assert loose.slo_attainment == 1.0
    assert loose.goodput_rps == pytest.approx(
        loose.completed / loose.duration_s)
    assert loose.to_dict()["goodput_rps"] == loose.goodput_rps


# ----------------------------------------- checkpoint loading (serving) ----

def test_template_from_manifest_matches_saved_tree(params, tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt import manifest as mf
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer
    from deeplearning4j_tpu.scaleout.ckpt.reshard import (
        latest_step_dir,
        template_from_manifest,
    )

    ck = Checkpointer(str(tmp_path / "ckpt"))
    ck.save(1, {"params": params}, meta=lm_checkpoint_meta(params, H))
    manifest = mf.read_manifest(latest_step_dir(str(tmp_path / "ckpt")))
    template = template_from_manifest(manifest)
    want = jax.tree_util.tree_leaves_with_path({"params": params})
    got = jax.tree_util.tree_leaves_with_path(template)
    assert len(want) == len(got)
    for (wp, wl), (gp, gl) in zip(want, got):
        assert jax.tree_util.keystr(wp) == jax.tree_util.keystr(gp)
        assert tuple(np.shape(gl)) == tuple(np.shape(wl))
        assert np.dtype(gl.dtype) == np.dtype(wl.dtype)


def test_from_checkpoint_round_trip_and_meta(params, tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    ck = Checkpointer(root)
    ck.save(3, {"params": params}, meta=lm_checkpoint_meta(params, H))
    eng = DecodeEngine.from_checkpoint(root, max_len=MAXLEN,
                                       serve_dtype=None)
    assert eng.n_heads == H and eng.dims == lm_dims(params)
    prompt = _prompts(1, seed=9)[0]
    # restored weights decode exactly like the in-memory ones
    direct = DecodeEngine(params, H, max_len=MAXLEN, serve_dtype=None)
    assert eng.generate(prompt, max_new_tokens=4) == \
        direct.generate(prompt, max_new_tokens=4)


def test_from_checkpoint_requires_heads_without_meta(params, tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    Checkpointer(root).save(1, {"params": params})  # no lm meta
    with pytest.raises(ValueError, match="n_heads"):
        DecodeEngine.from_checkpoint(root, max_len=MAXLEN)
    eng = DecodeEngine.from_checkpoint(root, n_heads=H, max_len=MAXLEN,
                                       serve_dtype=None)
    assert eng.n_heads == H


def test_from_checkpoint_rejects_non_lm_tree(tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    Checkpointer(root).save(1, {"params": {"w": np.ones((3, 3), np.float32)}})
    with pytest.raises(ValueError, match="not a flagship-LM"):
        DecodeEngine.from_checkpoint(root, n_heads=1)


# ------------------------------------------- bench_report latency rows ----

def _bench_round(path, p95_ms, tokens_per_sec, ref=None, fast_path=None):
    detail = {
        "serve_tokens_per_sec": tokens_per_sec,
        "serve_detail": {"latency": {"p50_ms": p95_ms / 2,
                                     "p95_ms": p95_ms,
                                     "mean_ms": p95_ms / 2}},
    }
    if ref is not None:  # the ISSUE 16 fixed reference micro-stage row
        detail["ref_micro_samples_per_sec"] = ref
    if fast_path is not None:
        detail["serve_detail"]["fast_path"] = fast_path
    rec = {"n": 1, "cmd": "bench", "rc": 0, "tail": "", "parsed": {
        "metric": "m", "value": 1.0, "detail": detail}}
    with open(path, "w") as fh:
        json.dump(rec, fh)


def test_bench_report_flags_latency_growth_lower_is_better(tmp_path):
    """ISSUE 10 satellite: serving-latency rows are tracked LOWER-IS-
    BETTER — growth past the threshold is a regression even when
    throughput held."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bench_report import build_trajectory, load_rounds

    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=20.0,
                 tokens_per_sec=100.0)
    traj = build_trajectory(load_rounds(str(tmp_path)), threshold_pct=10.0)
    rows = {r["metric"]: r for r in traj["table"]}
    assert rows["serve_latency_p95_ms"]["lower_is_better"] is True
    assert rows["serve_latency_p95_ms"]["regression"] is True
    assert rows["serve_latency_p50_ms"]["regression"] is True
    # throughput held → no flag on the rate row
    assert rows["serve_tokens_per_sec"]["regression"] is False
    flagged = {r["metric"] for r in traj["regressions"]}
    assert "serve_latency_p95_ms" in flagged


def test_bench_report_latency_improvement_not_flagged(tmp_path):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bench_report import build_trajectory, load_rounds

    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=20.0,
                 tokens_per_sec=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=100.0)
    traj = build_trajectory(load_rounds(str(tmp_path)), threshold_pct=10.0)
    rows = {r["metric"]: r for r in traj["table"]}
    assert rows["serve_latency_p95_ms"]["regression"] is False


# ----------------------------------- bench_report noise carry-over rows ----

def _traj(tmp_path, threshold_pct=10.0):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bench_report import build_trajectory, load_rounds

    return build_trajectory(load_rounds(str(tmp_path)),
                            threshold_pct=threshold_pct)


def test_bench_report_ref_unmasks_regression_on_faster_machine(tmp_path):
    """ISSUE 16 satellite, direction 1: the bench box got 5% FASTER
    (ref 100 -> 105) while the tracked rate only dropped 7.6% raw —
    under the old raw delta that hides a real regression (the machine
    speedup masks part of the code slowdown). Normalized by the
    reference drift the true delta is -12%, past the gate."""
    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0, ref=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=92.4, ref=105.0)
    traj = _traj(tmp_path)
    row = {r["metric"]: r for r in traj["table"]}["serve_tokens_per_sec"]
    assert row["ref_factor"] == 1.05
    assert row["delta_pct"] == -12.0
    assert row["regression"] is True
    assert not traj["ref_flags"]


def test_bench_report_ref_absorbs_machine_slowdown(tmp_path):
    """Direction 2: the box got 5% SLOWER (ref 100 -> 95); the tracked
    rate's raw -12% would false-flag, but dividing the drift out leaves
    -7.4% — under the gate, no regression."""
    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0, ref=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=88.0, ref=95.0)
    traj = _traj(tmp_path)
    row = {r["metric"]: r for r in traj["table"]}["serve_tokens_per_sec"]
    assert row["ref_factor"] == 0.95
    assert -8.0 < row["delta_pct"] < -7.0
    assert row["regression"] is False


def test_bench_report_ref_drift_flags_round_and_suppresses(tmp_path):
    """A reference that itself moved >10% is a broken reference —
    normalizing by it would hide real regressions, so the pair is
    flagged, deltas stay raw, and gating is suppressed (REF-NOISE, not
    REGRESSION: a round this noisy can't distinguish code from box)."""
    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0, ref=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=85.0, ref=80.0)
    traj = _traj(tmp_path)
    from tools.bench_report import render_text
    row = {r["metric"]: r for r in traj["table"]}["serve_tokens_per_sec"]
    assert row["regression"] is False
    assert row["suppressed_by_ref"] is True
    assert row["delta_pct"] == -15.0  # raw, NOT normalized by 0.8
    assert traj["ref_flags"] == [
        {"from_round": 1, "to_round": 2, "ref_factor": 0.8}]
    text = render_text(traj)
    assert "REF-NOISE" in text
    assert "drifted past the stability window" in text


def test_bench_report_ref_row_itself_never_gates(tmp_path):
    """The reference halving is the MACHINE halving — it must flag the
    pair, never read as a code regression on its own row (and rounds
    without the row at all keep the old raw behavior, covered by the
    latency tests above)."""
    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0, ref=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=100.0, ref=50.0)
    traj = _traj(tmp_path)
    rows = {r["metric"]: r for r in traj["table"]}
    assert rows["ref_micro_samples_per_sec"]["regression"] is False
    assert rows["ref_micro_samples_per_sec"]["ref_factor"] is None
    assert len(traj["ref_flags"]) == 1


def test_bench_report_fastpath_rows_tracked_both_directions(tmp_path):
    """ISSUE 16 satellite: the serve fast-path twin block lands as
    tracked rows — ratio/quality rows HIGHER-IS-BETTER (an eroding
    prefix-cache win gates), the inter-token p99s LOWER-IS-BETTER (a
    chunk-scheduling change that re-introduces the stream stall
    gates)."""
    fp1 = {"prefix_on_vs_off": 2.0, "spec_on_vs_off": 1.1,
           "chunk_vs_unchunked": 0.97, "cache_hit_rate": 0.9,
           "accepted_per_verify": 1.5, "inter_token_p99_ms_chunked": 5.0,
           "inter_token_p99_ms_unchunked": 20.0}
    fp2 = dict(fp1, prefix_on_vs_off=1.2, inter_token_p99_ms_chunked=9.0)
    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0, fast_path=fp1)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=100.0, fast_path=fp2)
    traj = _traj(tmp_path)
    rows = {r["metric"]: r for r in traj["table"]}
    assert rows["serve_fastpath_prefix_on_vs_off"]["regression"] is True
    assert rows["serve_fastpath_prefix_on_vs_off"][
        "lower_is_better"] is False
    p99 = rows["serve_fastpath_inter_token_p99_ms_chunked"]
    assert p99["lower_is_better"] is True
    assert p99["regression"] is True  # 5ms -> 9ms: the stall came back
    assert rows["serve_fastpath_cache_hit_rate"]["regression"] is False


# ---------------------------------------------------------- cache shape ----

def test_init_kv_cache_layout(params):
    cache = init_kv_cache(L, 3, H, D // H, MAXLEN)
    assert cache["k"].shape == (L, 3, H, MAXLEN, D // H)
    assert cache["v"].shape == cache["k"].shape
    assert cache["k"].dtype == jnp.float32
    assert not np.any(np.asarray(cache["k"]))  # zero-initialized


# ------------------------------------------- concurrency stress (ISSUE 11) ----

def test_engine_stress_concurrent_clients_under_lockwatch(params, lockwatch):
    """N client threads submit/stream while the background scheduler
    admits/retires, with the runtime lock-order watchdog armed: the
    engine's scheduler lock (and the registry under it) run as watched
    primitives, so a lock-order inversion raises at the acquire instead
    of deadlocking, and the summary proves real cross-thread contention
    was exercised."""
    import threading

    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    engine = DecodeEngine(params, H, n_slots=3, max_len=MAXLEN,
                          serve_dtype=None, registry=MetricsRegistry())
    engine.start()
    n_clients, per_client = 4, 3
    results = {}
    errors = []

    def client(i):
        try:
            out = []
            for j, prompt in enumerate(_prompts(per_client, seed=100 + i)):
                out.append(engine.generate(prompt, max_new_tokens=4,
                                           timeout=120.0))
            results[i] = out
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    try:
        assert not errors, errors
        assert not any(t.is_alive() for t in threads), "stress hung"
        assert sorted(results) == list(range(n_clients))
        for i, outs in results.items():
            assert len(outs) == per_client
            # every request retired with tokens (eos_id=None: full budget)
            assert all(len(tokens) == 4 for tokens in outs), outs
        # greedy parity survives the concurrency: re-run one prompt alone
        prompt = _prompts(1, seed=100)[0]
        want = _oracle_greedy(params, prompt, 4)
        assert engine.generate(prompt, max_new_tokens=4,
                               timeout=120.0) == want
    finally:
        engine.stop()
    watch = lockwatch.summary()
    assert watch["cycles"] == 0 and watch["watchdog_dumps"] == 0
    eng_stats = watch["locks"].get("serve.engine", {})
    assert eng_stats.get("acquires", 0) > n_clients * per_client, (
        "scheduler lock barely exercised", eng_stats)


# -------------------------------------- request-scoped tracing (ISSUE 12) ----

class TestServeTracing:
    """The serve half of the ISSUE 12 tentpole: every request a
    ``serve.request`` span tree, every scheduler iteration an
    ``engine.step`` span, attribution reconstructable by the real
    tools/trace_report.py — and tracing must not perturb decode output
    (greedy parity) nor the steady-state 0-compile budget."""

    @pytest.fixture
    def tracer(self, tmp_path):
        from deeplearning4j_tpu.telemetry import trace as tr

        tracer = tr.Tracer("serve-test", trace_dir=str(tmp_path / "trace"))
        prev = tr.set_tracer(tracer)
        yield tracer
        tr.set_tracer(prev)
        tracer.close()

    def _load(self, tracer):
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.trace_report import load_trace_dir

        return load_trace_dir(os.path.dirname(tracer.path))

    def test_request_span_tree_and_attribution(self, params, tracer):
        from tools.trace_report import serve_attribution

        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None, weight_version="w-test")
        prompts = _prompts(4, seed=11)
        reqs = [eng.submit(p, max_new_tokens=3) for p in prompts]
        eng.run_until_idle()
        assert all(r.done.is_set() for r in reqs)
        spans = self._load(tracer)
        by_name = {}
        for sp in spans.values():
            by_name.setdefault(sp["name"], []).append(sp)
        # one serve.request per submit, all closed, full child set
        assert len(by_name["serve.request"]) == 4
        for req_span in by_name["serve.request"]:
            assert req_span.get("end") is not None
            kids = [sp for sp in spans.values()
                    if sp.get("parent_id") == req_span["span_id"]]
            kid_names = sorted(k["name"] for k in kids)
            assert kid_names == ["serve.decode", "serve.prefill",
                                 "serve.queue_wait", "serve.retire"]
            # per-token accept events ride the decode span
            decode = [k for k in kids if k["name"] == "serve.decode"][0]
            accepts = [e for e in decode["events"] if e["name"] == "accept"]
            assert len(accepts) == 3
            # retire carries reason + weight forensics
            retire = [k for k in kids if k["name"] == "serve.retire"][0]
            assert retire["attrs"]["reason"] == "max_new_tokens"
            assert retire["attrs"]["weight_version"] == "w-test"
        # scheduler iterations traced with occupancy/admission accounting
        steps = by_name["engine.step"]
        assert steps and all(s.get("end") is not None for s in steps)
        assert sum(s["attrs"].get("admissions", 0) for s in steps) == 4
        assert max(s["attrs"].get("occupancy", 0) for s in steps) == 2
        assert sum(s["attrs"].get("retired", 0) for s in steps) == 4
        # the acceptance sum: queue+prefill+decode+gap within 1ms of the
        # engine-measured request latency, for every request
        rows = serve_attribution(spans)
        assert len(rows) == 4
        for row in rows:
            assert row["status"] == "ok"
            total = (row["queue_wait_ms"] + row["prefill_ms"]
                     + row["decode_ms"] + row["gap_ms"])
            assert abs(total - row["total_ms"]) <= 1.0, row
            assert row["tokens"] == 3
            assert row["weight_version"] == "w-test"

    def test_queue_wait_attributed_under_contention(self, params, tracer):
        """1 slot, 3 requests up front: the later requests' queue_wait
        must dominate their prefill (they sat queued through the earlier
        requests' full decode streams)."""
        from tools.trace_report import serve_attribution

        eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                           serve_dtype=None)
        for p in _prompts(3, seed=12):
            eng.submit(p, max_new_tokens=4)
        eng.run_until_idle()
        rows = sorted(serve_attribution(self._load(tracer)),
                      key=lambda r: r["rid"])
        assert rows[0]["queue_wait_ms"] < rows[-1]["queue_wait_ms"]
        assert rows[-1]["queue_wait_ms"] > rows[-1]["prefill_ms"]

    def test_greedy_parity_and_zero_retrace_with_tracer_armed(
            self, params, tracer, retrace_budget):
        """ISSUE 12 acceptance: arming the tracer changes NOTHING about
        the decode math (token-identical to the recompute-per-token
        oracle) and adds NO compiles to the steady-state loop — the
        instrumentation is host-side only."""
        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None)
        eng.generate([1] * 5, max_new_tokens=2)   # warm buckets 8
        eng.generate([1] * 12, max_new_tokens=2)  # and 16
        prompts = _prompts(3, seed=13)
        with retrace_budget(0, label="traced steady-state decode"):
            outs = [eng.generate(p, max_new_tokens=5) for p in prompts]
        for p, got in zip(prompts, outs):
            assert got == _oracle_greedy(params, p, 5), p

    def test_fast_path_attribution_cached_vs_suffix_and_verify(
            self, params, tracer):
        """ISSUE 16: the attribution table splits prefill into the
        cached-skip and the suffix actually computed, and tags verify
        rounds with accepted-token counts — a warm full-hit request shows
        cached time with ZERO suffix time."""
        from tools.trace_report import render_serve_text, serve_attribution

        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None, prefix_cache=True,
                           prefix_page_tokens=4, speculative=2)
        prompt = _prompts(1, seed=18, lo=9, hi=10)[0]  # 2 pages = n-1
        want = eng.generate(prompt, max_new_tokens=4)
        assert eng.generate(prompt, max_new_tokens=4) == want
        rows = sorted(serve_attribution(self._load(tracer)),
                      key=lambda r: r["rid"])
        assert len(rows) == 2
        cold, warm = rows
        assert cold["cached_tokens"] == 0
        assert cold["prefill_suffix_ms"] > 0
        assert warm["cached_tokens"] == 8
        assert warm["prefill_cached_ms"] > 0
        assert warm["prefill_suffix_ms"] == 0  # full hit: no prefill ran
        for r in rows:
            assert r["verify_steps"] > 0
            assert 0 <= r["spec_accepted_tokens"] <= r["tokens"]
        text = render_serve_text(rows)
        assert "cached" in text and "acc" in text

    def test_zero_cost_unconfigured(self, params):
        """No tracer ⇒ no span objects anywhere on the request path."""
        from deeplearning4j_tpu.telemetry import trace as tr

        assert tr.get_tracer() is None
        eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                           serve_dtype=None)
        req = eng.submit(_prompts(1, seed=14)[0], max_new_tokens=2)
        eng.run_until_idle()
        assert req.span is None and req.queue_span is None
        assert req.decode_span is None and req.decode_ms == 0.0

    def test_kill9_leaves_open_request_span_reconstructable(self, tmp_path):
        """Acceptance: kill -9 of a serving process leaves open
        ``serve.request`` spans the report reconstructs — the eager
        begin records ARE the forensics, no hook runs."""
        import signal
        import subprocess
        import sys

        from tools.trace_report import load_trace_dir, serve_attribution

        trace_dir = str(tmp_path / "trace")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "_serve_trace_child.py"), trace_dir],
            stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.strip() == "READY", line
            proc.send_signal(signal.SIGKILL)
        finally:
            proc.wait(timeout=30)
            proc.stdout.close()
        spans = load_trace_dir(trace_dir)
        rows = serve_attribution(spans)
        assert len(rows) == 1
        row = rows[0]
        assert row["status"] == "open"
        assert row["rid"] == 0
        assert row["process"] == "serve-victim"
        # the open decode child pins that the victim died mid-stream
        open_names = {sp["name"] for sp in spans.values()
                      if sp.get("end") is None}
        assert "serve.request" in open_names
        assert "serve.decode" in open_names

    def test_http_traceparent_end_to_end_tree(self, params, tracer):
        """One trace tree spans loadgen → HTTP server → engine: the HTTP
        loadgen driver emits traceparent, UiServer parents http.request
        under it, and the engine's serve.request tree hangs beneath —
        all sharing the loadgen root's trace id."""
        from deeplearning4j_tpu.serve.loadgen import run_open_loop_http
        from deeplearning4j_tpu.ui import UiServer

        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None)
        eng.start()
        server = UiServer()
        server.attach_engine(eng)
        server.start(port=0)
        try:
            rep = run_open_loop_http(
                f"http://127.0.0.1:{server.port}", _prompts(2, seed=15),
                rate_rps=100.0, max_new_tokens=3)
            assert rep.completed == 2
            assert rep.latency_p99_ms >= rep.latency_p50_ms > 0
        finally:
            server.stop()
            eng.stop()
        spans = self._load(tracer)
        roots = [sp for sp in spans.values()
                 if sp["name"] == "loadgen.request"]
        assert len(roots) == 2
        for root in roots:
            tree = [sp for sp in spans.values()
                    if sp.get("trace_id") == root["trace_id"]]
            names = {sp["name"] for sp in tree}
            # loadgen → http → serve.request → children, ONE trace id
            assert {"loadgen.request", "http.request", "serve.request",
                    "serve.prefill", "serve.decode",
                    "serve.retire"} <= names
            http = [sp for sp in tree if sp["name"] == "http.request"][0]
            assert http["parent_id"] == root["span_id"]
            sreq = [sp for sp in tree if sp["name"] == "serve.request"][0]
            assert sreq["parent_id"] == http["span_id"]


# --------------------------------------- in-flight request ages (ISSUE 12) ----

def test_stats_reports_in_flight_request_ages(params):
    """ISSUE 12 satellite: a stuck request is visible from /api/serve as
    a growing queued_s/running_s instead of only as a hung client."""
    import time as _time

    eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                       serve_dtype=None)
    prompts = _prompts(3, seed=16)
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    _time.sleep(0.02)
    st = eng.stats()
    flight = {f["rid"]: f for f in st["in_flight"]}
    assert sorted(flight) == [r.rid for r in reqs]
    assert all(f["state"] == "queued" for f in flight.values())
    assert all(f["queued_s"] >= 0.02 for f in flight.values())
    assert all(f["tokens"] == 0 for f in flight.values())
    eng.step()  # admit rid 0 into the single slot + first decode
    st = eng.stats()
    flight = {f["rid"]: f for f in st["in_flight"]}
    running = flight[reqs[0].rid]
    assert running["state"] == "running" and running["slot"] == 0
    assert running["tokens"] >= 1
    assert running["running_s"] >= 0.0
    assert running["prompt_len"] == len(prompts[0])
    # the other two still queued, ages still growing
    assert flight[reqs[1].rid]["state"] == "queued"
    eng.run_until_idle()
    assert eng.stats()["in_flight"] == []


def test_stats_and_retire_carry_weight_version(params, tmp_path):
    from deeplearning4j_tpu.models.transformer_lm import lm_checkpoint_meta
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    Checkpointer(root).save(7, {"params": params},
                            meta=lm_checkpoint_meta(params, H))
    eng = DecodeEngine.from_checkpoint(root, max_len=MAXLEN,
                                       serve_dtype=None)
    assert eng.weight_version == "ckpt-step-7"
    assert eng.stats()["weight_version"] == "ckpt-step-7"


# --------------------------------------- serving fast path (ISSUE 16) ----

def _fresh_engine(params, reg=None, **kw):
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    reg = reg if reg is not None else MetricsRegistry()
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", MAXLEN)
    kw.setdefault("serve_dtype", None)
    return DecodeEngine(params, H, registry=reg, **kw), reg


class TestSpeculative:
    """Draft/verify speculative decoding, pinned token-identical to the
    non-speculative recompute oracle — the whole point of the greedy
    accept-longest-prefix rule is that speedup NEVER changes the stream."""

    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_greedy_parity_across_k(self, params, k):
        eng, reg = _fresh_engine(params, speculative=k)
        for prompt in _prompts(3, seed=21):
            got = eng.generate(prompt, max_new_tokens=6)
            assert got == _oracle_greedy(params, prompt, 6), (k, prompt)
        st = eng.stats()["speculative"]
        assert st["k"] == k and st["verify_steps"] > 0
        # the flagship ran ONE verify dispatch per round, k+1 draft steps
        assert reg.counter("serve_spec_verify_steps_total").value == \
            st["verify_steps"]
        assert reg.counter("serve_spec_draft_steps_total").value == \
            st["verify_steps"] * (k + 1)
        # first-class accept metric: one observation per verify round
        h = reg.histogram("serve_spec_accepted_per_verify")
        assert h.count == st["verify_steps"]
        assert h.sum == st["accepted_tokens"]
        assert reg.histogram("serve_verify_step_ms").count == \
            st["verify_steps"]

    def test_all_accept_with_flagship_draft(self, params):
        """draft == flagship (draft_layers=L): every proposal matches, so
        every verify round emits k+1 tokens and accept_rate is exactly 1
        — this pins the draft-cache frontier bookkeeping (a fully
        accepted round must leave no K/V hole for the next round)."""
        eng, _ = _fresh_engine(
            params, speculative=SpeculativeConfig(k=2, draft_layers=L))
        prompts = _prompts(3, seed=22)
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            assert r.generated == _oracle_greedy(params, p, 6)
        st = eng.stats()["speculative"]
        assert st["accept_rate"] == 1.0
        # every verify round emitted multiple tokens for one dispatch
        assert st["accepted_tokens"] >= st["verify_steps"] * 2

    def test_zero_accept_still_token_identical(self, params):
        """A draft that ALWAYS proposes a token the flagship never emits
        (decoder bias +1e9 on one vocab slot): every verify round
        zero-accepts, emitting exactly the flagship's own greedy token —
        the slow path of speculation is the baseline stream, not garbage."""
        prompts = _prompts(3, seed=23)
        oracles = [_oracle_greedy(params, p, 6) for p in prompts]
        emitted = {t for o in oracles for t in o}
        junk = next(t for t in range(V) if t not in emitted)
        bias = np.zeros((V,), np.float32)
        bias[junk] = 1e9
        draft = {**params, "dec_b": params["dec_b"] + bias}
        eng, reg = _fresh_engine(
            params, speculative=SpeculativeConfig(k=2, draft_params=draft))
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for o, r in zip(oracles, reqs):
            assert r.generated == o
        st = eng.stats()["speculative"]
        assert st["verify_steps"] > 0 and st["accepted_tokens"] == 0
        assert st["accept_rate"] == 0.0
        assert reg.histogram("serve_spec_accepted_per_verify").sum == 0

    def test_accept_longest_prefix_rule(self):
        assert accept_longest_prefix([5, 7], [5, 7, 9]) == (2, [5, 7, 9])
        assert accept_longest_prefix([5, 7], [5, 8, 9]) == (1, [5, 8])
        assert accept_longest_prefix([5, 7], [6, 8, 9]) == (0, [6])
        assert accept_longest_prefix([3], [3, 4]) == (1, [3, 4])
        with pytest.raises(ValueError):
            accept_longest_prefix([1, 2], [1, 2])  # needs k+1 verify toks

    def test_resolve_speculative_seam(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_SERVE_SPEC", raising=False)
        assert resolve_speculative() is None           # defaults OFF
        assert resolve_speculative(False) is None
        assert resolve_speculative(True) == SpeculativeConfig()
        assert resolve_speculative(3).k == 3
        cfg = SpeculativeConfig(k=4, draft_layers=2)
        assert resolve_speculative(cfg) is cfg
        with pytest.raises(TypeError):
            resolve_speculative("yes")
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "4:2")
        env = resolve_speculative()
        assert env.k == 4 and env.draft_layers == 2
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "0")
        assert resolve_speculative() is None
        # explicit argument beats the env var
        assert resolve_speculative(2).k == 2
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "nope")
        with pytest.raises(ValueError):
            resolve_speculative()

    def test_sampling_slots_ride_along_unbroken(self, params):
        """temperature>0 slots batched next to greedy ones under
        speculation: greedy parity holds and the sampled slot still gets
        its full budget (it advances one token per verify round)."""
        eng, _ = _fresh_engine(params, speculative=2)
        pg, ps = _prompts(2, seed=24)
        rg = eng.submit(pg, max_new_tokens=5, temperature=0.0)
        rs = eng.submit(ps, max_new_tokens=5, temperature=1.0)
        eng.run_until_idle()
        assert rg.generated == _oracle_greedy(params, pg, 5)
        assert len(rs.generated) == 5
        assert all(0 <= t < V for t in rs.generated)

    def test_near_max_len_falls_back_to_plain_decode(self, params):
        """positions within k+1 of the cache edge would make the verify
        write out of range (dynamic_update_slice CLAMPS — silent
        corruption, not an error), so those ticks must take the plain
        decode path; the request still retires at max_len with the exact
        oracle stream."""
        eng, _ = _fresh_engine(params, n_slots=1, max_len=16, speculative=4)
        prompt = _prompts(1, seed=25, lo=10, hi=11)[0]  # len 10 of 16
        req = eng.submit(prompt, max_new_tokens=50)
        eng.run_until_idle()
        assert req.finish_reason == "max_len"
        want = _oracle_greedy(params, prompt, 16 - 10 + 1)
        assert req.generated == want

    def test_spec_steady_state_zero_retrace(self, params, retrace_budget):
        """the 0-compile budget survives speculation: draft decode,
        verify, and both prefill towers are pinned executables — a
        varying accept count can never pay a retrace."""
        eng, _ = _fresh_engine(params, speculative=2)
        eng.generate([1] * 5, max_new_tokens=2)   # warm buckets 8
        eng.generate([1] * 12, max_new_tokens=2)  # and 16
        prompts = _prompts(4, seed=26)
        with retrace_budget(0, label="speculative steady-state"):
            reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
            eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            assert r.generated == _oracle_greedy(params, p, 5)


class TestPrefixCache:
    """Shared-prefix KV page reuse: cached pages seed the slot and only
    the uncached suffix prefills — outputs pinned token-identical to the
    cold engine across hit, miss, partial hit, and eviction/readmit."""

    def test_full_hit_issues_zero_prefill_dispatches(self, params):
        """THE acceptance pin: a fully cached prompt admits without ANY
        prefill dispatch — the first token comes from the shared decode
        step, and serve_prefill_dispatches_total stays flat."""
        eng, reg = _fresh_engine(params, prefix_cache=True,
                                 prefix_page_tokens=4)
        prompt = _prompts(1, seed=31, lo=9, hi=10)[0]  # len 9: pages cover 8 = n-1
        want = _oracle_greedy(params, prompt, 5)
        assert eng.generate(prompt, max_new_tokens=5) == want
        cold = reg.counter("serve_prefill_dispatches_total").value
        assert cold >= 1
        assert eng.generate(prompt, max_new_tokens=5) == want
        assert reg.counter("serve_prefill_dispatches_total").value == cold
        st = eng.stats()["prefix_cache"]
        assert st["hits"] >= 1 and st["tokens_reused"] >= 8
        assert reg.counter("serve_prefix_cache_hits_total").value >= 1
        assert reg.gauge("serve_prefix_cache_hit_rate").value > 0

    def test_partial_hit_prefills_only_suffix(self, params):
        """Two prompts sharing a 8-token prefix: the second admission
        reuses the shared pages and prefills just its own suffix (visible
        as cached_tokens on the request and a shorter suffix span)."""
        rng = np.random.RandomState(32)
        shared = list(map(int, rng.randint(0, V, 8)))
        a = shared + list(map(int, rng.randint(0, V, 5)))
        b = shared + list(map(int, rng.randint(0, V, 7)))
        eng, _ = _fresh_engine(params, prefix_cache=True,
                               prefix_page_tokens=4)
        assert eng.generate(a, max_new_tokens=4) == \
            _oracle_greedy(params, a, 4)
        req = eng.submit(b, max_new_tokens=4)
        eng.run_until_idle()
        assert req.generated == _oracle_greedy(params, b, 4)
        assert req.cached_tokens == 8
        assert req.prefill_cached_ms > 0 and req.prefill_suffix_ms > 0

    def test_parity_under_eviction_pressure_and_readmit(self, params):
        """capacity of 3 pages against 4-page prompts: every admission
        evicts, and a prompt whose pages were evicted re-admits through
        the cold path with identical output (evict → readmit parity)."""
        from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry()
        cache = PrefixPageCache(page_tokens=4, capacity_pages=3,
                                registry=reg)
        eng, _ = _fresh_engine(params, reg=reg, prefix_cache=cache)
        prompts = _prompts(4, seed=33, lo=17, hi=20)
        for _round in range(2):
            for p in prompts:
                assert eng.generate(p, max_new_tokens=4) == \
                    _oracle_greedy(params, p, 4), p
                cache.check_invariants()
        st = cache.stats()
        assert st["evictions"] > 0
        assert st["pages"] <= 3
        assert reg.counter("serve_prefix_cache_evictions_total").value == \
            st["evictions"]

    def test_lru_keeps_hot_chain_under_pressure(self, params):
        """A hot prompt re-looked-up every round keeps its chain resident
        while cold chains churn: its later admissions are full hits even
        though the table is past capacity the whole time."""
        cache = PrefixPageCache(page_tokens=4, capacity_pages=6)
        eng, reg = _fresh_engine(params, prefix_cache=cache)
        hot = _prompts(1, seed=34, lo=9, hi=10)[0]
        cold = _prompts(3, seed=35, lo=9, hi=10)
        want = _oracle_greedy(params, hot, 3)
        assert eng.generate(hot, max_new_tokens=3) == want
        for p in cold:
            assert eng.generate(p, max_new_tokens=3) == \
                _oracle_greedy(params, p, 3)
            before = reg.counter("serve_prefill_dispatches_total").value
            assert eng.generate(hot, max_new_tokens=3) == want
            assert reg.counter(
                "serve_prefill_dispatches_total").value == before
        cache.check_invariants()

    def test_divergent_prompts_copy_on_write(self, params):
        """Prompts diverging INSIDE a page leave the shared parent chain
        untouched and create sibling nodes — both replay token-identical
        afterward (an insert can never corrupt a cached neighbor)."""
        rng = np.random.RandomState(36)
        shared = list(map(int, rng.randint(0, V, 4)))
        a = shared + list(map(int, rng.randint(0, V, 6)))
        b = shared + list(map(int, rng.randint(0, V, 6)))
        assert a != b
        cache = PrefixPageCache(page_tokens=4, capacity_pages=64)
        eng, _ = _fresh_engine(params, prefix_cache=cache)
        wa, wb = (_oracle_greedy(params, p, 4) for p in (a, b))
        assert eng.generate(a, max_new_tokens=4) == wa
        assert eng.generate(b, max_new_tokens=4) == wb
        # replay both after the sibling insert: still exact
        assert eng.generate(a, max_new_tokens=4) == wa
        assert eng.generate(b, max_new_tokens=4) == wb
        cache.check_invariants()
        st = cache.stats()
        assert st["pages"] >= 3  # shared root + two sibling chains

    def test_refcounts_under_concurrent_submit_lockwatch(
            self, params, lockwatch):
        """N client threads hammer shared-prefix prompts through the
        background scheduler with the lock-order watchdog armed: the page
        table's refcount/parent invariants hold at every quiescent point
        and no lock cycle forms between engine and cache locks."""
        import threading

        cache = PrefixPageCache(page_tokens=4, capacity_pages=8)
        eng, _ = _fresh_engine(params, n_slots=3, prefix_cache=cache)
        rng = np.random.RandomState(37)
        shared = list(map(int, rng.randint(0, V, 8)))
        eng.start()
        errors = []

        def client(i):
            try:
                rloc = np.random.RandomState(50 + i)
                for _ in range(3):
                    p = shared + list(map(int, rloc.randint(0, V, 5)))
                    out = eng.generate(p, max_new_tokens=3, timeout=120.0)
                    assert len(out) == 3
            except BaseException as exc:  # noqa: BLE001 — surfaced below
                errors.append(exc)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        try:
            assert not errors, errors
            assert not any(t.is_alive() for t in threads), "stress hung"
            cache.check_invariants()
            assert cache.stats()["hits"] > 0  # sharing really happened
            # parity survives the churn
            p = shared + [1, 2, 3]
            assert eng.generate(p, max_new_tokens=3, timeout=120.0) == \
                _oracle_greedy(params, p, 3)
            cache.check_invariants()
        finally:
            eng.stop()
        watch = lockwatch.summary()
        assert watch["cycles"] == 0 and watch["watchdog_dumps"] == 0
        assert watch["locks"].get("serve.prefix_cache",
                                  {}).get("acquires", 0) > 0

    def test_cache_unit_lookup_insert_evict(self):
        """Table-level semantics without an engine: page-aligned prefix
        match, page-granular insert, refcount-guarded LRU eviction."""
        cache = PrefixPageCache(page_tokens=2, capacity_pages=3)
        kv = np.arange(2 * 1 * 6 * 2, dtype=np.float32).reshape(2, 1, 6, 2)
        assert cache.insert([1, 2, 3, 4, 5, 6], kv, kv) == 3
        plen, ks, vs = cache.lookup([1, 2, 3, 4, 99, 98])
        assert plen == 4 and len(ks) == 2
        assert np.array_equal(np.asarray(ks[0]), kv[:, :, 0:2])
        assert np.array_equal(np.asarray(ks[1]), kv[:, :, 2:4])
        # interior nodes are eviction-immune while children live
        cache.insert([9, 9], kv[:, :, :2], kv[:, :, :2])
        st = cache.stats()
        assert st["pages"] <= 3 and st["evictions"] >= 1
        cache.check_invariants()
        # the evicted leaf no longer matches; its parents still do
        plen, _, _ = cache.lookup([1, 2, 3, 4, 5, 6])
        assert plen in (2, 4)
        with pytest.raises(ValueError):
            PrefixPageCache(page_tokens=0)
        with pytest.raises(ValueError):
            PrefixPageCache(capacity_pages=0)


class TestChunkedPrefill:
    """Long prompts prefill in fixed-width chunks interleaved with decode
    ticks — token-identical to unchunked, including at exact chunk
    boundaries, with pinned chunk shapes for the 0-compile budget."""

    @pytest.mark.parametrize("plen", [12, 13, 16, 5, 4])
    def test_parity_at_chunk_boundaries(self, params, plen):
        """prompt_len % chunk == 0 (12, 16, 4), != 0 (13), and shorter
        than a chunk (the inline path) all match the oracle exactly."""
        prompt = _prompts(1, seed=40 + plen, lo=plen, hi=plen + 1)[0]
        assert len(prompt) == plen
        eng, _ = _fresh_engine(params, prefill_chunk=4)
        req = eng.submit(prompt, max_new_tokens=5)
        eng.run_until_idle()
        assert req.generated == _oracle_greedy(params, prompt, 5), plen
        if plen > 4:
            assert req.prefill_chunks >= 2

    def test_decode_interleaves_with_chunked_prefill(self, params):
        """A running stream keeps producing tokens WHILE a long prompt
        chunk-prefills next to it (one chunk per scheduler iteration),
        and both match their oracles — the head-of-line blocking the
        chunking exists to kill is actually killed."""
        eng, _ = _fresh_engine(params, prefill_chunk=4)
        short = _prompts(1, seed=41)[0]
        long_p = _prompts(1, seed=42, lo=20, hi=21)[0]
        r_short = eng.submit(short, max_new_tokens=8)
        eng.step()  # short admitted, first token out
        tokens_before = len(r_short.generated)
        r_long = eng.submit(long_p, max_new_tokens=4)
        # drive while the long prompt is mid-chunking: the short stream
        # must advance during at least one chunking iteration
        advanced_mid_chunk = False
        while not (r_short.done.is_set() and r_long.done.is_set()):
            n0 = len(r_short.generated)
            eng.step()
            if eng.stats()["chunking_slots"] or r_long.slot in \
                    eng._chunking:
                advanced_mid_chunk |= len(r_short.generated) > n0
        assert r_long.prefill_chunks >= 2
        assert r_short.generated == _oracle_greedy(params, short, 8)
        assert r_long.generated == _oracle_greedy(params, long_p, 4)
        assert len(r_short.generated) > tokens_before

    def test_chunk_plus_prefix_suffix_path(self, params):
        """Chunked engine + prefix cache: the second admission seeds the
        cached pages then chunk-prefills ONLY the suffix — fewer prefill
        dispatches than the cold pass, same tokens."""
        eng, reg = _fresh_engine(params, prefill_chunk=4,
                                 prefix_cache=True, prefix_page_tokens=4)
        rng = np.random.RandomState(43)
        shared = list(map(int, rng.randint(0, V, 12)))
        a = shared + list(map(int, rng.randint(0, V, 6)))
        b = shared + list(map(int, rng.randint(0, V, 6)))
        assert eng.generate(a, max_new_tokens=4) == \
            _oracle_greedy(params, a, 4)
        cold = reg.counter("serve_prefill_dispatches_total").value
        req = eng.submit(b, max_new_tokens=4)
        eng.run_until_idle()
        warm = reg.counter("serve_prefill_dispatches_total").value - cold
        assert req.generated == _oracle_greedy(params, b, 4)
        assert req.cached_tokens == 12
        assert warm < cold  # suffix-only prefill beat the cold pass

    def test_chunked_steady_state_zero_retrace(self, params, retrace_budget):
        """chunk shapes are pinned at width C: admitting long prompts of
        DIFFERENT lengths retraces nothing once one chunked admission has
        warmed the executable."""
        eng, _ = _fresh_engine(params, prefill_chunk=4)
        eng.generate([1] * 12, max_new_tokens=2)  # warm chunk W=4 + decode
        prompts = _prompts(3, seed=44, lo=13, hi=24)
        with retrace_budget(0, label="chunked-prefill steady-state"):
            reqs = [eng.submit(p, max_new_tokens=3) for p in prompts]
            eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            assert r.generated == _oracle_greedy(params, p, 3)


def test_all_fast_paths_composed_parity(params):
    """prefix cache + chunked prefill + speculation in ONE engine: the
    composed fast path is still pinned token-identical to the cold
    baseline across a shared-prefix barrage."""
    eng, reg = _fresh_engine(params, n_slots=3, prefix_cache=True,
                             prefix_page_tokens=4, prefill_chunk=4,
                             speculative=2)
    rng = np.random.RandomState(45)
    shared = list(map(int, rng.randint(0, V, 8)))
    prompts = [shared + list(map(int, rng.randint(0, V, w)))
               for w in (3, 5, 7, 3)]
    reqs = [eng.submit(p, max_new_tokens=5) for p in prompts]
    eng.run_until_idle()
    for p, r in zip(prompts, reqs):
        assert r.generated == _oracle_greedy(params, p, 5), p
    st = eng.stats()
    assert st["prefix_cache"]["hits"] > 0
    assert st["speculative"]["verify_steps"] > 0


def test_engine_metrics_record_flat_keys(params):
    """Every serve_* registry instrument reaches the step-log record the
    telemetry report renders (histograms as _count/_sum, labeled
    counters summed) — the contract the ISSUE 12 meta-test leans on."""
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                       serve_dtype=None, registry=reg)
    eng.generate(_prompts(1, seed=17)[0], max_new_tokens=2)
    rec = eng.metrics_record()
    assert rec["serve_requests_total"] == 1.0
    assert rec["serve_tokens_total"] == 2.0
    assert rec["serve_completed_total"] == 1.0  # labels summed
    assert rec["serve_request_ms_count"] == 1.0
    assert rec["serve_request_ms_sum"] > 0
    # EVERY serve_* name in the registry surfaces in the record
    snap = reg.snapshot()
    names = {r["name"] for kind in ("counters", "gauges", "histograms")
             for r in snap[kind] if r["name"].startswith("serve_")}
    for name in names:
        assert name in rec or f"{name}_count" in rec, name
