"""Serving subsystem (ISSUE 10): KV-cached decode correctness against the
recompute-per-token full-forward oracle (dense AND blockwise prefill,
multi-block, MoE layers), cache eviction/readmission parity under
mid-stream turnover, the 0-compile steady-state decode retrace budget,
the serve_dtype quantization seam, the open-loop load generator, and the
template-free checkpoint restore behind ``DecodeEngine.from_checkpoint``.
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer_lm import (
    dense_moe,
    init_kv_cache,
    init_lm_params,
    lm_checkpoint_meta,
    lm_dims,
    lm_forward,
    lm_prefill,
)
from deeplearning4j_tpu.ops.flash_attention import attention_core
from deeplearning4j_tpu.serve import (
    DecodeEngine,
    QuantTensor,
    arrival_schedule,
    params_nbytes,
    prepare_serve_params,
    run_open_loop,
)

V, D, H, E, DFF, L = 61, 16, 2, 4, 32, 2
MAXLEN = 32


@pytest.fixture(scope="module")
def params():
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                          n_layers=L)


def _prompts(n, seed=1, lo=3, hi=12):
    rng = np.random.RandomState(seed)
    return [list(map(int, rng.randint(0, V, rng.randint(lo, hi))))
            for _ in range(n)]


@functools.lru_cache(maxsize=None)
def _oracle_fwd(attn_impl):
    """The full-forward logits fn the oracle recomputes per token — the
    EXACT training forward (lm_forward) with the dense MoE and the given
    attention core; jit-cached per (impl, length) across tests."""
    core = lambda q, k, v: attention_core(q, k, v, causal=True,  # noqa: E731
                                          impl=attn_impl)
    moe = lambda rw, ex, x: dense_moe(rw, ex, x, 2)  # noqa: E731
    return jax.jit(lambda p, t: lm_forward(p, t, H, core, moe)[0],
                   donate_argnums=())


def _oracle_greedy(params, prompt, max_new, attn_impl=None):
    """Recompute-per-token: at every step the FULL sequence so far runs
    through the training forward and the last position's argmax extends
    it — the O(t)-per-token reference the decode engine must reproduce
    token-for-token."""
    fwd = _oracle_fwd(attn_impl)
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        logits = fwd(params, jnp.asarray([toks], jnp.int32))
        nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
    return out


# ------------------------------------------------------- decode parity ----

def test_prefill_logits_bit_identical_to_training_forward(params):
    """lm_prefill IS the training forward plus K/V outputs: logits must be
    bit-identical (not just close) for both attention cores."""
    toks = jnp.asarray([_prompts(1, seed=7, lo=8, hi=9)[0]], jnp.int32)
    for impl in ("dense", "blockwise"):
        fwd = _oracle_fwd(impl)
        want = np.asarray(fwd(params, toks))
        logits, ks, vs = jax.jit(
            lambda p, t, i=impl: lm_prefill(p, t, H, attn_impl=i),
            donate_argnums=())(params, toks)
        assert np.array_equal(np.asarray(logits), want), impl
        assert ks.shape == (L, 1, H, toks.shape[1], D // H)
        assert vs.shape == ks.shape


@pytest.mark.parametrize("attn_impl", ["dense", "blockwise"])
def test_greedy_decode_matches_full_forward_oracle(params, attn_impl):
    """Acceptance criterion: the engine's greedy token sequence is
    bit-identical to the recompute-per-token oracle — multi-block (L=2),
    MoE FFNs, both prefill cores, varying prompt lengths (so both prefill
    buckets and the padded-cache attention mask are exercised)."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None, attn_impl=attn_impl)
    for prompt in _prompts(3, seed=2):
        got = eng.generate(prompt, max_new_tokens=6)
        want = _oracle_greedy(params, prompt, 6, attn_impl)
        assert got == want, (prompt, got, want)


def test_eviction_readmission_parity_under_turnover(params):
    """2 slots, 7 requests submitted up front: every request's output must
    match its isolated oracle even though slots are freed and reused
    mid-stream (stale cache pages from evicted requests must never leak
    into a readmitted one)."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    prompts = _prompts(7, seed=3)
    reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
    eng.run_until_idle()
    assert all(r.done.is_set() for r in reqs)
    # turnover really happened: more requests than slots, all completed
    assert eng.stats()["requests_total"] == 7
    for p, r in zip(prompts, reqs):
        want = _oracle_greedy(params, p, 4)
        assert r.generated == want, (p, r.generated, want)
    # occupancy was shared: the scheduler interleaved, not serialized
    assert eng.stats()["occupancy_mean"] > 1.0


def test_decode_steady_state_zero_retrace(params, retrace_budget):
    """ISSUE 10 satellite: with prefill buckets warmed, the decode loop
    holds a 0-compile budget across admissions, occupancy changes, and
    slot turnover — the continuous-batching scheduler can never pay a
    retrace for a varying active-request count."""
    eng = DecodeEngine(params, H, n_slots=3, max_len=MAXLEN,
                       serve_dtype=None)
    # warm both buckets the traffic below hits (8 and 16) + the decode step
    eng.generate([1] * 5, max_new_tokens=2)
    eng.generate([1] * 12, max_new_tokens=2)
    p = _prompts(6, seed=4)  # lengths 3..11 → buckets {8, 16}
    with retrace_budget(0, label="serve steady-state decode"):
        r1 = eng.submit(p[0], max_new_tokens=4)
        eng.step()  # occupancy 1
        r2 = eng.submit(p[1], max_new_tokens=6)
        r3 = eng.submit(p[2], max_new_tokens=3)
        eng.run_until_idle()  # occupancy up to 3, then draining
        # readmission wave into freed slots
        r4 = eng.submit(p[3], max_new_tokens=5)
        r5 = eng.submit(p[4], max_new_tokens=2)
        eng.run_until_idle()
    for r in (r1, r2, r3, r4, r5):
        assert r.done.is_set() and r.finish_reason == "max_new_tokens"


def test_mixed_greedy_and_sampled_slots_one_executable(params):
    """Greedy and temperature requests ride the SAME decode executable
    (in-graph select on the per-slot temperature vector): a greedy request
    batched next to a sampling one still matches the oracle."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    prompt_g, prompt_s = _prompts(2, seed=5)
    rg = eng.submit(prompt_g, max_new_tokens=5, temperature=0.0)
    rs = eng.submit(prompt_s, max_new_tokens=5, temperature=1.0)
    eng.run_until_idle()
    assert rg.generated == _oracle_greedy(params, prompt_g, 5)
    assert len(rs.generated) == 5
    assert all(0 <= t < V for t in rs.generated)


def test_sampling_reproducible_per_engine_seed(params):
    """Same seed + same submission order → identical sampled streams;
    different seed → (overwhelmingly) different."""
    prompt = _prompts(1, seed=6)[0]

    def run(seed):
        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None, seed=seed)
        return eng.generate(prompt, max_new_tokens=8, temperature=1.0)

    assert run(0) == run(0)
    assert run(0) != run(123)


def test_eos_retires_slot_and_excludes_token(params):
    """EOS eviction: pick the token the greedy oracle emits mid-stream as
    the EOS id — the engine must stop there, exclude it, and free the
    slot for the queue."""
    prompt = _prompts(1, seed=2)[0]
    oracle = _oracle_greedy(params, prompt, 6)
    eos = oracle[2]
    cut = oracle.index(eos)  # greedy streams repeat tokens: first hit wins
    eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                       serve_dtype=None, eos_id=eos)
    out = eng.generate(prompt, max_new_tokens=6)
    assert out == oracle[:cut]
    assert eos not in out
    st = eng.stats()
    assert st["active_slots"] == 0 and st["queue_depth"] == 0


def test_max_len_evicts_at_cache_capacity(params):
    """A request that would outrun its cache page retires with
    finish_reason="max_len" instead of writing out of bounds."""
    eng = DecodeEngine(params, H, n_slots=1, max_len=16, serve_dtype=None)
    prompt = [1] * 12
    req = eng.submit(prompt, max_new_tokens=50)
    eng.run_until_idle()
    assert req.finish_reason == "max_len"
    # cache positions 12..15 hold generated tokens; the final sample (from
    # position 15's logits) needs no write, so capacity yields
    # max_len - len(prompt) + 1 tokens
    assert len(req.generated) == 16 - 12 + 1


def test_submit_validation(params):
    eng = DecodeEngine(params, H, n_slots=1, max_len=16, serve_dtype=None)
    with pytest.raises(ValueError):
        eng.submit([])
    with pytest.raises(ValueError):
        eng.submit([V + 5])
    with pytest.raises(ValueError):
        eng.submit([1] * 16)  # needs one free position to generate
    with pytest.raises(ValueError):
        eng.submit([1, 2], max_new_tokens=0)
    with pytest.raises(ValueError):
        DecodeEngine(params, H, serve_dtype="fp7")


# ------------------------------------------------------ serve_dtype seam ----

def test_serve_dtype_twins_and_quant_error(params):
    f32b = params_nbytes(prepare_serve_params(params, None))
    bf16b = params_nbytes(prepare_serve_params(params, "bf16"))
    q = prepare_serve_params(params, "int8")
    int8b = params_nbytes(q)
    assert int8b < bf16b < f32b
    # every matmul weight got quantized; dequant error bounded by the
    # per-channel step size
    w = np.asarray(params["blocks"]["wq"], np.float32)
    qt = q["blocks"]["wq"]
    assert isinstance(qt, QuantTensor)
    deq = np.asarray(qt.dequantize(), np.float32)
    step = np.asarray(qt.scale, np.float32)
    assert np.all(np.abs(deq - w) <= step + 1e-2 * np.abs(w) + 1e-6)
    # biases/norm gains stay unquantized
    assert not isinstance(q["blocks"]["ln_g"], QuantTensor)
    # both twins actually decode
    for dt in ("bf16", "int8"):
        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=dt)
        out = eng.generate(_prompts(1)[0], max_new_tokens=4)
        assert len(out) == 4 and all(0 <= t < V for t in out)


def test_serve_metrics_flow_through_registry(params):
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None, registry=reg)
    eng.generate(_prompts(1)[0], max_new_tokens=3)
    assert reg.counter("serve_requests_total").value == 1
    assert reg.counter("serve_tokens_total").value == 3
    assert reg.counter("serve_completed_total",
                       {"reason": "max_new_tokens"}).value == 1
    assert reg.histogram("serve_prefill_ms").count >= 1
    assert reg.histogram("serve_decode_step_ms").count >= 1
    assert reg.histogram("serve_request_ms").count == 1


# ------------------------------------------------------------- loadgen ----

def test_arrival_schedule_deterministic():
    a = arrival_schedule(16, 10.0, seed=3)
    b = arrival_schedule(16, 10.0, seed=3)
    assert a == b and len(a) == 16
    assert all(x < y for x, y in zip(a, a[1:]))
    with pytest.raises(ValueError):
        arrival_schedule(4, 0.0)


def test_open_loop_drives_engine_to_completion(params):
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    eng.generate([1] * 5, max_new_tokens=2)  # warm
    prompts = _prompts(6, seed=8)
    rep = run_open_loop(eng, prompts, rate_rps=300.0, max_new_tokens=4)
    assert rep.completed == rep.n_requests == 6
    assert rep.tokens_out == 6 * 4
    assert rep.tokens_per_sec > 0
    assert rep.latency_p95_ms >= rep.latency_p50_ms > 0
    assert rep.latency_mean_ms > 0
    d = rep.to_dict()
    assert d["offered_rps"] == 300.0
    # without an SLO the goodput fields are explicitly absent-as-None
    assert d["slo_ms"] is None and d["goodput_rps"] is None


def test_open_loop_goodput_under_slo(params):
    """ISSUE 15 satellite: ``slo_ms`` turns the open-loop run into a
    goodput measurement — requests completing WITHIN the SLO per second,
    with attainment the matching fraction. Pinned at the two boundary
    SLOs (impossible → 0 goodput, generous → all requests count) so the
    accounting can't drift from the latency percentiles."""
    eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                       serve_dtype=None)
    eng.generate([1] * 5, max_new_tokens=2)  # warm
    prompts = _prompts(6, seed=8)
    tight = run_open_loop(eng, prompts, rate_rps=300.0,
                          max_new_tokens=4, slo_ms=1e-9)
    assert tight.slo_attainment == 0.0 and tight.goodput_rps == 0.0
    loose = run_open_loop(eng, prompts, rate_rps=300.0,
                          max_new_tokens=4, slo_ms=1e9)
    assert loose.slo_attainment == 1.0
    assert loose.goodput_rps == pytest.approx(
        loose.completed / loose.duration_s)
    assert loose.to_dict()["goodput_rps"] == loose.goodput_rps


# ----------------------------------------- checkpoint loading (serving) ----

def test_template_from_manifest_matches_saved_tree(params, tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt import manifest as mf
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer
    from deeplearning4j_tpu.scaleout.ckpt.reshard import (
        latest_step_dir,
        template_from_manifest,
    )

    ck = Checkpointer(str(tmp_path / "ckpt"))
    ck.save(1, {"params": params}, meta=lm_checkpoint_meta(params, H))
    manifest = mf.read_manifest(latest_step_dir(str(tmp_path / "ckpt")))
    template = template_from_manifest(manifest)
    want = jax.tree_util.tree_leaves_with_path({"params": params})
    got = jax.tree_util.tree_leaves_with_path(template)
    assert len(want) == len(got)
    for (wp, wl), (gp, gl) in zip(want, got):
        assert jax.tree_util.keystr(wp) == jax.tree_util.keystr(gp)
        assert tuple(np.shape(gl)) == tuple(np.shape(wl))
        assert np.dtype(gl.dtype) == np.dtype(wl.dtype)


def test_from_checkpoint_round_trip_and_meta(params, tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    ck = Checkpointer(root)
    ck.save(3, {"params": params}, meta=lm_checkpoint_meta(params, H))
    eng = DecodeEngine.from_checkpoint(root, max_len=MAXLEN,
                                       serve_dtype=None)
    assert eng.n_heads == H and eng.dims == lm_dims(params)
    prompt = _prompts(1, seed=9)[0]
    # restored weights decode exactly like the in-memory ones
    direct = DecodeEngine(params, H, max_len=MAXLEN, serve_dtype=None)
    assert eng.generate(prompt, max_new_tokens=4) == \
        direct.generate(prompt, max_new_tokens=4)


def test_from_checkpoint_requires_heads_without_meta(params, tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    Checkpointer(root).save(1, {"params": params})  # no lm meta
    with pytest.raises(ValueError, match="n_heads"):
        DecodeEngine.from_checkpoint(root, max_len=MAXLEN)
    eng = DecodeEngine.from_checkpoint(root, n_heads=H, max_len=MAXLEN,
                                       serve_dtype=None)
    assert eng.n_heads == H


def test_from_checkpoint_rejects_non_lm_tree(tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    Checkpointer(root).save(1, {"params": {"w": np.ones((3, 3), np.float32)}})
    with pytest.raises(ValueError, match="not a flagship-LM"):
        DecodeEngine.from_checkpoint(root, n_heads=1)


# ------------------------------------------- bench_report latency rows ----

def _bench_round(path, p95_ms, tokens_per_sec):
    rec = {"n": 1, "cmd": "bench", "rc": 0, "tail": "", "parsed": {
        "metric": "m", "value": 1.0, "detail": {
            "serve_tokens_per_sec": tokens_per_sec,
            "serve_detail": {"latency": {"p50_ms": p95_ms / 2,
                                         "p95_ms": p95_ms,
                                         "mean_ms": p95_ms / 2}},
        }}}
    with open(path, "w") as fh:
        json.dump(rec, fh)


def test_bench_report_flags_latency_growth_lower_is_better(tmp_path):
    """ISSUE 10 satellite: serving-latency rows are tracked LOWER-IS-
    BETTER — growth past the threshold is a regression even when
    throughput held."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bench_report import build_trajectory, load_rounds

    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=10.0,
                 tokens_per_sec=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=20.0,
                 tokens_per_sec=100.0)
    traj = build_trajectory(load_rounds(str(tmp_path)), threshold_pct=10.0)
    rows = {r["metric"]: r for r in traj["table"]}
    assert rows["serve_latency_p95_ms"]["lower_is_better"] is True
    assert rows["serve_latency_p95_ms"]["regression"] is True
    assert rows["serve_latency_p50_ms"]["regression"] is True
    # throughput held → no flag on the rate row
    assert rows["serve_tokens_per_sec"]["regression"] is False
    flagged = {r["metric"] for r in traj["regressions"]}
    assert "serve_latency_p95_ms" in flagged


def test_bench_report_latency_improvement_not_flagged(tmp_path):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.bench_report import build_trajectory, load_rounds

    _bench_round(tmp_path / "BENCH_r01.json", p95_ms=20.0,
                 tokens_per_sec=100.0)
    _bench_round(tmp_path / "BENCH_r02.json", p95_ms=10.0,
                 tokens_per_sec=100.0)
    traj = build_trajectory(load_rounds(str(tmp_path)), threshold_pct=10.0)
    rows = {r["metric"]: r for r in traj["table"]}
    assert rows["serve_latency_p95_ms"]["regression"] is False


# ---------------------------------------------------------- cache shape ----

def test_init_kv_cache_layout(params):
    cache = init_kv_cache(L, 3, H, D // H, MAXLEN)
    assert cache["k"].shape == (L, 3, H, MAXLEN, D // H)
    assert cache["v"].shape == cache["k"].shape
    assert cache["k"].dtype == jnp.float32
    assert not np.any(np.asarray(cache["k"]))  # zero-initialized


# ------------------------------------------- concurrency stress (ISSUE 11) ----

def test_engine_stress_concurrent_clients_under_lockwatch(params, lockwatch):
    """N client threads submit/stream while the background scheduler
    admits/retires, with the runtime lock-order watchdog armed: the
    engine's scheduler lock (and the registry under it) run as watched
    primitives, so a lock-order inversion raises at the acquire instead
    of deadlocking, and the summary proves real cross-thread contention
    was exercised."""
    import threading

    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    engine = DecodeEngine(params, H, n_slots=3, max_len=MAXLEN,
                          serve_dtype=None, registry=MetricsRegistry())
    engine.start()
    n_clients, per_client = 4, 3
    results = {}
    errors = []

    def client(i):
        try:
            out = []
            for j, prompt in enumerate(_prompts(per_client, seed=100 + i)):
                out.append(engine.generate(prompt, max_new_tokens=4,
                                           timeout=120.0))
            results[i] = out
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    try:
        assert not errors, errors
        assert not any(t.is_alive() for t in threads), "stress hung"
        assert sorted(results) == list(range(n_clients))
        for i, outs in results.items():
            assert len(outs) == per_client
            # every request retired with tokens (eos_id=None: full budget)
            assert all(len(tokens) == 4 for tokens in outs), outs
        # greedy parity survives the concurrency: re-run one prompt alone
        prompt = _prompts(1, seed=100)[0]
        want = _oracle_greedy(params, prompt, 4)
        assert engine.generate(prompt, max_new_tokens=4,
                               timeout=120.0) == want
    finally:
        engine.stop()
    watch = lockwatch.summary()
    assert watch["cycles"] == 0 and watch["watchdog_dumps"] == 0
    eng_stats = watch["locks"].get("serve.engine", {})
    assert eng_stats.get("acquires", 0) > n_clients * per_client, (
        "scheduler lock barely exercised", eng_stats)


# -------------------------------------- request-scoped tracing (ISSUE 12) ----

class TestServeTracing:
    """The serve half of the ISSUE 12 tentpole: every request a
    ``serve.request`` span tree, every scheduler iteration an
    ``engine.step`` span, attribution reconstructable by the real
    tools/trace_report.py — and tracing must not perturb decode output
    (greedy parity) nor the steady-state 0-compile budget."""

    @pytest.fixture
    def tracer(self, tmp_path):
        from deeplearning4j_tpu.telemetry import trace as tr

        tracer = tr.Tracer("serve-test", trace_dir=str(tmp_path / "trace"))
        prev = tr.set_tracer(tracer)
        yield tracer
        tr.set_tracer(prev)
        tracer.close()

    def _load(self, tracer):
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.trace_report import load_trace_dir

        return load_trace_dir(os.path.dirname(tracer.path))

    def test_request_span_tree_and_attribution(self, params, tracer):
        from tools.trace_report import serve_attribution

        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None, weight_version="w-test")
        prompts = _prompts(4, seed=11)
        reqs = [eng.submit(p, max_new_tokens=3) for p in prompts]
        eng.run_until_idle()
        assert all(r.done.is_set() for r in reqs)
        spans = self._load(tracer)
        by_name = {}
        for sp in spans.values():
            by_name.setdefault(sp["name"], []).append(sp)
        # one serve.request per submit, all closed, full child set
        assert len(by_name["serve.request"]) == 4
        for req_span in by_name["serve.request"]:
            assert req_span.get("end") is not None
            kids = [sp for sp in spans.values()
                    if sp.get("parent_id") == req_span["span_id"]]
            kid_names = sorted(k["name"] for k in kids)
            assert kid_names == ["serve.decode", "serve.prefill",
                                 "serve.queue_wait", "serve.retire"]
            # per-token accept events ride the decode span
            decode = [k for k in kids if k["name"] == "serve.decode"][0]
            accepts = [e for e in decode["events"] if e["name"] == "accept"]
            assert len(accepts) == 3
            # retire carries reason + weight forensics
            retire = [k for k in kids if k["name"] == "serve.retire"][0]
            assert retire["attrs"]["reason"] == "max_new_tokens"
            assert retire["attrs"]["weight_version"] == "w-test"
        # scheduler iterations traced with occupancy/admission accounting
        steps = by_name["engine.step"]
        assert steps and all(s.get("end") is not None for s in steps)
        assert sum(s["attrs"].get("admissions", 0) for s in steps) == 4
        assert max(s["attrs"].get("occupancy", 0) for s in steps) == 2
        assert sum(s["attrs"].get("retired", 0) for s in steps) == 4
        # the acceptance sum: queue+prefill+decode+gap within 1ms of the
        # engine-measured request latency, for every request
        rows = serve_attribution(spans)
        assert len(rows) == 4
        for row in rows:
            assert row["status"] == "ok"
            total = (row["queue_wait_ms"] + row["prefill_ms"]
                     + row["decode_ms"] + row["gap_ms"])
            assert abs(total - row["total_ms"]) <= 1.0, row
            assert row["tokens"] == 3
            assert row["weight_version"] == "w-test"

    def test_queue_wait_attributed_under_contention(self, params, tracer):
        """1 slot, 3 requests up front: the later requests' queue_wait
        must dominate their prefill (they sat queued through the earlier
        requests' full decode streams)."""
        from tools.trace_report import serve_attribution

        eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                           serve_dtype=None)
        for p in _prompts(3, seed=12):
            eng.submit(p, max_new_tokens=4)
        eng.run_until_idle()
        rows = sorted(serve_attribution(self._load(tracer)),
                      key=lambda r: r["rid"])
        assert rows[0]["queue_wait_ms"] < rows[-1]["queue_wait_ms"]
        assert rows[-1]["queue_wait_ms"] > rows[-1]["prefill_ms"]

    def test_greedy_parity_and_zero_retrace_with_tracer_armed(
            self, params, tracer, retrace_budget):
        """ISSUE 12 acceptance: arming the tracer changes NOTHING about
        the decode math (token-identical to the recompute-per-token
        oracle) and adds NO compiles to the steady-state loop — the
        instrumentation is host-side only."""
        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None)
        eng.generate([1] * 5, max_new_tokens=2)   # warm buckets 8
        eng.generate([1] * 12, max_new_tokens=2)  # and 16
        prompts = _prompts(3, seed=13)
        with retrace_budget(0, label="traced steady-state decode"):
            outs = [eng.generate(p, max_new_tokens=5) for p in prompts]
        for p, got in zip(prompts, outs):
            assert got == _oracle_greedy(params, p, 5), p

    def test_zero_cost_unconfigured(self, params):
        """No tracer ⇒ no span objects anywhere on the request path."""
        from deeplearning4j_tpu.telemetry import trace as tr

        assert tr.get_tracer() is None
        eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                           serve_dtype=None)
        req = eng.submit(_prompts(1, seed=14)[0], max_new_tokens=2)
        eng.run_until_idle()
        assert req.span is None and req.queue_span is None
        assert req.decode_span is None and req.decode_ms == 0.0

    def test_kill9_leaves_open_request_span_reconstructable(self, tmp_path):
        """Acceptance: kill -9 of a serving process leaves open
        ``serve.request`` spans the report reconstructs — the eager
        begin records ARE the forensics, no hook runs."""
        import signal
        import subprocess
        import sys

        from tools.trace_report import load_trace_dir, serve_attribution

        trace_dir = str(tmp_path / "trace")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__),
                          "_serve_trace_child.py"), trace_dir],
            stdout=subprocess.PIPE, text=True)
        try:
            line = proc.stdout.readline()
            assert line.strip() == "READY", line
            proc.send_signal(signal.SIGKILL)
        finally:
            proc.wait(timeout=30)
            proc.stdout.close()
        spans = load_trace_dir(trace_dir)
        rows = serve_attribution(spans)
        assert len(rows) == 1
        row = rows[0]
        assert row["status"] == "open"
        assert row["rid"] == 0
        assert row["process"] == "serve-victim"
        # the open decode child pins that the victim died mid-stream
        open_names = {sp["name"] for sp in spans.values()
                      if sp.get("end") is None}
        assert "serve.request" in open_names
        assert "serve.decode" in open_names

    def test_http_traceparent_end_to_end_tree(self, params, tracer):
        """One trace tree spans loadgen → HTTP server → engine: the HTTP
        loadgen driver emits traceparent, UiServer parents http.request
        under it, and the engine's serve.request tree hangs beneath —
        all sharing the loadgen root's trace id."""
        from deeplearning4j_tpu.serve.loadgen import run_open_loop_http
        from deeplearning4j_tpu.ui import UiServer

        eng = DecodeEngine(params, H, n_slots=2, max_len=MAXLEN,
                           serve_dtype=None)
        eng.start()
        server = UiServer()
        server.attach_engine(eng)
        server.start(port=0)
        try:
            rep = run_open_loop_http(
                f"http://127.0.0.1:{server.port}", _prompts(2, seed=15),
                rate_rps=100.0, max_new_tokens=3)
            assert rep.completed == 2
            assert rep.latency_p99_ms >= rep.latency_p50_ms > 0
        finally:
            server.stop()
            eng.stop()
        spans = self._load(tracer)
        roots = [sp for sp in spans.values()
                 if sp["name"] == "loadgen.request"]
        assert len(roots) == 2
        for root in roots:
            tree = [sp for sp in spans.values()
                    if sp.get("trace_id") == root["trace_id"]]
            names = {sp["name"] for sp in tree}
            # loadgen → http → serve.request → children, ONE trace id
            assert {"loadgen.request", "http.request", "serve.request",
                    "serve.prefill", "serve.decode",
                    "serve.retire"} <= names
            http = [sp for sp in tree if sp["name"] == "http.request"][0]
            assert http["parent_id"] == root["span_id"]
            sreq = [sp for sp in tree if sp["name"] == "serve.request"][0]
            assert sreq["parent_id"] == http["span_id"]


# --------------------------------------- in-flight request ages (ISSUE 12) ----

def test_stats_reports_in_flight_request_ages(params):
    """ISSUE 12 satellite: a stuck request is visible from /api/serve as
    a growing queued_s/running_s instead of only as a hung client."""
    import time as _time

    eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                       serve_dtype=None)
    prompts = _prompts(3, seed=16)
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    _time.sleep(0.02)
    st = eng.stats()
    flight = {f["rid"]: f for f in st["in_flight"]}
    assert sorted(flight) == [r.rid for r in reqs]
    assert all(f["state"] == "queued" for f in flight.values())
    assert all(f["queued_s"] >= 0.02 for f in flight.values())
    assert all(f["tokens"] == 0 for f in flight.values())
    eng.step()  # admit rid 0 into the single slot + first decode
    st = eng.stats()
    flight = {f["rid"]: f for f in st["in_flight"]}
    running = flight[reqs[0].rid]
    assert running["state"] == "running" and running["slot"] == 0
    assert running["tokens"] >= 1
    assert running["running_s"] >= 0.0
    assert running["prompt_len"] == len(prompts[0])
    # the other two still queued, ages still growing
    assert flight[reqs[1].rid]["state"] == "queued"
    eng.run_until_idle()
    assert eng.stats()["in_flight"] == []


def test_stats_and_retire_carry_weight_version(params, tmp_path):
    from deeplearning4j_tpu.models.transformer_lm import lm_checkpoint_meta
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    root = str(tmp_path / "ckpt")
    Checkpointer(root).save(7, {"params": params},
                            meta=lm_checkpoint_meta(params, H))
    eng = DecodeEngine.from_checkpoint(root, max_len=MAXLEN,
                                       serve_dtype=None)
    assert eng.weight_version == "ckpt-step-7"
    assert eng.stats()["weight_version"] == "ckpt-step-7"


def test_engine_metrics_record_flat_keys(params):
    """Every serve_* registry instrument reaches the step-log record the
    telemetry report renders (histograms as _count/_sum, labeled
    counters summed) — the contract the ISSUE 12 meta-test leans on."""
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    reg = MetricsRegistry()
    eng = DecodeEngine(params, H, n_slots=1, max_len=MAXLEN,
                       serve_dtype=None, registry=reg)
    eng.generate(_prompts(1, seed=17)[0], max_new_tokens=2)
    rec = eng.metrics_record()
    assert rec["serve_requests_total"] == 1.0
    assert rec["serve_tokens_total"] == 2.0
    assert rec["serve_completed_total"] == 1.0  # labels summed
    assert rec["serve_request_ms_count"] == 1.0
    assert rec["serve_request_ms_sum"] > 0
    # EVERY serve_* name in the registry surfaces in the record
    snap = reg.snapshot()
    names = {r["name"] for kind in ("counters", "gauges", "histograms")
             for r in snap[kind] if r["name"].startswith("serve_")}
    for name in names:
        assert name in rec or f"{name}_count" in rec, name
