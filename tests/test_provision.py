"""Provisioning tests (ref: aws/ec2/provision/ — Ec2BoxCreator,
HostProvisioner, ClusterSetup). Commands are asserted through a recording
runner; nothing touches a real cloud — except the launch-wiring test, which
drives the emitted env through two real local processes."""

import pytest

from deeplearning4j_tpu.scaleout.provision import (
    ClusterSetup,
    HostProvisioner,
    TpuPodCreator,
    TpuPodSpec,
)


class RecordingRunner:
    def __init__(self, code: int = 0, out: str = "ok"):
        self.calls = []
        self.code = code
        self.out = out

    def __call__(self, argv):
        self.calls.append(list(argv))
        return self.code, self.out


class TestTpuPodCreator:
    def test_create_command(self):
        spec = TpuPodSpec(name="pod1", accelerator_type="v5litepod-8",
                          zone="us-east5-b", project="proj",
                          labels={"team": "ml", "env": "dev"})
        cmd = TpuPodCreator(spec).create_command()
        assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
        assert "pod1" in cmd and "--zone=us-east5-b" in cmd
        assert "--project=proj" in cmd
        assert "--accelerator-type=v5litepod-8" in cmd
        assert "--labels=env=dev,team=ml" in cmd  # sorted, deterministic

    def test_lifecycle_through_runner(self):
        rec = RecordingRunner()
        creator = TpuPodCreator(TpuPodSpec(name="p"), runner=rec)
        creator.create()
        creator.destroy()
        assert rec.calls[0][4] == "create"
        assert rec.calls[1][4] == "delete" and "--quiet" in rec.calls[1]


class TestHostProvisioner:
    def test_run_remote_command(self):
        rec = RecordingRunner()
        HostProvisioner("pod", worker=3, runner=rec).run_remote_command("ls /tmp")
        argv = rec.calls[0]
        assert "ssh" in argv and "--worker=3" in argv
        assert "--command=ls /tmp" in argv

    def test_upload_and_run(self):
        rec = RecordingRunner()
        HostProvisioner("pod", runner=rec).upload_and_run("/local/setup.sh", "/opt")
        assert "scp" in rec.calls[0] and "pod:/opt" in rec.calls[0]
        assert any("bash setup.sh" in a for a in rec.calls[1])

    def test_upload_failure_short_circuits(self):
        rec = RecordingRunner(code=1, out="denied")
        code, _ = HostProvisioner("pod", runner=rec).upload_and_run("s.sh")
        assert code == 1 and len(rec.calls) == 1  # no remote run attempted


class TestClusterSetup:
    def test_exec_provisions_and_launches_every_host(self):
        rec = RecordingRunner()
        spec = TpuPodSpec(name="pod", num_hosts=4)
        setup = ClusterSetup(spec, ["python", "train.py", "--conf", "c.json"],
                             runner=rec)
        results = setup.exec("/local/setup.sh", coordinator_host="10.0.0.2")
        assert len(results) == 8  # 4 provision + 4 launches
        launches = [c for c in rec.calls if any("DL4J_PROCESS_ID" in a for a in c)]
        assert len(launches) == 4
        cmd0 = next(a for a in launches[0] if "DL4J_PROCESS_ID" in a)
        # multihost env wiring matches parallel/multihost.initialize()
        assert "DL4J_COORDINATOR=10.0.0.2:8476" in cmd0
        assert "DL4J_NUM_PROCESSES=4" in cmd0
        assert "python train.py --conf c.json" in cmd0

    def test_distinct_process_ids(self):
        rec = RecordingRunner()
        setup = ClusterSetup(TpuPodSpec(num_hosts=2), ["run"], runner=rec)
        setup.exec("s.sh")
        ids = set()
        for call in rec.calls:
            for a in call:
                if "DL4J_PROCESS_ID=" in a:
                    ids.add(a.split("DL4J_PROCESS_ID=")[1].split()[0])
        assert ids == {"0", "1"}


class TestReviewFixes:
    def test_tilde_root_dir_not_quoted(self):
        rec = RecordingRunner()
        HostProvisioner("pod", runner=rec).upload_and_run("/local/s.sh")  # default ~
        cmd = next(a for a in rec.calls[1] if a.startswith("--command="))
        assert "cd ~ &&" in cmd and "'~'" not in cmd

    def test_exec_aborts_when_provisioning_fails(self):
        import pytest as _pytest

        rec = RecordingRunner(code=1, out="boom")
        setup = ClusterSetup(TpuPodSpec(num_hosts=2), ["run"], runner=rec)
        with _pytest.raises(RuntimeError, match="provisioning failed"):
            setup.exec("s.sh")
        # no launch command was issued
        assert not any("DL4J_PROCESS_ID" in a for c in rec.calls for a in c)


class TestLaunchCommandDrivesRealTraining:
    """The emitted launch wiring is EXECUTED, not just asserted: two local
    processes are started with exactly the env string ClusterSetup emits,
    rendezvous through multihost.initialize(), and run a sync DP train step
    over the 2-process global mesh (ref: ClusterSetup.exec launching
    DistributedDeepLearningTrainer on every provisioned host)."""

    CHILD = r"""
import os, sys
import os as _os
_os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # 0.4.x: the XLA flag above already did it
sys.path.insert(0, os.environ["DL4J_REPO"])
import numpy as np
import jax.numpy as jnp
from deeplearning4j_tpu.parallel import multihost
from deeplearning4j_tpu.parallel.trainer import make_sync_train_step
from deeplearning4j_tpu.nn import functional as F
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from jax.sharding import NamedSharding, PartitionSpec as P

multihost.initialize()   # reads the DL4J_* env the launch command set
pid, n = multihost.process_info()
assert n == 2, n
conf = (NeuralNetConfiguration.Builder()
        .n_in(4).n_out(6).activation_function("tanh").lr(0.1)
        .num_iterations(1).seed(0).list(2)
        .override(1, layer_type="OUTPUT", n_in=6, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True).build())
params = F.init_params(conf, jax.random.PRNGKey(0))
states = F.init_train_state(conf, params)
mesh = multihost.global_mesh(("data",))
x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
y = np.eye(3, dtype=np.float32)[np.arange(8) % 3]
w = np.ones((8,), np.float32)
def place(a, spec):
    a = np.asarray(a)
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])
gp = jax.tree_util.tree_map(lambda a: place(a, P()), params)
gs = jax.tree_util.tree_map(lambda a: place(a, P()), states)
step = make_sync_train_step(conf, mesh)
_, _, score = step(gp, gs, jnp.asarray(0), place(x, P("data")),
                   place(y, P("data")), place(w, P("data")),
                   place(jax.random.PRNGKey(1), P()))
s = float(np.asarray(score.addressable_data(0)))
assert np.isfinite(s), s
print(f"TRAINOK {pid} {s:.6f}", flush=True)
"""

    @pytest.mark.slow
    def test_emitted_env_wiring_trains_across_two_processes(self, tmp_path):
        import os
        import subprocess
        import sys
        script = tmp_path / "train_child.py"
        script.write_text(self.CHILD)

        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        spec = TpuPodSpec(num_hosts=2)
        cs = ClusterSetup(spec, [sys.executable, str(script)],
                          coordinator_port=port)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        procs = []
        for pid in range(2):
            cmd = cs.launch_command(pid, "127.0.0.1")
            # exactly what would run on host `pid` — executed locally
            procs.append(subprocess.Popen(
                ["bash", "-c", cmd],
                env=dict(os.environ, DL4J_REPO=repo, JAX_PLATFORMS="cpu"),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = [p.communicate(timeout=180) for p in procs]
        scores = []
        for pid, (p, (out, err)) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"host {pid} failed:\n{err[-2000:]}"
            line = [ln for ln in out.splitlines()
                    if ln.startswith(f"TRAINOK {pid}")]
            assert line, out
            scores.append(line[0].split()[2])
        # both controllers computed the same global score
        assert scores[0] == scores[1], scores
