"""Kill/resume parity through the sharded checkpoint subsystem.

Each test runs the SAME deterministic training twice: uninterrupted, and
killed mid-training + resumed from the checkpoint (fresh step builders,
fresh templates — nothing survives the 'kill' but the files on disk). The
two trajectories must agree on every post-resume loss and on the final
params to 1e-6 or better; the only delta between the branches is the
checkpoint round-trip, so any divergence is checkpoint infidelity, not
math noise. Covers the acceptance matrix: same-mesh resume, dp×pp save →
dp×sp×ep resume, dp×ep save → single-device resume, plus the trainer
facade and RNG-stream resume (typed AND raw key flavors)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.models.transformer_lm import (
    init_lm_params,
    lm_param_shardings,
    make_composed_train_step,
    make_pp_loss,
    make_pp_stages,
    make_single_device_train_step,
    pp_trained_to_lm_params,
    shard_lm_batch,
    shard_lm_params,
)
from deeplearning4j_tpu.scaleout.ckpt import Checkpointer
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

V, D, H, DFF = 32, 16, 2, 32
B, T = 4, 16
ATOL = 1e-6  # the acceptance bound; the round-trip is byte-exact in practice


def _params(n_experts=4, n_layers=1):
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, n_experts, DFF,
                          n_layers=n_layers)


def _step_data(i, batch=B, seq=T):
    """Deterministic per-step batch: both the uninterrupted and the resumed
    run regenerate the identical stream from the step index alone."""
    k = jax.random.fold_in(jax.random.PRNGKey(7), i)
    toks = jax.random.randint(k, (batch, seq + 1), 0, V)
    return toks[:, :-1], toks[:, 1:]


def _ck(tmp_path):
    return Checkpointer(str(tmp_path), keep_last=3,
                        registry=MetricsRegistry())


def _assert_close(a, b, what, atol=ATOL):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree_util.tree_leaves_with_path(b)
    assert len(fa) == len(fb)
    for (pa, la), (_, lb) in zip(fa, fb):
        err = float(jnp.max(jnp.abs(jnp.asarray(la, jnp.float32)
                                    - jnp.asarray(lb, jnp.float32))))
        assert err <= atol, f"{what}: {jax.tree_util.keystr(pa)} diff {err}"


def _dp_ep_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "expert"))


def _dp_sp_ep_mesh(e=2):
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "sp", "expert"))


def test_same_mesh_kill_resume_parity(tmp_path):
    """dp2×ep4 composed-LM run checkpointed at step 3, killed, resumed on
    the SAME mesh: steps 4-6 losses and final params match the
    uninterrupted run to 1e-6."""
    mesh = _dp_ep_mesh()
    capacity = (B // 2) * T

    def run(params, start, n, step_fn, losses):
        for i in range(start, start + n):
            tk, tg = shard_lm_batch(*_step_data(i), mesh)
            params, loss = step_fn(params, tk, tg)
            jax.block_until_ready(loss)
            losses.append(float(loss))
        return params

    # uninterrupted: 6 steps
    step = make_composed_train_step(mesh, H, capacity)
    ref_losses = []
    ref = run(shard_lm_params(_params(), mesh), 0, 6, step, ref_losses)

    # interrupted twin: 3 steps, save, KILL (drop everything), resume
    ck = _ck(tmp_path)
    mid_losses = []
    mid = run(shard_lm_params(_params(), mesh), 0, 3, step, mid_losses)
    ck.save(3, {"params": mid}, meta={"note": "mid-training"}, mesh=mesh)
    del mid

    template = {"params": _params()}  # fresh template; values irrelevant
    shardings = {"params": lm_param_shardings(template["params"], mesh)}
    state, resumed_step, meta = ck.restore(template, shardings)
    assert resumed_step == 3 and meta["note"] == "mid-training"
    step2 = make_composed_train_step(mesh, H, capacity)  # fresh builder
    res_losses = []
    resumed = run(state["params"], 3, 3, step2, res_losses)

    np.testing.assert_allclose(res_losses, ref_losses[3:], atol=ATOL, rtol=0)
    _assert_close(resumed, ref, "same-mesh resume params")


def test_dp_pp_save_resumes_on_dp_sp_ep(tmp_path):
    """dp2×pp2 training for 3 steps → canonical-params checkpoint → killed
    → resumed onto a dp2×sp2×ep2 mesh and trained 3 more composed steps.
    The uninterrupted twin does the identical mesh hand-off in memory, so
    the only difference is the checkpoint round-trip."""
    n_layers, n_stages = 2, 2
    n_experts = 2
    mesh_pp = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                   ("data", "pipe"))
    params = _params(n_experts=n_experts, n_layers=n_layers)
    per_stage, stage_fn = make_pp_stages(params, H, n_stages=n_stages)
    from deeplearning4j_tpu.parallel.pipeline import (
        shard_stage_params,
        stack_stage_params,
    )

    stacked = shard_stage_params(stack_stage_params(per_stage), mesh_pp,
                                 "pipe")
    pipe_loss = make_pp_loss(stage_fn, mesh_pp, "pipe", batch_axis="data")
    pipe_vg = jax.jit(jax.value_and_grad(pipe_loss))
    lr = 0.1
    n_micro, mb = 4, 2

    trained = (stacked, params["embed"], params["dec_w"], params["dec_b"])
    for i in range(3):
        tk, tg = _step_data(i, batch=n_micro * mb)
        tk = tk.reshape(n_micro, mb, T)
        tg = tg.reshape(n_micro, mb, T)
        loss, grads = pipe_vg(trained, tk, tg)
        trained = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                         trained, grads)
        jax.block_until_ready(loss)

    # checkpoint boundary: persist the CANONICAL layout, not the staging
    canonical = pp_trained_to_lm_params(trained)
    ck = _ck(tmp_path)
    ck.save(3, {"params": canonical}, mesh=mesh_pp)

    # continuation config shared by both branches
    mesh_sp = _dp_sp_ep_mesh()
    capacity = (8 // 2) * (T // 2)

    def continue_composed(start_params, step_fn):
        p, losses = start_params, []
        for i in range(3, 6):
            tk, tg = shard_lm_batch(*_step_data(i, batch=8), mesh_sp)
            p, loss = step_fn(p, tk, tg)
            jax.block_until_ready(loss)
            losses.append(float(loss))
        return p, losses

    # uninterrupted twin: same hand-off, no disk
    step_a = make_composed_train_step(mesh_sp, H, capacity)
    ref, ref_losses = continue_composed(
        shard_lm_params(canonical, mesh_sp), step_a)

    # resumed branch: fresh template, restore resharded onto the new mesh
    template = {"params": _params(n_experts=n_experts, n_layers=n_layers)}
    shardings = {"params": lm_param_shardings(template["params"], mesh_sp)}
    state, step_no, _ = ck.restore(template, shardings)
    assert step_no == 3
    w1 = state["params"]["blocks"]["experts"]["w1"]
    assert w1.sharding.mesh.axis_names == ("data", "sp", "expert")
    step_b = make_composed_train_step(mesh_sp, H, capacity)
    resumed, res_losses = continue_composed(state["params"], step_b)

    np.testing.assert_allclose(res_losses, ref_losses, atol=ATOL, rtol=0)
    _assert_close(resumed, ref, "dp×pp → dp×sp×ep resume params")


def test_dp_ep_save_resumes_on_single_device(tmp_path):
    """dp2×ep4 composed training checkpointed at step 3, resumed UNSHARDED
    on a single device (dense step). The twin hands the same params over
    in memory; post-resume trajectories must match to 1e-6."""
    mesh = _dp_ep_mesh()
    capacity = (B // 2) * T
    step = make_composed_train_step(mesh, H, capacity)
    p = shard_lm_params(_params(), mesh)
    for i in range(3):
        tk, tg = shard_lm_batch(*_step_data(i), mesh)
        p, loss = step(p, tk, tg)
        jax.block_until_ready(loss)
    ck = _ck(tmp_path)
    ck.save(3, {"params": p}, mesh=mesh)

    def continue_single(start_params, step_fn):
        q, losses = start_params, []
        for i in range(3, 6):
            tk, tg = _step_data(i)
            q, loss = step_fn(q, tk, tg)
            losses.append(float(loss))
        return q, losses

    sd_step = make_single_device_train_step(H)
    ref, ref_losses = continue_single(
        jax.tree_util.tree_map(jnp.asarray, jax.device_get(p)), sd_step)

    template = {"params": _params()}
    state, _, _ = ck.restore(template, shardings=None)  # unsharded restore
    sd_step2 = make_single_device_train_step(H)
    resumed, res_losses = continue_single(state["params"], sd_step2)

    np.testing.assert_allclose(res_losses, ref_losses, atol=ATOL, rtol=0)
    _assert_close(resumed, ref, "dp×ep → single-device resume params")


def test_grouped_expert_cross_g_resume(tmp_path):
    """Grouped-expert resharding chain: a dp2×ep2 run (n_experts=8, G=4,
    all_to_all dispatch) checkpoints at step 3; the save's 4-expert-wide
    chunks are SPLIT onto a dp1×ep8 mesh (G=1, per-expert shards), trained
    3 more steps, saved again; those 1-expert chunks are MERGED back into
    an unsharded single-device restore for the final 3 dense steps. The
    uninterrupted twin does the identical mesh hand-offs in memory, so any
    divergence is checkpoint/reshard infidelity — the global (L, E, ...)
    expert layout is G-invariant and restores land where a fresh init
    would (lm_param_shardings)."""
    n_experts, n_layers = 8, 2
    mesh_g4 = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                   ("data", "expert"))
    mesh_g1 = Mesh(np.array(jax.devices()[:8]).reshape(1, 8),
                   ("data", "expert"))

    def composed_run(params, mesh, capacity, start, n, losses):
        step = make_composed_train_step(mesh, H, capacity,
                                        moe_impl="alltoall")
        for i in range(start, start + n):
            tk, tg = shard_lm_batch(*_step_data(i), mesh)
            params, loss = step(params, tk, tg)
            jax.block_until_ready(loss)
            losses.append(float(loss))
        return params

    def dense_run(params, start, n, losses):
        step = make_single_device_train_step(H)
        for i in range(start, start + n):
            tk, tg = _step_data(i)
            params, loss = step(params, tk, tg)
            losses.append(float(loss))
        return params

    cap_g4 = (B // 2) * T   # ample per token row on dp2
    cap_g1 = B * T          # ample on the single dp row

    def fresh():
        return _params(n_experts=n_experts, n_layers=n_layers)

    # uninterrupted twin: same hand-offs, no disk
    ref_losses = []
    p = composed_run(shard_lm_params(fresh(), mesh_g4), mesh_g4, cap_g4,
                     0, 3, ref_losses)
    p = composed_run(shard_lm_params(
        jax.tree_util.tree_map(jnp.asarray, jax.device_get(p)), mesh_g1),
        mesh_g1, cap_g1, 3, 3, ref_losses)
    ref = dense_run(jax.tree_util.tree_map(jnp.asarray, jax.device_get(p)),
                    6, 3, ref_losses)

    # checkpointed chain: G=4 save → G=1 restore+save → unsharded restore
    ck = _ck(tmp_path)
    res_losses = []
    q = composed_run(shard_lm_params(fresh(), mesh_g4), mesh_g4, cap_g4,
                     0, 3, res_losses)
    ck.save(3, {"params": q}, mesh=mesh_g4)
    del q

    template = {"params": fresh()}
    shardings = {"params": lm_param_shardings(template["params"], mesh_g1)}
    state, step_no, _ = ck.restore(template, shardings)
    assert step_no == 3
    w1 = state["params"]["blocks"]["experts"]["w1"]
    assert w1.shape == (n_layers, n_experts, D, DFF)
    # per-expert shards on the G=1 mesh (the split half of the round trip)
    starts = {tuple(sl.indices(n_experts)[0] for sl in s.index[1:2])
              for s in w1.addressable_shards}
    assert len(starts) == 8 and w1.addressable_shards[0].data.shape[1] == 1
    q = composed_run(state["params"], mesh_g1, cap_g1, 3, 3, res_losses)
    ck.save(6, {"params": q}, mesh=mesh_g1)
    del q

    state, step_no, _ = ck.restore({"params": fresh()}, shardings=None)
    assert step_no == 6
    resumed = dense_run(state["params"], 6, 3, res_losses)

    np.testing.assert_allclose(res_losses, ref_losses, atol=ATOL, rtol=0)
    _assert_close(resumed, ref, "G=4 → G=1 → single-device resume params")


# ------------------------------------------- optimizer-state resume ----

def test_adam_sharded_kill_resume_with_moments(tmp_path):
    """ISSUE 13 acceptance: a dp2×ep4 Adam run with the ZeRO-sharded
    update checkpointed at step 3 (params + CANONICAL moment trees via
    ``updaters.canonical_opt_state``), killed, and resumed twice — (a)
    same mesh, moments re-partitioned into the ZeRO layout, and (b)
    CROSS-MESH onto a single device with the replicated update (the
    moment trees reshard exactly like their params) — must match the
    uninterrupted run's losses and final params ≤1e-6. An Adam resume
    that dropped or zeroed the moments visibly diverges (the bias
    correction restarts), so this parity is what makes optimizer
    checkpoints real."""
    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_opt_state,
        lm_update_sharding,
    )
    from deeplearning4j_tpu.optimize.updaters import (
        OptimizerConfig,
        canonical_opt_state,
        init_opt_state,
        opt_state_shardings,
        partition_opt_state,
    )

    mesh = _dp_ep_mesh()
    capacity = (B // 2) * T
    cfg = OptimizerConfig(name="adam", lr=1e-3, update_sharding="sharded")
    zero = lm_update_sharding(mesh)

    def run(params, opt_state, step_fn, start, n, losses):
        for i in range(start, start + n):
            tk, tg = shard_lm_batch(*_step_data(i), mesh)
            params, opt_state, loss = step_fn(params, opt_state, tk, tg)
            jax.block_until_ready(loss)
            losses.append(float(loss))
        return params, opt_state

    # uninterrupted: 6 sharded-update steps
    step = make_composed_train_step(mesh, H, capacity, optimizer=cfg)
    ref_losses = []
    rp = shard_lm_params(_params(), mesh)
    rp, rst = run(rp, init_lm_opt_state(cfg, rp, mesh), step, 0, 6,
                  ref_losses)

    # interrupted twin: 3 steps, save params + canonical moments, KILL
    ck = _ck(tmp_path)
    mid_losses = []
    mp = shard_lm_params(_params(), mesh)
    mp, mst = run(mp, init_lm_opt_state(cfg, mp, mesh), step, 0, 3,
                  mid_losses)
    ck.save(3, {"params": mp, "opt": canonical_opt_state(mst, mp, zero)},
            mesh=mesh)
    del mp, mst

    # (a) same-mesh resume: fresh builders/templates, moments
    # re-partitioned into the ZeRO layout
    template = {"params": _params()}
    template["opt"] = canonical_opt_state(
        init_opt_state(OptimizerConfig(name="adam"), template["params"]),
        template["params"], None)
    psh = lm_param_shardings(template["params"], mesh)
    shardings = {"params": psh, "opt": opt_state_shardings(psh)}
    state, resumed_step, _ = ck.restore(template, shardings)
    assert resumed_step == 3
    step2 = make_composed_train_step(mesh, H, capacity, optimizer=cfg)
    res_losses = []
    ap, ast = run(state["params"], partition_opt_state(state["opt"], zero),
                  step2, 3, 3, res_losses)
    np.testing.assert_allclose(mid_losses + res_losses, ref_losses,
                               atol=ATOL, rtol=0)
    _assert_close(ap, rp, "adam same-mesh resume params")
    can_a = canonical_opt_state(ast, ap, zero)
    can_r = canonical_opt_state(rst, rp, zero)
    _assert_close(can_a["m"], can_r["m"], "adam resumed first moments")
    _assert_close(can_a["v"], can_r["v"], "adam resumed second moments")
    assert int(can_a["count"]) == int(can_r["count"]) == 6

    # (b) cross-mesh: unsharded single-device resume, replicated update —
    # identical math, so the trajectory must still track the dp×ep run
    state2, got2, _ = ck.restore(
        {"params": _params(), "opt": template["opt"]}, shardings=None)
    assert got2 == 3
    rep = OptimizerConfig(name="adam", lr=1e-3)
    sd = make_single_device_train_step(H, optimizer=rep)
    sp = jax.tree_util.tree_map(jnp.asarray, state2["params"])
    sst = jax.tree_util.tree_map(jnp.asarray, state2["opt"])
    sd_losses = []
    for i in range(3, 6):
        tk, tg = _step_data(i)
        sp, sst, loss = sd(sp, sst, tk, tg)
        sd_losses.append(float(loss))
    np.testing.assert_allclose(sd_losses, ref_losses[3:], atol=ATOL,
                               rtol=0)
    _assert_close(sp, jax.device_get(rp), "adam cross-mesh resume params")


# ------------------------------------------------------- trainer facade ----

def _mlp_conf(num_iterations=1, dropout=0.0, seed=11):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

    builder = (NeuralNetConfiguration.Builder()
               .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
               .num_iterations(num_iterations).seed(seed).weight_init("VI"))
    if dropout:
        builder = builder.dropout(dropout)
    return (builder.list(2)
            .override(0, layer_type="DENSE")
            .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build())


def _iris_batches():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

    rng = np.random.RandomState(0)
    x = rng.rand(32, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    return ListDataSetIterator(
        [DataSet(x[i:i + 8], y[i:i + 8]) for i in range(0, 32, 8)])


def test_parameter_averaging_trainer_kill_resume(tmp_path):
    """The DP trainer facade: checkpoint through the listener chain every
    4 sync iterations, kill, resume into a FRESH net+trainer, finish the
    second pass — params match the uninterrupted twin to 1e-6 (updater
    state, iteration counter, and the host RNG stream all resumed)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    from deeplearning4j_tpu.parallel.trainer import ParameterAveragingTrainer

    mesh = data_parallel_mesh(4)

    # uninterrupted: two passes over the data
    net_a = MultiLayerNetwork(_mlp_conf()).init()
    tr_a = ParameterAveragingTrainer(net_a, mesh,
                                     average_each_iteration=True)
    tr_a.fit_data_set(_iris_batches())
    tr_a.fit_data_set(_iris_batches())

    # interrupted: first pass with periodic checkpoints (4 batches → one
    # save at iteration 4 through the listener chain), then KILL
    ck = _ck(tmp_path)
    net_b = MultiLayerNetwork(_mlp_conf()).init()
    tr_b = ParameterAveragingTrainer(net_b, mesh,
                                     average_each_iteration=True,
                                     checkpointer=ck, checkpoint_every=4)
    tr_b.fit_data_set(_iris_batches())
    assert ck.latest_step() == 4
    del net_b, tr_b

    # resume in a fresh process-equivalent: new net, new trainer
    net_c = MultiLayerNetwork(_mlp_conf()).init()
    tr_c = ParameterAveragingTrainer(net_c, mesh,
                                     average_each_iteration=True)
    resumed_step = tr_c.resume(ck)
    assert resumed_step == 4 and tr_c._iteration == 4
    tr_c.fit_data_set(_iris_batches())

    np.testing.assert_allclose(np.asarray(net_a.params()),
                               np.asarray(net_c.params()), atol=ATOL)


def test_trainer_resume_without_checkpoint_is_noop(tmp_path):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    from deeplearning4j_tpu.parallel.trainer import ParameterAveragingTrainer

    net = MultiLayerNetwork(_mlp_conf()).init()
    tr = ParameterAveragingTrainer(net, data_parallel_mesh(2))
    assert tr.resume(_ck(tmp_path)) is None
    assert tr._iteration == 0


# ------------------------------------------------- RNG-stream resume ----

@pytest.mark.parametrize("flavor", ["raw", "typed"])
def test_rng_stream_resume_through_subsystem(tmp_path, flavor):
    """A dropout conf saved at step k through the NEW subsystem and
    resumed must produce the same step-k+1..n losses as an uninterrupted
    run — the host RNG stream position round-trips for BOTH key flavors
    (raw uint32 and typed PRNG key arrays)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize.listeners import CollectScoresListener
    from deeplearning4j_tpu.scaleout.ckpt import CheckpointIterationListener

    conf = _mlp_conf(num_iterations=5, dropout=0.3)
    rng = np.random.RandomState(0)
    x = rng.rand(16, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]

    def make_net():
        net = MultiLayerNetwork(conf).init()
        if flavor == "typed":
            net._keys._key = jax.random.key(conf.conf(0).seed)
        return net

    # uninterrupted: 10 iterations, record the step 6..10 losses
    net_a = make_net()
    scores_a = CollectScoresListener()
    net_a.listeners.append(scores_a)
    net_a.fit(x, y)
    net_a.fit(x, y)

    # interrupted: save at iteration 5 through the listener chain, kill,
    # rebuild the net from the checkpoint alone, run 5 more
    ck = _ck(tmp_path)
    net_b = make_net()
    net_b.listeners.append(CheckpointIterationListener(ck, save_every=5))
    net_b.fit(x, y)
    assert ck.latest_step() == 5
    del net_b

    net_c, it = ck.restore_net()
    assert it == 5
    if flavor == "typed":
        assert jax.dtypes.issubdtype(net_c._keys._key.dtype,
                                     jax.dtypes.prng_key), (
            "typed key flavor must survive the round-trip")
    scores_c = CollectScoresListener()
    net_c.listeners.append(scores_c)
    net_c.fit(x, y)

    tail_a = [s for i, s in scores_a.scores if i > 5]
    tail_c = [s for _i, s in scores_c.scores]
    np.testing.assert_allclose(tail_c, tail_a, atol=ATOL, rtol=0)
    np.testing.assert_allclose(np.asarray(net_a.params()),
                               np.asarray(net_c.params()), atol=ATOL)


# ------------------------------------------- crash mid-manifest-merge ----

def test_master_crash_mid_manifest_merge_resumes_prior_step(tmp_path):
    """Acceptance (c): a multi-host save killed between the per-host shard
    writes and the coordinator's manifest commit leaves NO committed
    manifest — ``latest_step()`` still answers the previous step, the
    restore from it is byte-clean, and the interrupted directory (shards +
    part manifests) is swept once a newer step commits."""
    import os

    from deeplearning4j_tpu.scaleout.ckpt import save_process_shards
    from deeplearning4j_tpu.scaleout.ckpt.manifest import (
        list_part_manifests,
        step_dir_name,
    )

    mesh = _dp_ep_mesh()
    capacity = (B // 2) * T
    step = make_composed_train_step(mesh, H, capacity)
    p = shard_lm_params(_params(), mesh)
    for i in range(3):
        tk, tg = shard_lm_batch(*_step_data(i), mesh)
        p, loss = step(p, tk, tg)
        jax.block_until_ready(loss)
    ck = _ck(tmp_path)
    ck.save(3, {"params": p}, mesh=mesh)
    p3 = jax.tree_util.tree_map(np.asarray, jax.device_get(p))

    # step 4's save: every host wrote its shards + part manifest, but the
    # coordinator CRASHED before merge_save — no MANIFEST.json ever lands
    tk, tg = shard_lm_batch(*_step_data(3), mesh)
    p, _ = step(p, tk, tg)
    interrupted = save_process_shards(str(tmp_path), 4, {"params": p},
                                      process_index=0)
    assert list_part_manifests(interrupted), "parts should exist"
    # (no merge happens — the simulated crash point)

    assert ck.latest_step() == 3  # the interrupted save is invisible
    template = {"params": _params()}
    shardings = {"params": lm_param_shardings(template["params"], mesh)}
    state, resumed_step, _ = ck.restore(template, shardings)
    assert resumed_step == 3
    _assert_close(state["params"], p3, "resume skips the interrupted save",
                  atol=0.0)

    # a later committed save supersedes and sweeps the debris
    ck.save(5, {"params": p}, mesh=mesh)
    assert not os.path.isdir(os.path.join(str(tmp_path), step_dir_name(4)))
    assert ck.latest_step() == 5


# ------------------------------------------------- last_good retention ----

def test_gc_never_collects_last_good_step(tmp_path):
    """ISSUE 8 satellite: the step the divergence watchdog tagged
    ``last_good`` survives ANY amount of retention pressure (extends the
    PR 6 retention-race pin) — and a rollback restore from it is
    byte-clean even after keep_last would have collected it, including
    from a FRESH Checkpointer (the tag is a marker file, not memory)."""
    ck = Checkpointer(str(tmp_path), keep_last=2,
                      registry=MetricsRegistry())
    step = make_single_device_train_step(H, attn_impl="dense")
    p = _params()
    snapshots = {}
    for i in range(1, 7):
        tk, tg = _step_data(i)
        p, loss = step(p, tk, tg)
        jax.block_until_ready(loss)
        ck.save(i, {"params": p})
        snapshots[i] = jax.tree_util.tree_map(np.asarray,
                                              jax.device_get(p))
        if i == 2:
            ck.mark_last_good(2)  # the watchdog's note_checkpoint path
    kept = [s for s, _ in ck.step_dirs()]
    # keep_last=2 keeps {5, 6}; step 2 SURVIVES because it is last_good
    assert kept == [2, 5, 6], kept
    assert ck.last_good_step() == 2
    # rollback-grade restore of the pinned step, via a FRESH reader
    ck2 = Checkpointer(str(tmp_path), keep_last=2,
                       registry=MetricsRegistry())
    assert ck2.last_good_step() == 2
    state, got, _meta = ck2.restore({"params": _params()},
                                    step=ck2.last_good_step())
    assert got == 2
    _assert_close(state["params"], snapshots[2], "last_good restore",
                  atol=0.0)
    # moving the tag releases the old pin on the next sweep (a fresh
    # reader: ck2's restore also reader-pinned step 2 — the PR 6 race pin)
    ck2.mark_last_good(6)
    ck3 = Checkpointer(str(tmp_path), keep_last=2,
                       registry=MetricsRegistry())
    ck3.gc()
    assert [s for s, _ in ck3.step_dirs()] == [5, 6]
