"""Tier-1 repo gate: graftlint over the whole package must report ZERO
findings outside the checked-in baseline, the baseline must be fully
justified and non-stale, and the standalone CLI must agree."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.lint_gate import (  # noqa: E402
    BASELINE_PATH,
    DEFAULT_TARGETS,
    REPO_ROOT,
    run_gate,
)
from tools.graftlint import load_baseline  # noqa: E402


def test_repo_zero_nonbaselined_findings():
    fresh, stale, all_findings = run_gate()
    msg = "\n\n".join(f.render() for f in fresh)
    assert not fresh, (
        f"graftlint found {len(fresh)} non-baselined finding(s) — fix them "
        f"or add a justified baseline/inline allow:\n\n{msg}")
    # the gate is doing real work, not matching an empty tree
    assert len(all_findings) > 0, "baselined findings should exist"


def test_baseline_has_no_stale_entries():
    _fresh, stale, _all = run_gate()
    assert not stale, (
        "stale baseline entries (the code they matched was fixed) — run "
        f"`python tools/lint_gate.py --update-baseline` to prune: {stale}")


def test_baseline_entries_all_justified():
    entries = load_baseline(BASELINE_PATH)
    assert entries, "expected a non-empty baseline"
    for e in entries:
        assert e["why"].strip(), f"baseline entry without why: {e}"
        assert not e["why"].startswith("FIXME"), (
            f"unjustified baseline entry (placeholder why): {e}")


def test_default_targets_cover_the_package():
    assert "deeplearning4j_tpu" in DEFAULT_TARGETS
    assert "bench.py" in DEFAULT_TARGETS
    assert "scaling_bench.py" in DEFAULT_TARGETS


def test_cli_json_gate_is_clean():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
         "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert payload["stale_baseline_entries"] == []
    assert payload["total_findings_including_baselined"] > 0


def test_cli_detects_a_planted_finding(tmp_path):
    bad = tmp_path / "planted.py"
    bad.write_text(
        "import jax\n\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x.sum())\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
         "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 1
    assert "jit-host-sync" in out.stdout


def test_cli_rule_filter_runs_one_rule(tmp_path):
    """ISSUE 11 triage mode: --rule restricts the run to one rule and
    does not report stale entries for the rules it skipped."""
    bad = tmp_path / "planted.py"
    bad.write_text(
        "import threading\n\n"
        "class S:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n\n"
        "    def _run(self):\n"
        "        x = float(1)\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
         "--rule", "unjoined-thread", "--no-baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 1
    assert "unjoined-thread" in out.stdout
    # the repo gate restricted to one rule is clean AND quiet about the
    # other rules' baseline entries (no stale noise in triage mode)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
         "--rule", "unjoined-thread", "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert payload["stale_baseline_entries"] == []


def test_cli_rule_filter_rejects_unknown_rule():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
         "--rule", "not-a-rule"],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 2
    assert "unknown rule" in out.stderr


def test_concurrency_rules_are_registered():
    """The five ISSUE 11 rules ride the same registry/gate as the JAX
    rules — DEFAULT_TARGETS sweeps them over the whole repo in tier-1."""
    from tools.graftlint import RULES

    for rule in ("unguarded-shared-state", "lock-order",
                 "blocking-under-lock", "unjoined-thread",
                 "condition-wait-no-predicate"):
        assert rule in RULES, rule


def test_net_rules_are_registered():
    """The five ISSUE 18 net/RPC rules ride the same registry/gate."""
    from tools.graftlint import RULES

    for rule in ("socket-no-timeout", "unbounded-retry",
                 "retry-no-backoff", "swallowed-thread-exception",
                 "nonidempotent-retry"):
        assert rule in RULES, rule


# ----------------------------------------- baseline hygiene (ISSUE 18) ----

_PLANTED = ("import jax\n\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return float(x.sum())\n")


def test_update_baseline_prunes_dead_entries(tmp_path):
    """An entry whose file is gone or whose rule was unregistered can
    never match again — --update-baseline drops it and says so."""
    bad = tmp_path / "planted.py"
    bad.write_text(_PLANTED)
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "jit-host-sync",
         "path": "deeplearning4j_tpu/definitely_gone.py",
         "snippet": "float(", "why": "covered code that was deleted"},
        {"rule": "retired-rule-id", "path": "bench.py",
         "snippet": "anything", "why": "covered a rule since removed"},
    ]}))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py"),
         "--baseline", str(baseline), "--update-baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "no longer exists" in out.stdout
    assert "no longer registered" in out.stdout
    assert "2 dead entr(ies) pruned" in out.stdout
    entries = json.loads(baseline.read_text())["entries"]
    assert all(e["rule"] != "retired-rule-id" for e in entries)
    assert all("definitely_gone" not in e["path"] for e in entries)
    # the planted finding got a seeded FIXME entry in the same pass
    assert any(e["why"].startswith("FIXME") for e in entries)


def test_json_reports_per_finding_baseline_status(tmp_path):
    """--json pins the CI contract: rule/path/line/message per finding,
    baselined findings separated with their why, and the exit code
    mirrored in the payload."""
    bad = tmp_path / "planted.py"
    bad.write_text(_PLANTED)
    cli = [sys.executable, os.path.join(REPO_ROOT, "tools", "lint_gate.py")]
    out = subprocess.run(
        cli + ["--json", "--baseline", str(tmp_path / "absent.json"),
               str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert payload["exit_code"] == 1
    hits = [f for f in payload["findings"]
            if f["rule"] == "jit-host-sync"]
    assert hits, payload["findings"]
    f = hits[0]
    assert f["path"].endswith("planted.py")
    assert isinstance(f["line"], int) and f["line"] > 0
    assert f["message"]
    assert payload["baselined_findings"] == []
    # baselined: same finding flips lists, carries its why, gate passes
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"version": 1, "entries": [
        {"rule": "jit-host-sync", "path": f["path"],
         "snippet": "float(", "why": "pinned for the test"}]}))
    out = subprocess.run(
        cli + ["--json", "--baseline", str(baseline), str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["exit_code"] == 0
    assert payload["findings"] == []
    assert [b["baseline_why"] for b in payload["baselined_findings"]
            if b["rule"] == "jit-host-sync"] == ["pinned for the test"]
