"""Record reader tests (ref: RecordReaderDataSetiteratorTest,
CSVDataSetIteratorTest, svmLight fixtures)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    ImageRecordReader,
    ListStringRecordReader,
    RecordReaderDataSetIterator,
    SVMLightRecordReader,
    load_image,
    read_pnm,
)


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("5.1,3.5,1.4,0.2,0\n"
                 "4.9,3.0,1.4,0.2,0\n"
                 "6.3,3.3,6.0,2.5,2\n"
                 "5.8,2.7,5.1,1.9,2\n"
                 "7.0,3.2,4.7,1.4,1\n")
    return str(p)


class TestCSV:
    def test_reads_all_rows(self, csv_file):
        rows = list(CSVRecordReader(csv_file))
        assert len(rows) == 5
        assert rows[0] == [5.1, 3.5, 1.4, 0.2, 0.0]

    def test_skip_lines(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("a,b\n1,2\n3,4\n")
        rows = list(CSVRecordReader(str(p), skip_lines=1))
        assert rows == [[1.0, 2.0], [3.0, 4.0]]

    def test_iterator_one_hot(self, csv_file):
        it = RecordReaderDataSetIterator(
            CSVRecordReader(csv_file), batch_size=2, num_possible_labels=3
        )
        batches = list(it)
        assert [b.num_examples() for b in batches] == [2, 2, 1]
        assert batches[0].features.shape == (2, 4)
        assert batches[0].labels.shape == (2, 3)
        assert batches[0].labels[0].tolist() == [1.0, 0.0, 0.0]
        assert batches[2].labels[0].tolist() == [0.0, 1.0, 0.0]

    def test_iterator_reset(self, csv_file):
        it = RecordReaderDataSetIterator(CSVRecordReader(csv_file), 5,
                                         num_possible_labels=3)
        a = it.next()
        it.reset()
        b = it.next()
        np.testing.assert_array_equal(a.features, b.features)

    def test_has_next_idempotent(self, csv_file):
        it = RecordReaderDataSetIterator(CSVRecordReader(csv_file), 2,
                                         num_possible_labels=3)
        it.reset()
        assert it.has_next() and it.has_next() and it.has_next()
        total = sum(b.num_examples() for b in iter(it))
        assert total == 5

    def test_regression_labels(self, csv_file):
        it = RecordReaderDataSetIterator(CSVRecordReader(csv_file), 5)
        ds = it.next()
        assert ds.labels.shape == (5, 1)
        assert ds.labels[2, 0] == 2.0

    def test_label_index_first_column(self, tmp_path):
        p = tmp_path / "lf.csv"
        p.write_text("1,10,20\n0,30,40\n")
        it = RecordReaderDataSetIterator(CSVRecordReader(str(p)), 2,
                                         label_index=0, num_possible_labels=2)
        ds = it.next()
        assert ds.features.tolist() == [[10.0, 20.0], [30.0, 40.0]]
        assert ds.labels.tolist() == [[0.0, 1.0], [1.0, 0.0]]


class TestSVMLight:
    def test_sparse_parse(self, tmp_path):
        p = tmp_path / "d.svm"
        p.write_text("1 1:0.5 3:2.0\n0 2:1.0 # comment\n")
        rows = list(SVMLightRecordReader(str(p), num_features=3))
        assert rows[0] == [0.5, 0.0, 2.0, 1.0]
        assert rows[1] == [0.0, 1.0, 0.0, 0.0]

    def test_through_iterator(self, tmp_path):
        p = tmp_path / "d.svm"
        p.write_text("1 1:1.0\n0 2:1.0\n1 1:2.0\n")
        it = RecordReaderDataSetIterator(
            SVMLightRecordReader(str(p), 2), 3, num_possible_labels=2
        )
        ds = it.next()
        assert ds.features.shape == (3, 2)
        assert ds.labels.argmax(1).tolist() == [1, 0, 1]


class TestImages:
    def test_pgm_binary_round(self, tmp_path):
        img = (np.arange(12, dtype=np.uint8).reshape(3, 4) * 20)
        p = tmp_path / "img.pgm"
        with open(p, "wb") as f:
            f.write(b"P5\n# comment\n4 3\n255\n")
            f.write(img.tobytes())
        arr = read_pnm(str(p))
        assert arr.shape == (3, 4)
        np.testing.assert_allclose(arr, img / 255.0, atol=1e-6)

    def test_ppm_ascii(self, tmp_path):
        p = tmp_path / "img.ppm"
        p.write_text("P3\n2 1\n255\n255 0 0  0 255 0\n")
        arr = read_pnm(str(p))
        assert arr.shape == (1, 2, 3)
        assert arr[0, 0].tolist() == [1.0, 0.0, 0.0]

    def test_npy(self, tmp_path):
        a = np.random.rand(5, 5).astype(np.float32)
        p = tmp_path / "a.npy"
        np.save(p, a)
        np.testing.assert_array_equal(load_image(str(p)), a)

    def test_unsupported_format(self, tmp_path):
        p = tmp_path / "img.png"
        p.write_bytes(b"\x89PNG")
        with pytest.raises(ValueError):
            load_image(str(p))

    def test_image_record_reader_directory_tree(self, tmp_path):
        for label in ["alice", "bob"]:
            d = tmp_path / label
            d.mkdir()
            for i in range(2):
                np.save(d / f"{i}.npy",
                        np.full((4, 4), 0.5 if label == "alice" else 0.9,
                                np.float32))
        reader = ImageRecordReader(str(tmp_path), width=2, height=2)
        rows = list(reader)
        assert reader.labels == ["alice", "bob"]
        assert len(rows) == 4
        assert len(rows[0]) == 5  # 2*2 pixels + label
        assert rows[0][-1] == 0.0 and rows[-1][-1] == 1.0

    def test_lfw_synthetic_fetcher(self):
        from deeplearning4j_tpu.datasets.impl import LFWDataSetIterator

        it = LFWDataSetIterator(batch=16, num_examples=48)
        ds = it.next()
        assert ds.features.shape == (16, 28 * 28)
        assert ds.labels.shape == (16, 5)
        total = 16 + sum(b.num_examples() for b in [it.next(), it.next()])
        assert total == 48


class TestListString:
    def test_in_memory(self):
        it = RecordReaderDataSetIterator(
            ListStringRecordReader([[1, 2, 0], [3, 4, 1]]), 2,
            num_possible_labels=2,
        )
        ds = it.next()
        assert ds.features.tolist() == [[1.0, 2.0], [3.0, 4.0]]
