"""Sharded checkpoint subsystem (scaleout/ckpt): manifest atomicity,
resharding restore, strictness, retention, checksums, telemetry, and the
ckpt_inspect CLI."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer_lm import (
    init_lm_params,
    lm_param_shardings,
    shard_lm_params,
)
from deeplearning4j_tpu.scaleout.ckpt import (
    Checkpointer,
    latest_step,
    restore_sharded,
    save_sharded,
    verify_checksums,
)
from deeplearning4j_tpu.scaleout.ckpt.manifest import (
    MANIFEST_NAME,
    read_manifest,
    step_dir_name,
)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

V, D, H, E, DFF = 32, 16, 2, 4, 32


def _params(n_layers=1, n_experts=E, seed=0):
    return init_lm_params(jax.random.PRNGKey(seed), V, D, H, n_experts, DFF,
                          n_layers=n_layers)


def _dp_ep_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "expert"))


def _dp_sp_ep_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "sp", "expert"))


def _assert_tree_equal(a, b, what, atol=0.0):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree_util.tree_leaves_with_path(b)
    assert len(fa) == len(fb)
    for (pa, la), (_, lb) in zip(fa, fb):
        err = float(jnp.max(jnp.abs(jnp.asarray(la, jnp.float32)
                                    - jnp.asarray(lb, jnp.float32))))
        assert err <= atol, f"{what}: {jax.tree_util.keystr(pa)} diff {err}"


class TestShardedRoundTrip:
    def test_replicated_roundtrip_exact(self, tmp_path):
        state = {"params": _params(), "extra": jnp.arange(7.0)}
        step_dir = save_sharded(str(tmp_path), 5, state)
        restored, manifest = restore_sharded(step_dir, state)
        assert manifest.step == 5
        _assert_tree_equal(restored, state, "replicated roundtrip")

    def test_sharded_save_writes_per_shard_chunks(self, tmp_path):
        mesh = _dp_ep_mesh()
        sharded = shard_lm_params(_params(), mesh)
        step_dir = save_sharded(str(tmp_path), 1, {"params": sharded},
                                mesh=mesh)
        manifest = read_manifest(step_dir)
        assert manifest.mesh == {"axis_names": ["data", "expert"],
                                 "shape": [2, 4]}
        by_path = {e.path: e for e in manifest.leaves}
        # expert-sharded leaves split into one chunk per expert shard;
        # replicated leaves dedupe to exactly ONE chunk
        assert len(by_path["['params']['blocks']['experts']['w1']"].chunks) == 4
        assert len(by_path["['params']['embed']"].chunks) == 1
        assert by_path["['params']['blocks']['experts']['w1']"].spec == [
            None, "expert"]
        # one file per owning device, all referenced by the manifest
        for fname in manifest.files:
            assert os.path.isfile(os.path.join(step_dir, fname))

    def test_reshard_across_meshes_and_to_single_device(self, tmp_path):
        """The resharding matrix: dp×ep save → dp×sp×ep restore and →
        unsharded restore, both bit-exact, target shards assembled from
        the covering saved slices."""
        params = _params(n_layers=2)
        mesh_a = _dp_ep_mesh()
        step_dir = save_sharded(str(tmp_path), 2,
                                {"params": shard_lm_params(params, mesh_a)},
                                mesh=mesh_a)

        mesh_b = _dp_sp_ep_mesh()
        template = {"params": _params(n_layers=2, seed=9)}  # values ignored
        shardings = {"params": lm_param_shardings(template["params"], mesh_b)}
        restored, _ = restore_sharded(step_dir, template, shardings)
        _assert_tree_equal(restored["params"], params, "dp×ep → dp×sp×ep")
        w1 = restored["params"]["blocks"]["experts"]["w1"]
        assert w1.sharding.spec == P(None, "expert")
        assert w1.sharding.mesh.axis_names == ("data", "sp", "expert")

        unsharded, _ = restore_sharded(step_dir, template, None)
        _assert_tree_equal(unsharded["params"], params, "dp×ep → unsharded")

    def test_save_time_sharding_is_irrelevant_to_restore(self, tmp_path):
        """Same values saved replicated and expert-sharded restore
        identically — chunk offsets, not save-time layout, drive
        assembly."""
        params = _params()
        mesh = _dp_ep_mesh()
        d_rep = save_sharded(str(tmp_path / "rep"), 1, {"params": params})
        d_shd = save_sharded(str(tmp_path / "shd"), 1,
                             {"params": shard_lm_params(params, mesh)},
                             mesh=mesh)
        t = {"params": _params(seed=3)}
        sh = {"params": lm_param_shardings(t["params"], mesh)}
        a, _ = restore_sharded(d_rep, t, sh)
        b, _ = restore_sharded(d_shd, t, sh)
        _assert_tree_equal(a, b, "layout-independent restore")


class TestAtomicityAndLatest:
    def test_manifestless_dir_is_invisible_to_latest(self, tmp_path):
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        assert ck.latest_step() is None
        ck.save(3, {"x": jnp.ones(4)})
        # an interrupted save: step dir + data file, NO manifest
        fake = tmp_path / step_dir_name(9)
        fake.mkdir()
        (fake / "shard_00000.npz").write_bytes(b"partial garbage")
        assert ck.latest_step() == 3
        assert latest_step(str(tmp_path)) == 3
        state, step, _meta = ck.restore({"x": jnp.zeros(4)})
        assert step == 3
        np.testing.assert_array_equal(np.asarray(state["x"]), np.ones(4))

    def test_manifest_commits_last(self, tmp_path, monkeypatch):
        """Kill the writer right before the manifest rename: the directory
        exists but no reader sees a checkpoint."""
        from deeplearning4j_tpu.scaleout.ckpt import manifest as mf

        def boom(step_dir, manifest):
            raise RuntimeError("killed before commit")

        monkeypatch.setattr(
            "deeplearning4j_tpu.scaleout.ckpt.sharded_io.write_manifest",
            boom)
        with pytest.raises(RuntimeError):
            save_sharded(str(tmp_path), 7, {"x": jnp.ones(3)})
        step_dir = tmp_path / step_dir_name(7)
        assert step_dir.is_dir()  # data landed...
        assert not (step_dir / MANIFEST_NAME).exists()  # ...but no commit
        assert latest_step(str(tmp_path)) is None

    def test_superseding_save_sweeps_interrupted_dir(self, tmp_path):
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        fake = tmp_path / step_dir_name(4)
        fake.mkdir()
        (fake / "shard_00000.npz").write_bytes(b"junk")
        ck.save(5, {"x": jnp.ones(2)})
        assert not fake.exists(), "superseded interrupted save must be GC'd"


class TestStrictness:
    def test_shape_mismatch_raises(self, tmp_path):
        step_dir = save_sharded(str(tmp_path), 1, {"w": jnp.ones((4, 4))})
        with pytest.raises(ValueError, match="shape"):
            restore_sharded(step_dir, {"w": jnp.ones((4, 5))})

    def test_lossy_dtype_narrowing_raises(self, tmp_path):
        # float64 state written from host numpy (x64 stays off in jax)
        step_dir = save_sharded(
            str(tmp_path), 1, {"w": np.ones((3,), np.float64)})
        with pytest.raises(TypeError, match="narrow"):
            restore_sharded(step_dir, {"w": jnp.ones((3,), jnp.float32)})

    def test_safe_widening_is_allowed(self, tmp_path):
        step_dir = save_sharded(
            str(tmp_path), 1, {"w": np.asarray([1, 2, 3], np.int8)})
        restored, _ = restore_sharded(
            step_dir, {"w": jnp.zeros((3,), jnp.int32)})
        assert restored["w"].dtype == jnp.int32
        np.testing.assert_array_equal(np.asarray(restored["w"]), [1, 2, 3])

    def test_missing_leaf_raises(self, tmp_path):
        step_dir = save_sharded(str(tmp_path), 1, {"a": jnp.ones(2)})
        with pytest.raises(KeyError, match="missing leaf"):
            restore_sharded(step_dir, {"a": jnp.ones(2), "b": jnp.ones(2)})


class TestRetentionAndTelemetry:
    def test_retention_keeps_last_n(self, tmp_path):
        ck = Checkpointer(str(tmp_path), keep_last=2,
                          registry=MetricsRegistry())
        for step in (1, 2, 3, 4):
            ck.save(step, {"x": jnp.full((2,), float(step))})
        steps = [s for s, _ in ck.step_dirs()]
        assert steps == [3, 4]
        state, step, _ = ck.restore({"x": jnp.zeros(2)})
        assert step == 4

    def test_save_restore_bump_registry(self, tmp_path):
        reg = MetricsRegistry()
        ck = Checkpointer(str(tmp_path), registry=reg, prefix="ckpt")
        ck.save(10, {"x": jnp.ones((8, 8))})
        assert reg.counter("ckpt_saves_total").value == 1
        assert reg.counter("ckpt_bytes_total").value == 8 * 8 * 4
        assert reg.gauge("ckpt_last_step").value == 10
        assert reg.gauge("ckpt_last_shards").value >= 1
        assert reg.histogram("ckpt_save_ms").count == 1
        ck.restore({"x": jnp.zeros((8, 8))})
        assert reg.counter("ckpt_restores_total").value == 1
        assert reg.histogram("ckpt_restore_ms").count == 1

    def test_verify_checksums_detects_corruption(self, tmp_path):
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry(),
                          verify_on_restore=True)
        step_dir = ck.save(1, {"x": jnp.arange(32.0)})
        assert verify_checksums(step_dir) == []
        # corrupt one stored chunk (rewrite the member with different data)
        fname = os.path.join(step_dir, "shard_00000.npz")
        with np.load(fname) as z:
            payload = {k: np.asarray(z[k]) for k in z.files}
        key = list(payload)[0]
        payload[key] = payload[key] + 1.0
        with open(fname, "wb") as f:
            np.savez(f, **payload)
        problems = verify_checksums(step_dir)
        assert problems and "crc32" in problems[0]
        with pytest.raises(ValueError, match="checksum"):
            ck.restore({"x": jnp.zeros(32)})


class TestCkptInspectCli:
    def _saved(self, tmp_path):
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        ck.save(2, {"params": _params()})
        return str(tmp_path)

    def test_summary_and_verify(self, tmp_path, capsys):
        from tools.ckpt_inspect import main

        root = self._saved(tmp_path)
        assert main([root]) == 0
        out = capsys.readouterr().out
        assert "step 2" in out and "['params']['embed']" in out
        assert main([root, "--verify"]) == 0
        assert "ok:" in capsys.readouterr().out
        assert main([root, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["step"] == 2 and payload["leaves"] > 0

    def test_diff(self, tmp_path, capsys):
        from tools.ckpt_inspect import main

        ck = Checkpointer(str(tmp_path), keep_last=5,
                          registry=MetricsRegistry())
        d1 = ck.save(1, {"params": _params()})
        d2 = ck.save(
            2, {"params": jax.tree_util.tree_map(lambda a: a + 1.0,
                                                 _params())})
        assert main([d1, "--diff", d1]) == 0
        assert "identical" in capsys.readouterr().out
        assert main([d1, "--diff", d2, "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert not payload["identical"]
        assert payload["max_abs_diff"] == pytest.approx(1.0)

    def test_interrupted_dir_rejected(self, tmp_path, capsys):
        from tools.ckpt_inspect import main

        fake = tmp_path / step_dir_name(1)
        fake.mkdir()
        assert main([str(tmp_path)]) == 2
        assert "interrupted" in capsys.readouterr().err


class TestFaultRobustness:
    """ISSUE 6 satellites: corrupt-shard naming, the gc retention race,
    per-process writes + the coordinator merge barrier, async saves."""

    def test_restore_names_corrupt_shard_leaf_and_chunk(self, tmp_path):
        """Acceptance (d): a RESTORE (not just --verify) over a corrupted
        shard fails loudly, and the error names the shard file, the leaf
        path, and the chunk index — enough for an operator to know which
        file to re-copy."""
        from deeplearning4j_tpu.scaleout.ckpt import CorruptShardError

        mesh = _dp_ep_mesh()
        params = shard_lm_params(_params(), mesh)
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        step_dir = ck.save(1, {"params": params}, mesh=mesh)
        # corrupt ONE member of one shard file
        fname = "shard_00002.npz"
        with np.load(os.path.join(step_dir, fname)) as z:
            payload = {k: np.asarray(z[k]) for k in z.files}
        victim_key = sorted(payload)[0]
        payload[victim_key] = payload[victim_key] + 1.0
        with open(os.path.join(step_dir, fname), "wb") as f:
            np.savez(f, **payload)

        template = {"params": _params()}
        shardings = {"params": lm_param_shardings(template["params"], mesh)}
        with pytest.raises(CorruptShardError) as ei:
            ck.restore(template, shardings)
        msg = str(ei.value)
        assert fname in msg, msg                       # the shard file
        assert victim_key in msg, msg                  # the leaf path
        assert "chunk" in msg and "crc32" in msg, msg  # the chunk index
        # the CLI exits nonzero on the same corruption
        from tools.ckpt_inspect import main

        assert main([step_dir, "--verify"]) == 1

    def test_gc_never_deletes_step_a_reader_just_resolved(self, tmp_path):
        """The retention race, pinned: latest_step() resolves step N; a
        concurrent writer then saves past keep_last. gc() must not delete
        N while the reader's restore is in flight — and releases the pin
        once the reader resolves a newer step."""
        ck = Checkpointer(str(tmp_path), keep_last=2,
                          registry=MetricsRegistry())
        ck.save(1, {"x": jnp.arange(8.0)})
        ck.save(2, {"x": jnp.arange(8.0) * 2})
        resolved = ck.latest_step()  # the reader's resolve: pins step 2
        assert resolved == 2
        for step in (3, 4, 5):      # concurrent writer races past keep_last
            ck.save(step, {"x": jnp.arange(8.0) * step})
        # keep_last=2 keeps {4, 5}; step 2 SURVIVES because it is pinned
        kept = [s for s, _ in ck.step_dirs()]
        assert kept == [2, 4, 5], kept
        state, step, _ = ck.restore({"x": jnp.zeros(8)}, step=resolved)
        assert step == 2
        np.testing.assert_array_equal(np.asarray(state["x"]),
                                      np.arange(8.0) * 2)
        # resolving the new latest moves the pin; the old step is now fair
        # game for the next sweep
        assert ck.latest_step() == 5
        ck.gc()
        assert [s for s, _ in ck.step_dirs()] == [4, 5]

    def test_process_shards_plus_merge_equals_single_save(self, tmp_path):
        """A (single-process) multi-host save — per-process shard writes,
        then the coordinator merge barrier — commits a checkpoint chunk-
        identical to save_sharded's, and stays invisible until merged."""
        mesh = _dp_ep_mesh()
        params = shard_lm_params(_params(), mesh)
        state = {"params": params}
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        ck.save_process(3, state, process_index=0)
        assert ck.latest_step() is None  # parts are not a commit
        ck.merge_save(3, n_processes=1, meta={"src": "mh"}, mesh=mesh,
                      state=state)
        assert ck.latest_step() == 3

        single = Checkpointer(str(tmp_path / "single"),
                              registry=MetricsRegistry())
        ref_dir = single.save(3, state, mesh=mesh)
        got = read_manifest(os.path.join(str(tmp_path), step_dir_name(3)))
        want = read_manifest(ref_dir)
        chunks = lambda m: [(e.path, sorted((c.file, c.start, c.shape,
                                             c.crc32) for c in e.chunks))
                            for e in m.leaves]
        assert chunks(got) == chunks(want)
        assert got.meta == {"src": "mh"}
        # no leftover part manifests after the commit
        from deeplearning4j_tpu.scaleout.ckpt.manifest import (
            list_part_manifests,
        )

        assert list_part_manifests(
            os.path.join(str(tmp_path), step_dir_name(3))) == []
        template = {"params": _params()}
        shardings = {"params": lm_param_shardings(template["params"], mesh)}
        state2, manifest = restore_sharded(
            os.path.join(str(tmp_path), step_dir_name(3)), template,
            shardings)
        _assert_tree_equal(state2["params"], params, "merged restore")

    def test_merge_barrier_refuses_holey_checkpoint(self, tmp_path):
        """A merge whose parts do not cover every leaf (a host's shards
        missing) must refuse to commit rather than land a checkpoint with
        holes."""
        from deeplearning4j_tpu.scaleout.ckpt.manifest import (
            read_part_manifest,
            part_manifest_path,
            write_part_manifest,
        )
        from deeplearning4j_tpu.scaleout.ckpt import (
            merge_process_manifests,
            save_process_shards,
        )

        mesh = _dp_ep_mesh()
        params = shard_lm_params(_params(), mesh)
        step_dir = save_process_shards(str(tmp_path), 7, {"params": params},
                                       process_index=0)
        # drop half the chunks from the part manifest: "process 1 died"
        proc, step, entries = read_part_manifest(
            part_manifest_path(step_dir, 0))
        from deeplearning4j_tpu.scaleout.ckpt.manifest import LeafEntry

        pruned = tuple(
            LeafEntry(path=e.path, shape=e.shape, dtype=e.dtype,
                      spec=e.spec, chunks=e.chunks[: len(e.chunks) // 2])
            for e in entries)
        write_part_manifest(step_dir, 0, step, pruned)
        with pytest.raises(ValueError, match="cover"):
            merge_process_manifests(str(tmp_path), 7, 1, timeout_s=5)
        assert latest_step(str(tmp_path)) is None  # nothing committed

    def test_async_checkpointer_keeps_training_thread_free(self, tmp_path):
        """AsyncCheckpointer: saves commit in the background (identical
        bytes to a blocking save), flush() surfaces failures, restore
        after save sees the save."""
        from deeplearning4j_tpu.scaleout.ckpt import (
            AsyncCheckpointer,
            Checkpointer,
        )

        reg = MetricsRegistry()
        ck = AsyncCheckpointer(
            Checkpointer(str(tmp_path), keep_last=3, registry=reg))
        trees = {i: {"x": jnp.arange(64.0) * i} for i in (1, 2, 3)}
        for i, tree in trees.items():
            ck.save(i, tree, meta={"i": i})
        state, step, meta = ck.restore({"x": jnp.zeros(64)})  # implies flush
        assert step == 3 and meta["i"] == 3
        np.testing.assert_array_equal(np.asarray(state["x"]),
                                      np.arange(64.0) * 3)
        assert reg.counter("ckpt_async_saves_total").value == 3
        assert reg.counter("ckpt_saves_total").value == 3
        ck.close()


class TestAsyncCheckpointerStress:
    def test_concurrent_save_flush_close_under_lockwatch(self, tmp_path,
                                                         lockwatch):
        """ISSUE 11 stress: saver threads racing flush() against the
        double-buffered (max_pending=2) backpressure path, lock-order
        cycle detection armed. Every enqueued save must commit exactly
        once, flush must never deadlock against a full queue, and the
        queue's error lock shows real cross-thread traffic."""
        import threading
        import time

        from deeplearning4j_tpu.scaleout.ckpt import (
            AsyncCheckpointer,
            Checkpointer,
        )

        reg = MetricsRegistry()
        ck = AsyncCheckpointer(
            Checkpointer(str(tmp_path), keep_last=100, registry=reg),
            max_pending=2)
        n_savers, per_saver = 3, 6
        errors = []

        def saver(i):
            try:
                for j in range(per_saver):
                    step = i * 1000 + j
                    ck.save(step, {"x": jnp.full((32,), float(step))},
                            meta={"step": step})
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        def flusher():
            try:
                for _ in range(4):
                    ck.flush()
                    time.sleep(0.005)
            except BaseException as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=saver, args=(i,))
                   for i in range(n_savers)]
        threads.append(threading.Thread(target=flusher))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "stress hung"
        ck.close()  # final drain + writer join
        assert not errors, errors
        total = n_savers * per_saver
        assert reg.counter("ckpt_async_saves_total").value == total
        assert reg.counter("ckpt_async_failures_total").value == 0
        # every save commit is restorable at its exact bytes
        steps = ck.step_dirs()
        assert len(steps) == total
        state, step, meta = ck.restore({"x": jnp.zeros(32)})
        np.testing.assert_array_equal(np.asarray(state["x"]),
                                      np.full((32,), float(step)))
        watch = lockwatch.summary()
        assert watch["cycles"] == 0 and watch["watchdog_dumps"] == 0
        assert watch["locks"].get("ckpt.async.error", {}).get(
            "acquires", 0) > 0, "error lock was not watched"
