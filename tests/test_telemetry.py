"""Unified training telemetry (ISSUE 2): in-graph metrics parity, host
registry/Prometheus/JSONL semantics, the dp×sp×ep telemetry run, listener
exception-safety, and the scaleout counter bridges."""

import json
import os
import subprocess
import sys
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.telemetry import (
    MetricsRegistry,
    TrainTelemetry,
    read_step_log,
    render_prometheus,
    summarize_step_log,
)
from deeplearning4j_tpu.telemetry.step_log import StepLogWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, D, H, E, DFF = 32, 16, 2, 4, 32
B, T = 4, 16


def _bits_equal(a, b):
    return np.asarray(a).tobytes() == np.asarray(b).tobytes()


def _tree_bits_equal(ta, tb):
    la = jax.tree_util.tree_leaves(jax.device_get(ta))
    lb = jax.tree_util.tree_leaves(jax.device_get(tb))
    assert len(la) == len(lb)
    return all(_bits_equal(a, b) for a, b in zip(la, lb))


def _lm_data(seed=1, vocab=V, b=B, t=T):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (b, t + 1), 0, vocab)
    return toks[:, :-1], toks[:, 1:]


# ------------------------------------------------------------- registry ----

class TestRegistry:
    def test_counter_labels_independent(self):
        reg = MetricsRegistry()
        reg.counter("jobs", {"worker": "a"}).inc()
        reg.counter("jobs", {"worker": "a"}).inc(2)
        reg.counter("jobs", {"worker": "b"}).inc(5)
        assert reg.counter("jobs", {"worker": "a"}).value == 3
        assert reg.counter("jobs", {"worker": "b"}).value == 5
        with pytest.raises(ValueError):
            reg.counter("jobs").inc(-1)

    def test_gauge_set_and_inc(self):
        reg = MetricsRegistry()
        g = reg.gauge("loss")
        g.set(2.5)
        assert reg.gauge("loss").value == 2.5
        g.inc(-0.5)
        assert g.value == 2.0

    def test_histogram_cumulative_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("ms", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 50.0, 500.0):
            h.observe(v)
        snap = h.snapshot()
        # cumulative le semantics: 1 <=1, 2 <=10, 3 <=100, 4 <=+Inf
        assert [b["count"] for b in snap["buckets"]] == [1, 2, 3, 4]
        assert snap["buckets"][-1]["le"] == float("inf")
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(555.5)
        assert h.percentile(50) == 10.0

    def test_snapshot_structure(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.gauge("g", {"x": "1"}).set(3)
        reg.histogram("h").observe(2)
        snap = reg.snapshot()
        assert snap["counters"][0] == {"name": "c", "labels": {}, "value": 1.0}
        assert snap["gauges"][0]["labels"] == {"x": "1"}
        assert snap["histograms"][0]["count"] == 1


# ----------------------------------------------------------- prometheus ----

class TestPrometheus:
    def test_golden_text_format(self):
        reg = MetricsRegistry()
        reg.counter("train_steps").inc(3)
        reg.gauge("train_loss").set(1.5)
        reg.gauge("router_load", {"expert": "0"}).set(0.25)
        reg.gauge("router_load", {"expert": "1"}).set(0.75)
        h = reg.histogram("step_ms", buckets=(10.0, 100.0))
        h.observe(5.0)
        h.observe(50.0)
        expected = (
            "# TYPE train_steps_total counter\n"
            "train_steps_total 3\n"
            "# TYPE router_load gauge\n"
            'router_load{expert="0"} 0.25\n'
            'router_load{expert="1"} 0.75\n'
            "# TYPE train_loss gauge\n"
            "train_loss 1.5\n"
            "# TYPE step_ms histogram\n"
            'step_ms_bucket{le="10"} 1\n'
            'step_ms_bucket{le="100"} 2\n'
            'step_ms_bucket{le="+Inf"} 2\n'
            "step_ms_sum 55\n"
            "step_ms_count 2\n"
        )
        assert render_prometheus(reg) == expected

    def test_name_sanitization_and_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("rounds.worker-0").inc()
        reg.gauge("g", {"path": 'a"b\nc'}).set(1)
        txt = render_prometheus(reg)
        assert "rounds_worker_0_total 1" in txt
        assert r'path="a\"b\nc"' in txt


# -------------------------------------------------------------- step log ----

class TestStepLog:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path, static={"mesh": "dp2xsp2xep2"}) as w:
            w.write(0, wall_ms=None, loss=1.5, router_load=[0.5, 0.5])
            w.write(1, wall_ms=12.5, tokens_per_sec=1000.0, loss=1.25)
        recs = read_step_log(path)
        assert [r["step"] for r in recs] == [0, 1]
        assert recs[0]["mesh"] == "dp2xsp2xep2"
        assert recs[0]["router_load"] == [0.5, 0.5]
        assert "wall_ms" not in recs[0] and recs[1]["wall_ms"] == 12.5
        assert recs[1]["tokens_per_sec"] == 1000.0

    def test_write_after_close_reopens_append(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        w = StepLogWriter(path)
        w.write(0, loss=1.0)
        w.close()
        w.write(1, loss=0.5)  # listener chains get closed and reused
        w.close()
        assert len(read_step_log(path)) == 2

    def test_jax_scalars_and_nonfinite(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=jnp.float32(2.0), bad=float("nan"))
        rec = read_step_log(path)[0]
        assert rec["loss"] == 2.0 and rec["bad"] == "nan"

    def test_summarize(self, tmp_path):
        recs = [
            {"step": 0, "loss": 2.0, "grad_norm": 1.0,
             "router_load": [0.4, 0.6]},
            {"step": 1, "wall_ms": 10.0, "tokens_per_sec": 100.0,
             "loss": 1.0, "grad_norm": 0.5, "router_load": [0.6, 0.4]},
        ]
        s = summarize_step_log(recs)
        assert s["steps"] == 2
        assert s["loss"] == {"first": 2.0, "last": 1.0}
        assert s["wall_ms"]["p50"] == 10.0
        assert s["router_load_mean"] == [0.5, 0.5]
        assert summarize_step_log([]) == {"steps": 0}


# ------------------------------------------------- in-graph metric parity ----

class TestInGraphParity:
    def test_lm_step_bit_identical_with_metrics(self):
        """The metrics-threaded flagship step returns the SAME loss and
        params as the unthreaded one — 0 ulp on CPU."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_single_device_train_step,
        )

        params = init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                                n_layers=2)
        tk, tg = _lm_data()
        plain = make_single_device_train_step(H, attn_impl="dense")
        threaded = make_single_device_train_step(H, attn_impl="dense",
                                                 with_metrics=True)
        p0 = p1 = params
        for _ in range(3):
            p0, l0 = plain(p0, tk, tg)
            p1, l1, metrics = threaded(p1, tk, tg)
            assert _bits_equal(l0, l1)
        assert _tree_bits_equal(p0, p1)
        m = jax.device_get(metrics)
        for key in ("loss", "task_loss", "aux_loss", "grad_norm",
                    "param_norm", "update_ratio", "router_load"):
            assert key in m
        assert m["router_load"].shape == (E,)
        assert abs(float(m["router_load"].sum()) - 1.0) < 1e-5
        assert float(m["grad_norm"]) > 0
        assert 0 < float(m["update_ratio"]) < 1

    def test_trainer_sync_step_bit_identical_with_metrics(self):
        from deeplearning4j_tpu.nn import functional as F
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.parallel import data_parallel_mesh
        from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .num_iterations(1).seed(0).list(2)
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax",
                          loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        mesh = data_parallel_mesh(8)
        params = F.init_params(conf, jax.random.PRNGKey(0))
        states = F.init_train_state(conf, params)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(16, 4).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
        w = jnp.ones((16,), jnp.float32)
        key = jax.random.PRNGKey(7)

        def copy(t):
            return jax.tree_util.tree_map(jnp.array, t)

        plain = make_sync_train_step(conf, mesh)
        threaded = make_sync_train_step(conf, mesh, with_metrics=True)
        p0, s0, sc0 = plain(copy(params), copy(states), jnp.asarray(0),
                            x, y, w, key)
        p1, s1, sc1, metrics = threaded(copy(params), copy(states),
                                        jnp.asarray(0), x, y, w, key)
        assert _bits_equal(sc0, sc1)
        assert _tree_bits_equal(p0, p1)
        m = jax.device_get(metrics)
        assert float(m["grad_norm"]) > 0
        assert float(m["update_ratio"]) > 0
        assert _bits_equal(m["loss"], np.asarray(sc0, np.float32))

    def test_pipeline_step_bit_identical_with_metrics(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_pp_loss,
            make_pp_stages,
        )
        from deeplearning4j_tpu.parallel.pipeline import (
            make_pipeline_train_step,
            shard_stage_params,
            stack_stage_params,
        )

        params = init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                                n_layers=2)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "pipe"))
        per_stage, stage_fn = make_pp_stages(params, H, n_stages=2,
                                             attn_impl="dense")
        stacked = shard_stage_params(stack_stage_params(per_stage), mesh,
                                     "pipe")
        n_micro, mb = 4, 2
        toks = jax.random.randint(jax.random.PRNGKey(3),
                                  (n_micro, mb, T + 1), 0, V)
        tk, tg = toks[..., :-1], toks[..., 1:]

        def run(with_metrics):
            loss_fn = make_pp_loss(stage_fn, mesh, "pipe",
                                   batch_axis="data")

            def pp_loss(y, tgt_mb):
                logits = y @ params["dec_w"] + params["dec_b"]
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(logp, tgt_mb[..., None],
                                           -1)[..., 0]
                return jnp.mean(nll)

            step = make_pipeline_train_step(
                stage_fn, pp_loss, mesh, "pipe", batch_axis="data",
                with_metrics=with_metrics)
            emb = params["embed"][tk]
            st = jax.tree_util.tree_map(jnp.array, stacked)
            return step(st, emb, tg)

        p0, l0 = run(False)
        p1, l1, metrics = run(True)
        assert _bits_equal(l0, l1)
        assert _tree_bits_equal(p0, p1)
        m = jax.device_get(metrics)
        assert m["microbatch_loss"].shape == (4,)
        assert float(m["grad_norm"]) > 0


# ----------------------------------------------- dp×sp×ep telemetry run ----

class TestComposedTelemetry:
    def _mesh(self):
        return Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                    ("data", "sp", "expert"))

    def test_router_load_sums_to_one_per_step(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            shard_lm_batch,
            shard_lm_params,
        )

        mesh = self._mesh()
        params = init_lm_params(jax.random.PRNGKey(0), V, D, H, 2, DFF,
                                n_layers=2)
        step = make_composed_train_step(mesh, H, capacity=B * T,
                                        with_metrics=True)
        tk, tg = _lm_data()
        sp = shard_lm_params(params, mesh)
        stk, stg = shard_lm_batch(tk, tg, mesh)
        for _ in range(3):
            sp, loss, metrics = step(sp, stk, stg)
            jax.block_until_ready(loss)
            m = jax.device_get(metrics)
            assert m["router_load"].shape == (2,)
            assert abs(float(m["router_load"].sum()) - 1.0) < 1e-5
            assert float(m["grad_norm"]) > 0
            # capacity path threads the drop gauge; ample capacity → 0
            assert float(m["moe_dropped_frac"]) == 0.0

    def test_dropped_frac_metric_reports_overflow(self):
        """A deliberately tight capacity surfaces a nonzero
        moe_dropped_frac through the metrics-threaded composed step — the
        in-graph twin of parallel.moe.expected_dropped."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            shard_lm_batch,
            shard_lm_params,
        )

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "expert"))
        params = init_lm_params(jax.random.PRNGKey(0), V, D, H, 4, DFF,
                                n_layers=1)
        step = make_composed_train_step(mesh, H, capacity=2,
                                        with_metrics=True)
        tk, tg = _lm_data()
        sp = shard_lm_params(params, mesh)
        stk, stg = shard_lm_batch(tk, tg, mesh)
        sp, loss, metrics = step(sp, stk, stg)
        jax.block_until_ready(loss)
        frac = float(jax.device_get(metrics)["moe_dropped_frac"])
        assert 0.0 < frac < 1.0, frac

    def test_step_log_prometheus_and_memory_endpoints(self, tmp_path):
        """The acceptance run: dp×sp×ep train with telemetry produces a
        JSONL step log with loss/grad-norm/tokens-per-sec/router-load per
        logged step, and the UI serves the same gauges at /metrics plus
        device memory at /api/memory."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            selected_attn_impl,
            shard_lm_batch,
            shard_lm_params,
        )
        from deeplearning4j_tpu.ui.server import UiServer

        mesh = self._mesh()
        params = init_lm_params(jax.random.PRNGKey(0), V, D, H, 2, DFF,
                                n_layers=2)
        step = make_composed_train_step(mesh, H, capacity=B * T,
                                        with_metrics=True)
        tk, tg = _lm_data()
        sp = shard_lm_params(params, mesh)
        stk, stg = shard_lm_batch(tk, tg, mesh)

        path = str(tmp_path / "steps.jsonl")
        reg = MetricsRegistry()
        session = TrainTelemetry(
            registry=reg, step_log_path=path, interval=2,
            tokens_per_step=B * T,
            static={"mesh": "dp2xsp2xep2",
                    "attn_impl": selected_attn_impl(T)})
        n_steps = 5
        for i in range(n_steps):
            sp, loss, metrics = step(sp, stk, stg)
            session.record(i, metrics)
        session.close()

        recs = read_step_log(path)
        assert len(recs) == n_steps
        for i, rec in enumerate(recs):
            assert rec["step"] == i
            assert isinstance(rec["loss"], float)
            assert isinstance(rec["grad_norm"], float)
            assert abs(sum(rec["router_load"]) - 1.0) < 1e-5
            assert rec["attn_impl"] in ("dense", "blockwise", "flash")
            if i > 0:  # first step only arms the clock
                assert rec["wall_ms"] > 0
                assert rec["tokens_per_sec"] > 0

        server = UiServer()
        server.attach_metrics(reg)
        port = server.start(port=0)
        try:
            def get(p):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{p}") as r:
                    return r.headers.get("Content-Type"), r.read().decode()

            ctype, text = get("/metrics")
            assert ctype.startswith("text/plain")
            assert "train_loss" in text
            assert "train_grad_norm" in text
            assert 'train_router_load{expert="0"}' in text
            assert f"train_steps_total {n_steps}" in text
            assert "train_tokens_per_sec" in text

            _, body = get("/api/telemetry")
            snap = json.loads(body)
            names = {g["name"] for g in snap["gauges"]}
            assert {"train_loss", "train_grad_norm",
                    "train_router_load"} <= names

            _, body = get("/api/memory")
            mem = json.loads(body)
            assert len(mem["devices"]) == len(jax.devices())
            assert all("device" in d for d in mem["devices"])
        finally:
            server.stop()

    def test_metrics_endpoint_404_without_registry(self):
        from deeplearning4j_tpu.ui.server import UiServer

        server = UiServer()
        port = server.start(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics")
            assert e.value.code == 404
        finally:
            server.stop()


# ------------------------------------------------------- listener safety ----

class _Closeable:
    def __init__(self, raise_on_call=False):
        self.calls = 0
        self.closed = 0
        self.raise_on_call = raise_on_call

    def __call__(self, model, iteration, score):
        self.calls += 1
        if self.raise_on_call:
            raise RuntimeError("bad listener")

    def close(self):
        self.closed += 1


def _small_net():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder()
            .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
            .num_iterations(3).seed(0).list(2)
            .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build())
    return MultiLayerNetwork(conf).init()


class TestListenerSafety:
    def test_bad_listener_does_not_kill_fit(self):
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresListener,
        )

        net = _small_net()
        bad = _Closeable(raise_on_call=True)
        good = CollectScoresListener()
        net.set_listeners([bad, good])
        rng = np.random.RandomState(0)
        net.fit(rng.rand(12, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)])
        assert bad.calls == 3  # kept being called, kept failing
        assert len(good.scores) == 3  # later listeners still ran

    def test_listeners_closed_on_crash_inside_fit(self):
        net = _small_net()
        closeable = _Closeable()
        net.set_listeners([closeable])
        with pytest.raises(ValueError, match="No labels"):
            net.fit(np.random.rand(12, 4).astype(np.float32), None)
        assert closeable.closed >= 1

    def test_listeners_closed_after_normal_fit(self):
        net = _small_net()
        closeable = _Closeable()
        net.set_listeners([closeable])
        rng = np.random.RandomState(0)
        net.fit(rng.rand(12, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)])
        assert closeable.closed >= 1

    def test_solver_dispatch_safe_and_closes(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.optimize.solver import Solver

        conf = (NeuralNetConfiguration.Builder()
                .n_in(2).n_out(2).num_iterations(4).seed(0).build())
        bad = _Closeable(raise_on_call=True)

        def score_fn(p, key):
            return jnp.sum(p ** 2)

        solver = Solver(conf, score_fn, listeners=[bad], num_iterations=4)
        solver.optimize(jnp.ones((3,)))
        assert bad.calls >= 1
        assert bad.closed >= 1

    def test_trainer_dispatch_safe(self):
        from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresListener,
        )
        from deeplearning4j_tpu.parallel import (
            ParameterAveragingTrainer,
            data_parallel_mesh,
        )

        net = _small_net()
        bad = _Closeable(raise_on_call=True)
        good = CollectScoresListener()
        net.set_listeners([bad, good])
        trainer = ParameterAveragingTrainer(net, data_parallel_mesh(8),
                                            average_each_iteration=True)
        it = IrisDataSetIterator(32, 144)
        trainer.fit_data_set(it)
        assert bad.calls > 0 and len(good.scores) == bad.calls
        assert bad.closed >= 1

    def test_profiler_listener_closed_via_chain(self, tmp_path):
        """ProfilerIterationListener with a window larger than the run: the
        fit's finally must stop the still-open trace (armed profiler would
        make the NEXT start_trace raise)."""
        from deeplearning4j_tpu.utils.profiling import (
            ProfilerIterationListener,
        )

        net = _small_net()
        listener = ProfilerIterationListener(str(tmp_path / "t"), start=1,
                                             steps=100)
        net.set_listeners([listener])
        rng = np.random.RandomState(0)
        net.fit(rng.rand(12, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)])
        assert not listener._active  # window closed by the finally
        # and the profiler is actually free: a fresh trace can start
        jax.profiler.start_trace(str(tmp_path / "t2"))
        jax.profiler.stop_trace()


# -------------------------------------------------- timing/tracker bridge ----

class TestTimingListener:
    def test_percentiles(self, monkeypatch):
        from deeplearning4j_tpu.optimize.listeners import (
            TimingIterationListener,
        )

        listener = TimingIterationListener()
        clock = iter([0.0, 0.010, 0.030, 0.060, 0.100, 0.200])
        monkeypatch.setattr("time.perf_counter", lambda: next(clock))
        for i in range(6):
            listener(None, i, 0.0)
        # gaps: 10, 20, 30, 40, 100 ms
        assert listener.timings_ms == pytest.approx([10, 20, 30, 40, 100])
        assert listener.p50_ms() == pytest.approx(30)
        assert listener.p95_ms() == pytest.approx(100)
        assert TimingIterationListener().p50_ms() == 0.0

    def test_tracker_and_registry_bridge(self):
        from deeplearning4j_tpu.optimize.listeners import (
            TimingIterationListener,
        )
        from deeplearning4j_tpu.scaleout.statetracker import (
            InMemoryStateTracker,
        )

        reg = MetricsRegistry()
        tracker = InMemoryStateTracker()
        listener = TimingIterationListener(tracker=tracker, registry=reg)
        for i in range(4):
            listener(None, i, 0.1)
        assert tracker.count("job_ms_total") == pytest.approx(
            listener.total_ms())
        assert reg.histogram("iteration_ms").count == 3

    def test_metrics_iteration_listener(self, tmp_path):
        from deeplearning4j_tpu.optimize.listeners import (
            MetricsIterationListener,
        )

        reg = MetricsRegistry()
        path = str(tmp_path / "iters.jsonl")
        listener = MetricsIterationListener(registry=reg,
                                            step_log_path=path)
        net = _small_net()
        net.set_listeners([listener])
        rng = np.random.RandomState(0)
        net.fit(rng.rand(12, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)])
        assert reg.counter("train_iterations_total").value == 3
        assert reg.gauge("train_score").value > 0
        recs = read_step_log(path)
        assert len(recs) == 3 and all("score" in r for r in recs)


class TestStateTrackerMirror:
    def test_increment_mirrors_into_registry(self):
        from deeplearning4j_tpu.scaleout.statetracker import (
            InMemoryStateTracker,
        )

        reg = MetricsRegistry()
        tracker = InMemoryStateTracker(metrics_registry=reg)
        tracker.increment("job_ms_total", 12.5)
        tracker.increment("jobs_done")
        tracker.increment("rounds.w-0")
        assert reg.counter("job_ms_total").value == 12.5
        assert reg.counter("jobs_done").value == 1
        # dotted key renders sanitized
        assert "rounds_w_0_total 1" in render_prometheus(reg)


# ------------------------------------------------------------------ tools ----

class TestTelemetryReport:
    def _write_log(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=2.0, grad_norm=1.5, router_load=[0.5, 0.5])
            w.write(1, wall_ms=10.0, tokens_per_sec=6400.0, loss=1.5,
                    grad_norm=1.2, router_load=[0.4, 0.6])
            w.write(2, wall_ms=12.0, tokens_per_sec=5333.3, loss=1.2,
                    grad_norm=1.1, router_load=[0.6, 0.4])
        return path

    def test_report_table(self, tmp_path):
        path = self._write_log(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"), path],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "steps" in out.stdout
        assert "2.0 -> 1.2" in out.stdout  # loss first -> last
        assert "tokens/s" in out.stdout
        assert "e0=0.5" in out.stdout

    def test_report_json(self, tmp_path):
        path = self._write_log(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"), path,
             "--json"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        summary = json.loads(out.stdout)
        assert summary["steps"] == 3
        assert summary["loss"] == {"first": 2.0, "last": 1.2}

    def test_report_missing_file(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"),
             str(tmp_path / "nope.jsonl")],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 2

    def test_report_truncated_log_clear_message(self, tmp_path):
        """ISSUE 7 satellite: a step log whose writer was killed mid-line
        (or whose disk filled) gets a clear message naming the bad line
        and a nonzero exit — never a JSONDecodeError traceback."""
        path = tmp_path / "steps.jsonl"
        with StepLogWriter(str(path)) as w:
            w.write(0, loss=2.0)
            w.write(1, loss=1.5)
        with open(path, "a") as fh:
            fh.write('{"ts": 3.0, "step": 2, "lo')  # torn tail
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"),
             str(path)],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 3
        assert "truncated or corrupt" in out.stderr
        assert "line 3" in out.stderr
        assert "Traceback" not in out.stderr

    def test_report_empty_log_clear_message(self, tmp_path):
        path = tmp_path / "steps.jsonl"
        path.write_text("\n\n")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"),
             str(path)],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 3
        assert "empty" in out.stderr

    def test_read_step_log_names_bad_line(self, tmp_path):
        path = tmp_path / "steps.jsonl"
        path.write_text('{"step": 0}\nnot json\n')
        with pytest.raises(ValueError, match=r"line 2"):
            read_step_log(str(path))


# ------------------------------------------- registry thread-safety pin ----

class TestRegistryConcurrency:
    """ISSUE 7 satellite: the AsyncCheckpointer writer thread, tracker
    server handler threads, UI scrapers, and the tracer all hit one
    registry concurrently with training-loop writers. Per-instrument
    locks must make increments exact and snapshots crash-free; the
    cross-PROCESS story is isolation by design (see registry.py doc)."""

    def test_concurrent_increments_are_exact(self, lockwatch):
        # armed lockwatch (ISSUE 11): the registry's get-or-create lock is
        # a watched primitive for the whole hammering — any lock-order
        # inversion raises here instead of deadlocking a real run
        import threading

        reg = MetricsRegistry()
        threads_n, per_thread = 8, 5000

        def work(i):
            c = reg.counter("hits", {"shared": "yes"})
            g = reg.gauge("level")
            h = reg.histogram("lat_ms")
            for k in range(per_thread):
                c.inc()
                g.inc(1.0)
                h.observe(float(k % 7))

        threads = [__import__("threading").Thread(target=work, args=(i,))
                   for i in range(threads_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = threads_n * per_thread
        assert reg.counter("hits", {"shared": "yes"}).value == total
        assert reg.gauge("level").value == total
        h = reg.histogram("lat_ms")
        assert h.count == total
        snap = h.snapshot()
        assert snap["buckets"][-1]["count"] == total  # +Inf is cumulative
        watch = lockwatch.summary()
        assert watch["locks"].get("telemetry.registry", {}).get(
            "acquires", 0) > 0, "registry lock was not watched"
        assert watch["cycles"] == 0

    def test_snapshot_safe_under_concurrent_writes(self):
        import threading

        reg = MetricsRegistry()
        stop = threading.Event()
        errors = []

        def writer(i):
            c = reg.counter(f"w{i}")
            while not stop.is_set():
                c.inc()
                reg.histogram("obs").observe(1.0)

        def reader():
            try:
                while not stop.is_set():
                    snap = reg.snapshot()
                    for c in snap["counters"]:
                        assert c["value"] >= 0
                    render_prometheus(reg)  # the /metrics path too
            except Exception as exc:  # surfaced after join
                errors.append(exc)

        threads = ([threading.Thread(target=writer, args=(i,))
                    for i in range(4)]
                   + [threading.Thread(target=reader) for _ in range(2)])
        for t in threads:
            t.start()
        import time as _time

        _time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors
        # get-or-create under the registry lock: exactly one instrument
        # per (name, labels) key survived the race
        snap = reg.snapshot()
        names = [c["name"] for c in snap["counters"]]
        assert len(names) == len(set(names))


# -------------------------------------------- nonfinite flagging (ISSUE 8) ----

class TestNonfiniteReport:
    """ISSUE 8 satellite: step_log.py preserves NaN/Inf as repr strings;
    the summarizer and tools/telemetry_report.py must SHOUT about them
    (a flagged ``nonfinite`` column) instead of silently dropping them."""

    def _write_faulty_log(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=2.0, grad_norm=1.5, nonfinite=0.0, clipped=0.0)
            w.write(1, loss=float("nan"), grad_norm=float("inf"),
                    nonfinite=1.0, clipped=0.0)
            w.write(2, loss=1.8, grad_norm=1.2, nonfinite=0.0, clipped=1.0)
            w.write(3, loss=float("-inf"), grad_norm=1.1, nonfinite=1.0,
                    clipped=0.0)
        return path

    def test_summary_counts_nonfinite_values(self, tmp_path):
        path = self._write_faulty_log(tmp_path)
        summary = summarize_step_log(read_step_log(path))
        assert summary["nonfinite"] == {"loss": 2, "grad_norm": 1}
        # guard flags roll up to skipped/clipped step totals
        assert summary["skipped_steps"] == 2
        assert summary["clipped_steps"] == 1
        # finite values still summarize (the strings are excluded)
        assert summary["loss"] == {"first": 2.0, "last": 1.8}

    def test_summary_counts_raw_float_nonfinite(self):
        # records built in-process (bench detail path) carry raw floats
        summary = summarize_step_log([
            {"ts": 0.0, "step": 0, "loss": 1.0},
            {"ts": 1.0, "step": 1, "loss": float("nan")},
        ])
        assert summary["nonfinite"] == {"loss": 1}

    def test_clean_log_has_no_nonfinite_block(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=2.0)
            w.write(1, loss=1.5)
        summary = summarize_step_log(read_step_log(path))
        assert "nonfinite" not in summary
        assert "skipped_steps" not in summary

    def test_report_table_shouts(self, tmp_path):
        path = self._write_faulty_log(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"), path],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "!! NONFINITE" in out.stdout
        assert "lossx2" in out.stdout and "grad_normx1" in out.stdout
        assert "skipped_steps" in out.stdout
        assert "WARNING" in out.stdout

    def test_report_json_carries_nonfinite(self, tmp_path):
        path = self._write_faulty_log(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "telemetry_report.py"), path,
             "--json"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        summary = json.loads(out.stdout)
        assert summary["nonfinite"] == {"loss": 2, "grad_norm": 1}


class TestLockwatchReport:
    """ISSUE 11: tools/telemetry_report.py surfaces lockwatch_* hold/
    contention metrics as a table section — and stays silent when the
    log carries none."""

    def _run_report(self, path):
        import subprocess
        import sys as _sys

        out = subprocess.run(
            [_sys.executable,
             os.path.join(REPO, "tools", "telemetry_report.py"), path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        return out.stdout

    def test_lockwatch_section_rendered(self, tmp_path):
        from deeplearning4j_tpu.utils import lockwatch as lw

        lw.reset()
        lw.enable()
        try:
            lock = lw.make_lock("report.lock")
            for _ in range(3):
                with lock:
                    pass
            rec = lw.metrics_record()
        finally:
            lw.disable()
            lw.reset()
        assert rec["lockwatch_report_lock_acquires"] == 3
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0)
            w.write(1, loss=0.5, **rec)
        summary = summarize_step_log(read_step_log(path))
        assert summary["lockwatch"]["lockwatch_report_lock_acquires"] == 3
        text = self._run_report(path)
        assert "lockwatch (per watched lock)" in text
        assert "report_lock" in text

    def test_silent_without_lockwatch_metrics(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0)
            w.write(1, loss=0.5)
        assert "lockwatch" not in summarize_step_log(read_step_log(path))
        assert "lockwatch (per watched lock)" not in self._run_report(path)


class TestNetwatchReport:
    """ISSUE 18: tools/telemetry_report.py surfaces netwatch_*
    per-endpoint socket-watch counters as a table section — and stays
    silent when the log carries none. Pinned off a REAL watched
    socketpair so the rendered names are the ones metrics_record()
    actually emits."""

    def _run_report(self, path):
        import subprocess
        import sys as _sys

        out = subprocess.run(
            [_sys.executable,
             os.path.join(REPO, "tools", "telemetry_report.py"), path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        return out.stdout

    def test_netwatch_section_rendered(self, tmp_path):
        import socket

        from deeplearning4j_tpu.utils import netwatch as nw

        nw.reset()
        nw.enable(registry=MetricsRegistry())
        try:
            a, b = socket.socketpair()
            wa = nw.wrap_socket(a, "report.peer")
            b.sendall(b"x")
            assert wa.recv(1) == b"x"
            nw.record_retry("report.peer")
            nw.record_reconnect("report.peer")
            rec = nw.metrics_record()
        finally:
            a.close()
            b.close()
            nw.disable()
            nw.reset()
        assert rec["netwatch_report_peer_ops"] == 1
        assert rec["netwatch_report_peer_retries"] == 1
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0)
            w.write(1, loss=0.5, **rec)
        summary = summarize_step_log(read_step_log(path))
        assert summary["netwatch"]["netwatch_report_peer_ops"] == 1
        text = self._run_report(path)
        assert "netwatch (per watched endpoint)" in text
        assert "report_peer" in text
        # meta pin: every stat metrics_record() flattens for an endpoint
        # has a column in the table, so a record can't ship unrendered
        header = text.split("netwatch (per watched endpoint)\n")[1]
        for stat in ("ops", "timeouts", "reconnects", "retries",
                     "wait"):
            assert stat in header.splitlines()[0], stat

    def test_silent_without_netwatch_metrics(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0)
            w.write(1, loss=0.5)
        assert "netwatch" not in summarize_step_log(read_step_log(path))
        assert ("netwatch (per watched endpoint)"
                not in self._run_report(path))


class TestServeFederationReport:
    """ISSUE 12 satellite + meta-test: every ``serve_*`` and
    ``federation_*`` registry metric name is rendered by
    tools/telemetry_report.py, silent-when-absent pinned both ways —
    riding the ISSUE 11 lockwatch pattern, so a future metric under
    either prefix can't ship unrendered (registry.flat_record is the one
    flattening every metrics_record() goes through)."""

    def _run_report(self, path):
        import subprocess
        import sys as _sys

        out = subprocess.run(
            [_sys.executable,
             os.path.join(REPO, "tools", "telemetry_report.py"), path],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        return out.stdout

    def _registry_names(self, registry, prefix):
        snap = registry.snapshot()
        return {r["name"] for kind in ("counters", "gauges", "histograms")
                for r in snap[kind] if r["name"].startswith(prefix)}

    def test_wall_ms_summary_includes_p99(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            for i in range(100):
                w.write(i, wall_ms=float(i + 1))
        s = summarize_step_log(read_step_log(path))
        assert s["wall_ms"]["p50"] == 50.0
        assert s["wall_ms"]["p95"] == 95.0
        assert s["wall_ms"]["p99"] == 99.0
        assert "p50 / p95 / p99 / mean" in self._run_report(path)

    def test_meta_every_serve_metric_rendered(self, tmp_path):
        """Exercise a REAL engine, take its live registry names, and pin
        each one into the rendered report output."""
        import jax

        from deeplearning4j_tpu.models.transformer_lm import init_lm_params
        from deeplearning4j_tpu.serve import DecodeEngine

        reg = MetricsRegistry()
        params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                                n_layers=1)
        eng = DecodeEngine(params, 2, n_slots=1, max_len=16,
                           serve_dtype=None, registry=reg)
        eng.generate([1, 2, 3], max_new_tokens=2)
        names = self._registry_names(reg, "serve_")
        assert names  # the engine really registered serve metrics
        rec = eng.metrics_record()
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0, **rec)
        summary = summarize_step_log(read_step_log(path))
        text = self._run_report(path)
        assert "serve metrics (registry)" in text
        for name in sorted(names):
            assert (name in summary["serve"]
                    or f"{name}_count" in summary["serve"]), name
            assert name in text, f"{name} not rendered by telemetry_report"

    def test_meta_every_federation_metric_rendered(self, tmp_path):
        from deeplearning4j_tpu.scaleout.statetracker import (
            InMemoryStateTracker,
        )
        from deeplearning4j_tpu.telemetry.federation import (
            ClusterAggregator,
            MetricsPusher,
        )

        tracker = InMemoryStateTracker()
        reg = MetricsRegistry()
        reg.counter("serve_requests_total").inc()
        pusher = MetricsPusher(tracker, "p0", registry=reg)
        pusher.push_once()
        agg = ClusterAggregator(tracker, registry=reg)
        agg.collect()
        names = self._registry_names(reg, "federation_")
        assert names
        rec = agg.metrics_record()
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0, **rec)
        summary = summarize_step_log(read_step_log(path))
        text = self._run_report(path)
        assert "federation metrics (registry)" in text
        for name in sorted(names):
            assert (name in summary["federation"]
                    or f"{name}_count" in summary["federation"]), name
            assert name in text, f"{name} not rendered by telemetry_report"

    def test_meta_every_alerts_and_history_metric_rendered(self, tmp_path):
        """ISSUE 15: the watchtower's own health metrics (``alerts_*``
        from a live AlertEngine, ``history_*`` from a live
        MetricsHistory) render through the report, pinned off the REAL
        registry names so a new watch metric can't ship unrendered."""
        from deeplearning4j_tpu.telemetry.alerts import AlertEngine
        from deeplearning4j_tpu.telemetry.history import MetricsHistory

        reg = MetricsRegistry()
        reg.counter("guard_skipped_steps_total").inc(0)
        history = MetricsHistory(registry=reg)
        engine = AlertEngine(history, registry=reg, process="meta")
        history.sample_once(now=1000.0)
        reg.counter("guard_skipped_steps_total").inc(2)
        history.sample_once(now=1010.0)
        engine.evaluate_once(now=1010.0, publish=False)
        rec = dict(history.metrics_record(), **engine.metrics_record())
        for prefix, block in (("alerts_", "alerts"),
                              ("history_", "history")):
            names = self._registry_names(reg, prefix)
            assert names
            path = str(tmp_path / f"steps_{block}.jsonl")
            with StepLogWriter(path) as w:
                w.write(0, loss=1.0, **rec)
            summary = summarize_step_log(read_step_log(path))
            text = self._run_report(path)
            title = ("alert metrics (registry)" if block == "alerts"
                     else "history metrics (registry)")
            assert title in text
            for name in sorted(names):
                assert (name in summary[block]
                        or f"{name}_count" in summary[block]), name
                assert name in text, \
                    f"{name} not rendered by telemetry_report"

    def test_silent_without_serve_or_federation_metrics(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        with StepLogWriter(path) as w:
            w.write(0, loss=1.0)
            w.write(1, loss=0.5)
        summary = summarize_step_log(read_step_log(path))
        assert "serve" not in summary and "federation" not in summary
        for key in ("alerts", "history"):
            assert key not in summary
        text = self._run_report(path)
        assert "serve metrics" not in text
        assert "federation metrics" not in text
        assert "alert metrics" not in text
        assert "history metrics" not in text
