"""Test configuration: force an 8-device CPU platform so multi-chip sharding
paths are exercised without TPU hardware (the strategy SURVEY.md §4 calls for:
in-process fakes, like the reference's embedded-Hazelcast / Spark local[8]
harnesses).

Note: the ambient sitecustomize registers the axon TPU plugin and pins
``jax_platforms`` programmatically, so env vars alone don't stick — the
override must go through jax.config before first backend use.
"""

import jax
import pytest

from deeplearning4j_tpu.compat import set_host_device_count

jax.config.update("jax_platforms", "cpu")
set_host_device_count(8)


@pytest.fixture
def lockwatch():
    """The utils.lockwatch runtime lock-order watchdog, armed for the
    test: every lock created through the seam (DecodeEngine scheduler,
    AsyncCheckpointer error lock, tracker client/state, registry, tracer,
    profile store/sampler) becomes a watched primitive — acquisition
    order feeds the cycle detector (raise armed: an order inversion fails
    the test at the acquire, not as a hang), wait/hold land in
    ``lockwatch_*`` registry metrics, and an acquire blocked past the
    watchdog threshold dumps all thread stacks through the flight
    recorder. Yields the module; ``lockwatch.summary()`` for assertions."""
    from deeplearning4j_tpu.utils import lockwatch as lw

    lw.reset()
    lw.enable(raise_on_cycle=True, watchdog_s=20.0)
    try:
        yield lw
    finally:
        lw.disable()
        lw.reset()


@pytest.fixture
def retrace_budget():
    """The utils.retrace_guard context manager as a fixture: pin a region's
    XLA compile budget with ``with retrace_budget(0, label="..."): ...`` —
    any retrace beyond the budget fails the test (shape/weak-type drift
    can never silently recompile a warmed step per call again)."""
    from deeplearning4j_tpu.utils.retrace_guard import retrace_guard

    return retrace_guard
