"""Test configuration: force an 8-device CPU platform so multi-chip sharding
paths are exercised without TPU hardware (the strategy SURVEY.md §4 calls for:
in-process fakes, like the reference's embedded-Hazelcast / Spark local[8]
harnesses).

Note: the ambient sitecustomize registers the axon TPU plugin and pins
``jax_platforms`` programmatically, so env vars alone don't stick — the
override must go through jax.config before first backend use.
"""

import jax

from deeplearning4j_tpu.compat import set_host_device_count

jax.config.update("jax_platforms", "cpu")
set_host_device_count(8)
