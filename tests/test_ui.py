"""UI server tests (ref: deeplearning4j-ui resources — nearest neighbours,
tsne coords, weights)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import UiServer


@pytest.fixture
def server(tmp_path):
    s = UiServer(artifact_dir=str(tmp_path))
    (tmp_path / "w.svg").write_text("<svg></svg>")
    words = ["king", "queen", "apple", "banana"]
    vecs = np.array([[1, 0.9, 0], [0.9, 1, 0], [0, 0, 1], [0, 0.1, 1]], float)
    s.upload_word_vectors(words, vecs)
    s.upload_tsne(np.array([[0.0, 1.0], [1.0, 0.0]]), ["a", "b"])
    s.upload_weight_histograms({"layer0_W": {"counts": [1, 2]}})
    s.start(port=0)
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{server.port}{path}") as r:
        return r.status, r.read()


def test_index(server):
    status, body = _get(server, "/")
    assert status == 200 and b"deeplearning4j-tpu" in body


def test_words_endpoint(server):
    status, body = _get(server, "/api/words")
    data = json.loads(body)
    assert data["count"] == 4 and "king" in data["words"]


def test_nearest_neighbours(server):
    _, body = _get(server, "/api/nearest?word=king&n=2")
    data = json.loads(body)
    names = [h["word"] for h in data["neighbours"]]
    assert names[0] == "queen"
    assert "king" not in names


def test_nearest_unknown_word(server):
    _, body = _get(server, "/api/nearest?word=zzz")
    assert json.loads(body)["neighbours"] == []


def test_tsne_and_weights(server):
    _, body = _get(server, "/api/tsne")
    assert json.loads(body)["labels"] == ["a", "b"]
    _, body = _get(server, "/api/weights")
    assert "layer0_W" in json.loads(body)


def test_artifact_listing_and_file(server):
    _, body = _get(server, "/artifacts/")
    assert b"w.svg" in body
    status, body = _get(server, "/artifacts/w.svg")
    assert status == 200 and body == b"<svg></svg>"


def test_artifact_traversal_blocked(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/artifacts/../../etc/passwd")
    assert e.value.code == 404


def test_unknown_route_404(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/nope")
    assert e.value.code == 404


def test_render_views_serve_html(server):
    """The render pages (ref: deeplearning4j-ui webapp assets) are served as
    self-contained HTML that fetches the matching /api endpoint."""
    for path, marker in [("/render/tsne", b"/api/tsne"),
                         ("/render/weights", b"/api/weights"),
                         ("/render/words", b"/api/nearest")]:
        status, body = _get(server, path)
        assert status == 200
        assert body.startswith(b"<!doctype html>")
        assert marker in body and b"<script>" in body


def test_filters_and_activations_from_trained_conv_net():
    """/render/filters and /render/activations serve artifacts extracted
    from an ACTUAL training run on a conv net (ref: FilterRenderer.java +
    NeuralNetPlotter.plotActivations feeding the webapp)."""
    from deeplearning4j_tpu.models.zoo import digits_conv
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.RandomState(3)
    x = rng.rand(16, 64).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 16)]
    net = MultiLayerNetwork(digits_conv(num_iterations=2)).init()
    net.fit(x, y)

    s = UiServer()
    s.upload_filters(net)
    s.upload_activations(net, x[:8])
    s.start(port=0)
    try:
        _, body = _get(s, "/api/filters")
        grids = json.loads(body)["grids"]
        assert grids, "no filter grids extracted"
        conv = grids[0]
        assert conv["name"] == "layer0/convweights"
        assert conv["width"] == 3 and conv["height"] == 3
        assert len(conv["tiles"]) == 16
        flat = [v for t in conv["tiles"] for row in t for v in row]
        assert max(flat) <= 1.0 and min(flat) >= 0.0

        _, body = _get(s, "/api/activations")
        layers = json.loads(body)["layers"]
        assert len(layers) >= 4  # conv, pool, dense, output
        assert layers[0]["rows"] == 8
        assert all(np.isfinite(L["mean"]) for L in layers)

        for path in ("/render/filters", "/render/activations"):
            status, body = _get(s, path)
            assert status == 200 and b"<script>" in body
    finally:
        s.stop()


def test_mlp_first_layer_filters_square_input():
    """A square-input dense first layer renders per-unit weight images
    (ref: FilterRenderer on RBM/dense W columns)."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ui.views import filter_grids

    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(64).n_out(12).activation_function("tanh").list(2)
        .override(1, layer_type="OUTPUT", n_in=12, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True).build()
    )
    net = MultiLayerNetwork(conf).init()
    grids = filter_grids(net)
    assert grids and grids[0]["name"] == "layer0/W"
    assert grids[0]["width"] == 8 and len(grids[0]["tiles"]) == 12


def test_tsne_view_has_pan_zoom(server):
    _, body = _get(server, "/render/tsne")
    assert b"viewBox" in body and b"wheel" in body and b"dblclick" in body


def test_weight_histograms_helper():
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.ui.views import weight_histograms

    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(3).activation_function("tanh").list(1)
        .override(0, layer_type="OUTPUT", activation_function="softmax",
                  loss_function="MCXENT")
        .pretrain(False).backward(True).build()
    )
    net = MultiLayerNetwork(conf).init()
    hists = weight_histograms(net, bins=10)
    assert "layer0/W" in hists and "layer0/b" in hists
    h = hists["layer0/W"]
    assert len(h["counts"]) == 10 and len(h["edges"]) == 11
    assert sum(h["counts"]) == 4 * 3


def _post(server, path, body: bytes, ctype="application/json"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}", data=body,
        headers={"Content-Type": ctype})
    with urllib.request.urlopen(req) as r:
        return r.status, r.read()


@pytest.fixture
def lm_engine():
    import jax

    from deeplearning4j_tpu.models.transformer_lm import init_lm_params
    from deeplearning4j_tpu.serve import DecodeEngine

    params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                            n_layers=1)
    return DecodeEngine(params, 2, n_slots=2, max_len=16, serve_dtype=None)


def test_api_generate_post_and_serve_stats(server, lm_engine):
    """ISSUE 10: POST /api/generate submits through the decode engine;
    GET /api/serve snapshots scheduler stats."""
    server.attach_engine(lm_engine)
    status, body = _post(server, "/api/generate",
                         json.dumps({"prompt": [1, 2, 3],
                                     "max_new_tokens": 4}).encode())
    assert status == 200
    out = json.loads(body)
    assert len(out["tokens"]) == out["n"] == 4
    assert out["prompt_len"] == 3
    assert all(0 <= t < 31 for t in out["tokens"])

    status, body = _get(server, "/api/serve")
    assert status == 200
    stats = json.loads(body)
    assert stats["slots"] == 2
    assert stats["tokens_total"] == 4
    assert stats["requests_total"] == 1
    assert stats["queue_depth"] == 0


def test_api_generate_without_engine_404(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/api/generate", b"{}")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/api/serve")
    assert e.value.code == 404


def test_post_error_handling(server, lm_engine):
    """ISSUE 10 satellite: do_POST's content-length/JSON error handling —
    each bad request gets a specific 4xx, never a hang or a 500."""
    server.attach_engine(lm_engine)
    # invalid JSON → 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/api/generate", b"{not json")
    assert e.value.code == 400
    # non-object body → 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/api/generate", b"[1,2]")
    assert e.value.code == 400
    # missing/invalid prompt → 400
    for bad in ({}, {"prompt": []}, {"prompt": "abc"},
                {"prompt": [1, "x"]}, {"prompt": [True]}):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(server, "/api/generate", json.dumps(bad).encode())
        assert e.value.code == 400, bad
    # engine-side validation (token id out of vocab) → 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/api/generate",
              json.dumps({"prompt": [500]}).encode())
    assert e.value.code == 400
    # bad knob types → 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/api/generate",
              json.dumps({"prompt": [1], "max_new_tokens": "many"}).encode())
    assert e.value.code == 400
    # unknown POST route → 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/api/nearest", b"{}")
    assert e.value.code == 404


def test_post_missing_content_length_411(server, lm_engine):
    """A POST without Content-Length is answered 411, not read forever.
    urllib always sets the header, so speak http.client directly."""
    import http.client

    server.attach_engine(lm_engine)
    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
    try:
        conn.putrequest("POST", "/api/generate", skip_host=False)
        conn.putheader("Content-Type", "application/json")
        conn.endheaders()  # no Content-Length, no body
        resp = conn.getresponse()
        assert resp.status == 411
        assert b"Content-Length" in resp.read()
    finally:
        conn.close()


def test_api_generate_concurrent_requests_share_slots(server, lm_engine):
    """Two handler threads generating concurrently ride the continuous-
    batching loop (engine background thread) and both complete."""
    import threading

    lm_engine.start()
    try:
        server.attach_engine(lm_engine)
        results = [None, None]

        def fire(i):
            _, body = _post(server, "/api/generate",
                            json.dumps({"prompt": [1 + i, 2],
                                        "max_new_tokens": 3}).encode())
            results[i] = json.loads(body)

        ts = [threading.Thread(target=fire, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert all(r is not None and r["n"] == 3 for r in results)
    finally:
        lm_engine.stop()


def test_api_trace_endpoint(server, tmp_path):
    """ISSUE 7: /api/trace serves the attached tracer's flight-recorder
    ring — open spans with elapsed durations + recent ended spans — and
    404s cleanly when no tracer is attached anywhere."""
    from deeplearning4j_tpu.telemetry import trace as tr
    from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

    prev = tr.set_tracer(None)
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server, "/api/trace")
        assert exc.value.code == 404

        tracer = tr.Tracer("ui-proc", trace_dir=str(tmp_path / "trace"),
                           registry=MetricsRegistry())
        server.attach_tracer(tracer)
        with tracer.span("finished-op", attrs={"round": 1}):
            pass
        open_span = tracer.start_span("live-op", attrs={"round": 2})
        status, body = _get(server, "/api/trace")
        assert status == 200
        snap = json.loads(body)
        assert snap["process"] == "ui-proc"
        assert [s["name"] for s in snap["open"]] == ["live-op"]
        assert snap["open"][0]["dur_ms"] >= 0
        assert any(r["name"] == "finished-op" for r in snap["recent"])
        open_span.end()

        status, body = _get(server, "/api/trace?limit=1")
        assert len(json.loads(body)["recent"]) == 1
    finally:
        tr.set_tracer(prev)


# ------------------------------------ traceparent propagation (ISSUE 12) ----

class TestTraceparentPropagation:
    """Raw http.client POSTs (full header control) pinning the W3C
    propagation contract of /api/generate: an inbound traceparent
    parents the handler span (and the engine's serve.request under it),
    the response carries the trace id both as JSON and as a traceparent
    header, a malformed header is TOLERATED (the request succeeds as a
    fresh root — never a 400), and with tracing off nothing changes."""

    @pytest.fixture
    def tracer(self, tmp_path):
        from deeplearning4j_tpu.telemetry import trace as tr

        tracer = tr.Tracer("ui-test", trace_dir=str(tmp_path / "trace"))
        prev = tr.set_tracer(tracer)
        yield tracer
        tr.set_tracer(prev)
        tracer.close()

    def _post_raw(self, server, body: bytes, headers: dict):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=30)
        try:
            all_headers = {"Content-Type": "application/json",
                           "Content-Length": str(len(body)), **headers}
            conn.request("POST", "/api/generate", body=body,
                         headers=all_headers)
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), resp.read()
        finally:
            conn.close()

    def _spans(self, tracer):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from tools.trace_report import load_trace_dir

        return load_trace_dir(os.path.dirname(tracer.path))

    def test_inbound_traceparent_parents_the_tree(self, server, lm_engine,
                                                  tracer):
        server.attach_engine(lm_engine)
        caller_trace, caller_span = "ab" * 16, "cd" * 8
        hdr = f"00-{caller_trace}-{caller_span}-01"
        status, headers, body = self._post_raw(
            server, json.dumps({"prompt": [1, 2], "max_new_tokens": 2}
                               ).encode(), {"traceparent": hdr})
        assert status == 200
        out = json.loads(body)
        # the response carries the CALLER's trace id (JSON + header)
        assert out["trace_id"] == caller_trace
        resp_tp = {k.lower(): v for k, v in headers.items()}["traceparent"]
        assert resp_tp.startswith(f"00-{caller_trace}-")
        spans = self._spans(tracer)
        http = [sp for sp in spans.values()
                if sp["name"] == "http.request"][0]
        assert http["trace_id"] == caller_trace
        assert http["parent_id"] == caller_span
        assert http["attrs"]["remote_trace"] is True
        sreq = [sp for sp in spans.values()
                if sp["name"] == "serve.request"][0]
        assert sreq["trace_id"] == caller_trace
        assert sreq["parent_id"] == http["span_id"]

    def test_without_traceparent_fresh_root(self, server, lm_engine,
                                            tracer):
        server.attach_engine(lm_engine)
        status, headers, body = self._post_raw(
            server, json.dumps({"prompt": [1], "max_new_tokens": 2}
                               ).encode(), {})
        assert status == 200
        out = json.loads(body)
        assert len(out["trace_id"]) == 32  # fresh W3C-width root
        spans = self._spans(tracer)
        http = [sp for sp in spans.values()
                if sp["name"] == "http.request"][0]
        assert http["parent_id"] is None
        assert http["attrs"]["remote_trace"] is False

    def test_malformed_traceparent_tolerated_not_400(self, server,
                                                     lm_engine, tracer):
        server.attach_engine(lm_engine)
        for bad in ("garbage", "00-zz-xx-01",
                    "00-" + "0" * 32 + "-" + "1" * 16 + "-01"):
            status, _headers, body = self._post_raw(
                server, json.dumps({"prompt": [1], "max_new_tokens": 1}
                                   ).encode(), {"traceparent": bad})
            assert status == 200, bad  # ignored per W3C, never rejected
            out = json.loads(body)
            assert out["trace_id"] not in bad
            assert out["n"] == 1

    def test_tracing_off_no_trace_fields(self, server, lm_engine):
        from deeplearning4j_tpu.telemetry import trace as tr

        assert tr.get_tracer() is None
        server.attach_engine(lm_engine)
        status, headers, body = self._post_raw(
            server, json.dumps({"prompt": [1], "max_new_tokens": 1}
                               ).encode(),
            {"traceparent": "00-" + "a" * 32 + "-" + "b" * 16 + "-01"})
        assert status == 200
        assert "trace_id" not in json.loads(body)
        assert "traceparent" not in {k.lower() for k in headers}


def test_api_serve_exposes_in_flight_ages(server, lm_engine):
    """ISSUE 12 satellite: /api/serve shows per-request queued_s /
    running_s / tokens so a stuck request is visible from the UI."""
    server.attach_engine(lm_engine)
    req = lm_engine.submit([1, 2, 3], max_new_tokens=8)
    _, body = _get(server, "/api/serve")
    stats = json.loads(body)
    flight = stats["in_flight"]
    assert len(flight) == 1
    assert flight[0]["rid"] == req.rid
    assert flight[0]["state"] == "queued"
    assert flight[0]["queued_s"] >= 0.0
    assert flight[0]["prompt_len"] == 3
    lm_engine.run_until_idle()
    _, body = _get(server, "/api/serve")
    assert json.loads(body)["in_flight"] == []
