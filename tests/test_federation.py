"""ISSUE 12: tracker-federated cluster metrics (telemetry/federation.py).

Merge semantics pinned both as pure functions (counter sum, gauge
per-process labeling, histogram bucket-merge incl. the union-of-bounds
fallback) AND against two live registries pushed through the real TCP
tracker (StateTrackerServer + two StateTrackerClients), with staleness
marking for a pusher whose heartbeat lapsed. The UI surface
(``/api/cluster``, ``/metrics?scope=cluster``) rides the same live pair.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.scaleout.remote_tracker import (
    StateTrackerClient,
    StateTrackerServer,
)
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.telemetry.federation import (
    KV_PREFIX,
    SCHEMA,
    ClusterAggregator,
    MetricsPusher,
    merge_snapshots,
)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry


def _registry(n_reqs: int, queue_depth: float, obs) -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("serve_requests_total").inc(n_reqs)
    reg.counter("serve_completed_total", {"reason": "eos"}).inc(n_reqs)
    reg.gauge("serve_queue_depth").set(queue_depth)
    for v in obs:
        reg.histogram("serve_request_ms").observe(v)
    return reg


# ------------------------------------------------------- merge semantics ----

class TestMergeSnapshots:
    def test_counters_sum_per_name_and_labels(self):
        a, b = _registry(3, 0, []), _registry(4, 0, [])
        b.counter("serve_requests_total").inc(10)  # b: 14 total
        merged = merge_snapshots([("a", a.snapshot()), ("b", b.snapshot())])
        rows = {(r["name"], tuple(sorted(r["labels"].items()))): r["value"]
                for r in merged["counters"]}
        assert rows[("serve_requests_total", ())] == 17.0
        # labeled counters sum per (name, labels), labels preserved
        assert rows[("serve_completed_total",
                     (("reason", "eos"),))] == 7.0

    def test_gauges_stay_per_process(self):
        a, b = _registry(0, 2.0, []), _registry(0, 7.0, [])
        merged = merge_snapshots([("a", a.snapshot()), ("b", b.snapshot())])
        rows = {(r["name"], r["labels"].get("process")): r["value"]
                for r in merged["gauges"]}
        # NOT averaged/overwritten: one labeled series per process — the
        # router signal (which replica is loaded) survives the merge
        assert rows[("serve_queue_depth", "a")] == 2.0
        assert rows[("serve_queue_depth", "b")] == 7.0

    def test_histograms_bucket_merge_exact_on_identical_bounds(self):
        a = _registry(0, 0, [3.0, 40.0])
        b = _registry(0, 0, [700.0])
        merged = merge_snapshots([("a", a.snapshot()), ("b", b.snapshot())])
        h = [r for r in merged["histograms"]
             if r["name"] == "serve_request_ms"][0]
        assert h["count"] == 3 and h["sum"] == 743.0
        by_le = {x["le"]: x["count"] for x in h["buckets"]}
        assert by_le[5.0] == 1       # only a's 3.0
        assert by_le[50.0] == 2      # a's two
        assert by_le[1000.0] == 3    # everything
        assert by_le[float("inf")] == 3

    def test_histogram_union_bounds_lower_bound_semantics(self):
        """Mismatched bounds merge over the union; a source without a
        bound contributes its cumulative count at its largest bound ≤ it
        (documented lower bound, never an invented observation)."""
        a, b = MetricsRegistry(), MetricsRegistry()
        ha = a.histogram("m", buckets=(10.0, 100.0))
        hb = b.histogram("m", buckets=(50.0,))
        ha.observe(5.0), ha.observe(60.0)
        hb.observe(20.0)
        merged = merge_snapshots([("a", a.snapshot()), ("b", b.snapshot())])
        h = merged["histograms"][0]
        by_le = {x["le"]: x["count"] for x in h["buckets"]}
        # union of bounds {10, 50, 100, inf}
        assert by_le[10.0] == 1      # a's 5.0; b has no bound ≤ 10 → 0
        assert by_le[50.0] == 2      # a cum@10 (1) + b cum@50 (1)
        assert by_le[100.0] == 3
        assert h["count"] == 3 and h["sum"] == 85.0

    def test_empty_merge(self):
        merged = merge_snapshots([])
        assert merged == {"counters": [], "gauges": [], "histograms": []}


# ------------------------------------------- live push → aggregate (TCP) ----

class TestLiveFederation:
    def test_two_live_pushed_registries_merge_and_staleness(self):
        """Acceptance: /api/cluster-grade aggregation of ≥2 live
        processes' registries with correct counter-sum / histogram-merge
        semantics, and a lapsed pusher marked stale while its last-known
        data stays in the merge."""
        with StateTrackerServer() as server:
            c1 = StateTrackerClient(server.address)
            c2 = StateTrackerClient(server.address)
            r1 = _registry(3, 2.0, [3.0])
            r2 = _registry(4, 7.0, [700.0])
            p1 = MetricsPusher(c1, "replica-0", registry=r1)
            p2 = MetricsPusher(c2, "replica-1", registry=r2)
            assert p1.push_once() and p2.push_once()
            agg = ClusterAggregator(server.tracker, stale_after_s=0.3,
                                    registry=MetricsRegistry())
            view = agg.collect()
            assert view["schema"] == SCHEMA
            procs = {p["process"]: p for p in view["processes"]}
            assert sorted(procs) == ["replica-0", "replica-1"]
            assert not any(p["stale"] for p in procs.values())
            counters = {r["name"]: r["value"]
                        for r in view["merged"]["counters"]
                        if not r["labels"]}
            assert counters["serve_requests_total"] == 7.0
            # the pusher's own health metrics federate too (a payload
            # reflects the counters as of its snapshot, so push #2 is
            # the first to carry pushes_total=1)
            assert p1.push_once()
            counters2 = {r["name"]: r["value"]
                         for r in agg.collect()["merged"]["counters"]
                         if not r["labels"]}
            assert counters2["federation_pushes_total"] == 1.0
            h = [r for r in view["merged"]["histograms"]
                 if r["name"] == "serve_request_ms"][0]
            assert h["count"] == 2 and h["sum"] == 703.0
            gauges = {(r["name"], r["labels"].get("process")): r["value"]
                      for r in view["merged"]["gauges"]}
            assert gauges[("serve_queue_depth", "replica-0")] == 2.0
            assert gauges[("serve_queue_depth", "replica-1")] == 7.0
            # replica-0's heartbeat lapses; replica-1 keeps pushing
            time.sleep(0.35)
            p2.push_once()
            view = agg.collect()
            procs = {p["process"]: p for p in view["processes"]}
            assert procs["replica-0"]["stale"] is True
            assert procs["replica-1"]["stale"] is False
            # stale ≠ dropped: the last-known counters still merge
            counters = {r["name"]: r["value"]
                        for r in view["merged"]["counters"]
                        if not r["labels"]}
            assert counters["serve_requests_total"] == 7.0
            assert agg.registry.gauge("federation_stale_processes").value \
                == 1.0
            rec = agg.metrics_record()
            assert rec["federation_collects_total"] == 3.0
            assert rec["federation_processes"] == 2.0
            c1.close(), c2.close()

    def test_pusher_background_thread_cadence_and_clean_stop(self):
        tracker = InMemoryStateTracker()
        reg = MetricsRegistry()
        reg.counter("serve_tokens_total").inc(5)
        before = threading.active_count()
        pusher = MetricsPusher(tracker, "bg", registry=reg,
                               interval_s=0.02)
        with pusher:
            deadline = time.time() + 5.0
            while (reg.counter("federation_pushes_total").value < 3
                   and time.time() < deadline):
                time.sleep(0.01)
        assert reg.counter("federation_pushes_total").value >= 3
        assert threading.active_count() == before  # joined, not leaked
        payload = json.loads(tracker.get_kv(KV_PREFIX + "bg"))
        assert payload["schema"] == SCHEMA and payload["process"] == "bg"
        assert payload["seq"] >= 2  # monotone versioning
        # stop() flushed a final push after the thread joined
        counters = {r["name"]: r["value"]
                    for r in payload["snapshot"]["counters"]}
        assert counters["serve_tokens_total"] == 5.0
        # idempotent stop / restartable start
        pusher.stop()
        pusher.start()
        pusher.stop()
        assert threading.active_count() == before

    def test_push_failure_absorbed_and_counted(self):
        class DeadTracker:
            def put_kv(self, key, value):
                raise ConnectionError("tracker down")

        reg = MetricsRegistry()
        pusher = MetricsPusher(DeadTracker(), "sad", registry=reg)
        assert pusher.push_once() is False
        assert reg.counter("federation_push_failures_total").value == 1.0
        assert reg.gauge("federation_last_push_error").value == 1.0

    def test_bad_payloads_skipped_and_counted(self):
        tracker = InMemoryStateTracker()
        tracker.put_kv(KV_PREFIX + "broken", "{not json")
        tracker.put_kv(KV_PREFIX + "wrong-schema",
                       json.dumps({"schema": "v999", "ts": time.time()}))
        reg = _registry(1, 0, [])
        MetricsPusher(tracker, "good", registry=reg).push_once()
        agg = ClusterAggregator(tracker, registry=MetricsRegistry())
        view = agg.collect()
        assert [p["process"] for p in view["processes"]] == ["good"]
        assert agg.registry.counter(
            "federation_bad_payloads_total").value == 2.0

    def test_kv_store_over_the_wire(self):
        """The tracker KV extension itself: last-write-wins, prefix
        snapshot, retry-safe idempotent classification."""
        from deeplearning4j_tpu.scaleout.remote_tracker import _IDEMPOTENT

        assert {"put_kv", "get_kv", "kv_snapshot"} <= _IDEMPOTENT
        with StateTrackerServer() as server:
            client = StateTrackerClient(server.address)
            client.put_kv("a.x", "1")
            client.put_kv("a.x", "2")  # last write wins
            client.put_kv("a.y", "3")
            client.put_kv("b.z", "4")
            assert client.get_kv("a.x") == "2"
            assert client.get_kv("missing") is None
            assert client.get_kv("missing", "dflt") == "dflt"
            assert client.kv_snapshot("a.") == {"a.x": "2", "a.y": "3"}
            assert sorted(client.kv_snapshot()) == ["a.x", "a.y", "b.z"]
            client.close()


# -------------------------------------------------------- UI surface ----

class TestClusterUi:
    @pytest.fixture
    def cluster(self):
        from deeplearning4j_tpu.ui import UiServer

        tracker = InMemoryStateTracker()
        MetricsPusher(tracker, "replica-0",
                      registry=_registry(3, 2.0, [3.0])).push_once()
        MetricsPusher(tracker, "replica-1",
                      registry=_registry(4, 7.0, [700.0])).push_once()
        agg = ClusterAggregator(tracker, stale_after_s=60.0,
                                registry=MetricsRegistry())
        server = UiServer()
        server.attach_federation(agg)
        server.start(port=0)
        yield server
        server.stop()

    def _get(self, server, path):
        url = f"http://127.0.0.1:{server.port}{path}"
        with urllib.request.urlopen(url) as r:
            return r.status, r.read()

    def test_api_cluster_merges_live_processes(self, cluster):
        status, body = self._get(cluster, "/api/cluster")
        assert status == 200
        view = json.loads(body)
        assert len(view["processes"]) == 2
        assert not any(p["stale"] for p in view["processes"])
        counters = {r["name"]: r["value"]
                    for r in view["merged"]["counters"] if not r["labels"]}
        assert counters["serve_requests_total"] == 7.0

    def test_metrics_cluster_scope_prometheus(self, cluster):
        status, body = self._get(cluster, "/metrics?scope=cluster")
        text = body.decode()
        assert status == 200
        assert "serve_requests_total 7" in text
        assert 'serve_queue_depth{process="replica-0"} 2' in text
        assert 'federation_process_up{process="replica-1"} 1' in text
        assert "# TYPE serve_request_ms histogram" in text

    def test_metrics_unknown_scope_400(self, cluster):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(cluster, "/metrics?scope=galaxy")
        assert e.value.code == 400

    def test_api_cluster_404_without_aggregator(self):
        from deeplearning4j_tpu.ui import UiServer

        server = UiServer()
        server.start(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                self._get(server, "/api/cluster")
            assert e.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as e:
                self._get(server, "/metrics?scope=cluster")
            assert e.value.code == 404
        finally:
            server.stop()
