"""ISSUE 18 runtime half: the utils.netwatch socket watchdog — the
dynamic twin of the graftlint net rules. Pins the seam's zero-cost
unarmed contract, the enforced default timeout, per-endpoint counters,
and the blocked-too-long flight-recorder dump."""

import json
import os
import socket
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry  # noqa: E402
from deeplearning4j_tpu.utils import netwatch as nw  # noqa: E402


@pytest.fixture
def netwatch():
    nw.reset()
    nw.enable(default_timeout_s=0.5, watchdog_s=0.15,
              registry=MetricsRegistry())
    yield nw
    nw.disable()
    nw.reset()


# ---------------------------------------------------------------- seam ----

def test_seam_hands_out_plain_socket_when_off():
    assert not nw.enabled()
    sock = nw.make_socket("off.ep")
    try:
        assert type(sock) is socket.socket
    finally:
        sock.close()


def test_wrap_is_identity_when_off():
    a, b = socket.socketpair()
    try:
        assert nw.wrap_socket(a, "off.ep") is a
    finally:
        a.close()
        b.close()


def test_seam_hands_out_watched_socket_when_armed(netwatch):
    sock = nw.make_socket("on.ep")
    try:
        assert isinstance(sock, nw.WatchedSocket)
    finally:
        sock.close()


def test_wrap_adopts_and_is_idempotent(netwatch):
    a, b = socket.socketpair()
    try:
        w = nw.wrap_socket(a, "wrap.ep")
        assert isinstance(w, nw.WatchedSocket)
        assert nw.wrap_socket(w, "wrap.ep") is w
    finally:
        a.close()
        b.close()


def test_env_var_arms_at_creation(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_NETWATCH", "1")
    try:
        sock = nw.make_socket("env.ep")
        try:
            assert isinstance(sock, nw.WatchedSocket)
            assert nw.enabled()
        finally:
            sock.close()
    finally:
        nw.disable()
        nw.reset()


# ------------------------------------------------- enforced timeout ----

def test_default_timeout_enforced_on_unset_socket(netwatch):
    a, b = socket.socketpair()
    w = nw.wrap_socket(a, "tracker.client")
    try:
        assert w.gettimeout() == 0.5  # enforced process default
        t0 = time.perf_counter()
        with pytest.raises(socket.timeout):
            w.recv(16)
        elapsed = time.perf_counter() - t0
        assert 0.3 < elapsed < 5.0
    finally:
        a.close()
        b.close()


def test_owner_timeout_wins_over_default(netwatch):
    a, b = socket.socketpair()
    w = nw.wrap_socket(a, "tracker.client")
    try:
        w.settimeout(0.1)
        assert w.gettimeout() == 0.1
        with pytest.raises(socket.timeout):
            w.recv(16)
    finally:
        a.close()
        b.close()


def test_data_flows_through_watched_pair(netwatch):
    a, b = socket.socketpair()
    wa = nw.wrap_socket(a, "pair.a")
    wb = nw.wrap_socket(b, "pair.b")
    try:
        wa.sendall(b"ping")
        assert wb.recv(16) == b"ping"
    finally:
        a.close()
        b.close()


def test_accept_wraps_returned_connection(netwatch):
    srv = nw.make_socket("srv.listener", socket.AF_INET,
                         socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    cli = socket.create_connection(srv.getsockname(), timeout=5)
    try:
        conn, _addr = srv.accept()
        assert isinstance(conn, nw.WatchedSocket)
        cli.sendall(b"hi")
        assert conn.recv(16) == b"hi"
        conn.close()
    finally:
        cli.close()
        srv.close()


def test_disable_quiesces_existing_wrappers(netwatch):
    a, b = socket.socketpair()
    w = nw.wrap_socket(a, "quiesce.ep")
    try:
        b.sendall(b"x")
        assert w.recv(1) == b"x"
        before = nw.summary()["endpoints"]["quiesce.ep"]["ops"]
        nw.disable()
        assert w.gettimeout() is None  # enforcement off with the watch
        b.sendall(b"y")
        assert w.recv(1) == b"y"  # still a working socket, no recording
        nw.enable(default_timeout_s=0.5, watchdog_s=0.15)
        assert nw.summary()["endpoints"]["quiesce.ep"]["ops"] == before
    finally:
        a.close()
        b.close()


# ------------------------------------------------ counters + metrics ----

def test_timeout_and_policy_counters_flow_through_registry():
    reg = MetricsRegistry()
    nw.reset()
    nw.enable(default_timeout_s=0.1, watchdog_s=5.0, registry=reg)
    try:
        a, b = socket.socketpair()
        w = nw.wrap_socket(a, "tracker.client")
        try:
            with pytest.raises(socket.timeout):
                w.recv(16)
        finally:
            a.close()
            b.close()
        nw.record_retry("tracker.client")
        nw.record_reconnect("tracker.client")
        labels = {"endpoint": "tracker.client"}
        assert reg.counter("netwatch_timeouts_total", labels).value == 1
        assert reg.counter("netwatch_retries_total", labels).value == 1
        assert reg.counter("netwatch_reconnects_total", labels).value == 1
        rec = nw.metrics_record()
        assert rec["netwatch_tracker_client_timeouts"] == 1
        assert rec["netwatch_tracker_client_retries"] == 1
        assert rec["netwatch_tracker_client_reconnects"] == 1
        assert rec["netwatch_tracker_client_wait_ms_max"] > 0
    finally:
        nw.disable()
        nw.reset()


def test_policy_hooks_are_noops_unarmed():
    nw.reset()
    assert not nw.enabled()
    nw.record_retry("never.ep")
    nw.record_reconnect("never.ep")
    assert nw.summary()["endpoints"] == {}


# ----------------------------------------------------------- watchdog ----

def test_stall_dumps_thread_stacks_through_flight_recorder(tmp_path):
    from deeplearning4j_tpu.telemetry import trace as tr

    nw.reset()
    nw.enable(default_timeout_s=0.6, watchdog_s=0.15)
    tracer = tr.Tracer("netwatch-test", trace_dir=str(tmp_path),
                       registry=MetricsRegistry())
    prev = tr.set_tracer(tracer)
    try:
        a, b = socket.socketpair()
        w = nw.wrap_socket(a, "stuck.ep")
        got = []

        def reader():
            try:
                w.recv(16)
            except socket.timeout as exc:
                got.append(exc)

        t = threading.Thread(target=reader, name="the-reader")
        t.start()
        t.join(timeout=10)
        assert not t.is_alive()
        a.close()
        b.close()
        assert len(got) == 1  # stall still times out after the dump
        assert nw.summary()["stall_dumps"] == 1  # one artifact per call
        dump_path = os.path.join(str(tmp_path),
                                 "flightrec_netwatch-test.json")
        assert os.path.exists(dump_path)
        payload = json.load(open(dump_path))
        assert payload["reason"] == "netwatch_stall"
        extra = payload["extra"]
        assert extra["netwatch"]["endpoint"] == "stuck.ep"
        assert extra["netwatch"]["op"] == "recv"
        stacks = extra["thread_stacks"]
        assert any("the-reader" in k for k in stacks), list(stacks)
    finally:
        tr.set_tracer(prev)
        nw.disable()
        nw.reset()
