"""End-to-end MultiLayerNetwork tests on Iris
(ref test model: nn/multilayer/MultiLayerTest.java, OutputLayerTest)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
from deeplearning4j_tpu.eval import Evaluation
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def iris_mlp_conf(num_iterations=60, lr=0.1):
    return (
        NeuralNetConfiguration.Builder()
        .n_in(4)
        .n_out(8)
        .activation_function("tanh")
        .lr(lr)
        .momentum(0.9)
        .use_ada_grad(True)
        .num_iterations(num_iterations)
        .seed(42)
        .weight_init("VI")
        .list(2)
        .override(0, layer_type="DENSE")
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False)
        .backward(True)
        .build()
    )


def test_init_and_param_shapes():
    net = MultiLayerNetwork(iris_mlp_conf()).init()
    p = net.params_tree
    assert p[0]["W"].shape == (4, 8)
    assert p[0]["b"].shape == (8,)
    assert p[1]["W"].shape == (8, 3)


def test_params_round_trip():
    """ref: MultiLayerTest.testSetParams"""
    net = MultiLayerNetwork(iris_mlp_conf()).init()
    flat = net.params()
    assert flat.shape == (4 * 8 + 8 + 8 * 3 + 3,)
    net2 = MultiLayerNetwork(iris_mlp_conf()).init()
    net2.set_params(flat)
    np.testing.assert_allclose(np.asarray(net2.params()), np.asarray(flat), rtol=1e-6)


def test_feed_forward_shapes():
    net = MultiLayerNetwork(iris_mlp_conf()).init()
    acts = net.feed_forward(np.zeros((5, 4), np.float32))
    assert [a.shape for a in acts] == [(5, 4), (5, 8), (5, 3)]


def test_fit_iris_converges():
    it = IrisDataSetIterator(150, 150)
    net = MultiLayerNetwork(iris_mlp_conf(num_iterations=120)).init()
    data = it.next()
    before = net.score(data)
    net.fit(it)
    after = net.score(data)
    assert after < before * 0.5, (before, after)

    ev = Evaluation()
    ev.eval(data.labels, np.asarray(net.output(data.features)))
    assert ev.accuracy() > 0.85, ev.stats()


def test_predict_labels():
    it = IrisDataSetIterator(150, 150)
    net = MultiLayerNetwork(iris_mlp_conf(num_iterations=100)).init()
    net.fit(it)
    data_it = IrisDataSetIterator(150, 150)
    d = data_it.next()
    preds = net.predict(d.features)
    assert preds.shape == (150,)
    assert set(np.unique(preds)).issubset({0, 1, 2})


def test_merge_parameter_averaging():
    net1 = MultiLayerNetwork(iris_mlp_conf()).init()
    net2 = MultiLayerNetwork(iris_mlp_conf()).init()
    p1 = np.asarray(net1.params())
    p2 = np.asarray(net2.params())
    net1.merge(net2, 4)
    np.testing.assert_allclose(np.asarray(net1.params()), p1 + p2 / 4, rtol=1e-5)


def test_save_load_round_trip(tmp_path):
    net = MultiLayerNetwork(iris_mlp_conf()).init()
    path = str(tmp_path / "model")
    net.save(path)
    loaded = MultiLayerNetwork.load(path)
    np.testing.assert_allclose(
        np.asarray(loaded.params()), np.asarray(net.params()), rtol=1e-6
    )
    assert loaded.conf == net.conf


def test_score_decreases_with_listeners():
    from deeplearning4j_tpu.optimize.listeners import CollectScoresListener

    it = IrisDataSetIterator(150, 150)
    net = MultiLayerNetwork(iris_mlp_conf(num_iterations=30)).init()
    collector = CollectScoresListener()
    net.set_listeners([collector])
    net.fit(it)
    assert len(collector.scores) == 30
    assert collector.scores[-1][1] < collector.scores[0][1]


def test_train_epoch_matches_sequential_steps():
    """make_train_epoch (device-resident scan) == the same make_train_step
    sequence with fold_in keys."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn import functional as F

    conf = iris_mlp_conf()
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    step = F.make_train_step(conf)
    epoch = F.make_train_epoch(conf, n_steps=3, donate=False)

    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.rand(3, 10, 4).astype(np.float32))
    ys = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, (3, 10))])
    key = jax.random.PRNGKey(7)

    p_seq, s_seq = params, states
    seq_scores = []
    for i in range(3):
        sub = jax.random.fold_in(key, i)
        p_seq, s_seq, sc = step(p_seq, s_seq, jnp.asarray(i), xs[i], ys[i], sub)
        seq_scores.append(float(sc))

    p_ep, s_ep, scores = epoch(params, states, jnp.asarray(0), xs, ys, key)
    np.testing.assert_allclose(np.asarray(scores), seq_scores, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_seq), jax.tree_util.tree_leaves(p_ep)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_char_lstm_trains_via_public_api():
    """The zoo char_lstm conf fits end-to-end through MultiLayerNetwork:
    LSTM head decoder gives per-timestep logits, labels are (batch, time,
    vocab) one-hots (VERDICT r1: LSTM previously could not train through
    the framework)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import char_lstm

    rng = np.random.RandomState(0)
    vocab = 8
    seq = rng.randint(0, vocab, size=(16, 20))
    x = np.eye(vocab, dtype=np.float32)[seq]
    # echo task: predict the previous timestep's token
    y = np.concatenate([np.zeros_like(x[:, :1]), x[:, :-1]], axis=1)

    net = MultiLayerNetwork(char_lstm(vocab=vocab, lr=0.05)).init()
    ds = DataSet(x, y)
    before = net.score(ds)
    net.fit_epochs(ds, num_epochs=150)
    after = net.score(ds)
    assert after < before * 0.6, (before, after)
    # predict() works on sequences: argmax over vocab per timestep
    pred = net.predict(x)
    assert pred.shape == (16, 20)
    # accuracy on the echo task (ignoring t=0 which has no history)
    truth = np.argmax(y, axis=-1)
    acc = float((pred[:, 1:] == truth[:, 1:]).mean())
    assert acc > 0.5, acc
