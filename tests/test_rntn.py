"""Tree / RNTN / RecursiveAutoEncoder tests (ref: RNTNTest.java,
TreeTests, RecursiveAutoEncoderTest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.rntn import RNTN, RNTNEval
from deeplearning4j_tpu.nn.tree import Tree, linearize


class TestTree:
    def test_parse_and_structure(self):
        t = Tree.parse("(3 (2 good) (3 (2 great) (2 movie)))")
        assert t.label == 3
        assert t.yield_words() == ["good", "great", "movie"]
        assert t.num_nodes() == 5
        assert t.depth() == 2
        assert [n.label for n in t.preorder()] == [3, 2, 3, 2, 2]

    def test_parse_rejects_trailing(self):
        with pytest.raises(AssertionError):
            Tree.parse("(1 a) (2 b)")

    def test_binarize_nary(self):
        t = Tree.parse("(1 (0 a) (0 b) (0 c))")
        b = t.binarize()
        assert all(len(n.children) in (0, 2) for n in b.preorder())
        assert b.yield_words() == ["a", "b", "c"]

    def test_linearize(self):
        t = Tree.parse("(3 (1 bad) (2 movie))").binarize()
        vocab = {"bad": 1, "movie": 2}
        leaf_ids, merges, labels = linearize(t, vocab)
        assert leaf_ids.tolist() == [1, 2]
        assert merges.tolist() == [[0, 1, 2]]
        assert labels.tolist() == [1, 2, 3]

    def test_linearize_unknown_word(self):
        t = Tree.parse("(1 (0 known) (0 zzz))").binarize()
        leaf_ids, _, _ = linearize(t, {"known": 1}, unk_index=0)
        assert leaf_ids.tolist() == [1, 0]


def _sentiment_corpus():
    """Tiny synthetic sentiment task: 'good'-rooted trees are positive (1),
    'bad'-rooted are negative (0)."""
    pos = ["(1 (1 good) (1 movie))", "(1 (1 great) (1 film))",
           "(1 (1 good) (1 film))", "(1 (1 great) (1 movie))",
           "(1 (1 (1 very) (1 good)) (1 movie))"]
    neg = ["(0 (0 bad) (0 movie))", "(0 (0 awful) (0 film))",
           "(0 (0 bad) (0 film))", "(0 (0 awful) (0 movie))",
           "(0 (0 (0 very) (0 bad)) (0 movie))"]
    return [Tree.parse(s) for s in pos + neg]


class TestRNTN:
    def test_learns_toy_sentiment(self):
        trees = _sentiment_corpus()
        model = RNTN(num_hidden=8, num_classes=2, lr=0.25, iterations=60,
                     l2=1e-5, seed=0)
        model.fit(trees)
        assert model.losses[-1] < model.losses[0]
        ev = RNTNEval()
        ev.eval(model, trees)
        assert ev.root_accuracy() >= 0.9, ev.stats()
        assert ev.node_accuracy() >= 0.8, ev.stats()

    def test_predict_root_unseen_composition(self):
        trees = _sentiment_corpus()
        model = RNTN(num_hidden=8, num_classes=2, lr=0.25, iterations=60,
                     l2=1e-5, seed=0)
        model.fit(trees)
        # novel combination of seen words
        t = Tree.parse("(1 (1 great) (1 great))")
        assert model.predict_root(t) in (0, 1)

    def test_eval_stats_format(self):
        ev = RNTNEval()
        assert "node acc" in ev.stats()


class TestRecursiveAutoEncoder:
    def _conf(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        return (NeuralNetConfiguration.Builder()
                .n_in(6).n_out(4).activation_function("tanh")
                .lr(0.05).num_iterations(80).seed(3)
                .weight_init("VI").build())

    def test_param_shapes(self):
        from deeplearning4j_tpu.nn.params import init_layer_params
        import dataclasses

        conf = dataclasses.replace(self._conf(), layer_type="RECURSIVE_AUTOENCODER")
        p = init_layer_params(jax.random.PRNGKey(0), conf)
        assert p["W"].shape == (10, 4)
        assert p["b"].shape == (4,)
        assert p["vb"].shape == (10,)

    def test_pretrain_reduces_reconstruction_error(self):
        import dataclasses

        from deeplearning4j_tpu.nn.layers import recursive_autoencoder as rae
        from deeplearning4j_tpu.nn.params import init_layer_params
        from deeplearning4j_tpu.optimize.solver import Solver

        conf = dataclasses.replace(self._conf(), layer_type="RECURSIVE_AUTOENCODER")
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(12, 6).astype(np.float32))
        params = init_layer_params(jax.random.PRNGKey(1), conf)
        loss0 = float(rae.pretrain_loss(conf, params, x, jax.random.PRNGKey(2)))
        solver = Solver(conf, lambda p, k: rae.pretrain_loss(conf, p, x, k),
                        num_iterations=conf.num_iterations)
        trained = solver.optimize(params, jax.random.PRNGKey(3))
        loss1 = float(rae.pretrain_loss(conf, trained, x, jax.random.PRNGKey(2)))
        assert loss1 < loss0 * 0.7, (loss0, loss1)

    def test_forward_shape_and_sequence_encoding(self):
        import dataclasses

        from deeplearning4j_tpu.nn.layers import recursive_autoencoder as rae
        from deeplearning4j_tpu.nn.params import init_layer_params

        conf = dataclasses.replace(self._conf(), layer_type="RECURSIVE_AUTOENCODER")
        params = init_layer_params(jax.random.PRNGKey(0), conf)
        x = jnp.zeros((5, 6), jnp.float32)
        assert rae.forward(conf, params, x).shape == (5, 4)
        assert rae.encode_sequence(conf, params, x).shape == (4,)

    def test_pretrain_through_multilayer(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.Builder()
                .n_in(6).n_out(4).activation_function("tanh")
                .lr(0.05).num_iterations(20).seed(3).weight_init("VI")
                .list(2)
                .override(0, layer_type="RECURSIVE_AUTOENCODER")
                .override(1, layer_type="OUTPUT", n_in=4, n_out=2,
                          activation_function="softmax", loss_function="MCXENT")
                .pretrain(True).backward(True)
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(1)
        x = rng.rand(16, 6).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        net.pretrain(x)
        net.fit(x, y)  # full path still works with the RAE in the stack
        out = net.output(x)
        assert out.shape == (16, 2)
        assert np.all(np.isfinite(np.asarray(out)))
