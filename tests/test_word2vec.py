"""Word2Vec + text pipeline tests (ref test model: Word2VecTests,
TokenizerFactory tests, Huffman usage in Word2Vec.fit)."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.embeddings import (
    load_word_vectors,
    write_word_vectors,
)
from deeplearning4j_tpu.models.word2vec import Word2Vec
from deeplearning4j_tpu.text.sentence_iterator import CollectionSentenceIterator
from deeplearning4j_tpu.text.tokenization import (
    CommonPreprocessor,
    DefaultTokenizerFactory,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.text.vocab import VocabCache, build_huffman


def test_default_tokenizer():
    t = DefaultTokenizerFactory().create("To be or not to be")
    assert t.get_tokens() == ["To", "be", "or", "not", "to", "be"]
    assert t.count_tokens() == 6
    assert t.has_more_tokens()
    assert t.next_token() == "To"


def test_tokenizer_preprocessor():
    t = DefaultTokenizerFactory(CommonPreprocessor()).create("Hello, World!")
    assert t.get_tokens() == ["hello", "world"]


def test_ngram_tokenizer():
    t = NGramTokenizerFactory(DefaultTokenizerFactory(), 1, 2).create("a b c")
    assert t.get_tokens() == ["a", "b", "c", "a b", "b c"]


def test_vocab_ordering_and_pruning():
    v = VocabCache()
    for w in ["a"] * 5 + ["b"] * 3 + ["c"]:
        v.add_token(w)
    v.finish(min_word_frequency=2)
    assert v.num_words() == 2
    assert v.word_at(0) == "a"  # most frequent first
    assert v.index_of("c") == -1


def test_huffman_codes_prefix_free():
    v = VocabCache()
    for w, n in [("a", 40), ("b", 30), ("c", 20), ("d", 10)]:
        for _ in range(n):
            v.add_token(w)
    v.finish()
    build_huffman(v)
    codes = {w.word: "".join(map(str, w.code)) for w in v.words()}
    # prefix-free property
    for w1, c1 in codes.items():
        for w2, c2 in codes.items():
            if w1 != w2:
                assert not c2.startswith(c1), codes
    # frequent words get shorter codes
    assert len(codes["a"]) <= len(codes["d"])
    # points index into syn1 (inner nodes): all < n-1
    for w in v.words():
        assert all(0 <= p < v.num_words() - 1 for p in w.points)
        assert len(w.points) == len(w.code)


def _toy_corpus():
    # two topic clusters: fruit words co-occur, machine words co-occur
    fruit = "apple banana cherry fruit sweet juice"
    tech = "cpu gpu chip silicon compute memory"
    sents = []
    rng = np.random.default_rng(0)
    for _ in range(200):
        words = rng.permutation(fruit.split()).tolist()
        sents.append(" ".join(words))
        words = rng.permutation(tech.split()).tolist()
        sents.append(" ".join(words))
    return sents


def test_word2vec_sgns_learns_topics():
    # lr 0.05: at 0.1 the SGNS steps over-shoot on this tiny corpus (the
    # neighbor set oscillates run to run / version to version); 0.05
    # converges to a clean 5/5 topic split
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=16, window=3, negative=5, iterations=10,
        lr=0.05, sample=0, batch_size=128, seed=1,
    )
    vec.fit()
    assert vec.has_word("apple")
    same = vec.similarity("apple", "banana")
    cross = vec.similarity("apple", "gpu")
    assert same > cross, (same, cross)
    nearest = vec.words_nearest("cpu", 5)
    tech_words = {"gpu", "chip", "silicon", "compute", "memory"}
    assert len(tech_words & set(nearest)) >= 3, nearest


def test_word2vec_classic_per_pair_negatives_learns():
    """shared_negatives=0 keeps the reference's per-pair draws
    (Word2Vec.java:303-342) as a selectable path — quality-equivalent to
    the default shared-group path on the topic corpus."""
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=16, window=3, negative=5, iterations=10,
        lr=0.1, sample=0, batch_size=128, seed=1, shared_negatives=0,
    )
    vec.fit()
    same = vec.similarity("apple", "banana")
    cross = vec.similarity("apple", "gpu")
    assert same > cross, (same, cross)


def test_shared_negative_group_divides_step():
    """The production group-size selection always divides the step's pair
    count, whatever batch_size/window imply (falls back to 1 — per-pair
    semantics — when the pair count is prime)."""
    from deeplearning4j_tpu.models.word2vec import neg_group_size

    for batch_size, window, cap in [(2048, 5, 25), (100, 3, 25),
                                    (7, 1, 25), (8192, 5, 25),
                                    (65536, 5, 25)]:
        block = max(-(-batch_size // (2 * window)), 1)
        bsz = block * 2 * window
        g = neg_group_size(bsz, cap)
        assert bsz % g == 0 and 1 <= g <= cap
    assert neg_group_size(7, 25) == 7   # bsz below cap: whole step one group
    assert neg_group_size(13, 5) == 1   # prime above cap: per-pair


def test_shared_grads_reduce_to_per_pair_at_group_one():
    """_sgns_grads_shared with one pair per group (negs_g: (B,K)) must be
    EXACTLY the per-pair _sgns_grads — the shared path is a strict
    generalization, so the sharded step's neg_group feature never changes
    semantics at the degenerate group size."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.word2vec import (
        _sgns_grads,
        _sgns_grads_shared,
    )

    V, D, B, K = 50, 8, 12, 3
    ks = jax.random.split(jax.random.PRNGKey(0), 5)
    syn0 = jax.random.normal(ks[0], (V, D))
    syn1neg = jax.random.normal(ks[1], (V, D)) * 0.1
    centers = jax.random.randint(ks[2], (B,), 0, V)
    contexts = jax.random.randint(ks[3], (B,), 0, V)
    weights = jnp.asarray([1.0] * 10 + [0.0] * 2)  # incl. padding mask
    negs = jax.random.randint(ks[4], (B, K), 0, V)

    ref = _sgns_grads(syn0, syn1neg, centers, contexts, weights, negs)
    shared = _sgns_grads_shared(syn0, syn1neg, centers, contexts, weights,
                                negs)
    for a, b, name in zip(ref, shared,
                          ("grad_v", "u_idx", "u_grad", "u_w", "loss")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6,
                                   err_msg=name)


def test_lookup_table_readable_after_failed_fit(monkeypatch):
    """A fit() that dies mid-epoch must leave the model READABLE: the host
    table (content as of the last sync/upload) becomes authoritative and
    later reads never crash on a half-donated device state."""
    import deeplearning4j_tpu.models.word2vec as w2v_mod

    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=8, window=2, negative=2, iterations=1,
        lr=0.1, sample=0, batch_size=64, seed=1,
    )
    vec.fit()
    _ = vec.word_vector("apple")  # sync once so the host has trained values
    host_before = np.array(vec.lookup_table.syn0)

    def boom(*a, **k):
        raise RuntimeError("injected epoch failure")

    monkeypatch.setattr(w2v_mod, "_sgns_device_epoch", boom)
    with pytest.raises(RuntimeError, match="injected"):
        vec.fit()
    v = vec.word_vector("apple")  # must not raise
    assert v is not None
    np.testing.assert_allclose(np.asarray(vec.lookup_table.syn0),
                               host_before)


def test_stale_host_table_rejects_inplace_writes():
    """After a fit, in-place writes through a retained host-table reference
    fail loudly (the arrays are frozen/read-only) instead of silently
    shadowing the device-side training; wholesale re-assignment remains the
    supported edit path."""
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=8, window=2, negative=2, iterations=1,
        lr=0.1, sample=0, batch_size=64, seed=1,
    )
    vec.build_vocab()
    retained = vec._lookup_table  # grabbed before training, bypasses sync
    vec.fit()
    with pytest.raises(ValueError):
        retained.syn0[0, 0] = 123.0


def test_word2vec_hierarchical_softmax_learns():
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=16, window=3, negative=0, use_hierarchic_softmax=True,
        iterations=10, lr=0.1, sample=0, batch_size=128, seed=1,
    )
    vec.fit()
    assert vec.similarity("banana", "cherry") > vec.similarity("banana", "chip")


def test_serializer_round_trip(tmp_path):
    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(["a b c", "b c d"] * 5),
        layer_size=8, negative=2, iterations=1, sample=0, batch_size=64,
    )
    vec.fit()
    path = str(tmp_path / "vecs.txt")
    write_word_vectors(vec.lookup_table, path)
    vocab, mat = load_word_vectors(path)
    assert vocab.num_words() == vec.vocab.num_words()
    for w in vec.vocab.words():
        np.testing.assert_allclose(
            mat[vocab.index_of(w.word)],
            vec.lookup_table.syn0[w.index],
            atol=1e-5,
        )


def test_word2vec_requires_objective():
    with pytest.raises(ValueError):
        Word2Vec(negative=0, use_hierarchic_softmax=False)


def test_distributed_word2vec_matches_single_device_quality():
    """Data-parallel SGNS on the 8-device mesh reaches the same topic
    separation as single-device training (ref parity surface:
    scaleout/perform/models/word2vec/Word2VecPerformer.java, spark
    dl4j-spark-nlp Word2VecPerformer)."""
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=16, window=3, negative=5, iterations=10,
        lr=0.1, sample=0, batch_size=128, seed=1,
        mesh=data_parallel_mesh(8),
    )
    vec.fit()
    same = vec.similarity("apple", "banana")
    cross = vec.similarity("apple", "gpu")
    assert same > cross, (same, cross)
    nearest = vec.words_nearest("cpu", 5)
    tech_words = {"gpu", "chip", "silicon", "compute", "memory"}
    assert len(tech_words & set(nearest)) >= 3, nearest


def test_distributed_hs_learns():
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh

    vec = Word2Vec(
        sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
        layer_size=16, window=3, negative=0, use_hierarchic_softmax=True,
        iterations=10, lr=0.1, sample=0, batch_size=128, seed=1,
        mesh=data_parallel_mesh(8),
    )
    vec.fit()
    assert vec.similarity("banana", "cherry") > vec.similarity("banana", "chip")


def test_vectorized_pairs_match_bruteforce():
    """The shifted-mask pair generator equals the per-position definition:
    pair (center i, context j) exists iff 0<|i-j|<=b_i within a sentence."""
    vec = Word2Vec(sentence_iterator=CollectionSentenceIterator(["x"]),
                   window=3, negative=1)
    sents = [np.array([1, 2, 3, 4, 5], np.int32),
             np.array([6, 7], np.int32),
             np.array([8, 9, 10], np.int32)]

    class FixedRng:
        def __init__(self, b):
            self._b = b

        def integers(self, lo, hi, size):
            return self._b[:size]

    b = np.array([1, 3, 2, 1, 2, 1, 2, 3, 1, 2], np.int64)
    c, t = vec._skipgram_pairs(sents, FixedRng(b))
    got = set(zip(c.tolist(), t.tolist()))
    flat = np.concatenate(sents)
    sid = np.repeat(np.arange(3), [5, 2, 3])
    want = set()
    for i in range(flat.size):
        for j in range(flat.size):
            if i != j and sid[i] == sid[j] and abs(i - j) <= b[i]:
                want.add((int(flat[i]), int(flat[j])))
    assert got == want


def test_device_pair_block_matches_host_pairs():
    """The in-graph pair generator (_pair_block) must produce exactly the
    host path's (_pairs_from_flat) pair multiset given the same corpus,
    reduced-window draws, and no subsampling."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.word2vec import _pair_block

    vec = Word2Vec(sentence_iterator=CollectionSentenceIterator(["x"]),
                   window=3, negative=1)
    flat = np.array([1, 2, 3, 4, 5, 6, 7, 8, 9, 10], np.int32)
    sid = np.array([0, 0, 0, 0, 0, 1, 1, 2, 2, 2], np.int32)
    b = np.array([1, 3, 2, 1, 2, 1, 2, 3, 1, 2], np.int64)

    class FixedRng:
        def integers(self, lo, hi, size):
            return b[:size]

    hc, ht = vec._pairs_from_flat(flat, sid, FixedRng())
    host_pairs = sorted(zip(hc.tolist(), ht.tolist()))

    block = 4  # force multiple blocks incl. a padded tail
    dev_pairs = []
    for pos0 in range(0, flat.size + block, block):  # overrun on purpose
        ctr, ctx, w = _pair_block(
            jnp.asarray(flat), jnp.asarray(sid), jnp.asarray(b),
            jnp.asarray(flat.size), pos0, block, 3)
        ctr, ctx, w = np.asarray(ctr), np.asarray(ctx), np.asarray(w)
        for i in range(block):
            for j in range(ctx.shape[1]):
                if w[i, j] > 0:
                    dev_pairs.append((int(ctr[i]), int(ctx[i, j])))
    assert sorted(dev_pairs) == host_pairs


def test_device_epoch_counts_and_trains():
    """_sgns_device_epoch: pairs_trained matches the analytic pair count and
    the embeddings move."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.word2vec import (
        _sgns_device_epoch, build_neg_table)

    V, D = 20, 8
    flat = np.arange(10, dtype=np.int32) % V
    sid = np.zeros(10, np.int32)
    keep = np.ones(V, np.float32)  # no subsampling
    syn0_np = np.random.default_rng(0).normal(size=(V, D)).astype(np.float32) * 0.01
    syn0 = jnp.asarray(syn0_np)  # donated by the epoch call
    syn1neg = jnp.zeros((V, D), jnp.float32)
    table = build_neg_table(np.ones(V) / V, slots=1 << 10)
    block, window = 4, 2
    n_steps = -(-10 // block)
    lrs = jnp.full((n_steps,), 0.05, jnp.float32)
    s0, s1n, losses, wtot = _sgns_device_epoch(
        syn0, jnp.asarray(syn1neg), jnp.asarray(flat), jnp.asarray(sid),
        jnp.asarray(keep), table, lrs, jax.random.PRNGKey(0),
        window=window, negative=2, block=block, n_steps=n_steps)
    # expected pairs with all windows (b in [1,2], random): between the
    # b=1-everywhere count and the full-window count
    full = sum(1 for i in range(10) for j in range(10)
               if i != j and abs(i - j) <= window)
    minimal = sum(1 for i in range(10) for j in range(10)
                  if i != j and abs(i - j) <= 1)
    assert minimal <= int(wtot) <= full
    assert np.isfinite(np.asarray(losses)).all()
    assert float(np.abs(np.asarray(s0) - syn0_np).max()) > 0  # params moved
