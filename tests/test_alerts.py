"""ISSUE 15: the alert rule engine (telemetry/alerts.py) and its wiring.

Pins, in order:

- **rule-pack fixtures + meta-test**: EVERY rule in ``default_rules()``
  has a firing and a non-firing fixture (the PR 11 rule-fixture pattern
  applied to alerts — a future rule can't ship unpinned);
- the **hysteresis state machine** (inactive → pending → firing →
  resolved, ``for_s`` honored, blips never fire);
- **firing side effects**: ``alerts_firing``/``alerts_transitions_total``
  registry bumps, the ``reason=alert:<rule>`` flight-recorder dump, the
  tracker-KV publish, and the transitions JSONL;
- the **cluster alert view**: two processes' engines publishing over the
  real TCP tracker, merged by ``ClusterAggregator.collect_alerts`` with
  staleness marking;
- **trace exemplars** end to end: real traced serve requests land trace
  ids in the latency histogram, a firing SLO-burn rule exposes the
  offending ids, and each id resolves to real spans through
  ``tools/trace_report.find_trace`` (the ISSUE 15 acceptance);
- the **end-to-end elastic pin**: a ``nan_at_step``-poisoned worker
  drives quarantine → the master watchtower's ``worker_divergence`` rule
  fires → forensic dump + cluster-visible alert over the real TCP
  tracker;
- thread lifecycle (PR 11 pattern) and the UI / alert_report surfaces.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.telemetry import trace as trace_mod
from deeplearning4j_tpu.telemetry.alerts import (
    ALERT_KV_PREFIX,
    SCHEMA,
    AlertEngine,
    AlertRule,
    Watchtower,
    arm_watchtower,
    default_rules,
    get_engine,
    set_engine,
)
from deeplearning4j_tpu.telemetry.federation import ClusterAggregator
from deeplearning4j_tpu.telemetry.history import MetricsHistory
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
T0 = 1_000_000.0


def _hist():
    reg = MetricsRegistry()
    return MetricsHistory(registry=reg), reg


def _two_sample_counter(name, v0, v1, dt=10.0):
    """History with a counter moving v0 → v1 across two samples."""
    h, reg = _hist()
    c = reg.counter(name)
    c.inc(v0)
    h.sample_once(now=T0)
    c.inc(v1 - v0)
    h.sample_once(now=T0 + dt)
    return h, T0 + dt


def _two_sample_gauge(name, v0, v1, dt=10.0):
    h, reg = _hist()
    g = reg.gauge(name)
    g.set(v0)
    h.sample_once(now=T0)
    g.set(v1)
    h.sample_once(now=T0 + dt)
    return h, T0 + dt


def _latency_history(values):
    h, reg = _hist()
    reg.histogram("serve_request_ms")  # born before the first sample
    h.sample_once(now=T0)
    for v in values:
        reg.histogram("serve_request_ms").observe(v)
    h.sample_once(now=T0 + 10.0)
    return h, T0 + 10.0


def _heartbeat_history(age_s):
    h, reg = _hist()
    reg.gauge("elastic_worker_heartbeat_unix",
              {"worker": "w1"}).set(T0 - age_s)
    h.sample_once(now=T0)
    return h, T0


def _fleet_heartbeat_history(age_s):
    h, reg = _hist()
    reg.gauge("fleet_replica_heartbeat_unix",
              {"replica": "r1"}).set(T0 - age_s)
    h.sample_once(now=T0)
    return h, T0


def _climbing_gauge(name, slope_per_s, until_s=60.0, dt=5.0):
    """History with a gauge climbing ``slope_per_s`` from T0 to
    T0+until_s, sampled every ``dt``; now = T0+10. Samples extend PAST
    the returned now because step_time_regression's ``for_s`` (45)
    outlasts its delta window (30) — the driver's second evaluation at
    now+for_s reads a window the growth must still be filling (exactly
    the sustained-growth shape the rule is sized for)."""
    h, reg = _hist()
    g = reg.gauge(name)
    t = 0.0
    while t <= until_s:
        g.set(slope_per_s * t)
        h.sample_once(now=T0 + t)
        t += dt
    return h, T0 + 10.0


# Every default rule's (firing, non-firing) history builders, each
# returning (history, now). The meta-test below pins this dict against
# the live pack, so a new rule cannot ship without both fixtures.
RULE_FIXTURES = {
    "nonfinite_step_rate": (
        lambda: _two_sample_counter("guard_skipped_steps_total", 0, 3),
        lambda: _two_sample_counter("guard_skipped_steps_total", 0, 0),
    ),
    "worker_divergence": (
        lambda: _two_sample_counter("elastic_workers_quarantined_total",
                                    0, 1),
        lambda: _two_sample_counter("elastic_workers_quarantined_total",
                                    0, 0),
    ),
    "worker_heartbeat_stale": (
        lambda: _heartbeat_history(30.0),
        lambda: _heartbeat_history(1.0),
    ),
    "tracker_reconnect_storm": (
        lambda: _two_sample_counter("tracker_reconnects_total", 0, 30),
        lambda: _two_sample_counter("tracker_reconnects_total", 0, 1,
                                    dt=30.0),
    ),
    "serve_queue_growth": (
        lambda: _two_sample_gauge("serve_queue_depth", 0, 30),
        lambda: _two_sample_gauge("serve_queue_depth", 5, 5),
    ),
    "serve_latency_slo_burn": (
        lambda: _latency_history([900.0] * 10),
        lambda: _latency_history([10.0] * 100),
    ),
    "lockwatch_contention_spike": (
        lambda: _two_sample_counter("lockwatch_contended_total", 0, 2000),
        lambda: _two_sample_counter("lockwatch_contended_total", 0, 10),
    ),
    "cluster_stale_process": (
        lambda: _two_sample_gauge("federation_stale_processes", 1, 1),
        lambda: _two_sample_gauge("federation_stale_processes", 0, 0),
    ),
    "serve_cache_hit_rate_low": (
        lambda: _two_sample_gauge("serve_prefix_cache_hit_rate",
                                  0.02, 0.02),
        lambda: _two_sample_gauge("serve_prefix_cache_hit_rate",
                                  0.8, 0.8),
    ),
    "serve_spec_accept_collapse": (
        lambda: _two_sample_gauge("serve_spec_accept_rate", 0.01, 0.01),
        lambda: _two_sample_gauge("serve_spec_accept_rate", 0.6, 0.6),
    ),
    # ISSUE 17 runprof rules. step_time_regression fires only on growth
    # that outlasts its 30s delta window (20 ms/s sustained for 60s);
    # quiet = a flat measured step time. The threshold gauges fire on a
    # collapsed MFU / high input-wait fraction, quiet on healthy values.
    "step_time_regression": (
        lambda: _climbing_gauge("runprof_step_ms", 20.0),
        lambda: _two_sample_gauge("runprof_step_ms", 120.0, 120.0),
    ),
    "mfu_collapse": (
        lambda: _two_sample_gauge("runprof_measured_mfu", 0.001, 0.001),
        lambda: _two_sample_gauge("runprof_measured_mfu", 0.3, 0.3),
    ),
    "input_wait_high": (
        lambda: _two_sample_gauge("runprof_input_wait_fraction",
                                  0.6, 0.6),
        lambda: _two_sample_gauge("runprof_input_wait_fraction",
                                  0.05, 0.05),
    ),
    # ISSUE 19 fleet rules: a replica heartbeat gauge 30s stale fires
    # the absence rule (1s fresh stays quiet); a router-published
    # max/mean queue-depth ratio of 8 fires imbalance (balanced ~1 is
    # quiet).
    "fleet_replica_down": (
        lambda: _fleet_heartbeat_history(30.0),
        lambda: _fleet_heartbeat_history(1.0),
    ),
    "fleet_queue_imbalance": (
        lambda: _two_sample_gauge("fleet_queue_imbalance_ratio",
                                  8.0, 8.0),
        lambda: _two_sample_gauge("fleet_queue_imbalance_ratio",
                                  1.0, 1.0),
    ),
    # ISSUE 20: a tuning-cache lookup counted entries searched under a
    # stale knob-space version (they resolve to defaults — the tuned
    # speedup is silently gone). 0 stale entries stays quiet.
    "tune_cache_stale": (
        lambda: _two_sample_gauge("tune_cache_stale_entries", 1.0, 1.0),
        lambda: _two_sample_gauge("tune_cache_stale_entries", 0.0, 0.0),
    ),
}


def _drive(rule: AlertRule, history, now: float) -> str:
    """Evaluate through the hysteresis window; the state after for_s."""
    eng = AlertEngine(history, rules=[rule], registry=MetricsRegistry())
    eng.evaluate_once(now=now, publish=False)
    states = eng.evaluate_once(now=now + rule.for_s + 0.001,
                               publish=False)
    return states[0]["state"]


class TestDefaultRulePack:
    def test_meta_every_default_rule_has_fixtures(self):
        """The PR 11 rule-fixture discipline: the fixture dict covers the
        live pack EXACTLY (an unpinned new rule, or a stale fixture for a
        removed rule, both fail here)."""
        assert set(RULE_FIXTURES) == {r.name for r in default_rules()}
        for name, fx in RULE_FIXTURES.items():
            assert len(fx) == 2, f"{name} needs (firing, quiet) fixtures"

    @pytest.mark.parametrize("rule", default_rules(),
                             ids=lambda r: r.name)
    def test_firing_fixture_fires(self, rule):
        history, now = RULE_FIXTURES[rule.name][0]()
        assert _drive(rule, history, now) == "firing"

    @pytest.mark.parametrize("rule", default_rules(),
                             ids=lambda r: r.name)
    def test_quiet_fixture_stays_quiet(self, rule):
        history, now = RULE_FIXTURES[rule.name][1]()
        assert _drive(rule, history, now) in ("inactive", "pending")
        # and specifically never fired
        eng = AlertEngine(history, rules=[rule],
                          registry=MetricsRegistry())
        states = eng.evaluate_once(now=now + rule.for_s + 1.0,
                                   publish=False)
        assert states[0]["fire_count"] == 0

    def test_buried_worker_sentinel_not_stale(self):
        """A buried/quarantined worker's heartbeat series is retired to a
        non-positive sentinel — already handled, must NOT keep firing."""
        h, reg = _hist()
        reg.gauge("elastic_worker_heartbeat_unix",
                  {"worker": "w1"}).set(-1.0)
        h.sample_once(now=T0)
        rule = [r for r in default_rules()
                if r.name == "worker_heartbeat_stale"][0]
        assert _drive(rule, h, T0) == "inactive"

    def test_buried_fleet_replica_sentinel_not_stale(self):
        """Same sentinel discipline for the fleet: burying a replica
        retires its heartbeat series to -1.0 (death handled — work
        requeued, cold start dispatched), so fleet_replica_down stops
        firing."""
        h, reg = _hist()
        reg.gauge("fleet_replica_heartbeat_unix",
                  {"replica": "r1"}).set(-1.0)
        h.sample_once(now=T0)
        rule = [r for r in default_rules()
                if r.name == "fleet_replica_down"][0]
        assert _drive(rule, h, T0) == "inactive"

    def test_no_data_never_fires(self):
        """A rule over a metric its subsystem never produced stays
        inactive — arming the pack on a process without serve/elastic
        must not page anyone."""
        h, _ = _hist()
        h.sample_once(now=T0)
        eng = AlertEngine(h, registry=MetricsRegistry())
        for st in eng.evaluate_once(now=T0, publish=False):
            assert st["state"] == "inactive", st

    def test_low_op_rules_not_prearmed_into_firing(self):
        """The pre-arm trap the ISSUE 16 ratio rules must dodge: with
        engine and history SHARING one registry (the arm_watchtower
        wiring), pre-arming a "<"-op gauge at 0.0 would make every idle
        process page hit-rate-low/accept-collapse. Those gauges must
        stay unborn until their subsystem emits, and the rules
        inactive."""
        reg = MetricsRegistry()
        h = MetricsHistory(registry=reg)
        eng = AlertEngine(h, registry=reg)
        h.sample_once(now=T0)
        eng.evaluate_once(now=T0, publish=False)
        h.sample_once(now=T0 + 120.0)
        for st in eng.evaluate_once(now=T0 + 120.0, publish=False):
            if st["rule"] in ("serve_cache_hit_rate_low",
                              "serve_spec_accept_collapse",
                              "mfu_collapse"):
                assert st["state"] == "inactive", st

    def test_step_time_one_off_jump_never_fires(self):
        """The birth/step-change shape step_time_regression is sized
        against (for_s > window_s): a single jump — a gauge born at a
        real value, or one slow step — satisfies the delta rule only
        while the jump is inside the 30s window; the 45s hysteresis
        outlasts it, so only SUSTAINED growth pages."""
        h, reg = _hist()
        g = reg.gauge("runprof_step_ms")
        g.set(20.0)
        h.sample_once(now=T0)
        g.set(220.0)  # one-off jump, then flat
        for t in range(10, 121, 10):
            h.sample_once(now=T0 + t)
        rule = [r for r in default_rules()
                if r.name == "step_time_regression"][0]
        assert _drive(rule, h, T0 + 10.0) in ("inactive", "pending")
        eng = AlertEngine(h, rules=[rule], registry=MetricsRegistry())
        for t in (10.0, 30.0, 60.0, 90.0, 120.0):
            states = eng.evaluate_once(now=T0 + t, publish=False)
        assert states[0]["fire_count"] == 0


class TestRuleValidation:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="kind"):
            AlertRule(name="x", kind="vibes", metric="m")

    def test_burn_rate_requires_slo(self):
        with pytest.raises(ValueError, match="slo_ms"):
            AlertRule(name="x", kind="burn_rate", metric="m")

    def test_duplicate_rule_names_rejected(self):
        h, _ = _hist()
        r = AlertRule(name="dup", kind="threshold", metric="m")
        with pytest.raises(ValueError, match="duplicate"):
            AlertEngine(h, rules=[r, r], registry=MetricsRegistry())


class TestHysteresis:
    def _rule(self, for_s=5.0):
        return AlertRule(name="r", kind="threshold", metric="g",
                         threshold=1.0, op=">", for_s=for_s,
                         severity="warning")

    def _engine(self, for_s=5.0):
        reg = MetricsRegistry()
        h = MetricsHistory(registry=reg)
        eng = AlertEngine(h, rules=[self._rule(for_s)],
                          registry=MetricsRegistry())
        return reg, h, eng

    def _set(self, reg, h, value, now):
        reg.gauge("g").set(value)
        h.sample_once(now=now)

    def test_pending_then_firing_then_resolved(self):
        reg, h, eng = self._engine(for_s=5.0)
        self._set(reg, h, 9.0, T0)
        assert eng.evaluate_once(now=T0, publish=False)[0]["state"] \
            == "pending"
        # still true but inside for_s: stays pending
        assert eng.evaluate_once(now=T0 + 3, publish=False)[0]["state"] \
            == "pending"
        st = eng.evaluate_once(now=T0 + 5.1, publish=False)[0]
        assert st["state"] == "firing" and st["fire_count"] == 1
        # condition clears → resolved (visible, with resolved_at)
        self._set(reg, h, 0.0, T0 + 6)
        st = eng.evaluate_once(now=T0 + 6, publish=False)[0]
        assert st["state"] == "resolved"
        assert st["resolved_at"] == T0 + 6

    def test_blip_never_fires(self):
        reg, h, eng = self._engine(for_s=5.0)
        self._set(reg, h, 9.0, T0)
        eng.evaluate_once(now=T0, publish=False)
        self._set(reg, h, 0.0, T0 + 1)
        st = eng.evaluate_once(now=T0 + 1, publish=False)[0]
        assert st["state"] == "inactive" and st["fire_count"] == 0

    def test_refire_after_resolved_goes_through_pending(self):
        reg, h, eng = self._engine(for_s=5.0)
        self._set(reg, h, 9.0, T0)
        eng.evaluate_once(now=T0, publish=False)
        eng.evaluate_once(now=T0 + 5.1, publish=False)
        self._set(reg, h, 0.0, T0 + 6)
        eng.evaluate_once(now=T0 + 6, publish=False)
        self._set(reg, h, 9.0, T0 + 7)
        st = eng.evaluate_once(now=T0 + 7, publish=False)[0]
        assert st["state"] == "pending"
        st = eng.evaluate_once(now=T0 + 12.1, publish=False)[0]
        assert st["state"] == "firing" and st["fire_count"] == 2

    def test_for_s_zero_fires_immediately(self):
        reg, h, eng = self._engine(for_s=0.0)
        self._set(reg, h, 9.0, T0)
        assert eng.evaluate_once(now=T0, publish=False)[0]["state"] \
            == "firing"


class TestFiringSideEffects:
    def _firing_setup(self, tmp_path, tracker=None):
        h, now = RULE_FIXTURES["nonfinite_step_rate"][0]()
        reg = MetricsRegistry()
        eng = AlertEngine(
            h, rules=[r for r in default_rules()
                      if r.name == "nonfinite_step_rate"],
            registry=reg, tracker=tracker, process="p0",
            log_path=str(tmp_path / "alerts_p0.jsonl"))
        return h, now, reg, eng

    def test_registry_bumps_and_transitions_log(self, tmp_path):
        h, now, reg, eng = self._firing_setup(tmp_path)
        labels = {"rule": "nonfinite_step_rate", "severity": "critical"}
        assert reg.gauge("alerts_firing", labels).value == 0.0
        eng.evaluate_once(now=now, publish=False)
        assert reg.gauge("alerts_firing", labels).value == 1.0
        assert reg.counter("alerts_transitions_total",
                           {"rule": "nonfinite_step_rate",
                            "to": "firing"}).value >= 1.0
        rec = eng.metrics_record()
        assert rec["alerts_evaluations_total"] >= 1.0
        assert rec["alerts_rules"] == 1.0
        # resolve drops the gauge back to 0
        h.sample_once(now=now + 120.0)  # the window drains → rate None
        eng.evaluate_once(now=now + 120.0, publish=False)
        assert reg.gauge("alerts_firing", labels).value == 0.0
        eng.close()
        lines = [json.loads(l) for l in
                 open(tmp_path / "alerts_p0.jsonl")]
        # for_s=0: one evaluation takes the rule straight to firing, so
        # the logged transition is inactive -> firing (pending is only a
        # logged state when a hysteresis window is configured)
        assert [(r["from"], r["to"]) for r in lines] == [
            ("inactive", "firing"), ("firing", "resolved")]
        assert all(r["schema"] == SCHEMA for r in lines)

    def test_flight_dump_on_firing(self, tmp_path):
        prev = trace_mod.set_tracer(trace_mod.Tracer(
            "alerts-test", trace_dir=str(tmp_path / "trace"),
            registry=MetricsRegistry()))
        try:
            h, now, reg, eng = self._firing_setup(tmp_path)
            eng.evaluate_once(now=now, publish=False)
        finally:
            trace_mod.set_tracer(prev)
        dump = json.load(open(tmp_path / "trace" /
                              "flightrec_alerts-test.json"))
        assert dump["reason"] == "alert:nonfinite_step_rate"
        assert dump["extra"]["severity"] == "critical"
        assert dump["extra"]["value"] > 0

    def test_publish_to_tracker_kv(self, tmp_path):
        tracker = InMemoryStateTracker()
        h, now, reg, eng = self._firing_setup(tmp_path, tracker=tracker)
        eng.evaluate_once(now=now)
        payload = json.loads(tracker.get_kv(ALERT_KV_PREFIX + "p0"))
        assert payload["schema"] == SCHEMA
        assert payload["process"] == "p0"
        states = {a["rule"]: a["state"] for a in payload["alerts"]}
        assert states["nonfinite_step_rate"] == "firing"
        assert reg.counter("alerts_publishes_total").value >= 1.0

    def test_publish_failure_absorbed(self, tmp_path):
        class DeadTracker:
            def put_kv(self, key, value):
                raise ConnectionError("down")

        h, now, reg, eng = self._firing_setup(tmp_path,
                                              tracker=DeadTracker())
        eng.evaluate_once(now=now)  # must not raise
        assert reg.counter("alerts_publish_failures_total").value >= 1.0


class TestClusterAlertView:
    def test_two_processes_over_real_tcp_tracker(self):
        from deeplearning4j_tpu.scaleout.remote_tracker import (
            StateTrackerClient,
            StateTrackerServer,
        )

        with StateTrackerServer() as server:
            c1 = StateTrackerClient(server.address)
            c2 = StateTrackerClient(server.address)
            h1, now = RULE_FIXTURES["worker_divergence"][0]()
            h2, _ = RULE_FIXTURES["worker_divergence"][1]()
            e1 = AlertEngine(h1, registry=MetricsRegistry(), tracker=c1,
                             process="master")
            e2 = AlertEngine(h2, registry=MetricsRegistry(), tracker=c2,
                             process="worker-1")
            e1.evaluate_once(now=now)
            e2.evaluate_once(now=now)
            agg = ClusterAggregator(server.tracker, stale_after_s=60.0,
                                    registry=MetricsRegistry())
            view = agg.collect_alerts()
            assert view["schema"] == SCHEMA
            assert sorted(p["process"] for p in view["processes"]) == \
                ["master", "worker-1"]
            by = {(a["process"], a["rule"]): a["state"]
                  for a in view["alerts"]}
            assert by[("master", "worker_divergence")] == "firing"
            assert by[("worker-1", "worker_divergence")] == "inactive"
            assert view["firing"] == 1
            # firing rows sort first (the router reads the top)
            assert view["alerts"][0]["state"] == "firing"
            assert agg.registry.gauge(
                "federation_cluster_alerts_firing").value == 1.0
            c1.close(), c2.close()

    def test_bad_payloads_skipped(self):
        tracker = InMemoryStateTracker()
        tracker.put_kv(ALERT_KV_PREFIX + "junk", "{nope")
        tracker.put_kv(ALERT_KV_PREFIX + "wrong",
                       json.dumps({"schema": "v999"}))
        agg = ClusterAggregator(tracker, registry=MetricsRegistry())
        view = agg.collect_alerts()
        assert view["processes"] == [] and view["alerts"] == []
        assert agg.registry.counter(
            "federation_bad_payloads_total").value == 2.0

    def test_stale_publisher_marked(self):
        tracker = InMemoryStateTracker()
        h, now = RULE_FIXTURES["worker_divergence"][0]()
        eng = AlertEngine(h, registry=MetricsRegistry(), tracker=tracker,
                          process="old")
        eng.evaluate_once(now=now)
        agg = ClusterAggregator(tracker, stale_after_s=0.0,
                                registry=MetricsRegistry())
        time.sleep(0.01)
        view = agg.collect_alerts()
        assert view["processes"][0]["stale"] is True
        # stale ≠ dropped: the last-known verdict stays visible
        assert any(a["rule"] == "worker_divergence"
                   and a["state"] == "firing" and a["stale"]
                   for a in view["alerts"])


class TestTraceExemplars:
    def test_histogram_captures_current_span(self, tmp_path):
        reg = MetricsRegistry()
        tracer = trace_mod.Tracer("ex", trace_dir=str(tmp_path),
                                  registry=MetricsRegistry())
        prev = trace_mod.set_tracer(tracer)
        try:
            with tracer.span("op") as sp:
                reg.histogram("h").observe(42.0)
            want = sp.trace_id
        finally:
            trace_mod.set_tracer(prev)
            tracer.close()
        ex = reg.histogram("h").exemplars()
        assert len(ex) == 1 and ex[0]["trace_id"] == want
        assert ex[0]["value"] == 42.0

    def test_no_tracer_no_exemplars_and_snapshot_shape_unchanged(self):
        reg = MetricsRegistry()
        reg.histogram("h").observe(1.0)
        snap = reg.histogram("h").snapshot()
        assert "exemplars" not in snap
        from deeplearning4j_tpu.telemetry.prometheus import (
            render_prometheus,
        )

        assert "#" not in render_prometheus(reg).replace("# TYPE", "")

    def test_prometheus_renders_openmetrics_exemplar(self):
        reg = MetricsRegistry()
        reg.histogram("lat_ms").observe(3.0, exemplar="aa" * 16)
        from deeplearning4j_tpu.telemetry.prometheus import (
            render_prometheus,
        )

        text = render_prometheus(reg)
        line = [l for l in text.splitlines()
                if l.startswith('lat_ms_bucket{le="5"')][0]
        assert f'# {{trace_id="{"aa" * 16}"}} 3' in line

    def test_serve_latency_exemplars_resolve_to_real_spans(self, tmp_path):
        """ISSUE 15 acceptance: trace ids from a firing serve-latency
        rule resolve to real spans through tools/trace_report.py — the
        metrics→trace correlation loop closed end to end on a REAL
        traced engine."""
        import jax

        from deeplearning4j_tpu.models.transformer_lm import init_lm_params
        from deeplearning4j_tpu.serve import DecodeEngine
        from tools.trace_report import find_trace, load_trace_dir

        reg = MetricsRegistry()
        trace_dir = str(tmp_path / "trace")
        tracer = trace_mod.Tracer("serve", trace_dir=trace_dir,
                                  registry=MetricsRegistry())
        prev = trace_mod.set_tracer(tracer)
        try:
            params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2,
                                    16, n_layers=1)
            eng = DecodeEngine(params, 2, n_slots=2, max_len=64,
                               serve_dtype=None, registry=reg)
            reg.histogram("serve_request_ms")  # baseline precedes sample
            history = MetricsHistory(registry=reg)
            history.sample_once(now=T0)
            for _ in range(3):
                eng.generate([1, 2, 3], max_new_tokens=32)
            history.sample_once(now=T0 + 10.0)
        finally:
            trace_mod.set_tracer(prev)
            tracer.close()
        # a 1ms SLO bound every CPU request blows → the burn rule fires
        rule = AlertRule(name="serve_latency_slo_burn", kind="burn_rate",
                         metric="serve_request_ms", slo_ms=1.0,
                         slo_target=0.99, threshold=2.0, window_s=60.0,
                         severity="critical")
        alert_engine = AlertEngine(history, rules=[rule], registry=reg)
        alert_engine.evaluate_once(now=T0 + 10.0, publish=False)
        states = alert_engine.states()
        assert states[0]["state"] == "firing"
        exemplars = states[0]["exemplars"]
        assert exemplars, "firing latency rule must carry exemplars"
        spans = load_trace_dir(trace_dir)
        for ex in exemplars:
            trace_spans = find_trace(spans, ex["trace_id"])
            assert trace_spans, f"exemplar {ex['trace_id']} has no spans"
            names = {sp["name"] for sp in trace_spans.values()}
            assert "serve.request" in names
        # and the CLI resolves one too (the human path)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "trace_report.py"),
             trace_dir, "--trace-id", exemplars[0]["trace_id"]],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "serve.request" in out.stdout


class TestThreadLifecycle:
    def test_engine_evaluator_stable_under_repeated_start_stop(self):
        h, _ = _hist()
        before = threading.active_count()
        eng = AlertEngine(h, registry=MetricsRegistry(),
                          interval_s=0.005)
        for _ in range(4):
            eng.start()
            eng.start()  # idempotent
            time.sleep(0.02)
            eng.stop()
            eng.stop()  # idempotent
            assert threading.active_count() == before
        eng.close()
        assert threading.active_count() == before

    def test_watchtower_arm_stop_joins_everything(self, tmp_path):
        before = threading.active_count()
        tower = arm_watchtower(registry=MetricsRegistry(),
                               tracker=InMemoryStateTracker(),
                               process="t", out_dir=str(tmp_path),
                               interval_s=0.01)
        assert isinstance(tower, Watchtower)
        time.sleep(0.05)
        tower.tick()
        tower.stop()
        assert threading.active_count() == before
        assert os.path.isfile(tmp_path / "history_t.jsonl")
        assert os.path.isfile(tmp_path / "alerts_t.jsonl")

    def test_process_global_engine_seam(self):
        prev = set_engine(None)
        try:
            assert get_engine() is None
            h, _ = _hist()
            eng = AlertEngine(h, registry=MetricsRegistry())
            assert set_engine(eng) is None
            assert get_engine() is eng
        finally:
            set_engine(prev)


# ------------------------------------------------------------- UI surface ----

class TestAlertUi:
    @pytest.fixture
    def server(self):
        from deeplearning4j_tpu.ui import UiServer

        reg = MetricsRegistry()
        history = MetricsHistory(registry=reg)
        reg.counter("guard_skipped_steps_total").inc(0)
        history.sample_once(now=T0)
        reg.counter("guard_skipped_steps_total").inc(4)
        history.sample_once(now=T0 + 10.0)
        engine = AlertEngine(history, registry=reg, process="ui-test")
        engine.evaluate_once(now=T0 + 10.0, publish=False)
        tracker = InMemoryStateTracker()
        pub = AlertEngine(history, registry=MetricsRegistry(),
                          tracker=tracker, process="remote")
        pub.evaluate_once(now=T0 + 10.0)
        srv = UiServer()
        srv.attach_history(history)
        srv.attach_alerts(engine)
        srv.attach_federation(ClusterAggregator(
            tracker, stale_after_s=3600.0, registry=MetricsRegistry()))
        srv.start(port=0)
        yield srv
        srv.stop()

    def _get(self, server, path):
        url = f"http://127.0.0.1:{server.port}{path}"
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read())

    def test_api_alerts_states(self, server):
        status, body = self._get(server, "/api/alerts")
        assert status == 200
        assert body["process"] == "ui-test"
        states = {a["rule"]: a["state"] for a in body["alerts"]}
        assert states["nonfinite_step_rate"] == "firing"
        assert body["firing"] >= 1

    def test_api_alerts_cluster_scope(self, server):
        status, body = self._get(server, "/api/alerts?scope=cluster")
        assert status == 200
        assert [p["process"] for p in body["processes"]] == ["remote"]
        assert any(a["rule"] == "nonfinite_step_rate"
                   and a["state"] == "firing" for a in body["alerts"])

    def test_api_alerts_bad_scope_400(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(server, "/api/alerts?scope=galaxy")
        assert e.value.code == 400

    def test_api_history_index_and_points(self, server):
        status, body = self._get(server, "/api/history")
        assert status == 200
        names = {s["name"] for s in body["series"]}
        assert "guard_skipped_steps_total" in names
        status, body = self._get(
            server, "/api/history?name=guard_skipped_steps_total")
        assert body["points"] == [[T0, 0.0], [T0 + 10.0, 4.0]]

    def test_api_history_bad_window_400(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(server, "/api/history?window_s=soon")
        assert e.value.code == 400

    def test_404_without_attachments(self):
        from deeplearning4j_tpu.ui import UiServer

        prev_h = __import__(
            "deeplearning4j_tpu.telemetry.history",
            fromlist=["set_history"]).set_history(None)
        prev_e = set_engine(None)
        srv = UiServer()
        srv.start(port=0)
        try:
            for path in ("/api/alerts", "/api/history"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    self._get(srv, path)
                assert e.value.code == 404
        finally:
            srv.stop()
            from deeplearning4j_tpu.telemetry.history import set_history

            set_history(prev_h)
            set_engine(prev_e)


# ------------------------------------------------------ alert_report CLI ----

class TestAlertReport:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "alert_report.py"), *args],
            capture_output=True, text=True, timeout=60)

    def _watch_dir(self, tmp_path):
        reg = MetricsRegistry()
        tower = arm_watchtower(registry=reg,
                               process="demo",
                               out_dir=str(tmp_path), start=False)
        reg.counter("guard_skipped_steps_total").inc(0)
        tower.history.sample_once(now=T0)
        reg.counter("guard_skipped_steps_total").inc(3)
        tower.history.sample_once(now=T0 + 10.0)
        tower.engine.evaluate_once(now=T0 + 10.0)
        tower.stop()
        return str(tmp_path)

    def test_renders_timeline_and_history(self, tmp_path):
        d = self._watch_dir(tmp_path)
        out = self._run(d)
        assert out.returncode == 0, out.stderr
        assert "nonfinite_step_rate" in out.stdout
        assert "inactive -> firing" in out.stdout
        assert "!! demo/nonfinite_step_rate: firing" in out.stdout
        assert "history [demo]" in out.stdout
        assert "guard_skipped_steps_total" in out.stdout

    def test_json_mode(self, tmp_path):
        d = self._watch_dir(tmp_path)
        out = self._run(d, "--json")
        assert out.returncode == 0, out.stderr
        rep = json.loads(out.stdout)
        assert any(t["to"] == "firing" for t in rep["transitions"])
        assert rep["verdicts"][0]["rule"] == "nonfinite_step_rate"
        assert rep["histories"][0]["samples"] == 2

    def test_missing_dir_exit_2(self, tmp_path):
        out = self._run(str(tmp_path / "nope"))
        assert out.returncode == 2

    def test_empty_dir_exit_3(self, tmp_path):
        out = self._run(str(tmp_path))
        assert out.returncode == 3
        assert "no alert transitions" in out.stderr


# -------------------------------------------- end-to-end elastic pin ----

def test_alert_pin_poisoned_worker_cluster_visible(tmp_path):
    """ISSUE 15 acceptance (the e2e satellite): the guardrails
    ``nan_at_step`` injection poisons an elastic worker → the master
    quarantines it (PR 8) → the master watchtower's ``worker_divergence``
    rule fires → the flight recorder dumps ``reason=alert:...``
    forensics, the transition lands in the alerts JSONL, and the alert is
    cluster-visible through a ClusterAggregator reading over the REAL
    TCP tracker."""
    from deeplearning4j_tpu.scaleout.elastic import (
        ElasticMaster,
        ElasticWorker,
        SyntheticRegressionModel,
    )
    from deeplearning4j_tpu.scaleout.remote_tracker import (
        StateTrackerClient,
    )

    def model(**kw):
        d = dict(d_in=4, d_hidden=8, batch=8, lr=0.05, mesh_devices=1)
        d.update(kw)
        return SyntheticRegressionModel(**d)

    blob = f"file://{tmp_path / 'blob'}"
    trace_dir = str(tmp_path / "trace")
    watch_dir = str(tmp_path / "watch")
    prev = trace_mod.set_tracer(trace_mod.Tracer(
        "master", trace_dir=trace_dir, registry=MetricsRegistry(),
        min_checkpoint_interval_s=3600.0))
    try:
        master = ElasticMaster(
            model(), blob, sync_every=2, min_workers=1,
            worker_timeout_s=30.0, register_timeout_s=60,
            round_timeout_s=90, registry=MetricsRegistry(),
            watch=True, watch_dir=watch_dir)
        clean = ElasticWorker(master.address, blob, model(),
                              worker_id="clean", worker_seed=1,
                              sync_every=2, round_timeout_s=90)
        poison = ElasticWorker(master.address, blob,
                               model(nan_at_step=2, nan_worker_seed=2),
                               worker_id="poison", worker_seed=2,
                               sync_every=2, round_timeout_s=90)
        threads = [threading.Thread(target=w.run, daemon=True)
                   for w in (clean, poison)]
        for t in threads:
            t.start()
        try:
            master.wait_for_workers(2)
            master.train(rounds=3)
            # deterministic final verdict (the background evaluator may
            # already have fired; tick() is idempotent on state)
            states = {s["rule"]: s for s in master.watchtower.tick()}
            assert states["worker_divergence"]["state"] == "firing", \
                states["worker_divergence"]
            assert states["worker_divergence"]["severity"] == "critical"
            # cluster-visible over the REAL TCP tracker, while the
            # master's embedded server is still up
            client = StateTrackerClient(master.address)
            try:
                agg = ClusterAggregator(client, stale_after_s=3600.0,
                                        registry=MetricsRegistry())
                view = agg.collect_alerts()
            finally:
                client.close()
            by = {(a["process"], a["rule"]): a["state"]
                  for a in view["alerts"]}
            assert by[("master", "worker_divergence")] == "firing"
            assert view["firing"] >= 1
        finally:
            master.shutdown()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
    finally:
        trace_mod.set_tracer(prev)
    # forensics: the firing transition dumped through the flight
    # recorder with the alert reason...
    dump = json.load(open(os.path.join(trace_dir,
                                       "flightrec_master.json")))
    assert dump["reason"].startswith("alert:"), dump["reason"]
    assert dump["extra"]["rule"] in (
        "worker_divergence", "worker_heartbeat_stale")
    # ...and the alerts JSONL pins worker_divergence specifically
    log = [json.loads(l) for l in
           open(os.path.join(watch_dir, "alerts_master.jsonl"))]
    assert any(r["rule"] == "worker_divergence" and r["to"] == "firing"
               for r in log), log
    # the history spill survived too (alert_report's raw material)
    assert os.path.isfile(os.path.join(watch_dir,
                                       "history_master.jsonl"))
