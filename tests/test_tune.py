"""Roofline-guided autotuner (ISSUE 20): search-space validity, the
two-phase searcher's dominance pruning + numerics gating on synthetic
cost models (no accelerator needed), the persistent tuning cache's
fingerprint/staleness/corruption/concurrency contracts, and — the part
that keeps tuning honest — numerics pins on every ``tuned=`` adoption
path: single-device blockwise tiles (<=1e-5), composed ``alltoall_2d``
dispatch (bitwise, matching test_moe's flat-vs-2d pin), the pipeline
overlap schedule (bitwise), and the decode engine's scheduling knobs
(token-identical greedy output).
"""

import json
import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
from deeplearning4j_tpu.telemetry.xprofile import StepProfile
from deeplearning4j_tpu.tune.cache import (
    TuningCache,
    fingerprint,
    resolve_step_tuning,
    resolve_tuned,
)
from deeplearning4j_tpu.tune.search import search, spearman
from deeplearning4j_tpu.tune.space import (
    Knob,
    SearchSpace,
    get_space,
    space_names,
    space_version,
)


@pytest.fixture(autouse=True)
def _no_ambient_tuning(monkeypatch):
    """Keep every test hermetic: the env gate off, the cache path off the
    repo's real TUNE_CACHE.json."""
    monkeypatch.delenv("DL4J_TPU_TUNED", raising=False)
    monkeypatch.delenv("DL4J_TPU_TUNE_CACHE", raising=False)


# ------------------------------------------------------------- spaces ----

def test_registered_spaces_cover_the_tunable_seams():
    assert set(space_names()) >= {"flash_attention", "moe", "pipeline",
                                  "serve"}
    for seam in space_names():
        space = get_space(seam)
        assert space.size() > 0
        assert isinstance(space_version(seam), int)


def test_flash_space_rejects_non_dividing_and_oversize_blocks():
    space = get_space("flash_attention")
    ctx = {"seq_len": 256}
    valid = [cfg for cfg, reason in space.configs(ctx) if reason is None]
    # exactly the tiles that divide 256 and fit: {64,128,256}^2
    assert len(valid) == 9
    for cfg in valid:
        assert 256 % cfg["block_q"] == 0 and 256 % cfg["block_k"] == 0
    reasons = {json.dumps(cfg, sort_keys=True): reason
               for cfg, reason in space.configs(ctx) if reason}
    assert any("exceeds seq_len" in r for r in reasons.values())


def test_moe_space_applies_the_factorization_predicate():
    space = get_space("moe")
    # prime expert axis: alltoall_2d invalid, flat alltoall fine
    by_impl = {}
    for cfg, reason in space.configs({"expert_devices": 3}):
        by_impl.setdefault(cfg["moe_impl"], set()).add(reason is None)
    assert by_impl["alltoall_2d"] == {False}
    assert True in by_impl["alltoall"]
    # composite axis >= 4: alltoall_2d becomes valid
    ok = [cfg for cfg, reason in space.configs({"expert_devices": 4})
          if reason is None and cfg["moe_impl"] == "alltoall_2d"]
    assert ok
    # a single device rejects every sharded dispatch
    for cfg, reason in space.configs({"expert_devices": 1}):
        if cfg["moe_impl"] != "replicated":
            assert reason is not None


def test_pipeline_and_serve_space_validity():
    assert all(reason is None or "does not divide" in reason
               for _, reason in get_space("pipeline").configs({"batch": 8}))
    assert any(reason for _, reason
               in get_space("pipeline").configs({"batch": 6}))
    serve_reasons = [reason for cfg, reason
                     in get_space("serve").configs({"max_len": 16})
                     if cfg["min_bucket"] >= 16]
    assert serve_reasons and all(r for r in serve_reasons)


# ---------------------------------------------------- synthetic search ----

def _profile(flops, nbytes, peak, wire=0.0):
    return StepProfile(label="syn", platform="cpu", flops=flops,
                       bytes_accessed=nbytes, peak_bytes=peak,
                       collective_wire_bytes=wire, compile_seconds=0.01)


def _syn_space(candidates=(1, 2, 3, 4), validity=None):
    return SearchSpace(seam="synthetic", version=7,
                       knobs=(Knob("x", tuple(candidates)),),
                       validity=validity)


def test_search_prunes_dominated_without_executing(tmp_path):
    """x=3 is strictly dominated by x=2 in phase 1 and must NEVER reach
    measure_fn; x=4 is invalid and must never reach compile_fn."""
    profiles = {1: _profile(100.0, 100.0, 100), 2: _profile(50.0, 50.0, 50),
                3: _profile(80.0, 80.0, 200)}
    times = {1: 0.010, 2: 0.005}
    compiled, measured = [], []

    def compile_fn(cfg):
        compiled.append(cfg["x"])
        return profiles[cfg["x"]]

    def measure_fn(cfg):
        measured.append(cfg["x"])
        return times[cfg["x"]], "same-output"

    validity = lambda cfg, ctx: "four is right out" if cfg["x"] == 4 else None  # noqa: E731
    res = search(_syn_space(validity=validity), {"seq_len": 1}, {"x": 1},
                 compile_fn, measure_fn, repeats=3, out_dir=str(tmp_path))

    assert 4 not in compiled and 3 not in measured and 4 not in measured
    rec3 = next(r for r in res.candidates if r.config == {"x": 3})
    assert rec3.pruned_by == {"x": 2} and rec3.pruned_reason
    assert not rec3.measured
    assert res.winner_config == {"x": 2}
    assert res.tuned_vs_default == pytest.approx(2.0)
    assert res.counts == {"total": 4, "invalid": 1, "profiled": 3,
                          "pruned": 1, "measured": 2}
    # the cost model predicted the measured order -> perfect rank corr
    assert res.rank_correlation == pytest.approx(1.0)
    # auditable decisions file, schema'd
    rec = json.loads((tmp_path / "tuning_synthetic.json").read_text())
    assert rec["schema"] == "dl4j-tpu-tuning-v1"
    assert rec["space_version"] == 7
    assert any(c["pruned_by"] for c in rec["candidates"])


def test_search_numerics_mismatch_cannot_win():
    """A faster candidate whose outputs differ from the default's is
    excluded from winning — tuning changes speed, never results."""
    times = {1: 0.010, 2: 0.002}

    def measure_fn(cfg):
        return times[cfg["x"]], ("ref" if cfg["x"] == 1 else "DIFFERENT")

    res = search(_syn_space(candidates=(1, 2)), {}, {"x": 1},
                 lambda cfg: None, measure_fn, repeats=3)
    assert res.winner_config == {"x": 1}
    assert res.tuned_vs_default == pytest.approx(1.0)
    rec2 = next(r for r in res.candidates if r.config == {"x": 2})
    assert rec2.measured and rec2.numerics_match is False and not rec2.winner


def test_search_compile_none_keeps_candidate_on_frontier():
    """Host-side knobs (no per-config executable) skip pruning but are
    still measured."""
    times = {1: 0.010, 2: 0.004}
    res = search(_syn_space(candidates=(1, 2)), {}, {"x": 1},
                 lambda cfg: None, lambda cfg: (times[cfg["x"]], "ok"),
                 repeats=3)
    assert res.counts["profiled"] == 0 and res.counts["pruned"] == 0
    assert res.counts["measured"] == 2
    assert res.winner_config == {"x": 2}


def test_search_injects_missing_default_and_rejects_invalid_default():
    res = search(_syn_space(candidates=(1, 2)), {}, {"x": 99},
                 lambda cfg: None,
                 lambda cfg: (0.01 if cfg["x"] == 99 else 0.02, "ok"),
                 repeats=3)
    assert res.counts["total"] == 3
    assert res.winner_config == {"x": 99}

    with pytest.raises(ValueError, match="default config"):
        search(_syn_space(candidates=(1, 2),
                          validity=lambda cfg, ctx: "no"), {}, {"x": 1},
               lambda cfg: None, lambda cfg: (0.01, "ok"))


def test_spearman_basics():
    assert spearman([1.0, 2.0, 3.0], [10.0, 20.0, 30.0]) == pytest.approx(1.0)
    assert spearman([1.0, 2.0, 3.0], [3.0, 2.0, 1.0]) == pytest.approx(-1.0)
    assert spearman([1.0, 1.0, 1.0], [1.0, 2.0, 3.0]) is None
    assert spearman([1.0], [1.0]) is None


# -------------------------------------------------------------- cache ----

_CTX = {"kind": "lm", "d_model": 64, "n_heads": 2, "mesh": (2, 4),
        "backend": "cpu"}


def test_fingerprint_is_shape_sensitive_and_order_stable():
    assert fingerprint(_CTX) == fingerprint(dict(reversed(list(
        _CTX.items()))))
    # tuples and lists canonicalize identically (JSON has no tuples)
    assert fingerprint(_CTX) == fingerprint({**_CTX, "mesh": [2, 4]})
    for key, val in (("d_model", 128), ("mesh", (4, 2)), ("backend", "tpu")):
        assert fingerprint({**_CTX, key: val}) != fingerprint(_CTX)


def test_cache_store_lookup_hit_and_shape_miss(tmp_path):
    cache = TuningCache(str(tmp_path / "cache.json"))
    key = cache.store("flash_attention", _CTX, {"block_q": 64, "block_k": 64})
    assert key == f"flash_attention:{fingerprint(_CTX)}"
    assert cache.lookup("flash_attention", _CTX) == {"block_q": 64,
                                                     "block_k": 64}
    # any shape change is a miss, never a silent adoption
    assert cache.lookup("flash_attention", {**_CTX, "d_model": 128}) is None
    assert cache.lookup("flash_attention", {**_CTX, "mesh": (4, 2)}) is None
    assert cache.lookup("flash_attention", {**_CTX, "backend": "tpu"}) is None
    assert cache.lookup("serve", _CTX) is None  # seam keys the entry too


def test_corrupt_cache_is_ignored_loudly(tmp_path, caplog):
    path = tmp_path / "cache.json"
    path.write_text("{this is not json", encoding="utf-8")
    cache = TuningCache(str(path), registry=MetricsRegistry())
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.tune.cache"):
        assert cache.lookup("flash_attention", _CTX) is None
    assert any("unreadable" in r.message for r in caplog.records)
    # an alien schema warns too (never a crash, never silent)
    path.write_text(json.dumps({"schema": "someone-elses", "entries": {}}),
                    encoding="utf-8")
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.tune.cache"):
        assert cache.lookup("flash_attention", _CTX) is None
    assert any("unexpected schema" in r.message for r in caplog.records)
    # a store after corruption rebuilds a valid file
    cache.store("flash_attention", _CTX, {"block_q": 64, "block_k": 64})
    assert cache.lookup("flash_attention", _CTX) is not None


def test_stale_space_version_misses_and_sets_gauge(tmp_path, caplog):
    reg = MetricsRegistry()
    path = tmp_path / "cache.json"
    cache = TuningCache(str(path), registry=reg)
    cache.store("flash_attention", _CTX, {"block_q": 64, "block_k": 64})
    # simulate a knob-space bump since the search ran
    data = json.loads(path.read_text())
    for entry in data["entries"].values():
        entry["space_version"] = 999
    path.write_text(json.dumps(data), encoding="utf-8")
    with caplog.at_level(logging.WARNING,
                         logger="deeplearning4j_tpu.tune.cache"):
        assert cache.lookup("flash_attention", _CTX) is None
    assert any("stale" in r.message for r in caplog.records)
    # the watchtower signal (alert rule tune_cache_stale fires on > 0)
    assert reg.gauge("tune_cache_stale_entries").value == 1.0
    assert cache.stale_count() == 1


def test_concurrent_store_and_lookup_under_lockwatch(tmp_path, lockwatch):
    """8 threads hammer store+lookup on one cache file: every entry lands,
    the file never tears, and the lockwatch cycle detector (armed by the
    fixture, raise-on-cycle) sees no lock-order inversion."""
    cache = TuningCache(str(tmp_path / "cache.json"))
    errors = []

    def worker(i):
        try:
            for j in range(5):
                ctx = {**_CTX, "d_model": 64 + i * 10 + j}
                cache.store("flash_attention", ctx,
                            {"block_q": 64, "block_k": 64 * (1 + j % 2)})
                got = cache.lookup("flash_attention", ctx)
                assert got is not None
                cache.entries()
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(cache.entries()) == 40
    # the file on disk is a single valid JSON document (atomic writes)
    data = json.loads((tmp_path / "cache.json").read_text())
    assert len(data["entries"]) == 40


def test_resolve_tuned_precedence(tmp_path, monkeypatch):
    cache = TuningCache(str(tmp_path / "cache.json"))
    cache.store("serve", _CTX, {"min_bucket": 4, "slots": 8})
    # explicit dict outranks everything (no cache read)
    assert resolve_tuned({"slots": 2}, "serve", _CTX, cache) == {"slots": 2}
    # False = hard off
    assert resolve_tuned(False, "serve", _CTX, cache) is None
    # None + env unset = off
    assert resolve_tuned(None, "serve", _CTX, cache) is None
    # None + env set = cache
    monkeypatch.setenv("DL4J_TPU_TUNED", "1")
    assert resolve_tuned(None, "serve", _CTX, cache) == {"min_bucket": 4,
                                                         "slots": 8}
    # True = cache regardless of env
    monkeypatch.delenv("DL4J_TPU_TUNED")
    assert resolve_tuned(True, "serve", _CTX, cache) == {"min_bucket": 4,
                                                         "slots": 8}
    with pytest.raises(TypeError):
        resolve_tuned(3.14, "serve", _CTX, cache)


def test_resolve_step_tuning_contract(monkeypatch):
    assert resolve_step_tuning({"block_q": 64}, None,
                               ("flash_attention",)) == {"block_q": 64}
    assert resolve_step_tuning(False, _CTX, ("flash_attention",)) == {}
    # tuned=True without a context is a programming error: cache keys are
    # shape-fingerprinted, an improvised lookup would just always miss
    with pytest.raises(ValueError, match="tune_context"):
        resolve_step_tuning(True, None, ("flash_attention",))
    # the env gate without a context quietly resolves to defaults
    monkeypatch.setenv("DL4J_TPU_TUNED", "1")
    assert resolve_step_tuning(None, None, ("flash_attention",)) == {}


# --------------------------------------- tuned-adoption numerics pins ----

_V, _D, _H, _E, _DFF = 32, 16, 2, 4, 32


def _lm_params(n_layers=1, n_experts=_E):
    from deeplearning4j_tpu.models.transformer_lm import init_lm_params
    return init_lm_params(jax.random.PRNGKey(0), _V, _D, _H, n_experts,
                          _DFF, n_layers=n_layers)


def _lm_data(b, t, seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (b, t + 1), 0, _V)
    return toks[:, :-1], toks[:, 1:]


def _tree_max_abs_diff(a, b):
    return max(float(jnp.max(jnp.abs(jnp.asarray(x, jnp.float32)
                                     - jnp.asarray(y, jnp.float32))))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def test_single_device_tuned_blocks_parity_1e5():
    """tuned={block_q, block_k} on the single-device step: loss AND params
    within 1e-5 of the default block policy over 3 SGD steps (reduction
    order moves with the tiling, so the pin is allclose, not bitwise)."""
    from deeplearning4j_tpu.models.transformer_lm import (
        make_single_device_train_step,
    )

    toks, tgts = _lm_data(2, 128)
    default = make_single_device_train_step(_H, attn_impl="blockwise")
    tuned = make_single_device_train_step(
        _H, attn_impl="blockwise", tuned={"block_q": 64, "block_k": 64})
    p_d, p_t = _lm_params(), _lm_params()
    for i in range(3):
        p_d, l_d = default(p_d, toks, tgts)
        p_t, l_t = tuned(p_t, toks, tgts)
        assert abs(float(l_d) - float(l_t)) < 1e-5, (i, float(l_d),
                                                     float(l_t))
    assert _tree_max_abs_diff(p_d, p_t) < 1e-5


def test_composed_tuned_alltoall_2d_bitwise():
    """tuned={moe_impl: alltoall_2d} on the dp2xep4 composed step is
    BITWISE identical to the default flat-alltoall step — the same pin
    test_moe carries for the raw dispatchers, here through the cache-
    adoption seam (capacity_factor=1.0 keeps capacity untouched)."""
    from deeplearning4j_tpu.models.transformer_lm import (
        make_composed_train_step,
        shard_lm_batch,
        shard_lm_params,
    )
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "expert"))
    b, t = 4, 16
    capacity = (b // 2) * t
    toks, tgts = _lm_data(b, t)
    stoks, stgts = shard_lm_batch(toks, tgts, mesh)
    default = make_composed_train_step(mesh, _H, capacity)
    tuned = make_composed_train_step(
        mesh, _H, capacity,
        tuned={"moe_impl": "alltoall_2d", "capacity_factor": 1.0})
    p_d = shard_lm_params(_lm_params(), mesh)
    p_t = shard_lm_params(_lm_params(), mesh)
    for _ in range(2):
        p_d, l_d = default(p_d, stoks, stgts)
        jax.block_until_ready(l_d)
        p_t, l_t = tuned(p_t, stoks, stgts)
        jax.block_until_ready(l_t)
        assert float(l_d) == float(l_t)
    for a, c in zip(jax.tree_util.tree_leaves(jax.device_get(p_d)),
                    jax.tree_util.tree_leaves(jax.device_get(p_t))):
        assert np.array_equal(np.asarray(a), np.asarray(c))


def test_pipeline_tuned_overlap_bitwise():
    """tuned={overlap: True} through the pipeline factory's seam is
    bitwise identical (loss AND params) to the strict-tick default —
    the ISSUE 14 overlap guarantee, re-pinned through cache adoption."""
    from deeplearning4j_tpu.parallel.pipeline import (
        PIPE_AXIS,
        make_pipeline_train_step,
        shard_stage_params,
        stack_stage_params,
    )
    from jax.sharding import Mesh

    d, n_stages, n_micro, mb = 8, 4, 8, 2
    mesh = Mesh(np.array(jax.devices()[:n_stages]), (PIPE_AXIS,))
    ks = jax.random.split(jax.random.PRNGKey(3), n_stages)
    per_stage = [{"w": jax.random.normal(k, (d, d)) / np.sqrt(d),
                  "b": jnp.zeros((d,))} for k in ks]
    stage_fn = lambda p, x: jnp.tanh(x @ p["w"] + p["b"])  # noqa: E731
    loss_fn = lambda y, tt: jnp.mean((y - tt) ** 2)  # noqa: E731
    x = jax.random.normal(jax.random.PRNGKey(4), (n_micro, mb, d))
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(5),
                                     (n_micro, mb, d)))
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh)
    strict = make_pipeline_train_step(stage_fn, loss_fn, mesh, lr=0.2)
    tuned = make_pipeline_train_step(
        stage_fn, loss_fn, mesh, lr=0.2,
        tuned={"microbatches": n_micro, "overlap": True})
    p_s = jax.tree_util.tree_map(jnp.array, stacked)
    p_t = jax.tree_util.tree_map(jnp.array, stacked)
    for _ in range(3):
        p_s, l_s = strict(p_s, x, tgt)
        jax.block_until_ready(l_s)
        p_t, l_t = tuned(p_t, x, tgt)
        jax.block_until_ready(l_t)
        assert float(l_s) == float(l_t)
    for a, c in zip(jax.tree_util.tree_leaves(p_s),
                    jax.tree_util.tree_leaves(p_t)):
        assert jnp.array_equal(a, c)


def test_engine_tuned_knobs_token_identical():
    """tuned={min_bucket, slots} on DecodeEngine changes SCHEDULING only:
    the greedy token streams match the default engine exactly, and the
    knobs verifiably landed (slots/bucket observable on the engine)."""
    from deeplearning4j_tpu.serve import DecodeEngine

    params = _lm_params(n_layers=2, n_experts=2)
    rng = np.random.RandomState(11)
    prompts = [list(map(int, rng.randint(0, _V, rng.randint(3, 10))))
               for _ in range(4)]
    eng_d = DecodeEngine(params, _H, n_slots=2, max_len=32,
                         serve_dtype=None, tuned=False)
    eng_t = DecodeEngine(params, _H, n_slots=2, max_len=32,
                         serve_dtype=None,
                         tuned={"min_bucket": 4, "slots": 3})
    assert eng_t.n_slots == 3 and eng_d.n_slots == 2
    for p in prompts:
        assert (eng_t.generate(p, max_new_tokens=5)
                == eng_d.generate(p, max_new_tokens=5)), p


def test_engine_env_gate_adopts_cached_winner(tmp_path, monkeypatch):
    """End-to-end cache adoption: a winner stored under the engine's OWN
    context (serve_context of its param dims) is picked up via the
    DL4J_TPU_TUNED env gate — proving the fingerprint the engine builds
    matches the one the searcher stores under."""
    from deeplearning4j_tpu.models.transformer_lm import lm_dims
    from deeplearning4j_tpu.serve import DecodeEngine
    from deeplearning4j_tpu.tune.seams import serve_context

    params = _lm_params(n_layers=2, n_experts=2)
    cache_path = str(tmp_path / "cache.json")
    ctx = serve_context(lm_dims(params), _H, 32)
    TuningCache(cache_path).store("serve", ctx,
                                  {"min_bucket": 4, "slots": 5})
    monkeypatch.setenv("DL4J_TPU_TUNE_CACHE", cache_path)
    monkeypatch.setenv("DL4J_TPU_TUNED", "1")
    eng = DecodeEngine(params, _H, n_slots=2, max_len=32, serve_dtype=None)
    assert eng.n_slots == 5
    # a different max_len is a different fingerprint -> defaults hold
    eng2 = DecodeEngine(params, _H, n_slots=2, max_len=16, serve_dtype=None)
    assert eng2.n_slots == 2
