"""Clustering tests — mirrors ref test strategy (tree invariants + small-data
clustering assertions: KDTreeTest, VPTreeTest, QuadTreeTest, SPTreeTest,
KMeansClustering usage in BarnesHutTsne)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree,
    KMeansClustering,
    Point,
    QuadTree,
    SpTree,
    VPTree,
)


def _blobs(n_per=30, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.array([[0.0, 0.0], [10.0, 10.0], [-10.0, 10.0]])
    pts = np.concatenate(
        [c + rng.randn(n_per, 2) for c in centers]
    ).astype(np.float32)
    labels = np.repeat(np.arange(3), n_per)
    return pts, labels


class TestKMeans:
    def test_separable_blobs(self):
        pts, labels = _blobs()
        km = KMeansClustering.setup(3, max_iterations=50, seed=3)
        cs = km.apply_to(pts)
        assert len(cs.clusters) == 3
        # each true blob maps to exactly one cluster
        assign = np.array([np.argmin(np.linalg.norm(cs.centers - p, axis=1))
                           for p in pts])
        for lab in range(3):
            assert len(set(assign[labels == lab])) == 1
        # cost decreased monotonically-ish and converged
        assert km.iteration_costs[-1] <= km.iteration_costs[0]

    def test_convergence_mode_stops_early(self):
        pts, _ = _blobs()
        km = KMeansClustering.setup_convergence(3, 1e-4, max_iterations=500, seed=3)
        km.apply_to(pts)
        assert len(km.iteration_costs) < 500

    def test_cosine_distance(self):
        rng = np.random.RandomState(1)
        a = rng.rand(20, 5) + np.array([10, 0, 0, 0, 0])
        b = rng.rand(20, 5) + np.array([0, 10, 0, 0, 0])
        km = KMeansClustering.setup(2, 20, distance="cosine")
        cs = km.apply_to(np.concatenate([a, b]).astype(np.float32))
        sizes = sorted(len(c.points) for c in cs.clusters)
        assert sizes == [20, 20]

    def test_classify_point(self):
        pts, _ = _blobs()
        km = KMeansClustering.setup(3, 30)
        cs = km.apply_to(pts)
        c = cs.classify_point(Point(np.array([10.0, 10.0])), add=False)
        assert np.linalg.norm(c.center - [10, 10]) < 2.0


class TestKDTree:
    def test_nn_matches_bruteforce(self):
        rng = np.random.RandomState(0)
        data = rng.rand(200, 3)
        tree = KDTree(3)
        for row in data:
            tree.insert(row)
        assert tree.size == 200
        for _ in range(20):
            q = rng.rand(3)
            p, d = tree.nn(q)
            brute = np.linalg.norm(data - q, axis=1)
            assert d == pytest.approx(brute.min())

    def test_knn(self):
        rng = np.random.RandomState(1)
        data = rng.rand(100, 2)
        tree = KDTree(2)
        for row in data:
            tree.insert(row)
        q = np.array([0.5, 0.5])
        res = tree.knn(q, 5)
        brute = np.sort(np.linalg.norm(data - q, axis=1))[:5]
        assert np.allclose([d for _, d in res], brute)

    def test_range_search(self):
        tree = KDTree(2)
        grid = np.array([[i, j] for i in range(5) for j in range(5)], float)
        for row in grid:
            tree.insert(row)
        found = tree.range_search([1, 1], [3, 3])
        assert len(found) == 9


class TestVPTree:
    def test_knn_matches_bruteforce(self):
        rng = np.random.RandomState(2)
        data = rng.rand(150, 4)
        tree = VPTree(data)
        for _ in range(10):
            q = rng.rand(4)
            res = tree.search(q, 7)
            brute_idx = np.argsort(np.linalg.norm(data - q, axis=1))[:7]
            assert set(i for i, _ in res) == set(brute_idx.tolist())

    def test_labels(self):
        data = np.eye(4)
        tree = VPTree(data, labels=["a", "b", "c", "d"])
        res = tree.search(np.array([1.0, 0.1, 0, 0]), 1)
        assert tree.word_for(res[0][0]) == "a"

    def test_cosine(self):
        data = np.array([[1, 0], [0, 1], [0.9, 0.1]], float)
        tree = VPTree(data, similarity="cosine")
        res = tree.search(np.array([1.0, 0.0]), 2)
        assert set(i for i, _ in res) == {0, 2}


class TestQuadTree:
    def test_invariants(self):
        rng = np.random.RandomState(3)
        data = rng.randn(64, 2)
        tree = QuadTree(data)
        assert tree.is_correct()
        assert tree.cum_size == 64
        assert np.allclose(tree.center_of_mass, data.mean(0))

    def test_non_edge_forces_nonzero(self):
        rng = np.random.RandomState(4)
        data = rng.randn(32, 2)
        tree = QuadTree(data)
        neg_f = np.zeros(2)
        z = tree.compute_non_edge_forces(0, data[0], theta=0.5, neg_f=neg_f)
        assert z > 0
        assert np.linalg.norm(neg_f) > 0


class TestSpTree:
    def test_invariants_3d(self):
        rng = np.random.RandomState(5)
        data = rng.randn(50, 3)
        tree = SpTree(data)
        assert tree.is_correct()
        assert tree.cum_size == 50
        assert np.allclose(tree.center_of_mass, data.mean(0))

    def test_theta_zero_matches_exact_repulsion(self):
        # theta=0 → never approximate → matches brute-force t-SNE repulsion
        rng = np.random.RandomState(6)
        y = rng.randn(20, 2)
        tree = SpTree(y)
        i = 3
        neg_f = np.zeros(2)
        z = tree.compute_non_edge_forces(i, y[i], theta=0.0, neg_f=neg_f)
        diff = y[i] - np.delete(y, i, axis=0)
        q = 1.0 / (1.0 + (diff * diff).sum(1))
        assert z == pytest.approx(q.sum(), rel=1e-9)
        assert np.allclose(neg_f, (q[:, None] ** 2 * diff).sum(0))

    def test_edge_forces(self):
        y = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
        rows = np.array([0, 2, 3, 4])
        cols = np.array([1, 2, 0, 0])
        vals = np.array([0.5, 0.5, 1.0, 1.0])
        pos_f = SpTree.compute_edge_forces(rows, cols, vals, y)
        assert pos_f.shape == (3, 2)
        assert np.allclose(pos_f[0], 0.5 * (y[0] - y[1]) / 2 + 0.5 * (y[0] - y[2]) / 2)
