"""Config construction + JSON round-trip tests
(ref test model: NeuralNetConfigurationTest, MultiLayerNeuralNetConfigurationTest)."""

import pytest

from deeplearning4j_tpu.nn.api import LayerType, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration, NeuralNetConfiguration
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.ops.losses import LossFunction


def test_defaults_match_reference():
    c = NeuralNetConfiguration()
    assert c.lr == pytest.approx(0.1)
    assert c.momentum == pytest.approx(0.5)
    assert c.use_ada_grad is True
    assert c.weight_init == WeightInit.VI
    assert c.loss_function == LossFunction.RECONSTRUCTION_CROSSENTROPY
    assert c.k == 1
    assert c.corruption_level == pytest.approx(0.3)


def test_json_round_trip_single():
    c = NeuralNetConfiguration(
        layer_type=LayerType.OUTPUT,
        n_in=4,
        n_out=3,
        lr=0.05,
        activation_function="softmax",
        loss_function=LossFunction.MCXENT,
        momentum_after={5: 0.9},
        optimization_algo=OptimizationAlgorithm.CONJUGATE_GRADIENT,
    )
    c2 = NeuralNetConfiguration.from_json(c.to_json())
    assert c2 == c


def test_json_round_trip_multi():
    base = NeuralNetConfiguration(n_in=4, n_out=8, activation_function="tanh")
    ml = (
        NeuralNetConfiguration.Builder()
        .n_in(4)
        .n_out(8)
        .activation_function("tanh")
        .list(3)
        .hidden_layer_sizes(8, 8)
        .override(2, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False)
        .backward(True)
        .build()
    )
    assert ml.n_layers == 3
    assert ml.conf(2).layer_type == LayerType.OUTPUT
    ml2 = MultiLayerConfiguration.from_json(ml.to_json())
    assert ml2 == ml
    assert base.n_in == 4  # base untouched by overrides


def test_builder_fluent():
    c = (
        NeuralNetConfiguration.Builder()
        .lr(0.01)
        .momentum(0.9)
        .n_in(10)
        .n_out(5)
        .build()
    )
    assert c.lr == pytest.approx(0.01)
    assert c.momentum == pytest.approx(0.9)


def test_momentum_schedule():
    c = NeuralNetConfiguration(momentum=0.5, momentum_after={10: 0.9})
    assert c.momentum_at(0) == pytest.approx(0.5)
    assert c.momentum_at(10) == pytest.approx(0.9)
    assert c.momentum_at(50) == pytest.approx(0.9)


def test_hashable_for_jit():
    c1 = NeuralNetConfiguration(n_in=3, n_out=2)
    c2 = NeuralNetConfiguration(n_in=3, n_out=2)
    assert hash(c1) == hash(c2)
    ml = MultiLayerConfiguration(confs=(c1, c2))
    hash(ml)  # must not raise


class TestHessianFree:
    """HESSIAN_FREE now runs true truncated Newton (ref:
    StochasticHessianFree.java + the R-op machinery it drives)."""

    def test_solves_quadratic_in_one_outer_iteration(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from deeplearning4j_tpu.nn.api import OptimizationAlgorithm
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.optimize.solver import Solver

        rng = np.random.RandomState(0)
        a = rng.rand(6, 6)
        h = jnp.asarray(a @ a.T + 6 * np.eye(6), jnp.float32)  # SPD
        b = jnp.asarray(rng.rand(6), jnp.float32)

        def score(params, key):
            x = params["x"]
            return 0.5 * x @ h @ x - b @ x

        conf = NeuralNetConfiguration(n_in=1, n_out=1, num_iterations=5)
        solver = Solver(conf, score, num_iterations=5)
        out = solver.optimize({"x": jnp.zeros(6, jnp.float32)},
                              jax.random.PRNGKey(0),
                              algo=OptimizationAlgorithm.HESSIAN_FREE)
        expected = np.linalg.solve(np.asarray(h), np.asarray(b))
        np.testing.assert_allclose(np.asarray(out["x"]), expected,
                                   atol=1e-3, rtol=1e-3)
        # newton on a quadratic: essentially converged after iteration 1
        assert solver.score_history[1] <= solver.score_history[0]

    def test_trains_iris_network(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .num_iterations(15).seed(42).weight_init("VI")
                .optimization_algo("HESSIAN_FREE")
                .list(2)
                .override(0, layer_type="DENSE")
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax", loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        net = MultiLayerNetwork(conf).init()
        it = ds = IrisDataSetIterator(150, 150)
        x = it.next()
        s0 = net.score(x.features, x.labels)
        net.finetune(x.features, x.labels)
        s1 = net.score(x.features, x.labels)
        assert s1 < s0, (s0, s1)


class TestStepFunctions:
    """ref: optimize/stepfunctions/ + nn/conf/stepfunctions/ — the conf's
    step_function field selects how line-search solvers apply (direction,
    step) to the parameter vector."""

    def test_registry_semantics(self):
        import jax.numpy as jnp
        import numpy as np
        from deeplearning4j_tpu.optimize.stepfunctions import step_function

        x = jnp.asarray([1.0, 2.0])
        d = jnp.asarray([0.5, -0.5])
        np.testing.assert_allclose(step_function("default")(x, d, 2.0), [2.0, 1.0])
        np.testing.assert_allclose(step_function("negative_default")(x, d, 2.0), [0.0, 3.0])
        np.testing.assert_allclose(step_function("gradient")(x, d, 2.0), [1.5, 1.5])
        np.testing.assert_allclose(step_function("negative_gradient")(x, d, 2.0), [0.5, 2.5])

    def test_unknown_name_raises_at_conf_time(self):
        import pytest
        with pytest.raises(ValueError, match="step function"):
            NeuralNetConfiguration(step_function="sideways")

    def test_negative_default_ascends(self):
        """CG with negative_default flips descent into ascent (maximization
        parity with the reference's negative step functions)."""
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.api import OptimizationAlgorithm
        from deeplearning4j_tpu.optimize.solver import Solver

        def score(params, key):
            x = params["x"]
            return jnp.sum((x - 3.0) ** 2)

        conf = NeuralNetConfiguration(n_in=1, n_out=1, num_iterations=4,
                                      step_function="negative_default")
        solver = Solver(conf, score, num_iterations=4)
        out = solver.optimize({"x": jnp.zeros(3, jnp.float32)},
                              jax.random.PRNGKey(0),
                              algo=OptimizationAlgorithm.CONJUGATE_GRADIENT)
        # moved AWAY from the minimum: score increased
        assert float(score(out, None)) > float(score({"x": jnp.zeros(3)}, None))

    def test_norm2_termination_stops_at_minimum(self):
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.api import OptimizationAlgorithm
        from deeplearning4j_tpu.optimize.solver import Solver

        def score(params, key):
            return jnp.sum(params["x"] ** 2)

        conf = NeuralNetConfiguration(n_in=1, n_out=1, num_iterations=50)
        solver = Solver(conf, score, num_iterations=50)
        solver.optimize({"x": jnp.zeros(3, jnp.float32)},
                        jax.random.PRNGKey(0),
                        algo=OptimizationAlgorithm.CONJUGATE_GRADIENT)
        # grad norm 0 at the start point → Norm2/ZeroDirection stop on iter 0
        assert len(solver.score_history) == 1
