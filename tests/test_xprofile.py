"""Performance-attribution profiler tests (ISSUE 9).

Acceptance surface: every composed train-step path yields a StepProfile
with non-null FLOPs and a collective inventory matching the path's known
comm pattern (all_to_all exactly on the MoE alltoall dispatch,
collective-permute on ring sp / pipeline handoffs, all-reduce on the
grad syncs); profiling is compile-time-only (the profiled step runs at a
ZERO steady-state retrace budget); the bench ``MODEL_FLOPS`` analytic
tables cross-check against XLA ``cost_analysis()`` within documented
per-model bands; memory fields degrade to explicit ``None``s when a
backend withholds memory_analysis; and the store/registry/UI export
chain serves the blobs live.
"""

import json
import os
import sys
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.telemetry.xprofile import (
    MemoryWatermarkSampler,
    ProfiledStep,
    ProfileStore,
    StepProfile,
    attribute,
    maybe_profiled,
    parse_collectives,
    profile_compiled,
    profile_lowered,
    summarize_collectives,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, D, H, E, DFF = 32, 16, 2, 2, 32
B, T = 2, 16


# ------------------------------------------------------------ HLO parsing ----

SYNTH_HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, {}, must-alias) }, entry_computation_layout={(f32[4]{0})->f32[4]{0}}
  %all-reduce.1 = f32[4,4]{1,0} all-reduce(f32[4,4]{1,0} %p), channel_id=1, replica_groups={{0,2},{1,3}}, use_global_device_ids=true, to_apply=%sum
  %all-to-all.2 = (f32[1,8]{1,0}, f32[1,8]{1,0}) all-to-all(f32[1,8]{1,0} %a, f32[1,8]{1,0} %b), channel_id=2, replica_groups={{0,1},{2,3}}
  %collective-permute.1 = bf16[4,4]{1,0} collective-permute(bf16[4,4]{1,0} %p), channel_id=3, source_target_pairs={{0,1},{1,0}}
  %all-gather-start = f32[8]{0} all-gather-start(f32[4]{0} %p), replica_groups={{0,1}}
  %all-gather-done = f32[8]{0} all-gather-done(f32[8]{0} %all-gather-start)
"""


class TestHloParsing:
    def test_inventory_kinds_and_bytes(self):
        ops = parse_collectives(SYNTH_HLO)
        by_kind = {op.kind: op for op in ops}
        assert set(by_kind) == {"all-reduce", "all-to-all",
                                "collective-permute", "all-gather"}
        ar = by_kind["all-reduce"]
        assert ar.payload_bytes == 4 * 4 * 4 and ar.group_size == 2
        # ring convention: 2(g-1)/g * B
        assert ar.wire_bytes == pytest.approx(2 * 0.5 * 64)
        a2a = by_kind["all-to-all"]
        assert a2a.payload_bytes == 2 * 8 * 4  # tuple output summed
        assert a2a.wire_bytes == pytest.approx(0.5 * 64)
        cp = by_kind["collective-permute"]
        assert cp.payload_bytes == 4 * 4 * 2  # bf16
        assert cp.wire_bytes == cp.payload_bytes  # one hop
        ag = by_kind["all-gather"]  # -start counted once, -done skipped
        assert ag.payload_bytes == 8 * 4
        summary = summarize_collectives(ops)
        assert summary["all-gather"]["count"] == 1
        assert summary["all-reduce"]["group_sizes"] == [2]

    def test_singleton_group_carries_no_wire_bytes(self):
        hlo = ("%all-reduce.9 = f32[8]{0} all-reduce(f32[8]{0} %p), "
               "replica_groups={{0}}, to_apply=%sum")
        (op,) = parse_collectives(hlo)
        assert op.group_size == 1 and op.wire_bytes == 0.0


# -------------------------------------------------------- profile goldens ----

class TestStepProfileGoldens:
    def test_tiny_jitted_step_golden(self):
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(p, x):
            return p - 0.1 * x, (p * x).sum()

        prof = profile_compiled(step, jnp.ones((64, 64)), jnp.ones((64, 64)),
                                label="tiny")
        assert prof.label == "tiny" and prof.platform == "cpu"
        assert prof.flops and prof.flops > 0
        assert prof.bytes_accessed and prof.bytes_accessed > 0
        assert prof.collectives == {} and prof.collective_wire_bytes == 0
        assert prof.donated_args == 1
        assert prof.compile_seconds is not None
        # this CPU toolchain reports memory_analysis; its fields are real
        assert prof.temp_bytes is not None and prof.temp_bytes >= 0
        assert prof.argument_bytes == 2 * 64 * 64 * 4
        assert prof.peak_bytes is not None

    def test_memory_fields_degrade_to_explicit_none(self):
        """A backend without memory_analysis (or one that raises, as older
        plugin runtimes do) yields explicit Nones — never zeros."""

        class _NoMemCompiled:
            def cost_analysis(self):
                return [{"flops": 12.0, "bytes accessed": 7.0}]

            def memory_analysis(self):
                raise NotImplementedError("backend withholds memory stats")

            def as_text(self):
                return "HloModule stub"

        class _Lowered:
            def compile(self):
                return _NoMemCompiled()

        prof = profile_lowered(_Lowered(), label="degraded")
        assert prof.flops == 12.0
        assert prof.temp_bytes is None
        assert prof.argument_bytes is None
        assert prof.output_bytes is None
        assert prof.peak_bytes is None
        d = prof.to_dict()
        assert d["temp_bytes"] is None and d["peak_bytes"] is None

    def test_serialization_round_trip(self):
        prof = profile_compiled(jax.jit(lambda x: (x * x).sum()),
                                jnp.ones((8, 8)), label="rt")
        d = json.loads(prof.to_json())
        assert "_compiled" not in d
        back = StepProfile.from_dict(d)
        assert back.flops == prof.flops
        assert back.collectives == prof.collectives
        assert back.label == "rt"

    def test_attribute_roofline_math(self):
        prof = StepProfile(label="x", platform="tpu", flops=1e12,
                           bytes_accessed=1e9, collective_wire_bytes=0.0)
        att = attribute(prof, step_seconds=0.01, peak_flops=2e14,
                        hbm_bytes_per_sec=8e11, ici_bytes_per_sec=4.5e10)
        assert att["measured_mfu"] == pytest.approx(1e12 / 0.01 / 2e14)
        assert att["arithmetic_intensity"] == pytest.approx(1000.0)
        assert att["ridge_intensity"] == pytest.approx(250.0)
        # AI=1000 >> ridge=250: compute implied time dominates
        assert att["bound"] == "compute"
        prof2 = StepProfile(label="y", platform="tpu", flops=1e9,
                            bytes_accessed=1e9,
                            collective_wire_bytes=4.5e9)
        att2 = attribute(prof2, 0.01, peak_flops=2e14,
                         hbm_bytes_per_sec=8e11, ici_bytes_per_sec=4.5e10)
        assert att2["bound"] == "comm"
        assert att2["comm_fraction"] == pytest.approx(0.1 / 0.01)


# ---------------------------------------------------- the profile= seam ----

def _lm_toks(key=1):
    toks = jax.random.randint(jax.random.PRNGKey(key), (B, T + 1), 0, V)
    return toks[:, :-1], toks[:, 1:]


class TestProfileSeamPaths:
    """Acceptance: every composed path yields a StepProfile whose
    collective inventory matches the path's known comm pattern."""

    def test_single_device_no_collectives(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_single_device_train_step,
        )

        params = init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                                n_layers=2)
        step = make_single_device_train_step(H, profile=True)
        tk, tg = _lm_toks()
        params, loss = step(params, tk, tg)
        prof = step.step_profile
        assert prof is not None and prof.flops > 0
        assert prof.label == "lm_single_device"
        assert prof.collectives == {}
        assert np.isfinite(float(loss))

    def test_dp_ep_alltoall_has_all_to_all(self, retrace_budget):
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            shard_lm_batch,
            shard_lm_params,
        )

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "expert"))
        params = shard_lm_params(
            init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF), mesh)
        tk, tg = _lm_toks()
        stoks, stgts = shard_lm_batch(tk, tg, mesh)
        step = make_composed_train_step(mesh, H, capacity=B * T,
                                        moe_impl="alltoall", profile=True)
        params, loss = step(params, stoks, stgts)
        prof = step.step_profile
        assert prof.flops > 0
        assert prof.label == "lm_composed[dataxexpert]"
        # the MoE capacity exchange: all_to_all present on THIS dispatch...
        assert "all-to-all" in prof.collectives
        assert prof.collectives["all-to-all"]["count"] >= 2  # fwd + bwd
        # ...and the grad syncs. (No negative pin on collective-permute:
        # GSPMD may emit reshard permutes on some shapes even without a
        # ring axis — the ring-rotation POSITIVE pin lives in the
        # dp×sp×ep test.)
        assert "all-reduce" in prof.collectives
        assert prof.collective_wire_bytes > 0
        # compile-time-only: the profiled step holds a 0 steady-state
        # retrace budget (the acceptance criterion's cheap half; the wall
        # -clock half is the bench `profile` stage)
        with retrace_budget(0, label="profiled dp×ep steady state"):
            for _ in range(3):
                params, loss = step(params, stoks, stgts)
            jax.block_until_ready(loss)
        assert step.signature_fallbacks == 0

    def test_dp_ep_alltoall_2d_factorized_inventory(self, retrace_budget):
        """ISSUE 14 acceptance: on a dp×ep mesh whose expert axis
        factorizes (ep=4 → 2×2), the ``alltoall_2d`` step's compiled HLO
        replaces every flat all_to_all DEFINITION with two group-
        factorized ones — twice the op count, every replica group of size
        2 instead of 4, per-op wire bytes matching the analytic
        (g−1)/g·B model and strictly below the flat op's — with loss AND
        updated params within 1e-5 of the flat dispatch, at a 0-compile
        steady retrace budget."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            shard_lm_batch,
            shard_lm_params,
        )

        mesh = Mesh(np.array(jax.devices()).reshape(2, 4),
                    ("data", "expert"))
        n_experts = 4  # one per expert-axis device
        base = init_lm_params(jax.random.PRNGKey(0), V, D, H, n_experts,
                              DFF)
        tk, tg = _lm_toks()

        def run(moe_impl):
            params = shard_lm_params(
                jax.tree_util.tree_map(jnp.array, base), mesh)
            stoks, stgts = shard_lm_batch(tk, tg, mesh)
            step = make_composed_train_step(mesh, H, capacity=B * T,
                                            moe_impl=moe_impl, profile=True)
            params, loss = step(params, stoks, stgts)
            return step, params, loss, stoks, stgts

        step_f, p_f, l_f, _, _ = run("alltoall")
        step_2, p_2, l_2, stoks, stgts = run("alltoall_2d")
        prof_f = step_f.step_profile
        prof_2 = step_2.step_profile

        a2a_f = prof_f.collectives["all-to-all"]
        a2a_2 = prof_2.collectives["all-to-all"]
        assert a2a_f["group_sizes"] == [4]

        ops_f = [o for o in prof_f.collective_ops
                 if o["kind"] == "all-to-all"]
        ops_2 = [o for o in prof_2.collective_ops
                 if o["kind"] == "all-to-all"]
        assert len(ops_f) == a2a_f["count"]  # nothing truncated
        assert len(ops_2) == a2a_2["count"]
        for op in ops_f + ops_2:
            # the analytic ring model holds per definition: (g−1)/g·B
            g, payload = op["group_size"], op["payload_bytes"]
            assert op["wire_bytes"] == pytest.approx(
                (g - 1) / g * payload, rel=1e-6), op
        # GSPMD may insert flat-group respec a2a ops OUTSIDE the MoE
        # dispatch (batch resharding); those appear unchanged in both
        # programs. The MoE exchange ops are the remainder — and every
        # one of them factorizes into TWO group-2 definitions.
        respec = [o for o in ops_2 if o["group_size"] == 4]
        factored = [o for o in ops_2 if o["group_size"] == 2]
        assert factored and len(ops_2) == len(respec) + len(factored)
        assert len(factored) == 2 * (len(ops_f) - len(respec)), (
            ops_f, ops_2)
        # per-collective reduction at the SAME per-op payload B: a
        # factorized definition moves (1/2)·B vs the flat one's (3/4)·B
        flat_payloads = {o["payload_bytes"] for o in ops_f}
        for op in factored:
            assert op["payload_bytes"] in flat_payloads, op
            assert op["wire_bytes"] < (3 / 4) * op["payload_bytes"] - 1e-6

        # parity vs the flat impl (bit-identical routing; ≤1e-5 pinned)
        assert abs(float(l_f) - float(l_2)) <= 1e-5
        for a, b in zip(jax.tree_util.tree_leaves(p_f),
                        jax.tree_util.tree_leaves(p_2)):
            assert float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b)))
                         ) <= 1e-5

        with retrace_budget(0, label="alltoall_2d dp×ep steady state"):
            for _ in range(2):
                p_2, l_2 = step_2(p_2, stoks, stgts)
            jax.block_until_ready(l_2)
        assert step_2.signature_fallbacks == 0

    def test_dp_ep_replicated_has_no_all_to_all(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            shard_lm_batch,
            shard_lm_params,
        )

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "expert"))
        params = shard_lm_params(
            init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF), mesh)
        tk, tg = _lm_toks()
        stoks, stgts = shard_lm_batch(tk, tg, mesh)
        step = make_composed_train_step(mesh, H, capacity=B * T,
                                        moe_impl="replicated", profile=True)
        params, _ = step(params, stoks, stgts)
        prof = step.step_profile
        # the replicated dispatch combines via dense psum: all-reduce only
        assert "all-to-all" not in prof.collectives
        assert "all-reduce" in prof.collectives

    def test_dp_sp_ep_has_ring_permutes(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_composed_train_step,
            shard_lm_batch,
            shard_lm_params,
        )

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                    ("data", "sp", "expert"))
        params = shard_lm_params(
            init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF), mesh)
        tk, tg = _lm_toks()
        stoks, stgts = shard_lm_batch(tk, tg, mesh)
        step = make_composed_train_step(mesh, H, capacity=B * T,
                                        moe_impl="alltoall", profile=True)
        params, loss = step(params, stoks, stgts)
        prof = step.step_profile
        assert prof.flops > 0
        # ring sp: K/V rotation is a collective-permute chain
        assert "collective-permute" in prof.collectives
        assert "all-to-all" in prof.collectives
        assert "all-reduce" in prof.collectives
        assert np.isfinite(float(loss))

    def test_pipeline_has_stage_handoff_permutes(self):
        from deeplearning4j_tpu.parallel.pipeline import (
            PIPE_AXIS,
            make_pipeline_train_step,
            shard_stage_params,
            stack_stage_params,
        )

        mesh = Mesh(np.array(jax.devices()[:4]), (PIPE_AXIS,))
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        per_stage = [{"w": jax.random.normal(k, (D, D)) / np.sqrt(D),
                      "b": jnp.zeros((D,))} for k in ks]
        stage_fn = lambda p, x: jnp.tanh(x @ p["w"] + p["b"])  # noqa: E731
        params = shard_stage_params(stack_stage_params(per_stage), mesh)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 2, D))
        tgt = jax.random.normal(jax.random.PRNGKey(2), (4, 2, D))
        step = make_pipeline_train_step(
            stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh, lr=0.1,
            profile=True)
        params, loss = step(params, x, tgt)
        prof = step.step_profile
        assert prof.flops > 0
        assert prof.label == "pipeline[pipe]"
        # the tick schedule's stage handoffs
        assert "collective-permute" in prof.collectives
        # output replication + grad reduction psums
        assert "all-reduce" in prof.collectives
        assert np.isfinite(float(loss))

    def test_dp_sync_trainer_has_grad_allreduce(self):
        from deeplearning4j_tpu.models.zoo import mnist_mlp
        from deeplearning4j_tpu.nn import functional as F
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
        from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

        conf = mnist_mlp(32, 16)
        params = F.init_params(conf, jax.random.PRNGKey(0))
        states = F.init_train_state(conf, params)
        mesh = data_parallel_mesh(4)
        step = make_sync_train_step(conf, mesh, profile=True)
        kx, ky = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.uniform(kx, (16, 784), jnp.float32)
        y = jax.nn.one_hot(jax.random.randint(ky, (16,), 0, 10), 10,
                           dtype=jnp.float32)
        w = jnp.ones((16,), jnp.float32)
        params, states, score = step(params, states, jnp.asarray(0), x, y, w,
                                     jax.random.PRNGKey(2))
        prof = step.step_profile
        assert prof.flops > 0
        assert prof.label == "dp_sync[4]"
        assert "all-reduce" in prof.collectives
        assert prof.collectives["all-reduce"]["group_sizes"] == [4]
        assert np.isfinite(float(score))

    def test_elastic_worker_model_profiles(self):
        from deeplearning4j_tpu.scaleout.elastic import (
            SyntheticRegressionModel,
        )

        model = SyntheticRegressionModel(d_in=8, d_hidden=16, batch=16,
                                         mesh_devices=2, profile=True)
        assert model.step_profile is None  # nothing compiled yet
        p = model.init_params()
        p, loss = model.run_steps(p, 0, 2, worker_seed=0)
        prof = model.step_profile
        assert prof is not None and prof.flops > 0
        assert prof.label == "elastic_worker"
        # data-parallel grad sync over the 2-device mesh
        assert "all-reduce" in prof.collectives
        assert np.isfinite(float(loss))

    def test_seam_off_is_zero_cost_passthrough(self):
        f = jax.jit(lambda x: x + 1)
        assert maybe_profiled(f, None, "label") is f
        assert maybe_profiled(f, False, "label") is f
        wrapped = maybe_profiled(f, "custom", "default")
        assert isinstance(wrapped, ProfiledStep)
        assert wrapped.label == "custom"

    def test_signature_drift_falls_back_not_fails(self):
        step = ProfiledStep(jax.jit(lambda x: (x * 2).sum()), label="drift")
        step(jnp.ones((4,)))
        out = step(jnp.ones((6,)))  # aval drift -> jit-cache fallback
        assert float(out) == 12.0
        assert step.signature_fallbacks == 1


# ------------------------------------------- FLOPs-table cross-check ----

class TestModelFlopsCrossCheck:
    """ISSUE 9 satellite: bench.py's analytic MODEL_FLOPS formulas vs the
    XLA cost_analysis() FLOPs of the exact compiled train step, at
    CPU-sized shapes. The formulas are parametric and TRAIN_FLOPS
    evaluates the same formulas at the bench shapes, so agreement here
    means the MFU tables cannot silently rot.

    Documented tolerance bands (why the ratio is not exactly 1.0):
    the analytic ×3 train factor assumes BOTH backward matmuls for every
    layer, but XLA eliminates the FIRST layer's input gradient (no one
    needs dL/dx of the data), which is the dominant matmul for mlp/conv
    and the one-hot input for lstm — hence the sub-1.0 centers there.
    Scanned programs are checked at trip count 1 (the lax.scan body is
    counted ONCE by HloCostAnalysis — pinned below) so the comparison is
    like-for-like. Bands are ±~10% around the measured centers; a
    structural edit (extra layer, changed width wiring) moves the ratio
    far outside them."""

    # model → (batch, per-sample analytic fwd FLOPs thunk, lo, hi)
    def _cases(self):
        sys.path.insert(0, REPO)
        import bench

        return bench, {
            "mlp": (64, lambda b: b.mlp_fwd_flops(), 0.70, 0.90),
            "lenet": (32, lambda b: b.lenet_fwd_flops(), 0.90, 1.15),
            "conv": (4, lambda b: b.conv_wide_fwd_flops(), 0.70, 0.92),
            "attn": (4, lambda b: b.attn_fwd_flops(), 0.90, 1.10),
        }

    def test_conf_models_match_cost_analysis(self):
        from deeplearning4j_tpu.nn import functional as F

        bench, cases = self._cases()
        for model, (batch, fwd, lo, hi) in cases.items():
            conf = bench._conf(model)
            params = F.init_params(conf, jax.random.PRNGKey(0))
            states = F.init_train_state(conf, params)
            x, y = bench._make_data(model, 1, batch)
            step = F.make_train_step(conf)
            prof = profile_compiled(step, params, states, 0, x[0], y[0],
                                    jax.random.PRNGKey(1),
                                    label=f"crosscheck_{model}")
            ratio = prof.flops / batch / (3 * fwd(bench))
            assert lo <= ratio <= hi, (
                f"{model}: XLA/analytic train-FLOPs ratio {ratio:.3f} "
                f"outside [{lo}, {hi}] — the MODEL_FLOPS formula and the "
                "model diverged; update the formula (and MFU history "
                "note) together")

    def test_lstm_matches_at_scan_trip_one(self):
        """The LSTM scans timesteps; HloCostAnalysis counts the body once,
        so the like-for-like check runs one timestep."""
        from deeplearning4j_tpu.nn import functional as F

        bench, _ = self._cases()
        conf = bench._conf("lstm")
        params = F.init_params(conf, jax.random.PRNGKey(0))
        states = F.init_train_state(conf, params)
        vocab, batch = bench.LSTM_VOCAB, 8
        toks = jax.random.randint(jax.random.PRNGKey(2), (batch, 2), 0,
                                  vocab)
        xs = jax.nn.one_hot(toks[..., :-1], vocab, dtype=jnp.float32)
        ys = jax.nn.one_hot(toks[..., 1:], vocab, dtype=jnp.float32)
        step = F.make_train_step(conf)
        prof = profile_compiled(step, params, states, 0, xs, ys,
                                jax.random.PRNGKey(1),
                                label="crosscheck_lstm")
        analytic = 3 * bench.lstm_fwd_flops(vocab, seq=1)
        ratio = prof.flops / batch / analytic
        assert 0.75 <= ratio <= 1.05, ratio

    def test_lm_composed_matches_scan_adjusted(self):
        """The flagship's layer stack is a scan: the compiled step's FLOPs
        must match bench.lmc_xla_flops_expectation (3× the single-layer
        formula), which is also the cross-check bench.py embeds in its
        profile blobs."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            make_single_device_train_step,
        )

        bench, _ = self._cases()
        vocab, d, heads, experts, dff = 64, 32, 2, 2, 64
        seq, batch, layers = 32, 2, 2
        params = init_lm_params(jax.random.PRNGKey(0), vocab, d, heads,
                                experts, dff, n_layers=layers)
        step = make_single_device_train_step(heads, attn_impl="dense")
        toks = jax.random.randint(jax.random.PRNGKey(2), (batch, seq + 1),
                                  0, vocab)
        prof = profile_compiled(step, params, toks[:, :-1], toks[:, 1:],
                                label="crosscheck_lmc")
        expectation = bench.lmc_xla_flops_expectation(
            vocab, d, experts, dff, seq, batch)
        ratio = prof.flops / expectation
        assert 0.85 <= ratio <= 1.25, ratio

    def test_scan_body_counted_once_is_still_true(self):
        """The convention the scan adjustments stand on: if a jaxlib
        upgrade starts multiplying loop bodies by trip count, this pin
        fails loudly and the adjustments must be removed together."""
        w = jnp.ones((64, 64))

        def scanned(h):
            h, _ = jax.lax.scan(lambda c, _: (jnp.tanh(c @ w), None), h,
                                None, length=8)
            return h.sum()

        def unrolled(h):
            for _ in range(8):
                h = jnp.tanh(h @ w)
            return h.sum()

        h = jnp.ones((64, 64))
        ps = profile_compiled(jax.jit(scanned), h, label="scan8")
        pu = profile_compiled(jax.jit(unrolled), h, label="unroll8")
        assert ps.flops < pu.flops / 4, (ps.flops, pu.flops)

    def test_train_flops_derive_from_the_formulas(self):
        """TRAIN_FLOPS is the same formulas at the bench shapes — no
        independent constants left to rot."""
        bench, _ = self._cases()
        assert bench.TRAIN_FLOPS["mlp"] == 3 * bench.mlp_fwd_flops()
        assert bench.TRAIN_FLOPS["lstm_wide"] == 3 * bench.lstm_fwd_flops(
            bench.LSTM_WIDE_HID)
        assert bench.TRAIN_FLOPS["attn_long"] == 3 * bench.attn_fwd_flops(
            bench.ATTN_LONG_VOCAB, bench.ATTN_LONG_D, bench.ATTN_LONG_SEQ)
        assert bench.TRAIN_FLOPS["lm_composed"] == 3 * bench.lmc_fwd_flops()
        assert set(bench.MODEL_FLOPS) == set(bench.TRAIN_FLOPS)


# ------------------------------------------------ store / sampler / UI ----

class TestStoreAndExport:
    def test_store_records_and_mirrors_gauges(self):
        from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry()
        store = ProfileStore(registry=reg)
        prof = profile_compiled(jax.jit(lambda x: (x @ x).sum()),
                                jnp.ones((32, 32)), label="store_me",
                                store=store)
        rec = store.get("store_me")
        assert rec is not None and rec["flops"] == prof.flops
        assert [r["label"] for r in store.snapshot()] == ["store_me"]
        g = reg.gauge("profile_flops", {"step": "store_me"})
        assert g.value == prof.flops
        assert reg.gauge("profile_peak_bytes",
                         {"step": "store_me"}).value > 0

    def test_watermark_sampler_cpu_degrades_gracefully(self):
        from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

        reg = MetricsRegistry()
        sampler = MemoryWatermarkSampler(registry=reg, interval_s=0.02)
        with sampler:
            jax.block_until_ready(jnp.ones((128, 128)) @ jnp.ones((128, 128)))
        assert sampler.samples >= 2  # start + stop at minimum
        # CPU devices report no memory_stats: EXPLICITLY empty, not zeros
        assert sampler.watermarks() == {}
        assert reg.counter("profile_memory_samples_total").value >= 2

    def test_watermark_math_on_synthetic_stats(self, monkeypatch):
        from deeplearning4j_tpu.telemetry.registry import MetricsRegistry
        from deeplearning4j_tpu.utils import profiling as prof_mod

        seq = iter([
            [{"device": "tpu:0", "bytes_in_use": 100,
              "peak_bytes_in_use": 120}],
            [{"device": "tpu:0", "bytes_in_use": 300,
              "peak_bytes_in_use": 320}],
            [{"device": "tpu:0", "bytes_in_use": 50,
              "peak_bytes_in_use": 320}],
        ])
        monkeypatch.setattr(prof_mod, "device_memory_stats",
                            lambda: next(seq))
        reg = MetricsRegistry()
        sampler = MemoryWatermarkSampler(registry=reg)
        for _ in range(3):
            sampler.sample_once()
        assert sampler.watermarks() == {"tpu:0": 300}
        assert reg.gauge("profile_memory_bytes_in_use",
                         {"device": "tpu:0"}).value == 50
        assert reg.gauge("profile_memory_watermark_bytes",
                         {"device": "tpu:0"}).value == 300
        assert reg.gauge("profile_memory_peak_bytes",
                         {"device": "tpu:0"}).value == 320

    def test_ui_serves_api_profile(self):
        from deeplearning4j_tpu.ui.server import UiServer

        store = ProfileStore()
        profile_compiled(jax.jit(lambda x: x.sum()), jnp.ones((4,)),
                         label="ui_step", store=store)
        server = UiServer()
        server.attach_profiles(store)
        port = server.start(port=0)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/profile") as resp:
                body = json.loads(resp.read())
            assert [p["label"] for p in body["profiles"]] == ["ui_step"]
            assert body["profiles"][0]["flops"] is not None
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/profile?label=ui_step"
            ) as resp:
                one = json.loads(resp.read())
            assert one["label"] == "ui_step"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/profile?label=nope")
        finally:
            server.stop()


# --------------------------------------------------- report tooling ----

def _write_round(tmp_path, n, profile_blob, rate=100.0):
    detail = {
        "profile_overhead_pct": 1.0,
        "profile_detail": {"overhead_pct": 1.0, "profile": profile_blob,
                           "attribution": {"measured_mfu": 0.31,
                                           "bound": "compute"}},
        "lm_composed_samples_per_sec": rate,
    }
    rec = {"n": n, "cmd": "bench", "rc": 0, "tail": "",
           "parsed": {"metric": "m", "value": rate, "detail": detail}}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))


class TestProfileReportTools:
    def _blob(self, flops=1e9, peak=1000, wire=50.0):
        return {"label": "lm_single_device", "platform": "tpu",
                "flops": flops, "bytes_accessed": 2e8, "peak_bytes": peak,
                "temp_bytes": peak // 2, "collective_wire_bytes": wire,
                "collectives": {"all-reduce": {"count": 2,
                                               "payload_bytes": 64,
                                               "wire_bytes": wire,
                                               "group_sizes": [4]}},
                "donated_args": 1, "compile_seconds": 0.5}

    def test_profile_report_renders_rounds(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import profile_report

        _write_round(tmp_path, 6, self._blob(peak=1000, wire=50.0))
        _write_round(tmp_path, 7, self._blob(peak=1500, wire=50.0))
        rc = profile_report.main(["--dir", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "profile" in out and "all-reducex2" in out
        assert "+50.0%" in out and "GREW" in out  # peak bytes delta

        rc = profile_report.main(["--dir", str(tmp_path), "--json"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 0 and rep["selected"] == 7
        (stage,) = [s for s in rep["stages"] if s["stage"] == "profile"]
        assert stage["collective_counts"] == {"all-reduce": 2}
        assert stage["attribution"]["bound"] == "compute"

    def test_profile_report_no_blobs_is_explicit(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import profile_report

        rc = profile_report.main(["--dir", str(tmp_path)])
        assert rc == 0
        assert "no profile blobs" in capsys.readouterr().out

    def test_bench_report_flags_footprint_growth(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import bench_report

        # rates steady, but peak bytes and collective bytes balloon
        _write_round(tmp_path, 6, self._blob(peak=1000, wire=50.0))
        _write_round(tmp_path, 7, self._blob(peak=2000, wire=500.0))
        rounds = bench_report.load_rounds(str(tmp_path))
        assert rounds[-1]["metrics"]["profile_profile_peak_bytes"] == 2000
        traj = bench_report.build_trajectory(rounds, threshold_pct=10.0)
        regressed = {r["metric"] for r in traj["regressions"]}
        assert "profile_profile_peak_bytes" in regressed
        assert "profile_profile_collective_bytes" in regressed
        # the rate metric did NOT regress
        assert "lm_composed_samples_per_sec" not in regressed
        assert all(r["lower_is_better"] for r in traj["regressions"])
        # ...and --fail-on-regression trips on the growth
        rc = bench_report.main(["--dir", str(tmp_path),
                                "--fail-on-regression"])
        assert rc == 1

    def test_bench_report_shrinking_footprint_is_not_a_regression(
            self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import bench_report

        _write_round(tmp_path, 6, self._blob(peak=2000, wire=500.0))
        _write_round(tmp_path, 7, self._blob(peak=1000, wire=50.0))
        rounds = bench_report.load_rounds(str(tmp_path))
        traj = bench_report.build_trajectory(rounds, threshold_pct=10.0)
        assert traj["regressions"] == []

    def _comm_overlap_round(self, tmp_path, n, wire, ratio=1.1):
        """A round whose detail mimics the ISSUE 14 comm_overlap stage:
        ratio rows at top level + the stage detail's tracked wire total."""
        detail = {
            "comm_overlap_overlap_vs_strict": ratio,
            "comm_overlap_a2a_2d_vs_flat": ratio,
            "comm_overlap_ring_prefetch_vs_rotate_after": ratio,
            "comm_overlap_detail": {"collective_wire_bytes": wire,
                                    "profile": self._blob(wire=wire)},
        }
        rec = {"n": n, "cmd": "bench", "rc": 0, "tail": "",
               "parsed": {"metric": "m", "value": ratio, "detail": detail}}
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))

    def test_bench_report_tracks_comm_overlap_rows_and_wire_bytes(
            self, tmp_path):
        """ISSUE 14 satellite: the comm_overlap_* ratio rows are tracked
        (HIGHER is better — a shrinking overlap ratio flags) and the
        stage's collective_wire_bytes row is LOWER-IS-BETTER, pinned BOTH
        directions: comm growth trips --fail-on-regression, shrink does
        not."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import bench_report

        # growth direction: wire balloons, ratios steady → regression
        self._comm_overlap_round(tmp_path, 6, wire=1000.0)
        self._comm_overlap_round(tmp_path, 7, wire=5000.0)
        rounds = bench_report.load_rounds(str(tmp_path))
        m = rounds[-1]["metrics"]
        assert m["comm_overlap_collective_wire_bytes"] == 5000.0
        assert m["comm_overlap_overlap_vs_strict"] == 1.1
        assert m["comm_overlap_a2a_2d_vs_flat"] == 1.1
        assert m["comm_overlap_ring_prefetch_vs_rotate_after"] == 1.1
        traj = bench_report.build_trajectory(rounds, threshold_pct=10.0)
        regressed = {r["metric"] for r in traj["regressions"]}
        assert "comm_overlap_collective_wire_bytes" in regressed
        rc = bench_report.main(["--dir", str(tmp_path),
                                "--fail-on-regression"])
        assert rc == 1

        # shrink direction: wire drops (the factorization landing) → clean
        for f in tmp_path.glob("BENCH_r*.json"):
            f.unlink()
        self._comm_overlap_round(tmp_path, 6, wire=5000.0)
        self._comm_overlap_round(tmp_path, 7, wire=1000.0)
        rounds = bench_report.load_rounds(str(tmp_path))
        traj = bench_report.build_trajectory(rounds, threshold_pct=10.0)
        assert traj["regressions"] == []
        # ...but an eroding overlap ratio DOES flag (higher-is-better row)
        for f in tmp_path.glob("BENCH_r*.json"):
            f.unlink()
        self._comm_overlap_round(tmp_path, 6, wire=1000.0, ratio=1.2)
        self._comm_overlap_round(tmp_path, 7, wire=1000.0, ratio=0.8)
        rounds = bench_report.load_rounds(str(tmp_path))
        traj = bench_report.build_trajectory(rounds, threshold_pct=10.0)
        regressed = {r["metric"] for r in traj["regressions"]}
        assert "comm_overlap_overlap_vs_strict" in regressed

    def test_profile_report_per_collective_delta_table(self, tmp_path,
                                                       capsys):
        """ISSUE 14 satellite: the per-collective cross-round delta table
        — op kind × count × payload × wire per stage — renders the
        factorization's shape change (one flat group-4 a2a becoming two
        group-2 definitions) in both text and JSON."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import profile_report

        flat = self._blob(wire=36.0)
        flat["collectives"] = {"all-to-all": {
            "count": 1, "payload_bytes": 48, "wire_bytes": 36.0,
            "group_sizes": [4]}}
        factored = self._blob(wire=48.0)
        factored["collectives"] = {"all-to-all": {
            "count": 2, "payload_bytes": 96, "wire_bytes": 48.0,
            "group_sizes": [2]}}
        _write_round(tmp_path, 8, flat)
        _write_round(tmp_path, 9, factored)

        rc = profile_report.main(["--dir", str(tmp_path), "--json"])
        rep = json.loads(capsys.readouterr().out)
        assert rc == 0
        (row,) = [r for r in rep["collective_deltas"]
                  if r["kind"] == "all-to-all"]
        assert row["count"] == {"prev": 1, "last": 2, "delta_pct": 100.0}
        assert row["payload_bytes"]["last"] == 96
        assert row["wire_bytes"]["prev"] == 36.0
        assert row["group_sizes"] == {"prev": [4], "last": [2]}

        rc = profile_report.main(["--dir", str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "per-collective deltas" in out
        assert "all-to-all" in out and "1->2" in out
