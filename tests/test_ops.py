"""Activation / loss / weight-init substrate tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.weights import WeightInit, init_weights
from deeplearning4j_tpu.ops.activations import activation, derivative
from deeplearning4j_tpu.ops.losses import LossFunction, loss, loss_from_logits


def test_softmax_rows_sum_to_one():
    x = jnp.array([[1.0, 2.0, 3.0], [0.0, 0.0, 0.0]])
    s = activation("softmax")(x)
    np.testing.assert_allclose(np.sum(np.asarray(s), axis=-1), [1.0, 1.0], rtol=1e-6)


def test_sigmoid_derivative():
    y = activation("sigmoid")(jnp.array([0.3, -1.2]))
    d = derivative("sigmoid", y)
    np.testing.assert_allclose(np.asarray(d), np.asarray(y * (1 - y)), rtol=1e-6)


def test_unknown_activation_raises():
    with pytest.raises(ValueError):
        activation("nope")


def test_mcxent_matches_fused():
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (8, 5))
    labels = jax.nn.one_hot(jnp.arange(8) % 5, 5)
    probs = jax.nn.softmax(logits, axis=-1)
    dense = loss(LossFunction.MCXENT, labels, probs)
    fused = loss_from_logits(LossFunction.MCXENT, labels, logits)
    np.testing.assert_allclose(float(dense), float(fused), rtol=1e-5)


def test_xent_matches_fused():
    key = jax.random.PRNGKey(1)
    logits = jax.random.normal(key, (4, 3))
    labels = (jax.random.uniform(jax.random.PRNGKey(2), (4, 3)) > 0.5).astype(jnp.float32)
    dense = loss(LossFunction.XENT, labels, jax.nn.sigmoid(logits))
    fused = loss_from_logits(LossFunction.XENT, labels, logits)
    np.testing.assert_allclose(float(dense), float(fused), rtol=1e-4)


def test_mse_zero_when_equal():
    y = jnp.ones((3, 2))
    assert float(loss(LossFunction.MSE, y, y)) == 0.0


@pytest.mark.parametrize("scheme", list(WeightInit))
def test_weight_init_shapes(scheme):
    w = init_weights(jax.random.PRNGKey(0), (6, 4), scheme, dist=("normal", 0.0, 0.01))
    assert w.shape == (6, 4)
    if scheme == WeightInit.ZERO:
        assert float(jnp.abs(w).sum()) == 0.0
    else:
        assert float(jnp.abs(w).sum()) > 0.0


def test_vi_range():
    w = init_weights(jax.random.PRNGKey(0), (100, 100), WeightInit.VI)
    r = np.sqrt(6.0) / np.sqrt(201.0)
    assert float(jnp.max(jnp.abs(w))) <= r + 1e-6
