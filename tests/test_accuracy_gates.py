"""Real-data accuracy gates (fast pytest versions of accuracy_gates.py).

The BASELINE north star is "train to reference accuracy". These gates run on
REAL data available offline: Fisher's Iris (embedded) and sklearn's bundled
UCI digits scans. The full protocol (more epochs + SdA wall-clock + labeled
synthetic-MNIST convergence proofs) lives in accuracy_gates.py and records
ACCURACY_r04.json.
"""

import pytest

pytest.importorskip("sklearn")

import accuracy_gates as ag


def test_digits_mlp_real_data_gate():
    r = ag.gate_digits_mlp(epochs=20, threshold=0.95)
    assert r["provenance"] == "real"
    assert r["passed"], f"digits MLP test accuracy {r['test_accuracy']} < 0.95"


def test_digits_conv_real_data_gate():
    r = ag.gate_digits_conv(epochs=15, threshold=0.93)
    assert r["provenance"] == "real"
    assert r["passed"], f"digits conv test accuracy {r['test_accuracy']} < 0.93"


def test_iris_real_data_gate():
    r = ag.gate_iris(epochs=150, threshold=0.9)
    assert r["provenance"] == "real"
    assert r["passed"], f"iris test accuracy {r['test_accuracy']} < 0.9"
