"""Expert-parallel MoE tests: grouped (G experts per device) capacity
dispatch, BOTH impls (GShard all_to_all exchange and the replicated-psum
path) pinned against shard-aware dense references — loss AND gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.moe import (
    EXPERT_AXIS,
    _routing,
    dropped_route_fraction,
    expected_dropped,
    expert_load,
    factor_expert_axis,
    load_balance_loss,
    moe_apply,
    moe_reference,
    resolve_moe_impl,
    route_shards,
    set_moe_impl,
    shard_expert_params,
    stack_expert_params,
)

D = 8
N_EXPERTS = 8
N_TOKENS = 64


def _mesh(n_dev=N_EXPERTS):
    return Mesh(np.array(jax.devices()[:n_dev]), (EXPERT_AXIS,))


def _expert_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _setup(seed=0, n_experts=N_EXPERTS):
    ks = jax.random.split(jax.random.PRNGKey(seed), n_experts + 2)
    per_expert = [
        {"w": jax.random.normal(k, (D, D)) / np.sqrt(D), "b": jnp.zeros((D,))}
        for k in ks[:n_experts]
    ]
    router_w = jax.random.normal(ks[-2], (D, n_experts)) / np.sqrt(D)
    x = jax.random.normal(ks[-1], (N_TOKENS, D))
    return router_w, per_expert, x


def _shards(mesh, impl):
    return route_shards(mesh, (), EXPERT_AXIS, N_TOKENS, impl)


def _dense_jax(router_w, stacked, x, capacity, top_k=1, n_shards=1):
    """Pure-JAX single-device replica of the sharded dispatch math (same
    capacity/ordering semantics, per-sub-shard routing) — differentiable,
    for gradient parity against EITHER impl (pass its route_shards)."""
    n = x.shape[0]
    n_experts = router_w.shape[1]
    per = n // n_shards
    out = jnp.zeros_like(x)
    for s in range(n_shards):
        xs = x[s * per:(s + 1) * per]
        idx, gates = _routing(xs @ router_w, top_k)
        for e in range(n_experts):
            mine_k = idx == e
            mine = mine_k.any(-1)
            gate = jnp.sum(gates * mine_k, axis=-1)
            order = jnp.argsort(
                jnp.where(mine, jnp.arange(per), per + jnp.arange(per)))
            slots = order[:capacity]
            valid = mine[slots]
            params_e = jax.tree_util.tree_map(lambda a: a[e], stacked)
            y = _expert_fn(params_e, xs[slots] * valid[:, None])
            out = out.at[s * per + slots].add(
                y * (gate[slots] * valid)[:, None])
    return out


@pytest.mark.parametrize("impl", ["replicated", "alltoall", "alltoall_2d"])
def test_moe_matches_dense_reference(impl):
    router_w, per_expert, x = _setup()
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    capacity = N_TOKENS  # ample: nothing dropped on either dispatch
    out = moe_apply(router_w, stacked, x, mesh, _expert_fn, capacity,
                    impl=impl)
    ref = moe_reference(router_w, per_expert, x, _expert_fn, capacity,
                        n_token_shards=_shards(mesh, impl))
    assert jnp.allclose(out, ref, atol=1e-5), float(
        jnp.max(jnp.abs(out - ref)))
    assert expected_dropped(router_w, x, capacity) == 0


@pytest.mark.parametrize("impl", ["replicated", "alltoall"])
def test_capacity_overflow_drops_tokens(impl):
    """Overflow semantics per impl: capacity binds per (expert, sub-shard)
    — the whole replicated token row vs each alltoall source device — and
    the shard-aware reference reproduces either exactly."""
    router_w, per_expert, x = _setup(1)
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    capacity = 4  # 64 tokens / 8 experts: busy experts must overflow
    n_shards = _shards(mesh, impl)
    dropped = expected_dropped(router_w, x, capacity, n_shards=n_shards)
    assert dropped > 0
    assert abs(float(dropped_route_fraction(
        router_w, x, capacity, n_shards=n_shards)) - dropped / N_TOKENS) < 1e-6
    out = moe_apply(router_w, stacked, x, mesh, _expert_fn, capacity,
                    impl=impl)
    ref = moe_reference(router_w, per_expert, x, _expert_fn, capacity,
                        n_token_shards=n_shards)
    assert jnp.allclose(out, ref, atol=1e-5)
    # dropped tokens contribute exactly zero
    n_zero_rows = int(jnp.sum(jnp.all(out == 0, axis=-1)))
    assert n_zero_rows >= dropped


@pytest.mark.parametrize("group", [1, 2, 4])
@pytest.mark.parametrize("top_k", [1, 2])
def test_grouped_alltoall_matches_dense_with_grads(group, top_k):
    """The tentpole parity matrix: grouped all_to_all dispatch
    (n_experts = G × devices) vs the differentiable dense oracle with
    IDENTICAL per-device capacity semantics — loss AND router/expert
    gradients to 1e-5, at a capacity tight enough to force overflow
    drops."""
    n_dev = 4
    mesh = _mesh(n_dev)
    n_experts = group * n_dev
    router_w, per_expert, x = _setup(seed=2 + group, n_experts=n_experts)
    sharded = shard_expert_params(stack_expert_params(per_expert), mesh)
    local = stack_expert_params(per_expert)
    tgt = jax.random.normal(jax.random.PRNGKey(9), (N_TOKENS, D))
    # n_local = 16 tokens/device: cap 3 overflows whenever >3 of a device's
    # tokens pick one expert (guaranteed-ish at G=1: 16 tokens, 4 experts)
    capacity = 3
    n_shards = n_dev  # alltoall routes per device

    def sharded_loss(rw, params):
        out = moe_apply(rw, params, x, mesh, _expert_fn, capacity,
                        top_k=top_k, impl="alltoall")
        return jnp.mean((out - tgt) ** 2), out

    def dense_loss(rw, params):
        out = _dense_jax(rw, params, x, capacity, top_k, n_shards)
        return jnp.mean((out - tgt) ** 2), out

    (ls, out_s), (gr_s, ge_s) = jax.value_and_grad(
        sharded_loss, argnums=(0, 1), has_aux=True)(router_w, sharded)
    (ld, out_d), (gr_d, ge_d) = jax.value_and_grad(
        dense_loss, argnums=(0, 1), has_aux=True)(router_w, local)
    assert abs(float(ls) - float(ld)) < 1e-5
    assert jnp.allclose(out_s, out_d, atol=1e-5)
    assert jnp.allclose(gr_s, gr_d, atol=1e-5), float(
        jnp.max(jnp.abs(gr_s - gr_d)))
    for k in ("w", "b"):
        err = float(jnp.max(jnp.abs(jnp.asarray(ge_s[k]) - ge_d[k])))
        assert err < 1e-5, (k, err)


@pytest.mark.parametrize("group", [1, 4])
@pytest.mark.parametrize("top_k", [1, 2])
def test_alltoall_2d_matches_flat(group, top_k):
    """ISSUE 14 tentpole parity: the hierarchical 2-phase dispatch vs the
    flat exchange at G ∈ {1, 4} × top-k ∈ {1, 2}, at a capacity tight
    enough to force overflow drops — loss, outputs AND router/expert
    gradients within 1e-5 (the values are bit-identical by construction:
    only the wire schedule differs, pinned exact here)."""
    mesh = _mesh()  # 8 devices → (outer, inner) = (4, 2)
    n_experts = group * N_EXPERTS
    router_w, per_expert, x = _setup(seed=20 + group, n_experts=n_experts)
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    capacity = 2  # 8 tokens/device: busy experts overflow
    assert expected_dropped(router_w, x, capacity, top_k,
                            n_shards=_shards(mesh, "alltoall")) > 0
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(21), (N_TOKENS, D)))

    def loss_fn(rw, ps, impl):
        out = moe_apply(rw, ps, x, mesh, _expert_fn, capacity, top_k=top_k,
                        impl=impl)
        return jnp.mean((out - tgt) ** 2), out

    (l_f, o_f), g_f = jax.value_and_grad(
        lambda rw, ps: loss_fn(rw, ps, "alltoall"),
        argnums=(0, 1), has_aux=True)(router_w, stacked)
    (l_2, o_2), g_2 = jax.value_and_grad(
        lambda rw, ps: loss_fn(rw, ps, "alltoall_2d"),
        argnums=(0, 1), has_aux=True)(router_w, stacked)
    assert abs(float(l_f) - float(l_2)) <= 1e-5
    assert jnp.array_equal(o_f, o_2)  # same routed values, bitwise
    for a, b in zip(jax.tree_util.tree_leaves(g_f),
                    jax.tree_util.tree_leaves(g_2)):
        assert float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b)))) \
            <= 1e-5


def test_alltoall_2d_rejects_non_factorizable_axis():
    """A prime (or < 4) expert-axis size has no (outer, inner) grid: the
    seam rejects alltoall_2d LOUDLY at the call, through every selection
    layer (factor helper, per-call impl, env override)."""
    for bad in (2, 3, 5, 7):
        with pytest.raises(ValueError, match="not factorizable"):
            factor_expert_axis(bad)
    assert factor_expert_axis(4) == (2, 2)
    assert factor_expert_axis(8) == (4, 2)
    router_w, per_expert, x = _setup(1)
    mesh = _mesh(2)  # 2-device expert axis: prime
    stacked = shard_expert_params(
        stack_expert_params(per_expert[:2]), mesh)
    with pytest.raises(ValueError, match="not factorizable"):
        moe_apply(router_w[:, :2], stacked, x, mesh, _expert_fn, 8,
                  impl="alltoall_2d")


def test_moe_impl_seam_accepts_alltoall_2d(monkeypatch):
    """The precedence chain carries the new impl: env var, setter, and
    per-call all resolve "alltoall_2d"; its routing sub-shard semantics
    match the flat alltoall (route_shards equal), and the dispatched
    output matches the alltoall reference at drop-discriminating
    capacity."""
    router_w, per_expert, x = _setup(1)
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    capacity = 4

    def run(**kw):
        return moe_apply(router_w, stacked, x, mesh, _expert_fn, capacity,
                         **kw)

    ref = moe_reference(router_w, per_expert, x, _expert_fn, capacity,
                        n_token_shards=_shards(mesh, "alltoall_2d"))
    assert _shards(mesh, "alltoall_2d") == _shards(mesh, "alltoall")
    # env override resolves the 2D impl
    monkeypatch.setenv("DL4J_TPU_MOE_IMPL", "alltoall_2d")
    assert resolve_moe_impl(N_TOKENS, 8) == "alltoall_2d"
    assert jnp.allclose(run(), ref, atol=1e-5)
    monkeypatch.delenv("DL4J_TPU_MOE_IMPL")
    # setter
    set_moe_impl("alltoall_2d")
    try:
        assert resolve_moe_impl(N_TOKENS, 8) == "alltoall_2d"
        assert jnp.allclose(run(), ref, atol=1e-5)
    finally:
        set_moe_impl(None)
    # per-call
    assert jnp.allclose(run(impl="alltoall_2d"), ref, atol=1e-5)
    # bogus env value still rejected loudly
    monkeypatch.setenv("DL4J_TPU_MOE_IMPL", "alltoall_3d")
    with pytest.raises(ValueError, match="alltoall_3d"):
        resolve_moe_impl(N_TOKENS, 8)
    monkeypatch.delenv("DL4J_TPU_MOE_IMPL")


def test_alltoall_2d_step_retrace_budget(retrace_budget):
    """A warmed jitted SGD step through the 2-phase dispatch holds the
    same 0-compile steady budget as the flat exchange (ISSUE 14
    acceptance: the factorization must not introduce per-step retraces)."""
    router_w, per_expert, x = _setup(7)
    mesh = _mesh()
    params = shard_expert_params(stack_expert_params(per_expert), mesh)
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(13), (N_TOKENS, D)))
    jax.block_until_ready(
        moe_apply(router_w, params, x, mesh, _expert_fn, 16,
                  impl="alltoall_2d"))  # collective warmup, see test_moe_trains

    @jax.jit
    def step(rw, ps):
        def loss_fn(rw, ps):
            out = moe_apply(rw, ps, x, mesh, _expert_fn, 16, top_k=2,
                            impl="alltoall_2d")
            return jnp.mean((out - tgt) ** 2)

        loss, (gr, ge) = jax.value_and_grad(loss_fn, argnums=(0, 1))(rw, ps)
        return rw - 0.5 * gr, jax.tree_util.tree_map(
            lambda p, g: p - 0.5 * g, ps, ge), loss

    for _ in range(2):  # compile + committed-sharding warmup
        router_w, params, loss = step(router_w, params)
        jax.block_until_ready(loss)
    with retrace_budget(0, label="alltoall_2d moe step steady state"):
        for _ in range(2):
            router_w, params, loss = step(router_w, params)
            jax.block_until_ready(loss)
    assert np.isfinite(float(loss))


def test_grouped_replicated_matches_dense():
    """The generalized replicated path at G=2: per-row capacity semantics
    with a local expert GROUP per device (vmap'd compute, one psum)."""
    n_dev = 4
    mesh = _mesh(n_dev)
    router_w, per_expert, x = _setup(seed=6, n_experts=2 * n_dev)
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    for capacity in (N_TOKENS, 5):
        out = moe_apply(router_w, stacked, x, mesh, _expert_fn, capacity,
                        top_k=2, impl="replicated")
        ref = moe_reference(router_w, per_expert, x, _expert_fn, capacity,
                            top_k=2, n_token_shards=1)
        assert jnp.allclose(out, ref, atol=1e-5), float(
            jnp.max(jnp.abs(out - ref)))


def test_moe_impl_seam_precedence(monkeypatch):
    """per-call impl > set_moe_impl > DL4J_TPU_MOE_IMPL env > auto — the
    same chain as the attention core seam. Observable discriminator: the
    two impls drop DIFFERENT tokens at a tight capacity, so each resolved
    impl is verified against its own reference."""
    router_w, per_expert, x = _setup(1)
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    capacity = 4

    def run(**kw):
        return moe_apply(router_w, stacked, x, mesh, _expert_fn, capacity,
                         **kw)

    def ref(impl):
        return moe_reference(router_w, per_expert, x, _expert_fn, capacity,
                             n_token_shards=_shards(mesh, impl))

    # the two semantics genuinely differ at this capacity (else no signal)
    assert not jnp.allclose(ref("alltoall"), ref("replicated"), atol=1e-5)
    # auto (divisible tokens) → alltoall
    assert resolve_moe_impl(N_TOKENS, 8) == "alltoall"
    assert jnp.allclose(run(), ref("alltoall"), atol=1e-5)
    # env var outranks auto
    monkeypatch.setenv("DL4J_TPU_MOE_IMPL", "replicated")
    assert resolve_moe_impl(N_TOKENS, 8) == "replicated"
    assert jnp.allclose(run(), ref("replicated"), atol=1e-5)
    # setter outranks env
    set_moe_impl("alltoall")
    try:
        assert resolve_moe_impl(N_TOKENS, 8) == "alltoall"
        assert jnp.allclose(run(), ref("alltoall"), atol=1e-5)
        # per-call outranks everything
        assert jnp.allclose(run(impl="replicated"), ref("replicated"),
                            atol=1e-5)
    finally:
        set_moe_impl(None)
    monkeypatch.delenv("DL4J_TPU_MOE_IMPL")


def test_moe_validation_errors():
    router_w, per_expert, x = _setup()
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    # n_experts not a multiple of the axis size
    with pytest.raises(ValueError, match="multiple"):
        moe_apply(router_w[:, :6], stacked, x, mesh, _expert_fn, 8)
    # forced alltoall on a token count that does not subdivide
    with pytest.raises(ValueError, match="divide"):
        moe_apply(router_w, stacked, x[:60], mesh, _expert_fn, 8,
                  impl="alltoall")
    # auto falls back to replicated on the same shape (60 % 8 != 0)
    out = moe_apply(router_w, stacked, x[:60], mesh, _expert_fn, N_TOKENS)
    ref = moe_reference(router_w, per_expert, x[:60], _expert_fn, N_TOKENS)
    assert jnp.allclose(out, ref, atol=1e-5)


def test_alltoall_step_retrace_budget(retrace_budget):
    """A warmed jitted SGD step through the all_to_all dispatch holds a
    0-compile steady budget — the exchange/scatter shapes are static, so
    per-step retraces would be a regression."""
    router_w, per_expert, x = _setup(7)
    mesh = _mesh()
    params = shard_expert_params(stack_expert_params(per_expert), mesh)
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(13), (N_TOKENS, D)))
    # collective warmup: see the comment in test_moe_trains
    jax.block_until_ready(
        moe_apply(router_w, params, x, mesh, _expert_fn, 16,
                  impl="alltoall"))

    @jax.jit
    def step(rw, ps):
        def loss_fn(rw, ps):
            out = moe_apply(rw, ps, x, mesh, _expert_fn, 16, top_k=2,
                            impl="alltoall")
            return jnp.mean((out - tgt) ** 2)

        loss, (gr, ge) = jax.value_and_grad(loss_fn, argnums=(0, 1))(rw, ps)
        return rw - 0.5 * gr, jax.tree_util.tree_map(
            lambda p, g: p - 0.5 * g, ps, ge), loss

    # two warm steps: the first compiles; the second compiles ONCE more
    # against the committed shardings the first update's outputs carry
    # (host-placed inputs became device-committed outputs — same warmup
    # the dp×pp parity harness documents in test_composed.py)
    for _ in range(2):
        router_w, params, loss = step(router_w, params)
        jax.block_until_ready(loss)
    with retrace_budget(0, label="alltoall moe step steady state"):
        for _ in range(2):
            router_w, params, loss = step(router_w, params)
            jax.block_until_ready(loss)
    assert np.isfinite(float(loss))


def test_moe_trains():
    """Router + experts train jointly through the sharded dispatch (smoke:
    loss strictly decreases; gradient EXACTNESS is pinned by
    test_moe_gradients_match_dense)."""
    router_w, per_expert, x = _setup(3)
    mesh = _mesh()
    params = shard_expert_params(stack_expert_params(per_expert), mesh)
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(11), (N_TOKENS, D)))
    capacity = 16

    # Warm the runtime with a forward-only dispatch first: on a single-core
    # host, XLA CPU's 8-thread all-reduce rendezvous can spuriously hit its
    # 40 s termination timeout when the very first collective program in
    # the process is this fused fwd+bwd step (observed deterministic abort
    # in rendezvous.cc; never once any collective has run first). Pure
    # CPU-runtime scheduling quirk — TPU doesn't use CPU collectives.
    jax.block_until_ready(
        moe_apply(router_w, params, x, mesh, _expert_fn, capacity))

    @jax.jit
    def step(rw, ps):
        def loss_fn(rw, ps):
            out = moe_apply(rw, ps, x, mesh, _expert_fn, capacity)
            return jnp.mean((out - tgt) ** 2)

        loss, (gr, ge) = jax.value_and_grad(loss_fn, argnums=(0, 1))(rw, ps)
        rw = rw - 1.0 * gr
        ps = jax.tree_util.tree_map(lambda p, g: p - 1.0 * g, ps, ge)
        return rw, ps, loss

    _, _, first = step(router_w, params)
    for _ in range(60):
        router_w, params, loss = step(router_w, params)
        # serialize dispatch: queuing 60 async multi-device executions on a
        # single-core host can starve one rendezvous participant past XLA
        # CPU's 40 s collective termination timeout (observed flaky abort)
        jax.block_until_ready(loss)
    # top-1 gating scales outputs by ~1/E at init, so MSE to an O(1) target
    # moves slowly; assert a real monotone improvement, not a large one
    assert float(loss) < float(first) * 0.99, (float(first), float(loss))


def test_top2_matches_reference():
    """Top-2 dispatch parity: a token's two experts both contribute, gates
    renormalized — sharded == dense reference, with and without overflow
    (auto resolves the impl; the reference follows its shard semantics)."""
    router_w, per_expert, x = _setup(4)
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    for capacity in (N_TOKENS, 5):
        out = moe_apply(router_w, stacked, x, mesh, _expert_fn, capacity,
                        top_k=2)
        ref = moe_reference(router_w, per_expert, x, _expert_fn, capacity,
                            top_k=2, n_token_shards=_shards(mesh, None))
        assert jnp.allclose(out, ref, atol=1e-5), float(
            jnp.max(jnp.abs(out - ref)))
    # with ample capacity every token got BOTH experts: no zero rows and
    # outputs differ from the top-1 dispatch
    out_ample = moe_apply(router_w, stacked, x, mesh, _expert_fn, N_TOKENS,
                          top_k=2)
    out1 = moe_apply(router_w, stacked, x, mesh, _expert_fn, N_TOKENS)
    assert not jnp.allclose(out_ample, out1)
    assert int(jnp.sum(jnp.all(out_ample == 0, axis=-1))) == 0


def test_top2_validation():
    router_w, per_expert, x = _setup(5)
    mesh = _mesh()
    stacked = shard_expert_params(stack_expert_params(per_expert), mesh)
    import pytest

    with pytest.raises(ValueError, match="top_k"):
        moe_apply(router_w, stacked, x, mesh, _expert_fn, 8, top_k=3)


def test_load_balance_loss_uniform_and_collapsed():
    x = jax.random.normal(jax.random.PRNGKey(0), (N_TOKENS, D))
    # zero router → uniform probs and (tie-broken) assignments: loss == 1
    uniform = float(load_balance_loss(jnp.zeros((D, N_EXPERTS)), x))
    assert abs(uniform - 1.0) < 1e-5
    # a router collapsed onto expert 0: f0≈1, P0≈1 → loss ≈ E
    rw = jnp.zeros((D, N_EXPERTS)).at[:, 0].set(5.0)
    x_pos = jnp.abs(x)  # make column-0 logits strictly dominant
    collapsed = float(load_balance_loss(rw, x_pos))
    assert collapsed > 4.0, collapsed
    loads = expert_load(rw, x_pos)
    assert int(loads[0]) == N_TOKENS


def test_aux_loss_rebalances_collapsed_router():
    """Training on the aux loss alone un-collapses a router that starts
    with every token on one expert — the dynamics the Switch loss exists
    for (no-aux top-1 routing collapses; VERDICT r04 weak #6)."""
    key = jax.random.PRNGKey(6)
    # positive features make the +2.0 column-0 weights act like a large
    # constant bias: every token's top-1 is expert 0 at start
    x = jnp.abs(jax.random.normal(key, (256, D)))
    rw = (jax.random.normal(jax.random.PRNGKey(7), (D, N_EXPERTS)) * 0.02
          ).at[:, 0].add(2.0)  # heavily biased toward expert 0
    start_max = int(jnp.max(expert_load(rw, x)))
    assert start_max > 200  # collapsed at start

    grad_fn = jax.jit(jax.grad(load_balance_loss, argnums=0))
    # pure-aux dynamics oscillate (argmax in f jumps between experts), so a
    # single late iterate can land on an oscillation peak; evaluate the
    # trailing-average (Polyak) iterate, which averages the oscillation out
    avg = jnp.zeros_like(rw)
    for i in range(600):
        rw = rw - 0.5 * grad_fn(rw, x)
        if i >= 300:
            avg = avg + rw
    avg = avg / 300.0
    loads = expert_load(avg, x)
    max_share = float(jnp.max(loads)) / 256.0
    # assert the mechanism's guarantees — the loss leaves the collapsed
    # regime (≈E) for near-uniform (≈1) and no expert dominates — rather
    # than exact uniformity, which only task-gradient noise provides
    assert float(load_balance_loss(avg, x)) < 2.0
    assert max_share < 0.7, f"still collapsed: {np.asarray(loads)}"


def test_moe_trains_balanced_with_aux():
    """Joint training (task + 1e-2·aux, top-2) keeps expert load spread
    across the mesh over a short run; the identical run WITHOUT the aux
    term ends more concentrated."""
    router_w, per_expert, x = _setup(8)
    mesh = _mesh()
    params0 = shard_expert_params(stack_expert_params(per_expert), mesh)
    tgt = jnp.tanh(jax.random.normal(jax.random.PRNGKey(12), (N_TOKENS, D)))
    capacity = 16
    jax.block_until_ready(
        moe_apply(router_w, params0, x, mesh, _expert_fn, capacity, top_k=2))

    def train(aux_weight):
        rw, ps = router_w, params0

        @jax.jit
        def step(rw, ps):
            def loss_fn(rw, ps):
                out = moe_apply(rw, ps, x, mesh, _expert_fn, capacity,
                                top_k=2)
                task = jnp.mean((out - tgt) ** 2)
                return task + aux_weight * load_balance_loss(rw, x)

            loss, (gr, ge) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(rw, ps)
            return rw - 1.0 * gr, jax.tree_util.tree_map(
                lambda p, g: p - 1.0 * g, ps, ge), loss

        first = None
        for _ in range(60):
            rw, ps, loss = step(rw, ps)
            jax.block_until_ready(loss)  # see test_moe_trains comment
            first = first if first is not None else float(loss)
        return rw, first, float(loss)

    rw_aux, first_aux, last_aux = train(1e-2)
    rw_noaux, _, _ = train(0.0)
    assert last_aux < first_aux  # still learns the task
    max_aux = float(jnp.max(expert_load(rw_aux, x, top_k=2))) / (2 * N_TOKENS)
    max_noaux = float(jnp.max(expert_load(rw_noaux, x, top_k=2))) / (2 * N_TOKENS)
    assert max_aux < 0.4, f"aux run concentrated: {max_aux}"
    assert max_aux <= max_noaux + 0.05, (max_aux, max_noaux)
