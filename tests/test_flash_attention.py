"""Blockwise/flash attention parity and O(T)-memory behavior.

The core claim: every impl behind ops/flash_attention.attention_core
computes the IDENTICAL function as the materializing reference, and the
blockwise path's backward (hand-written flash-style VJP) matches autodiff
through the dense path. Memory: the jitted blockwise program's temp
footprint must scale ~O(T), not O(T^2) (checked from XLA's compiled
memory analysis, no execution needed).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.flash_attention import (
    ATTN_IMPL_ENV,
    attention_core,
    blockwise_attention,
    blockwise_block_partials,
    dense_attention,
    get_attention_impl,
    resolve_attention_impl,
    set_attention_impl,
)


def _qkv(b=2, h=2, t=256, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, h, t, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,bq,bk", [(256, 64, 64), (256, 128, 64),
                                     (192, 64, 64), (256, 64, 128)])
def test_blockwise_matches_dense_fwd(causal, t, bq, bk):
    if t % bq or t % bk:
        pytest.skip("blocks must divide T")
    q, k, v = _qkv(t=t)
    out = blockwise_attention(q, k, v, causal, bq, bk)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_grads_match_dense(causal):
    q, k, v = _qkv(t=256, d=32)
    tgt = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def loss(attn):
        def f(q, k, v):
            return jnp.sum((attn(q, k, v) - tgt) ** 2)
        return f

    g_blk = jax.grad(loss(lambda q, k, v: blockwise_attention(
        q, k, v, causal, 64, 64)), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(lambda q, k, v: dense_attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_blk, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_blockwise_bf16_close():
    q, k, v = _qkv(t=256, d=32, dtype=jnp.bfloat16)
    out = blockwise_attention(q, k, v, True, 64, 64)
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_dispatcher_override_and_auto():
    q, k, v = _qkv(t=128, d=32)
    try:
        set_attention_impl("blockwise")
        out_b = attention_core(q, k, v, causal=True)
        set_attention_impl("dense")
        out_d = attention_core(q, k, v, causal=True)
    finally:
        set_attention_impl(None)
    np.testing.assert_allclose(np.asarray(out_b), np.asarray(out_d),
                               atol=2e-5, rtol=2e-5)
    # auto at short T = dense; long divisible T = blockwise (CPU)
    out_auto = attention_core(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_d),
                               atol=2e-5, rtol=2e-5)


def test_bad_impl_name_rejected():
    with pytest.raises(ValueError, match="flash"):
        set_attention_impl("fast")
    q, k, v = _qkv(t=64, d=16)
    with pytest.raises(ValueError, match="blockwise"):
        attention_core(q, k, v, impl="fast")


def test_env_var_override(monkeypatch):
    """DL4J_TPU_ATTN_IMPL forces the core without code edits; the
    programmatic set_attention_impl still wins over it, and a per-call
    impl= wins over both (precedence chain in the module docstring)."""
    monkeypatch.setenv(ATTN_IMPL_ENV, "blockwise")
    assert get_attention_impl() == "blockwise"
    assert resolve_attention_impl(64) == "blockwise"  # env beats auto gate
    try:
        set_attention_impl("dense")
        assert get_attention_impl() == "dense"  # programmatic beats env
    finally:
        set_attention_impl(None)
    # env-forced blockwise computes the same function at a short T
    q, k, v = _qkv(t=128, d=32)
    out_env = attention_core(q, k, v, causal=True)
    monkeypatch.delenv(ATTN_IMPL_ENV)
    out_dense = attention_core(q, k, v, causal=True, impl="dense")
    np.testing.assert_allclose(np.asarray(out_env), np.asarray(out_dense),
                               atol=2e-5, rtol=2e-5)


def test_env_var_bad_value_rejected(monkeypatch):
    monkeypatch.setenv(ATTN_IMPL_ENV, "pallas-ultra")
    with pytest.raises(ValueError, match=ATTN_IMPL_ENV):
        get_attention_impl()


def test_resolve_auto_gate():
    assert resolve_attention_impl(64) == "dense"  # below the threshold
    assert resolve_attention_impl(2048) == "blockwise"
    assert resolve_attention_impl() is None  # no override, no length


@pytest.mark.parametrize("causal", [False, True])
def test_block_partials_merge_matches_dense(causal):
    """blockwise_block_partials over K/V shards merges (logsumexp weights)
    to exactly the full attention — the ring seam's algebra, checked
    without a mesh. Offsets are the shards' global positions."""
    t, shards = 256, 4
    q, k, v = _qkv(t=t, d=32)
    ts = t // shards
    o_parts, lse_parts = [], []
    for j in range(shards):
        kj = k[:, :, j * ts:(j + 1) * ts]
        vj = v[:, :, j * ts:(j + 1) * ts]
        o_j, lse_j = blockwise_block_partials(
            q, kj, vj, q_offset=0, k_offset=j * ts, causal=causal,
            block_q=64, block_k=64)
        o_parts.append(o_j)
        lse_parts.append(lse_j)
    lse = jnp.stack(lse_parts)  # (S, B, H, T)
    w = jax.nn.softmax(lse, axis=0)[..., None]
    out = jnp.sum(jnp.stack(o_parts) * w, axis=0)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def _train_temp_bytes(t, impl):
    """Compiled temp allocation of a value_and_grad step at length t, via
    the shared compiled-step profiler (ISSUE 9 — the one-off
    memory_analysis() call this helper used to make, now through
    telemetry/xprofile.py so every introspection site shares one parser)."""
    from deeplearning4j_tpu.telemetry.xprofile import profile_compiled

    b, h, d = 1, 2, 64
    q, k, v = _qkv(b=b, h=h, t=t, d=d)

    def loss(q, k, v):
        if impl == "blockwise":
            o = blockwise_attention(q, k, v, True, 512, 512)
        else:
            o = dense_attention(q, k, v, causal=True)
        return jnp.sum(o ** 2)

    f = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
    prof = profile_compiled(f, q, k, v, label=f"attn_{impl}_t{t}")
    assert prof.temp_bytes is not None, (
        "CPU memory_analysis went missing — the O(T) linearity check "
        "needs temp bytes")
    return int(prof.temp_bytes)


def test_blockwise_memory_is_linear_in_t():
    """Doubling T must grow blockwise temps ~2x (O(T)), while the dense
    path grows ~4x (O(T^2)) — the whole point of the flash recipe."""
    t1, t2 = 2048, 4096
    blk1, blk2 = _train_temp_bytes(t1, "blockwise"), _train_temp_bytes(t2, "blockwise")
    dn1, dn2 = _train_temp_bytes(t1, "dense"), _train_temp_bytes(t2, "dense")
    blk_ratio = blk2 / max(blk1, 1)
    dense_ratio = dn2 / max(dn1, 1)
    assert blk_ratio < 2.6, f"blockwise temps grew {blk_ratio:.2f}x for 2x T"
    assert dense_ratio > 3.0, f"dense temps grew only {dense_ratio:.2f}x"
    # and at equal T the blockwise program is much smaller
    assert blk2 < dn2 / 4, (blk2, dn2)


# ------------------------------------------------- default block policy ----

def test_default_block_policy_contract():
    """The named default-tile policy (ISSUE 20): largest tile <= 512 that
    divides T, else T itself — and it IS what the core resolves when no
    explicit blocks are passed."""
    from deeplearning4j_tpu.ops.flash_attention import default_block_policy

    assert default_block_policy(2048) == 512
    assert default_block_policy(512) == 512
    assert default_block_policy(256) == 256
    assert default_block_policy(192) == 192  # <=512: the whole T is one tile
    assert default_block_policy(1536) == 512
    assert default_block_policy(1000) == 1000  # 512 doesn't divide: one block
    assert default_block_policy(193) == 193    # prime: one block, no error


@pytest.mark.parametrize("bq,bk", [(64, 64), (64, 128), (128, 64),
                                   (128, 256), (256, 64)])
def test_any_legal_block_pair_loss_and_grad_parity(bq, bk):
    """ISSUE 20 gate every tuned (block_q, block_k) rides through: any
    legal pair is loss+grad parity <= 1e-5 with the default policy —
    the tiling moves the reduction order, never the function."""
    t = 256
    q, k, v = _qkv(t=t, d=32)
    tgt = jax.random.normal(jax.random.PRNGKey(7), q.shape)

    def loss_with(blocks):
        def f(q, k, v):
            out = attention_core(q, k, v, causal=True, impl="blockwise",
                                 block_q=blocks[0] if blocks else None,
                                 block_k=blocks[1] if blocks else None)
            return jnp.mean((out - tgt) ** 2)
        return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))

    l_def, g_def = loss_with(None)(q, k, v)
    l_tun, g_tun = loss_with((bq, bk))(q, k, v)
    assert abs(float(l_def) - float(l_tun)) < 1e-5
    for a, b, name in zip(g_def, g_tun, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4,
                                   err_msg=f"d{name} mismatch at "
                                           f"({bq},{bk})")
