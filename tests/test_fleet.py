"""Serving fleet (ISSUE 19): the multi-replica decode front end.

Layers under test, bottom-up:

- ``pick_replica`` routing policy in isolation — deterministic
  least-loaded tie-break, session affinity, stale exclusion.
- ``FleetRouter`` over a scripted tracker (no engines): stale replicas
  get zero new dispatches and recover without burial; affinity survives
  a stale/rejoin cycle; a death requeues with the carried tokens and a
  decremented budget, and the buried attempt's late rows are inert.
- In-process fleet end-to-end (real ``FleetReplica`` serve loops over
  ``InMemoryStateTracker``): routed greedy output token-identical to the
  single-engine oracle, affinity pinned, UiServer ``/api/generate`` +
  ``/api/fleet`` surface, thread-count hygiene under start/stop cycles.
- The chaos pin: two SUBPROCESS replicas over the real TCP tracker,
  ``kill -9`` one mid-stream under open-loop submission — every accepted
  request completes token-identical to the oracle through requeue, the
  ``fleet_replica_down`` absence rule fires and resolves (the burial
  sentinel retires the series), and the cold-started replacement
  rejoins the membership.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer_lm import init_lm_params
from deeplearning4j_tpu.scaleout.remote_tracker import (
    StateTrackerClient,
    StateTrackerServer,
)
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.serve import (
    DecodeEngine,
    FleetReplica,
    FleetRouter,
    pick_replica,
)
from deeplearning4j_tpu.serve.router import (
    HB_PREFIX,
    LOAD_PREFIX,
    PROG_PREFIX,
    REQ_PREFIX,
)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, D, H, E, DFF, L = 61, 16, 2, 4, 32, 2
MAXLEN = 32
SYNTH = f"{V},{D},{H},{E},{DFF},{L}"


@pytest.fixture(scope="module")
def params():
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF,
                          n_layers=L)


def _prompts(n, seed=1, lo=3, hi=9):
    rng = np.random.RandomState(seed)
    return [list(map(int, rng.randint(0, V, rng.randint(lo, hi))))
            for _ in range(n)]


def _engine(params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", MAXLEN)
    kw.setdefault("serve_dtype", None)  # exact fp32: oracle parity
    return DecodeEngine(params, H, **kw)


# ------------------------------------------ pick_replica policy (pure) ----

def _view(rid, state="alive", outstanding=0, queue_depth=0,
          active_slots=0):
    return {"replica_id": rid, "state": state, "outstanding": outstanding,
            "queue_depth": queue_depth, "active_slots": active_slots}


class TestPickReplica:
    def test_least_loaded_wins(self):
        views = [_view("r1", outstanding=3), _view("r2", queue_depth=1)]
        assert pick_replica(views) == "r2"

    def test_load_sums_outstanding_queue_and_slots(self):
        # 1+1+1 on r1 vs a bare queue_depth=2 on r2: r2 is lighter
        views = [_view("r1", outstanding=1, queue_depth=1, active_slots=1),
                 _view("r2", queue_depth=2)]
        assert pick_replica(views) == "r2"

    def test_tie_break_is_deterministic_and_order_independent(self):
        a = [_view("r2"), _view("r1"), _view("r3")]
        b = [_view("r3"), _view("r2"), _view("r1")]
        # equal loads: lexicographically smallest id, however the views
        # are ordered, on every call — equal fleets route identically
        for _ in range(5):
            assert pick_replica(a) == "r1"
            assert pick_replica(b) == "r1"

    def test_stale_and_dead_excluded_even_at_zero_load(self):
        views = [_view("r1", state="stale"),
                 _view("r2", state="dead"),
                 _view("r3", outstanding=10)]
        assert pick_replica(views) == "r3"

    def test_nothing_alive_returns_none(self):
        assert pick_replica([]) is None
        assert pick_replica([_view("r1", state="stale")]) is None

    def test_pinned_live_session_beats_load(self):
        views = [_view("r1", outstanding=10), _view("r2")]
        assert pick_replica(views, session="s",
                            affinity={"s": "r1"}) == "r1"

    def test_pin_to_non_alive_replica_falls_back_to_least_loaded(self):
        views = [_view("r1", state="stale"), _view("r2", outstanding=1),
                 _view("r3")]
        assert pick_replica(views, session="s",
                            affinity={"s": "r1"}) == "r3"


# ----------------------------- router over a scripted tracker (no engines) ----

class _Scripted:
    """Drives the tracker exactly like a FleetReplica would, but under
    test control: heartbeats only when told, dispatch rows claimed and
    progress rows emitted on demand — so membership transitions and
    requeue semantics are deterministic, no real engine timing."""

    def __init__(self, tracker, rid):
        self.tracker = tracker
        self.rid = rid

    def register(self):
        self.tracker.add_worker(self.rid)
        self.beat()
        self.publish_load()

    def beat(self):
        self.tracker.increment(HB_PREFIX + self.rid, 1.0)

    def publish_load(self, queue_depth=0, active_slots=0, slots=2):
        self.tracker.put_kv(LOAD_PREFIX + self.rid, json.dumps({
            "replica_id": self.rid, "queue_depth": queue_depth,
            "active_slots": active_slots, "slots": slots,
            "weight_version": "scripted"}))

    def claim(self):
        """{request_rid: latest dispatch spec} addressed to this replica."""
        rows = self.tracker.kv_snapshot(f"{REQ_PREFIX}{self.rid}.")
        out = {}
        for key in sorted(rows):
            spec = json.loads(rows[key])
            out[spec["rid"]] = spec
        return out

    def emit(self, req_rid, attempt, tokens, done=False,
             finish_reason=None):
        self.tracker.put_kv(PROG_PREFIX + req_rid, json.dumps({
            "attempt": attempt, "tokens": list(tokens), "done": done,
            "finish_reason": finish_reason, "replica": self.rid}))


def _router(tracker, **kw):
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("poll_s", 0.001)
    return FleetRouter(tracker, **kw)


def _step_until(router, cond, beat=(), timeout_s=10.0, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while not cond():
        assert time.monotonic() < deadline, f"timed out waiting: {msg}"
        for rep in beat:
            rep.beat()
        router.step()


def _state(router, rid):
    rows = {r["replica_id"]: r
            for r in router.fleet_snapshot()["replicas"]}
    return rows.get(rid, {}).get("state")


class TestScriptedMembership:
    def test_stale_replica_gets_zero_dispatches_then_recovers(self):
        tracker = InMemoryStateTracker()
        r1, r2 = _Scripted(tracker, "r1"), _Scripted(tracker, "r2")
        r1.register()
        r2.register()
        router = _router(tracker, stale_after_s=0.08, dead_after_s=30.0)
        router.step()
        assert _state(router, "r1") == "alive"
        # r1 falls silent; r2 keeps beating → r1 stale, NOT buried
        _step_until(router, lambda: _state(router, "r1") == "stale",
                    beat=(r2,), msg="r1 stale")
        assert router.fleet_snapshot()["failed_replicas"] == []
        for _ in range(4):
            router.submit([1, 2, 3])
        router.step()
        snap = {r["replica_id"]: r
                for r in router.fleet_snapshot()["replicas"]}
        assert snap["r2"]["dispatches"] == 4
        assert snap["r1"]["dispatches"] == 0
        # recovery without burial: one fresh heartbeat → alive again
        r1.beat()
        _step_until(router, lambda: _state(router, "r1") == "alive",
                    beat=(r2,), msg="r1 recovered")
        assert router.fleet_snapshot()["failed_replicas"] == []

    def test_affinity_survives_stale_rejoin(self):
        tracker = InMemoryStateTracker()
        r1, r2 = _Scripted(tracker, "r1"), _Scripted(tracker, "r2")
        r1.register()
        r2.register()
        router = _router(tracker, stale_after_s=0.08, dead_after_s=30.0)
        router.step()
        req = router.submit([1, 2, 3], max_new_tokens=2, session="s")
        router.step()
        assert req.replica == "r1"  # tie-break
        assert router.fleet_snapshot()["affinity"] == {"s": "r1"}
        r1.emit(req.rid, 1, [4, 5], done=True)
        _step_until(router, lambda: req.t_done is not None,
                    beat=(r1, r2), msg="req done")
        # r1 goes stale, then rejoins — the pin must survive the cycle
        _step_until(router, lambda: _state(router, "r1") == "stale",
                    beat=(r2,), msg="r1 stale")
        r1.beat()
        _step_until(router, lambda: _state(router, "r1") == "alive",
                    beat=(r2,), msg="r1 rejoined")
        assert router.fleet_snapshot()["affinity"] == {"s": "r1"}
        # r1 is now the HEAVIER choice; the pin must still win
        r1.publish_load(queue_depth=5)
        req2 = router.submit([1, 2, 3], session="s")
        router.step()
        assert req2.replica == "r1"
        # while a fresh session routes by load, to r2
        req3 = router.submit([1, 2, 3], session="t")
        router.step()
        assert req3.replica == "r2"

    def test_death_requeues_carried_tokens_and_decrements_budget(self):
        tracker = InMemoryStateTracker()
        r1, r2 = _Scripted(tracker, "r1"), _Scripted(tracker, "r2")
        r1.register()
        r2.register()
        router = _router(tracker, stale_after_s=0.05, dead_after_s=0.12)
        router.step()
        prompt = [1, 2, 3, 4]
        req = router.submit(prompt, max_new_tokens=8, session="s")
        router.step()
        assert req.replica == "r1"
        spec = r1.claim()[req.rid]
        assert spec["attempt"] == 1
        assert spec["prompt"] == prompt
        assert spec["max_new"] == 8
        # r1 streams 3 tokens, then dies (heartbeats stop)
        r1.emit(req.rid, 1, [11, 12, 13])
        _step_until(router, lambda: req.generated == [11, 12, 13],
                    beat=(r1, r2), msg="partial progress")
        _step_until(router,
                    lambda: "r1" in router.fleet_snapshot()[
                        "failed_replicas"],
                    beat=(r2,), msg="r1 buried")
        assert req.requeues == 1
        assert req.t_requeue is not None
        # the pin died with the replica: the session re-pins at redispatch
        router.step()
        spec2 = r2.claim()[req.rid]
        assert spec2["attempt"] == 2
        assert spec2["prompt"] == prompt + [11, 12, 13]  # retained stream
        assert spec2["max_new"] == 5                     # budget shrunk
        assert router.fleet_snapshot()["affinity"] == {"s": "r2"}
        # a late zombie row from the buried attempt must be inert
        r1.emit(req.rid, 1, [11, 12, 13, 99, 98], done=True)
        router.step()
        assert req.t_done is None
        assert req.generated == [11, 12, 13]
        # the replacement attempt publishes ONLY its continuation
        r2.emit(req.rid, 2, [14, 15, 16, 17, 18], done=True)
        _step_until(router, lambda: req.t_done is not None,
                    beat=(r2,), msg="continuation done")
        assert req.generated == [11, 12, 13, 14, 15, 16, 17, 18]
        assert req.t_first_after_requeue is not None
        assert req.t_first_after_requeue >= req.t_requeue
        snap = router.fleet_snapshot()
        assert snap["requeued_total"] == 1
        assert snap["completed_total"] == 1


# ------------------------------ in-process fleet (real replica loops) ----

def _fleet(params, tracker, rids, **router_kw):
    reps = []
    for rid in rids:
        rep = FleetReplica(_engine(params), tracker, rid,
                           heartbeat_s=0.05, poll_s=0.005, publish_s=0.1)
        rep.start()
        reps.append(rep)
    router_kw.setdefault("stale_after_s", 0.5)
    router_kw.setdefault("dead_after_s", 2.0)
    router_kw.setdefault("poll_s", 0.005)
    return reps, _router(tracker, **router_kw)


def test_fleet_generates_token_identical_with_affinity(params):
    tracker = InMemoryStateTracker()
    reps, router = _fleet(params, tracker, ("r1", "r2"))
    try:
        prompts = _prompts(6, seed=3)
        sessions = [f"s{i % 2}" for i in range(6)]
        reqs = [router.submit(p, max_new_tokens=6, session=s)
                for p, s in zip(prompts, sessions)]
        router.run_until_idle(timeout_s=120.0)
        oracle = _engine(params)
        for p, r in zip(prompts, reqs):
            assert r.generated == oracle.generate(p, max_new_tokens=6)
            assert r.finish_reason is not None
        snap = router.fleet_snapshot()
        assert snap["alive"] == 2
        assert set(snap["affinity"]) == {"s0", "s1"}
        # each session rode exactly one replica
        assert snap["completed_total"] == 6
        assert snap["requeued_total"] == 0
    finally:
        for rep in reps:
            rep.stop()


def test_uiserver_fleet_surface(params):
    from deeplearning4j_tpu.ui import UiServer

    tracker = InMemoryStateTracker()
    reps, router = _fleet(params, tracker, ("r1",))
    router.start()
    server = UiServer()
    server.attach_fleet(router, generate_timeout_s=60.0)
    server.start(port=0)
    base = f"http://127.0.0.1:{server.port}"
    try:
        prompt = [1, 2, 3, 4]
        body = json.dumps({"prompt": prompt, "max_new_tokens": 4,
                           "session": "sess-a"}).encode()
        with urllib.request.urlopen(urllib.request.Request(
                base + "/api/generate", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=60) as resp:
            out = json.loads(resp.read())
        assert out["tokens"] == _engine(params).generate(
            prompt, max_new_tokens=4)
        assert out["n"] == 4 and out["prompt_len"] == 4
        with urllib.request.urlopen(base + "/api/fleet",
                                    timeout=10) as resp:
            fleet = json.loads(resp.read())
        assert fleet["alive"] == 1
        assert fleet["affinity"] == {"sess-a": "r1"}
        assert fleet["replicas"][0]["replica_id"] == "r1"
        assert fleet["completed_total"] == 1
        # a non-string session is a 400, not a routed request
        bad = json.dumps({"prompt": prompt, "session": 7}).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                base + "/api/generate", data=bad,
                headers={"Content-Type": "application/json"}), timeout=10)
        assert ei.value.code == 400
    finally:
        server.stop()
        router.stop()
        for rep in reps:
            rep.stop()


def test_fleet_start_stop_leaves_thread_count_stable(params):
    tracker = InMemoryStateTracker()
    engine = _engine(params)
    before = threading.active_count()
    for _ in range(3):
        rep = FleetReplica(engine, tracker, "r1", heartbeat_s=0.02,
                           poll_s=0.005, publish_s=0.05)
        router = _router(tracker, stale_after_s=0.5, dead_after_s=2.0,
                         poll_s=0.005)
        rep.start()
        router.start()
        time.sleep(0.05)
        router.stop()
        rep.stop()
    assert threading.active_count() == before


# --------------------------------------------- the chaos pin (tier-1) ----

def _spawn_replica(address, rid):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.serve.fleet",
         "--replica", "--tracker", address, "--replica-id", rid,
         "--synthetic", SYNTH, "--seed", "0", "--serve-dtype", "none",
         "--slots", "2", "--max-len", str(MAXLEN),
         "--heartbeat-s", "0.05", "--poll-s", "0.005",
         "--publish-s", "0.1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)


def _wait_ready(proc, timeout_s=120.0):
    box = {}
    ready = threading.Event()

    def scan():
        for line in proc.stdout:
            if line.startswith("FLEET_REPLICA_READY"):
                box["rid"] = line.split()[1]
                ready.set()
                break
        ready.set()
        # keep draining so the child never blocks on a full pipe
        proc.stdout.read()

    threading.Thread(target=scan, daemon=True).start()
    # wait on the READY event, not the thread: the scanner keeps
    # draining the pipe for the life of the subprocess
    ready.wait(timeout_s)
    assert box.get("rid"), "replica subprocess did not become ready"
    return box["rid"]


def test_chaos_kill9_mid_stream_completes_token_identical(params):
    """The acceptance pin: two subprocess replicas over the real TCP
    tracker, SIGKILL one mid-stream — every accepted request completes
    with zero client-visible failures, the routed greedy output is
    token-identical to the single-engine oracle, ``fleet_replica_down``
    fires off the heartbeat gauge and resolves once the burial sentinel
    retires the series, and the cold-started replacement subprocess
    rejoins the membership."""
    from deeplearning4j_tpu.telemetry.alerts import (
        AlertEngine,
        default_rules,
    )
    from deeplearning4j_tpu.telemetry.history import MetricsHistory

    prompts = _prompts(6, seed=11)
    max_new = 12
    oracle = _engine(params)
    expected = [oracle.generate(p, max_new_tokens=max_new)
                for p in prompts]

    procs = {}
    spawned = []
    with StateTrackerServer() as tsrv:
        addr = tsrv.address
        for rid in ("rA", "rB"):
            procs[rid] = _spawn_replica(addr, rid)
        for rid, proc in procs.items():
            assert _wait_ready(proc) == rid

        def cold_start(_failed_rid):
            proc = _spawn_replica(addr, "rC")
            procs["rC"] = proc
            spawned.append(proc)

        reg = MetricsRegistry()
        client = StateTrackerClient(tsrv.address)
        router = _router(tracker=client, registry=reg,
                         stale_after_s=0.3, dead_after_s=1.0,
                         poll_s=0.01, cold_start=cold_start)
        # watchtower view over the ROUTER's registry: the absence rule
        # must fire between the kill and the burial sentinel
        rule = dataclasses.replace(
            [r for r in default_rules()
             if r.name == "fleet_replica_down"][0],
            stale_s=0.4)
        hist = MetricsHistory(registry=reg)
        alerts = AlertEngine(hist, rules=[rule],
                             registry=MetricsRegistry())
        try:
            _step_until(router,
                        lambda: router.fleet_snapshot()["alive"] >= 2,
                        timeout_s=60.0, msg="both replicas alive")
            reqs = [router.submit(p, max_new_tokens=max_new)
                    for p in prompts]
            killed = False
            down_fired = False
            deadline = time.monotonic() + 180.0
            while router.has_work():
                assert time.monotonic() < deadline, "chaos did not drain"
                router.step()
                hist.sample_once()
                if any(s["state"] == "firing"
                       for s in alerts.evaluate_once()):
                    down_fired = True
                if not killed and any(
                        r.t_done is None and r.replica == "rA"
                        and len(r.generated) >= 1 for r in reqs):
                    # rA is mid-stream on an unfinished request (and,
                    # with 3 dispatches on 2 slots, necessarily holds
                    # more unfinished work): kill -9, no goodbye
                    os.kill(procs["rA"].pid, signal.SIGKILL)
                    killed = True
            assert killed, "victim was never mid-stream"
            # zero client-visible failures, token-identical throughout
            for req, exp in zip(reqs, expected):
                assert req.t_done is not None
                assert req.generated == exp
                assert req.finish_reason == "max_new_tokens"
            snap = router.fleet_snapshot()
            assert snap["failed_replicas"] == ["rA"]
            assert snap["requeued_total"] >= 1
            assert down_fired, "fleet_replica_down never fired"
            # burial retired the heartbeat series to the -1 sentinel:
            # the rule resolves instead of firing forever
            hist.sample_once()
            final = {s["rule"]: s["state"]
                     for s in alerts.evaluate_once()}
            assert final["fleet_replica_down"] != "firing"
            # the replacement spawned by the burial joins the fleet
            assert spawned, "cold_start never ran"
            _step_until(
                router,
                lambda: any(r["replica_id"] == "rC"
                            and r["state"] == "alive"
                            for r in router.fleet_snapshot()["replicas"]),
                timeout_s=120.0, msg="replacement rC alive")
        finally:
            for proc in procs.values():
                proc.kill()
            client.close()
