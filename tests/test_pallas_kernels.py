"""Pallas kernel tests — interpret mode on CPU; forward/backward parity
against plain-lax references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas_kernels import (
    _dense_ref,
    _lstm_gates_ref,
    fused_dense,
    lstm_gates,
)


class TestFusedDense:
    @pytest.mark.parametrize("act", ["linear", "relu", "tanh", "sigmoid"])
    def test_forward_matches_ref_tiled_shapes(self, act):
        key = jax.random.PRNGKey(0)
        kx, kw, kb = jax.random.split(key, 3)
        x = jax.random.normal(kx, (16, 128), jnp.float32)
        w = jax.random.normal(kw, (128, 256), jnp.float32) * 0.1
        b = jax.random.normal(kb, (256,), jnp.float32)
        out = fused_dense(x, w, b, act)
        ref = _dense_ref(x, w, b, act)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_forward_unaligned_falls_back(self):
        x = jnp.ones((5, 33), jnp.float32)
        w = jnp.ones((33, 7), jnp.float32)
        b = jnp.zeros((7,), jnp.float32)
        out = fused_dense(x, w, b, "relu")
        assert out.shape == (5, 7)
        np.testing.assert_allclose(np.asarray(out), np.full((5, 7), 33.0))

    @pytest.mark.parametrize("act", ["linear", "relu", "tanh", "sigmoid"])
    def test_grad_matches_ref(self, act):
        key = jax.random.PRNGKey(1)
        kx, kw, kb = jax.random.split(key, 3)
        x = jax.random.normal(kx, (8, 128), jnp.float32)
        w = jax.random.normal(kw, (128, 128), jnp.float32) * 0.1
        b = jax.random.normal(kb, (128,), jnp.float32) * 0.1

        g1 = jax.grad(lambda *a: fused_dense(*a, act).sum(), argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(lambda *a: _dense_ref(*a, act).sum(), argnums=(0, 1, 2))(x, w, b)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-4, rtol=1e-4)

    def test_unknown_activation_raises(self):
        x = jnp.ones((8, 128)); w = jnp.ones((128, 128)); b = jnp.ones((128,))
        with pytest.raises(ValueError, match="unsupported activation"):
            fused_dense(x, w, b, "swishh")

    def test_jit_compiles(self):
        x = jnp.ones((8, 128), jnp.float32)
        w = jnp.ones((128, 128), jnp.float32)
        b = jnp.zeros((128,), jnp.float32)
        out = jax.jit(lambda *a: fused_dense(*a, "tanh"))(x, w, b)
        assert out.shape == (8, 128)


class TestLSTMGates:
    def _inputs(self, b=16, h=128, seed=0):
        k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
        ifog = jax.random.normal(k1, (b, 4 * h), jnp.float32)
        c = jax.random.normal(k2, (b, h), jnp.float32)
        return ifog, c

    def test_forward_matches_ref(self):
        ifog, c = self._inputs()
        c1, h1 = lstm_gates(ifog, c)
        c2, h2 = _lstm_gates_ref(ifog, c)
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-6)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), atol=1e-6)

    def test_unaligned_shapes_fall_back(self):
        ifog, c = self._inputs(b=3, h=10)
        c1, h1 = lstm_gates(ifog, c)
        c2, h2 = _lstm_gates_ref(ifog, c)
        np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), atol=1e-6)

    def test_grad_matches_autodiff_of_ref(self):
        ifog, c = self._inputs(b=8, h=128, seed=3)

        def loss_fused(a, b):
            cn, hn = lstm_gates(a, b)
            return (cn * 0.3 + hn * 0.7).sum()

        def loss_ref(a, b):
            cn, hn = _lstm_gates_ref(a, b)
            return (cn * 0.3 + hn * 0.7).sum()

        g1 = jax.grad(loss_fused, argnums=(0, 1))(ifog, c)
        g2 = jax.grad(loss_ref, argnums=(0, 1))(ifog, c)
        for a, b_ in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-5, rtol=1e-5)

    def test_inside_scan(self):
        """Usable as the cell of a scanned LSTM over time."""
        b, h, t = 8, 128, 5
        key = jax.random.PRNGKey(4)
        seq = jax.random.normal(key, (t, b, 4 * h), jnp.float32)

        def step(c, x_t):
            c_new, h_new = lstm_gates(x_t, c)
            return c_new, h_new

        c_final, hs = jax.lax.scan(step, jnp.zeros((b, h)), seq)
        assert hs.shape == (t, b, h)
        assert np.isfinite(np.asarray(c_final)).all()


class TestFusedDenseLayerIntegration:
    def test_dense_layer_routes_through_fused_kernel(self):
        """Force-enable the fused path (tests run on an 8-device CPU
        platform where the auto gate is off) and check the layer forward
        matches the unfused route."""
        import dataclasses

        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.layers import dense
        from deeplearning4j_tpu.nn.params import init_layer_params
        from deeplearning4j_tpu.ops.pallas_kernels import set_fused_dense, use_fused_dense

        conf = (NeuralNetConfiguration.Builder()
                .n_in(128).n_out(128).activation_function("tanh")
                .weight_init("VI").seed(0).build())
        params = init_layer_params(jax.random.PRNGKey(0), conf)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 128), jnp.float32)
        assert not use_fused_dense()  # 8-device CPU platform → auto off
        unfused = dense.forward(conf, params, x)
        set_fused_dense(True)
        try:
            assert use_fused_dense()
            fused = dense.forward(conf, params, x)
        finally:
            set_fused_dense(None)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                                   atol=1e-5, rtol=1e-5)
