"""GloVe / ParagraphVectors / vectorizer tests (ref: GloveTest.java,
ParagraphVectorsTest.java, BagOfWordsVectorizerTest, TfidfVectorizerTest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.glove import CoOccurrences, Glove
from deeplearning4j_tpu.models.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.text.sentence_iterator import CollectionSentenceIterator
from deeplearning4j_tpu.text.vectorizers import BagOfWordsVectorizer, TfidfVectorizer


def _topic_corpus():
    fruit = "apple banana cherry fruit sweet juice"
    tech = "cpu gpu chip silicon compute memory"
    rng = np.random.default_rng(0)
    sents = []
    for _ in range(150):
        sents.append(" ".join(rng.permutation(fruit.split()).tolist()))
        sents.append(" ".join(rng.permutation(tech.split()).tolist()))
    return sents


class TestCoOccurrences:
    def test_window_weighting(self):
        co = CoOccurrences(window=2)
        co.add_sentence([0, 1, 2])
        # pairs: (0,1) at dist 1 → 1.0; (1,2) at dist 1 → 1.0; (0,2) at dist 2 → 0.5
        assert co.counts[(0, 1)] == pytest.approx(1.0)
        assert co.counts[(1, 2)] == pytest.approx(1.0)
        assert co.counts[(0, 2)] == pytest.approx(0.5)

    def test_symmetric_key(self):
        co = CoOccurrences(window=3)
        co.add_sentence([5, 2])
        co.add_sentence([2, 5])
        assert co.counts[(2, 5)] == pytest.approx(2.0)


class TestGlove:
    def test_learns_topics(self):
        glove = Glove(
            sentence_iterator=CollectionSentenceIterator(_topic_corpus()),
            layer_size=16, window=5, lr=0.1, iterations=25,
            x_max=10.0, seed=2,
        )
        glove.fit()
        assert glove.losses[-1] < glove.losses[0]
        same = glove.similarity("apple", "banana")
        cross = glove.similarity("apple", "gpu")
        assert same > cross, (same, cross)
        nearest = glove.words_nearest("cpu", 5)
        tech = {"gpu", "chip", "silicon", "compute", "memory"}
        assert len(tech & set(nearest)) >= 3, nearest

    def test_unknown_word(self):
        glove = Glove(
            sentence_iterator=CollectionSentenceIterator(["a b c"] * 3),
            layer_size=4, iterations=1,
        )
        glove.fit()
        assert glove.word_vector("zzz") is None
        assert np.isnan(glove.similarity("a", "zzz"))


class TestParagraphVectors:
    def test_doc_vectors_separate_topics(self):
        fruit_docs = [(f"fruit_{i}", "apple banana cherry sweet juice fruit "
                       "banana apple juice") for i in range(10)]
        tech_docs = [(f"tech_{i}", "cpu gpu chip silicon compute memory "
                      "gpu cpu compute") for i in range(10)]
        pv = ParagraphVectors(
            documents=fruit_docs + tech_docs,
            layer_size=16, window=3, negative=5, iterations=30,
            lr=0.25, sample=0, batch_size=128, seed=3, min_word_frequency=1,
        )
        pv.fit()
        assert pv.doc_vectors.shape == (20, 16)
        same = pv.similarity_docs("fruit_0", "fruit_1")
        cross = pv.similarity_docs("fruit_0", "tech_0")
        assert same > cross, (same, cross)
        near = pv.nearest_docs("tech_0", 5)
        assert sum(1 for lab in near if lab.startswith("tech_")) >= 4, near

    def test_doc_vector_lookup(self):
        pv = ParagraphVectors(
            documents=[("d1", "a b c"), ("d2", "b c d")],
            layer_size=8, iterations=1, min_word_frequency=1,
        )
        pv.fit()
        assert pv.doc_vector("d1") is not None
        assert pv.doc_vector("nope") is None


class TestVectorizers:
    DOCS = ["the cat sat on the mat", "the dog sat on the log",
            "cats and dogs are animals"]

    def test_bow_counts(self):
        bow = BagOfWordsVectorizer()
        m = bow.fit_transform(self.DOCS)
        assert m.shape[0] == 3
        the = bow.vocab.index_of("the")
        assert m[0, the] == 2.0
        assert m[2, the] == 0.0

    def test_bow_vectorize_with_label(self):
        bow = BagOfWordsVectorizer().fit(self.DOCS)
        features, onehot = bow.vectorize("the cat", label=1, num_labels=3)
        assert features[bow.vocab.index_of("cat")] == 1.0
        assert onehot.tolist() == [0.0, 1.0, 0.0]

    def test_tfidf_downweights_common_terms(self):
        tv = TfidfVectorizer()
        m = tv.fit_transform(self.DOCS)
        the = tv.vocab.index_of("the")  # in 2/3 docs
        cat = tv.vocab.index_of("cat")  # in 1/3 docs
        # 'the' appears twice in doc0 but idf penalty keeps it below 'cat'
        assert m[0, cat] > 0
        assert tv.idf[cat] > tv.idf[the]

    def test_transform_unseen_word_ignored(self):
        tv = TfidfVectorizer().fit(self.DOCS)
        m = tv.transform(["unseen words only"])
        assert m.shape == (1, tv.vocab.num_words())
        assert m.sum() == 0.0


class TestBinarySerializer:
    def test_binary_round_trip(self, tmp_path):
        from deeplearning4j_tpu.models.embeddings import (
            InMemoryLookupTable,
            load_word_vectors_binary,
            write_word_vectors_binary,
        )
        from deeplearning4j_tpu.text.vocab import VocabCache

        vocab = VocabCache()
        for w in ["alpha", "beta", "gamma"]:
            for _ in range(3):
                vocab.add_token(w)
        vocab.finish(1)
        table = InMemoryLookupTable(vocab, layer_size=7, negative=1)
        path = str(tmp_path / "vec.bin")
        write_word_vectors_binary(table, path)
        vocab2, mat = load_word_vectors_binary(path)
        assert vocab2.num_words() == 3
        for w in ["alpha", "beta", "gamma"]:
            np.testing.assert_array_equal(
                mat[vocab2.index_of(w)], table.syn0[vocab.index_of(w)]
            )
