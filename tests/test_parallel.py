"""Data-parallel ParameterAveraging tests on the 8-device CPU mesh
(ref test model: Spark BaseSparkTest local[8] harness, SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParameterAveragingTrainer, data_parallel_mesh, mesh_2d
from deeplearning4j_tpu.parallel.sharding import apply_shardings, param_shardings


def iris_conf(num_iterations=40):
    return (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).activation_function("tanh")
        .lr(0.1).momentum(0.9).num_iterations(num_iterations).seed(42)
        .list(2)
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True)
        .build()
    )


def test_eight_devices_available():
    assert jax.device_count() == 8


def test_sync_averaging_trains():
    """average_each_iteration=True: per-step AllReduce DP-SGD."""
    net = MultiLayerNetwork(iris_conf()).init()
    mesh = data_parallel_mesh(8)
    trainer = ParameterAveragingTrainer(net, mesh, average_each_iteration=True)
    it = IrisDataSetIterator(144, 144)
    data = it.next()
    before = net.score(data)
    for _ in range(30):
        it.reset()
        trainer.fit_data_set(it)
    after = net.score(data)
    assert after < before * 0.7, (before, after)


def test_local_fit_averaging_trains():
    """average_each_iteration=False: local fits + one param AllReduce."""
    net = MultiLayerNetwork(iris_conf(num_iterations=40)).init()
    mesh = data_parallel_mesh(8)
    trainer = ParameterAveragingTrainer(net, mesh, average_each_iteration=False)
    it = IrisDataSetIterator(144, 144)
    data = it.next()
    before = net.score(data)
    it.reset()
    trainer.fit_data_set(it)
    after = net.score(data)
    assert after < before, (before, after)


def test_parallel_matches_single_device_direction():
    """8-device sync DP on the full batch ≈ single-device full-batch step."""
    net_par = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq.set_params(net_par.params())

    it = IrisDataSetIterator(144, 144)
    trainer = ParameterAveragingTrainer(net_par, data_parallel_mesh(8),
                                        average_each_iteration=True)
    trainer.fit_data_set(it)

    it.reset()
    batch = it.next()
    net_seq._do_backward(batch.features[:144], batch.labels[:144])
    # same data, same seed-derived dropout-free path, pmean of per-shard mean
    # grads == full-batch mean grad → parameter trajectories should agree
    np.testing.assert_allclose(
        np.asarray(net_par.params()), np.asarray(net_seq.params()),
        rtol=2e-3, atol=2e-4,
    )


def test_mesh_2d_tp_sharding_compiles():
    """dp×tp mesh with Megatron-style alternating dense shardings."""
    conf = iris_conf(num_iterations=3)
    net = MultiLayerNetwork(conf).init()
    mesh = mesh_2d(4, 2)
    shardings = param_shardings(conf, mesh)
    # hidden layer (4→8): column-parallel over model axis
    assert "W" in shardings[0]
    placed = apply_shardings(net.params_tree, shardings, mesh)
    trainer = ParameterAveragingTrainer(net, mesh, average_each_iteration=True)
    it = IrisDataSetIterator(144, 144)
    trainer.fit_data_set(it)  # executes with the 2-D mesh
    assert net.params().shape[0] == 4 * 8 + 8 + 8 * 3 + 3
    del placed


def test_uneven_batch_padding():
    net = MultiLayerNetwork(iris_conf(num_iterations=2)).init()
    trainer = ParameterAveragingTrainer(net, data_parallel_mesh(8),
                                        average_each_iteration=True)
    it = IrisDataSetIterator(150, 150)  # 150 % 8 != 0
    trainer.fit_data_set(it)  # must not raise


def test_uneven_batch_gradient_unbiased():
    """Padded rows are 0-weighted: one sync-DP step on an uneven batch must
    land on the SAME params as the single-device step on the unpadded batch
    (padding duplicates previously entered the loss at full weight)."""
    net_par = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq.set_params(net_par.params())

    it = IrisDataSetIterator(150, 150)  # 150 % 8 = 6 → 2 padded rows
    trainer = ParameterAveragingTrainer(net_par, data_parallel_mesh(8),
                                        average_each_iteration=True)
    trainer.fit_data_set(it)

    it.reset()
    batch = it.next()
    assert batch.features.shape[0] == 150
    net_seq._do_backward(batch.features, batch.labels)
    np.testing.assert_allclose(
        np.asarray(net_par.params()), np.asarray(net_seq.params()),
        rtol=2e-3, atol=2e-4,
    )


class TestMultihost:
    """Single-process behavior of the multi-host glue (a real multi-host run
    needs multiple controllers; here we validate the single-controller path
    and mesh construction over the 8 virtual devices)."""

    def test_initialize_single_process_noop(self):
        from deeplearning4j_tpu.parallel import multihost

        multihost.initialize()  # no coordinator configured → no-op
        idx, count = multihost.process_info()
        assert idx == 0 and count == 1
        assert multihost.is_coordinator()

    def test_global_mesh_default(self):
        import jax
        from deeplearning4j_tpu.parallel import multihost

        mesh = multihost.global_mesh(("data",))
        assert mesh.shape["data"] == jax.device_count()

    def test_global_mesh_multi_axis(self):
        from deeplearning4j_tpu.parallel import multihost

        mesh = multihost.global_mesh(("data", "model"), (4, 2))
        assert dict(mesh.shape) == {"data": 4, "model": 2}

    def test_global_mesh_validation(self):
        import pytest as _pytest

        from deeplearning4j_tpu.parallel import multihost

        with _pytest.raises(ValueError):
            multihost.global_mesh(("a", "b"))
        with _pytest.raises(ValueError):
            multihost.global_mesh(("a", "b"), (3, 2))

    def test_explicit_coordinator_requires_rank(self):
        import pytest as _pytest

        from deeplearning4j_tpu.parallel import multihost

        multihost._initialized = False
        try:
            with _pytest.raises(ValueError):
                multihost.initialize(coordinator="h:1234")
        finally:
            multihost._initialized = True
