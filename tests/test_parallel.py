"""Data-parallel ParameterAveraging tests on the 8-device CPU mesh
(ref test model: Spark BaseSparkTest local[8] harness, SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParameterAveragingTrainer, data_parallel_mesh, mesh_2d
from deeplearning4j_tpu.parallel.sharding import apply_shardings, param_shardings
from deeplearning4j_tpu.utils.retrace_guard import retrace_guard


def iris_conf(num_iterations=40):
    return (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).activation_function("tanh")
        .lr(0.1).momentum(0.9).num_iterations(num_iterations).seed(42)
        .list(2)
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True)
        .build()
    )


def test_eight_devices_available():
    assert jax.device_count() == 8


def test_sync_averaging_trains():
    """average_each_iteration=True: per-step AllReduce DP-SGD."""
    net = MultiLayerNetwork(iris_conf()).init()
    mesh = data_parallel_mesh(8)
    trainer = ParameterAveragingTrainer(net, mesh, average_each_iteration=True)
    it = IrisDataSetIterator(144, 144)
    data = it.next()
    before = net.score(data)
    for r in range(30):
        it.reset()
        if r < 2:
            trainer.fit_data_set(it)  # rounds 0-1: compile + commit shardings
        else:
            # a warmed DP-sync round must be retrace-free end to end —
            # including the trainer's host plumbing around the jitted step
            with retrace_guard(0, label=f"DP-sync averaging round {r}"):
                trainer.fit_data_set(it)
    after = net.score(data)
    assert after < before * 0.7, (before, after)


def test_local_fit_averaging_trains():
    """average_each_iteration=False: local fits + one param AllReduce."""
    net = MultiLayerNetwork(iris_conf(num_iterations=40)).init()
    mesh = data_parallel_mesh(8)
    trainer = ParameterAveragingTrainer(net, mesh, average_each_iteration=False)
    it = IrisDataSetIterator(144, 144)
    data = it.next()
    before = net.score(data)
    it.reset()
    trainer.fit_data_set(it)
    after = net.score(data)
    assert after < before, (before, after)


def test_parallel_matches_single_device_direction():
    """8-device sync DP on the full batch ≈ single-device full-batch step."""
    net_par = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq.set_params(net_par.params())

    it = IrisDataSetIterator(144, 144)
    trainer = ParameterAveragingTrainer(net_par, data_parallel_mesh(8),
                                        average_each_iteration=True)
    trainer.fit_data_set(it)

    it.reset()
    batch = it.next()
    net_seq._do_backward(batch.features[:144], batch.labels[:144])
    # same data, same seed-derived dropout-free path, pmean of per-shard mean
    # grads == full-batch mean grad → parameter trajectories should agree
    np.testing.assert_allclose(
        np.asarray(net_par.params()), np.asarray(net_seq.params()),
        rtol=2e-3, atol=2e-4,
    )


def test_mesh_2d_tp_sharding_compiles():
    """dp×tp mesh with Megatron-style alternating dense shardings."""
    conf = iris_conf(num_iterations=3)
    net = MultiLayerNetwork(conf).init()
    mesh = mesh_2d(4, 2)
    shardings = param_shardings(conf, mesh)
    # hidden layer (4→8): column-parallel over model axis
    assert "W" in shardings[0]
    placed = apply_shardings(net.params_tree, shardings, mesh)
    trainer = ParameterAveragingTrainer(net, mesh, average_each_iteration=True)
    it = IrisDataSetIterator(144, 144)
    trainer.fit_data_set(it)  # executes with the 2-D mesh
    assert net.params().shape[0] == 4 * 8 + 8 + 8 * 3 + 3
    del placed


def test_uneven_batch_padding():
    net = MultiLayerNetwork(iris_conf(num_iterations=2)).init()
    trainer = ParameterAveragingTrainer(net, data_parallel_mesh(8),
                                        average_each_iteration=True)
    it = IrisDataSetIterator(150, 150)  # 150 % 8 != 0
    trainer.fit_data_set(it)  # must not raise


def test_uneven_batch_gradient_unbiased():
    """Padded rows are 0-weighted: one sync-DP step on an uneven batch must
    land on the SAME params as the single-device step on the unpadded batch
    (padding duplicates previously entered the loss at full weight)."""
    net_par = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq = MultiLayerNetwork(iris_conf(num_iterations=1)).init()
    net_seq.set_params(net_par.params())

    it = IrisDataSetIterator(150, 150)  # 150 % 8 = 6 → 2 padded rows
    trainer = ParameterAveragingTrainer(net_par, data_parallel_mesh(8),
                                        average_each_iteration=True)
    trainer.fit_data_set(it)

    it.reset()
    batch = it.next()
    assert batch.features.shape[0] == 150
    net_seq._do_backward(batch.features, batch.labels)
    np.testing.assert_allclose(
        np.asarray(net_par.params()), np.asarray(net_seq.params()),
        rtol=2e-3, atol=2e-4,
    )


class TestMultihost:
    """Single-process behavior of the multi-host glue (a real multi-host run
    needs multiple controllers; here we validate the single-controller path
    and mesh construction over the 8 virtual devices)."""

    def test_initialize_single_process_noop(self):
        from deeplearning4j_tpu.parallel import multihost

        multihost.initialize()  # no coordinator configured → no-op
        idx, count = multihost.process_info()
        assert idx == 0 and count == 1
        assert multihost.is_coordinator()

    def test_global_mesh_default(self):
        import jax
        from deeplearning4j_tpu.parallel import multihost

        mesh = multihost.global_mesh(("data",))
        assert mesh.shape["data"] == jax.device_count()

    def test_global_mesh_multi_axis(self):
        from deeplearning4j_tpu.parallel import multihost

        mesh = multihost.global_mesh(("data", "model"), (4, 2))
        assert dict(mesh.shape) == {"data": 4, "model": 2}

    def test_global_mesh_validation(self):
        import pytest as _pytest

        from deeplearning4j_tpu.parallel import multihost

        with _pytest.raises(ValueError):
            multihost.global_mesh(("a", "b"))
        with _pytest.raises(ValueError):
            multihost.global_mesh(("a", "b"), (3, 2))

    def test_explicit_coordinator_requires_rank(self):
        import pytest as _pytest

        from deeplearning4j_tpu.parallel import multihost

        multihost._initialized = False
        try:
            with _pytest.raises(ValueError):
                multihost.initialize(coordinator="h:1234")
        finally:
            multihost._initialized = True


def test_attention_tp_sharded_step_matches_single_device():
    """Megatron-style MHA tensor parallelism: the attention LM's train step
    over a dp2×tp2 mesh (qkv column-, wo row-parallel; heads split across
    the model axis) reproduces the single-device step's score and updated
    params to 1e-5."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.models.zoo import char_attention_lm
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.parallel.mesh import DATA_AXIS

    conf = char_attention_lm(vocab=8, d_model=16, n_heads=4, lr=0.1,
                             num_iterations=1)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    mesh = mesh_2d(2, 2)
    shardings = param_shardings(conf, mesh)
    assert "wq" in shardings[1] and "wo" in shardings[1]  # TP actually applied

    B, T, V = 4, 8, 8
    toks = np.arange(B)[:, None] + np.arange(T + 1)[None]
    x = jnp.asarray(np.eye(V, dtype=np.float32)[toks % V][:, :-1])
    y = jnp.asarray(np.eye(V, dtype=np.float32)[toks % V][:, 1:])

    step = F.make_train_step(conf)
    placed = apply_shardings(params, shardings, mesh)
    states_p = F.init_train_state(conf, placed)
    xs = jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS)))
    ys = jax.device_put(y, NamedSharding(mesh, P(DATA_AXIS)))
    new_p, _, score = step(placed, states_p, jnp.asarray(0), xs, ys,
                           jax.random.PRNGKey(1))

    ref_p, _, ref_score = F.make_train_step(conf)(
        params, states, jnp.asarray(0), x, y, jax.random.PRNGKey(1))
    assert abs(float(score) - float(ref_score)) < 1e-5
    for la, lb in zip(new_p, ref_p):
        for k in lb:
            err = float(jnp.max(jnp.abs(jnp.asarray(la[k]) - jnp.asarray(lb[k]))))
            assert err < 1e-5, (k, err)
