"""CLI tests (ref: TrainTest.java, BaseSubCommandTest — invoke subcommands
against small conf + data fixtures)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.cli.driver import main
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration


@pytest.fixture
def conf_path(tmp_path):
    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
        .momentum(0.9).use_ada_grad(True).num_iterations(60).seed(42)
        .weight_init("VI").list(2)
        .override(0, layer_type="DENSE")
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True).build()
    )
    p = tmp_path / "model.json"
    p.write_text(conf.to_json())
    return str(p)


@pytest.fixture
def iris_csv(tmp_path):
    from deeplearning4j_tpu.datasets.fetchers import iris_data

    x, y = iris_data()  # y: (150,) integer classes
    lines = [",".join(f"{v:.4f}" for v in row) + f",{int(lab)}"
             for row, lab in zip(x, y)]
    p = tmp_path / "iris.csv"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_train_test_predict_round_trip(tmp_path, conf_path, iris_csv, capsys):
    model = str(tmp_path / "params.npz")
    assert main(["train", "--conf", conf_path, "--input", iris_csv,
                 "--model", model, "--labels", "3", "--batch", "150"]) == 0
    assert np.load(model)["params"].ndim == 1

    assert main(["test", "--conf", conf_path, "--input", iris_csv,
                 "--model", model, "--labels", "3", "--batch", "150"]) == 0
    out = capsys.readouterr().out
    assert "Accuracy" in out

    pred_file = str(tmp_path / "preds.txt")
    assert main(["predict", "--conf", conf_path, "--input", iris_csv,
                 "--model", model, "--labels", "3", "--batch", "150",
                 "--output", pred_file]) == 0
    preds = [int(l) for l in open(pred_file).read().split()]
    assert len(preds) == 150
    assert set(preds) <= {0, 1, 2}
    # trained model beats chance comfortably
    from deeplearning4j_tpu.datasets.fetchers import iris_data

    _, y = iris_data()
    acc = np.mean(np.asarray(preds) == y)
    assert acc > 0.8, acc


def test_predict_to_stdout(tmp_path, conf_path, iris_csv, capsys):
    model = str(tmp_path / "params.npz")
    main(["train", "--conf", conf_path, "--input", iris_csv,
          "--model", model, "--labels", "3", "--batch", "150"])
    main(["predict", "--conf", conf_path, "--input", iris_csv,
          "--model", model, "--labels", "3", "--batch", "150"])
    out = capsys.readouterr().out.split()
    assert len(out) == 150


def test_svmlight_requires_features(conf_path, tmp_path):
    svm = tmp_path / "d.svm"
    svm.write_text("0 1:1.0\n")
    with pytest.raises(SystemExit):
        main(["train", "--conf", conf_path, "--input", str(svm),
              "--model", str(tmp_path / "m.npz"), "--labels", "3"])


def test_missing_subcommand_errors():
    with pytest.raises(SystemExit):
        main([])


def test_mem_uri_model_round_trip(conf_path, iris_csv, capsys):
    """mem:// models persist for the process (ADVICE r02: a fresh store per
    open_store call silently dropped every write), and a key directly after
    the scheme must not create a literal local 'mem:' directory."""
    import os

    from deeplearning4j_tpu.cli.driver import main

    for uri in ("mem://models/iris-params", "mem://iris-params.npz"):
        rc = main(["train", "--conf", str(conf_path), "--input", str(iris_csv),
                   "--model", uri, "--labels", "3", "--epochs", "2"])
        assert rc == 0
        rc = main(["test", "--conf", str(conf_path), "--input", str(iris_csv),
                   "--model", uri, "--labels", "3"])
        assert rc == 0
        assert "Accuracy" in capsys.readouterr().out
    assert not os.path.exists("mem:")


def test_predict_lm_checkpoint_generates_through_decode_engine(tmp_path,
                                                               capsys):
    """ISSUE 10 satellite: ``predict --model <ckpt_dir>`` routes LM
    checkpoints through the KV-cached decode engine (no --conf needed) and
    the output matches a direct engine run with the same knobs; non-LM
    predicts keep the classic path (pinned above)."""
    import jax

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        lm_checkpoint_meta,
    )
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer
    from deeplearning4j_tpu.serve import DecodeEngine

    params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                            n_layers=2)
    root = str(tmp_path / "lm_ckpt")
    Checkpointer(root).save(7, {"params": params},
                            meta=lm_checkpoint_meta(params, 2))
    prompts = tmp_path / "prompts.txt"
    prompts.write_text("1 2 3 4\n10, 20, 30\n\n5 6\n")
    out_path = str(tmp_path / "gen.txt")
    rc = main(["predict", "--model", root, "--input", str(prompts),
               "--output", out_path, "--max-new-tokens", "4",
               "--serve-dtype", "f32"])
    assert rc == 0
    rows = [[int(t) for t in line.split()]
            for line in open(out_path).read().strip().splitlines()]
    assert len(rows) == 3  # blank prompt lines are skipped
    assert all(len(r) == 4 for r in rows)

    eng = DecodeEngine.from_checkpoint(root, serve_dtype="f32")
    want = [eng.generate(p, max_new_tokens=4)
            for p in ([1, 2, 3, 4], [10, 20, 30], [5, 6])]
    assert rows == want

    # stdout path + verbose engine stats line
    rc = main(["predict", "--model", root, "--input", str(prompts),
               "--max-new-tokens", "2", "--serve-dtype", "f32",
               "--verbose"])
    assert rc == 0
    out = capsys.readouterr().out
    assert len(out.strip().splitlines()) == 3 + 1  # 3 rows + stats line
    assert "decode engine:" in out


def test_predict_lm_rejects_bad_prompt_file(tmp_path):
    import jax

    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        lm_checkpoint_meta,
    )
    from deeplearning4j_tpu.scaleout.ckpt.checkpointer import Checkpointer

    params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16)
    root = str(tmp_path / "lm_ckpt")
    Checkpointer(root).save(1, {"params": params},
                            meta=lm_checkpoint_meta(params, 2))
    bad = tmp_path / "bad.txt"
    bad.write_text("1 2 three\n")
    with pytest.raises(SystemExit, match="token ids"):
        main(["predict", "--model", root, "--input", str(bad)])
    empty = tmp_path / "empty.txt"
    empty.write_text("\n\n")
    with pytest.raises(SystemExit, match="no prompts"):
        main(["predict", "--model", root, "--input", str(empty)])


def test_predict_without_conf_on_non_lm_model_errors(tmp_path, iris_csv):
    with pytest.raises(SystemExit, match="--conf is required"):
        main(["predict", "--model", str(tmp_path / "nope.npz"),
              "--input", iris_csv])


def test_split_store_uri():
    from deeplearning4j_tpu.scaleout.blobstore import split_store_uri

    assert split_store_uri("mem://a/b/key.npz") == ("mem://a/b", "key.npz")
    assert split_store_uri("mem://key.npz") == ("mem://", "key.npz")
    assert split_store_uri("file:///d/key.npz") == ("file:///d", "key.npz")
    # root-level keys keep the leading '/' (never CWD-relative)
    assert split_store_uri("file:///key.npz") == ("file:///", "key.npz")
    assert split_store_uri("/key.npz") == ("/", "key.npz")
    assert split_store_uri("/d/key.npz") == ("/d", "key.npz")
