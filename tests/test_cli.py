"""CLI tests (ref: TrainTest.java, BaseSubCommandTest — invoke subcommands
against small conf + data fixtures)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.cli.driver import main
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration


@pytest.fixture
def conf_path(tmp_path):
    conf = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
        .momentum(0.9).use_ada_grad(True).num_iterations(60).seed(42)
        .weight_init("VI").list(2)
        .override(0, layer_type="DENSE")
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True).build()
    )
    p = tmp_path / "model.json"
    p.write_text(conf.to_json())
    return str(p)


@pytest.fixture
def iris_csv(tmp_path):
    from deeplearning4j_tpu.datasets.fetchers import iris_data

    x, y = iris_data()  # y: (150,) integer classes
    lines = [",".join(f"{v:.4f}" for v in row) + f",{int(lab)}"
             for row, lab in zip(x, y)]
    p = tmp_path / "iris.csv"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_train_test_predict_round_trip(tmp_path, conf_path, iris_csv, capsys):
    model = str(tmp_path / "params.npz")
    assert main(["train", "--conf", conf_path, "--input", iris_csv,
                 "--model", model, "--labels", "3", "--batch", "150"]) == 0
    assert np.load(model)["params"].ndim == 1

    assert main(["test", "--conf", conf_path, "--input", iris_csv,
                 "--model", model, "--labels", "3", "--batch", "150"]) == 0
    out = capsys.readouterr().out
    assert "Accuracy" in out

    pred_file = str(tmp_path / "preds.txt")
    assert main(["predict", "--conf", conf_path, "--input", iris_csv,
                 "--model", model, "--labels", "3", "--batch", "150",
                 "--output", pred_file]) == 0
    preds = [int(l) for l in open(pred_file).read().split()]
    assert len(preds) == 150
    assert set(preds) <= {0, 1, 2}
    # trained model beats chance comfortably
    from deeplearning4j_tpu.datasets.fetchers import iris_data

    _, y = iris_data()
    acc = np.mean(np.asarray(preds) == y)
    assert acc > 0.8, acc


def test_predict_to_stdout(tmp_path, conf_path, iris_csv, capsys):
    model = str(tmp_path / "params.npz")
    main(["train", "--conf", conf_path, "--input", iris_csv,
          "--model", model, "--labels", "3", "--batch", "150"])
    main(["predict", "--conf", conf_path, "--input", iris_csv,
          "--model", model, "--labels", "3", "--batch", "150"])
    out = capsys.readouterr().out.split()
    assert len(out) == 150


def test_svmlight_requires_features(conf_path, tmp_path):
    svm = tmp_path / "d.svm"
    svm.write_text("0 1:1.0\n")
    with pytest.raises(SystemExit):
        main(["train", "--conf", conf_path, "--input", str(svm),
              "--model", str(tmp_path / "m.npz"), "--labels", "3"])


def test_missing_subcommand_errors():
    with pytest.raises(SystemExit):
        main([])


def test_mem_uri_model_round_trip(conf_path, iris_csv, capsys):
    """mem:// models persist for the process (ADVICE r02: a fresh store per
    open_store call silently dropped every write), and a key directly after
    the scheme must not create a literal local 'mem:' directory."""
    import os

    from deeplearning4j_tpu.cli.driver import main

    for uri in ("mem://models/iris-params", "mem://iris-params.npz"):
        rc = main(["train", "--conf", str(conf_path), "--input", str(iris_csv),
                   "--model", uri, "--labels", "3", "--epochs", "2"])
        assert rc == 0
        rc = main(["test", "--conf", str(conf_path), "--input", str(iris_csv),
                   "--model", uri, "--labels", "3"])
        assert rc == 0
        assert "Accuracy" in capsys.readouterr().out
    assert not os.path.exists("mem:")


def test_split_store_uri():
    from deeplearning4j_tpu.scaleout.blobstore import split_store_uri

    assert split_store_uri("mem://a/b/key.npz") == ("mem://a/b", "key.npz")
    assert split_store_uri("mem://key.npz") == ("mem://", "key.npz")
    assert split_store_uri("file:///d/key.npz") == ("file:///d", "key.npz")
    # root-level keys keep the leading '/' (never CWD-relative)
    assert split_store_uri("file:///key.npz") == ("file:///", "key.npz")
    assert split_store_uri("/key.npz") == ("/", "key.npz")
    assert split_store_uri("/d/key.npz") == ("/d", "key.npz")
