"""ISSUE 13: in-graph Adam/LAMB with ZeRO-style cross-replica sharded
optimizer state on every composed path (optimize/updaters.py).

The pins: (a) the update math against plain-numpy references and against
the legacy GradientAdjustment facade at equivalent hyperparameters (the
two update stacks can't silently diverge); (b) the acceptance parity —
``update_sharding="sharded"`` vs ``"replicated"`` Adam on dp×ep agrees on
loss AND params ≤1e-6 at identical math, with the xprofile collective
inventory asserting the expected params all-gather appears and the
per-replica update FLOPs/peak bytes DROP; (c) moments shard like their
params (expert-sharded MoE leaves, stage-sharded pp leaves, 1/dp
per-replica bytes in ZeRO mode) and survive guard skips bitwise; (d) the
steady-state 0-compile retrace budget on the dp×ep Adam step; (e) the
checkpoint canonicalization round-trip + ckpt_inspect's optimizer-state
summary and moment-covering --diff; (f) the with_metrics optimizer block
rendered by tools/telemetry_report.py, silent-when-absent both ways."""

import contextlib
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.models.transformer_lm import (
    init_lm_opt_state,
    init_lm_params,
    lm_param_shardings,
    lm_update_sharding,
    make_composed_train_step,
    make_single_device_train_step,
    shard_lm_batch,
    shard_lm_params,
)
from deeplearning4j_tpu.optimize.updaters import (
    OptimizerConfig,
    ZeroSharding,
    canonical_opt_state,
    init_opt_state,
    opt_state_shardings,
    opt_update,
    partition_opt_state,
    resolve_update_sharding,
)
from deeplearning4j_tpu.utils.retrace_guard import retrace_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

V, D, H, E, DFF = 32, 16, 2, 4, 32
B, T = 4, 16
ATOL = 1e-6  # the sharded-vs-replicated acceptance bound


def _params(n_layers=2, n_experts=E):
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, n_experts, DFF,
                          n_layers=n_layers)


def _data(seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (B, T + 1), 0, V)
    return toks[:, :-1], toks[:, 1:]


def _dp_ep_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "expert"))


def _copy(t):
    return jax.tree_util.tree_map(jnp.array, t)


def _bits_equal(a, b):
    return np.asarray(a).tobytes() == np.asarray(b).tobytes()


def _tree_bits_equal(ta, tb):
    la = jax.tree_util.tree_leaves(jax.device_get(ta))
    lb = jax.tree_util.tree_leaves(jax.device_get(tb))
    assert len(la) == len(lb)
    return all(_bits_equal(a, b) for a, b in zip(la, lb))


def _max_diff(ta, tb):
    return max(
        float(np.max(np.abs(np.asarray(a, np.float64)
                            - np.asarray(b, np.float64))))
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(ta)),
                        jax.tree_util.tree_leaves(jax.device_get(tb))))


# ----------------------------------------------------------- config seam ----

class TestOptimizerConfig:
    def test_coerce(self):
        assert OptimizerConfig.coerce(None) is None
        assert OptimizerConfig.coerce(False) is None
        assert OptimizerConfig.coerce("adam") == OptimizerConfig(name="adam")
        assert OptimizerConfig.coerce("lamb").name == "lamb"
        # the adagrad bridge pins the legacy epsilon
        assert OptimizerConfig.coerce("adagrad").eps == 1e-6
        cfg = OptimizerConfig(name="lamb", lr=1e-3)
        assert OptimizerConfig.coerce(cfg) is cfg
        with pytest.raises(TypeError, match="optimizer="):
            OptimizerConfig.coerce(123)
        with pytest.raises(ValueError, match="optimizer name"):
            OptimizerConfig(name="adamw")

    def test_update_sharding_env_precedence(self, monkeypatch):
        """Explicit field > DL4J_TPU_UPDATE_SHARDING env > replicated —
        the same no-code-edit A/B switch the attn/moe seams give bench."""
        monkeypatch.delenv("DL4J_TPU_UPDATE_SHARDING", raising=False)
        assert resolve_update_sharding(None) == "replicated"
        monkeypatch.setenv("DL4J_TPU_UPDATE_SHARDING", "sharded")
        assert resolve_update_sharding(None) == "sharded"
        assert OptimizerConfig(name="adam").sharded
        # explicit outranks env
        assert resolve_update_sharding("replicated") == "replicated"
        assert not OptimizerConfig(
            name="adam", update_sharding="replicated").sharded
        monkeypatch.setenv("DL4J_TPU_UPDATE_SHARDING", "zippy")
        with pytest.raises(ValueError, match="DL4J_TPU_UPDATE_SHARDING"):
            resolve_update_sharding(None)

    def test_single_device_rejects_sharded(self):
        with pytest.raises(ValueError, match="dp mesh axis"):
            make_single_device_train_step(
                H, optimizer=OptimizerConfig(name="adam",
                                             update_sharding="sharded"))

    def test_zero_sharding_needs_the_axis(self):
        mesh = Mesh(np.array(jax.devices()[:2]), ("expert",))
        with pytest.raises(ValueError, match="dp axis"):
            ZeroSharding(mesh, "data")


# ---------------------------------------------------------- update math ----

def _np_adam_lamb(name, params, grad_steps, lr, b1=0.9, b2=0.999, eps=1e-8,
                  wd=0.0):
    """Plain-numpy reference trajectory (float64 intermediates would hide
    f32 drift — stay f32 like the in-graph updater)."""
    p = {k: np.asarray(v, np.float32).copy() for k, v in params.items()}
    m = {k: np.zeros_like(v) for k, v in p.items()}
    v2 = {k: np.zeros_like(vv) for k, vv in p.items()}
    for t, grads in enumerate(grad_steps, start=1):
        for k in p:
            g = np.asarray(grads[k], np.float32)
            m[k] = b1 * m[k] + (1 - b1) * g
            v2[k] = b2 * v2[k] + (1 - b2) * g * g
            mhat = m[k] / (1 - b1 ** np.float32(t))
            vhat = v2[k] / (1 - b2 ** np.float32(t))
            r = mhat / (np.sqrt(vhat) + eps)
            if wd:
                r = r + wd * p[k]
            if name == "lamb":
                pn = np.sqrt(np.sum(p[k] ** 2))
                rn = np.sqrt(np.sum(r ** 2))
                trust = pn / rn if (pn > 0 and rn > 0) else 1.0
                p[k] = p[k] - lr * trust * r
            else:
                p[k] = p[k] - lr * r
    return p


class TestUpdateMath:
    def _tree(self):
        k = jax.random.PRNGKey(3)
        return {"w": jax.random.normal(k, (5, 3)),
                "b": jax.random.normal(jax.random.fold_in(k, 1), (3,))}

    def _grads(self, i):
        k = jax.random.fold_in(jax.random.PRNGKey(11), i)
        return {"w": jax.random.normal(k, (5, 3)) * 0.1,
                "b": jax.random.normal(jax.random.fold_in(k, 1), (3,)) * 0.1}

    @pytest.mark.parametrize("name", ["adam", "lamb"])
    def test_matches_numpy_reference(self, name):
        cfg = OptimizerConfig(name=name, lr=1e-2, weight_decay=1e-3)
        params = self._tree()
        state = init_opt_state(cfg, params)
        grad_steps = [self._grads(i) for i in range(3)]
        p = params
        for g in grad_steps:
            p, state = opt_update(cfg, p, g, state, lr=0.5)  # cfg.lr wins
        ref = _np_adam_lamb(name, jax.device_get(params),
                            [jax.device_get(g) for g in grad_steps],
                            lr=1e-2, wd=1e-3)
        for k in ref:
            np.testing.assert_allclose(np.asarray(p[k]), ref[k], atol=1e-6,
                                       rtol=1e-6)
        assert int(state["count"]) == 3

    def test_builder_lr_used_when_cfg_lr_unset(self):
        cfg = OptimizerConfig(name="adam")
        params = self._tree()
        g = self._grads(0)
        p1, _ = opt_update(cfg, params, g, init_opt_state(cfg, params),
                           lr=1e-2)
        ref = _np_adam_lamb("adam", jax.device_get(params),
                            [jax.device_get(g)], lr=1e-2)
        np.testing.assert_allclose(np.asarray(p1["w"]), ref["w"], atol=1e-6)


class TestLegacyUpdaterParity:
    """The deflake/ride-along satellite: the legacy GradientAdjustment
    facade (optimize/updater.py — the reference's AdaGrad/momentum
    lineage) against the new seam at equivalent hyperparameters. The two
    stacks share no code, so this pin is what keeps them from silently
    diverging."""

    def _conf(self, **over):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        base = dict(lr=0.05, use_ada_grad=False, momentum=0.0,
                    use_regularization=False)
        base.update(over)
        return (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(3).lr(base["lr"]).seed(0)
                .use_ada_grad(base["use_ada_grad"])
                .momentum(base["momentum"])
                .use_regularization(base["use_regularization"])
                .build())

    def _run_legacy(self, conf, params, grad_steps):
        from deeplearning4j_tpu.optimize.updater import (
            apply_updater,
            init_updater_state,
        )

        state = init_updater_state(params)
        p = params
        for i, g in enumerate(grad_steps):
            upd, state = apply_updater(conf, jnp.asarray(i), g, p, state)
            p = jax.tree_util.tree_map(lambda a, u: a - u, p, upd)
        return p

    def _run_new(self, cfg, params, grad_steps, lr):
        state = init_opt_state(cfg, params)
        p = params
        for g in grad_steps:
            p, state = opt_update(cfg, p, g, state, lr=lr)
        return p

    def _tree_and_grads(self):
        k = jax.random.PRNGKey(5)
        params = {"w": jax.random.normal(k, (4, 3))}
        grads = [{"w": jax.random.normal(jax.random.fold_in(k, 10 + i),
                                         (4, 3)) * 0.3}
                 for i in range(4)]
        return params, grads

    def test_adagrad_parity(self):
        params, grads = self._tree_and_grads()
        conf = self._conf(use_ada_grad=True)
        legacy = self._run_legacy(conf, _copy(params), grads)
        new = self._run_new(OptimizerConfig.coerce("adagrad"),
                            _copy(params), grads, lr=conf.lr)
        assert _max_diff(legacy, new) <= 1e-7

    def test_momentum_parity(self):
        params, grads = self._tree_and_grads()
        conf = self._conf(use_ada_grad=False, momentum=0.9)
        legacy = self._run_legacy(conf, _copy(params), grads)
        new = self._run_new(OptimizerConfig(name="momentum", momentum=0.9),
                            _copy(params), grads, lr=conf.lr)
        assert _max_diff(legacy, new) <= 1e-7


# ------------------------------------------------- composed dp×ep parity ----

class TestComposedAdamZero:
    CFG_REP = OptimizerConfig(name="adam", lr=1e-3,
                              update_sharding="replicated")
    CFG_SH = OptimizerConfig(name="adam", lr=1e-3,
                             update_sharding="sharded")

    def _run(self, mesh, cfg, steps=4, retrace_pin=False):
        cap = (B // 2) * T
        step = make_composed_train_step(mesh, H, cap, optimizer=cfg)
        p = shard_lm_params(_params(), mesh)
        st = init_lm_opt_state(cfg, p, mesh)
        losses = []
        for i in range(steps):
            tk, tg = shard_lm_batch(*_data(i + 1), mesh)
            guard = (retrace_guard(0, label=f"adam {cfg.update_sharding} "
                                            f"step {i}")
                     if retrace_pin and i >= 1 else contextlib.nullcontext())
            with guard:
                p, st, loss = step(p, st, tk, tg)
                jax.block_until_ready(loss)
            losses.append(float(loss))
        return p, st, losses

    def test_sharded_vs_replicated_parity(self):
        """THE ACCEPTANCE PIN: update-sharded vs replicated Adam on dp×ep
        — loss AND params ≤1e-6 over 4 steps, moments too (canonicalized
        back to the param-shaped layout for the compare). Identical math,
        different placement."""
        mesh = _dp_ep_mesh()
        p_r, st_r, l_r = self._run(mesh, self.CFG_REP)
        p_s, st_s, l_s = self._run(mesh, self.CFG_SH)
        np.testing.assert_allclose(l_r, l_s, atol=ATOL, rtol=0)
        assert _max_diff(p_r, p_s) <= ATOL
        can_r = canonical_opt_state(st_r, p_r, None)
        can_s = canonical_opt_state(st_s, p_s, lm_update_sharding(mesh))
        assert _max_diff(can_r["m"], can_s["m"]) <= ATOL
        assert _max_diff(can_r["v"], can_s["v"]) <= ATOL
        assert int(can_r["count"]) == int(can_s["count"]) == 4

    def test_sharded_moment_placement(self):
        """Moments shard like their params PLUS the dp axis: expert
        leaves keep the expert axis on their expert dim with the dp shard
        nested inside; every leaf's per-replica moment bytes are 1/dp of
        the replicated layout (the at-rest half of the 2004.13336 win)."""
        mesh = _dp_ep_mesh()
        p = shard_lm_params(_params(), mesh)
        st = init_lm_opt_state(self.CFG_SH, p, mesh)
        m_emb = st["m"]["embed"]
        assert m_emb.sharding.spec == jax.sharding.PartitionSpec("data")
        assert m_emb.shape == (2, (V * D) // 2)
        w1 = st["m"]["blocks"]["experts"]["w1"]
        assert w1.sharding.spec == jax.sharding.PartitionSpec(
            None, "expert", "data")
        # per-device shard: all layers × its experts slab × its dp chunk
        local = w1.addressable_shards[0].data.shape
        assert local == (2, E // 4, 1, (D * DFF) // 2)
        # replicated-mode twin holds the FULL leaf per replica
        st_rep = init_lm_opt_state(self.CFG_REP, p, mesh)
        dev0 = jax.devices()[0]

        def bytes_on_dev0(state):
            return sum(
                sh.data.nbytes
                for leaf in jax.tree_util.tree_leaves(
                    {"m": state["m"], "v": state["v"]})
                for sh in leaf.addressable_shards if sh.device == dev0)

        # dp=2 on this mesh: the replicated layout holds exactly 2x the
        # per-replica moment bytes of the ZeRO layout (every flattened
        # remainder here divides evenly, so no padding slack)
        assert bytes_on_dev0(st_rep) == 2 * bytes_on_dev0(st)

    def test_collective_inventory_and_footprint(self):
        """The profiler-provable half: the sharded step's HLO carries the
        params all-gather, and BOTH the per-replica FLOPs (the redundant
        update work) and the compiled peak bytes drop vs replicated."""
        from deeplearning4j_tpu.telemetry.xprofile import profile_compiled

        mesh = _dp_ep_mesh()
        cap = (B // 2) * T
        tk, tg = shard_lm_batch(*_data(), mesh)
        profs = {}
        for cfg in (self.CFG_REP, self.CFG_SH):
            step = make_composed_train_step(mesh, H, cap, optimizer=cfg)
            p = shard_lm_params(_params(), mesh)
            st = init_lm_opt_state(cfg, p, mesh)
            profs[cfg.update_sharding] = profile_compiled(
                step, p, st, tk, tg, label=f"adam_{cfg.update_sharding}")
        sh, rep = profs["sharded"], profs["replicated"]
        assert "all-gather" in sh.collectives, sh.collectives
        assert sh.flops < rep.flops, (sh.flops, rep.flops)
        assert sh.peak_bytes < rep.peak_bytes, (sh.peak_bytes,
                                                rep.peak_bytes)

    def test_steady_state_retrace_budget(self):
        """0-compile steady state on the dp×ep ZeRO Adam step (the
        decode-style pin): after the compiling first call, steps 2-4 must
        not retrace."""
        self._run(_dp_ep_mesh(), self.CFG_SH, steps=4, retrace_pin=True)

    def test_composed_adam_matches_single_device(self):
        """The composed replicated Adam tracks the dense single-device
        Adam oracle (same parity discipline as the SGD composed tests)."""
        mesh = _dp_ep_mesh()
        cap = (B // 2) * T
        cfg = OptimizerConfig(name="adam", lr=1e-3)
        step = make_composed_train_step(mesh, H, cap, attn_impl="dense",
                                        optimizer=cfg)
        sd = make_single_device_train_step(H, attn_impl="dense",
                                           optimizer=cfg)
        params = _params()
        p = shard_lm_params(params, mesh)
        st = init_lm_opt_state(cfg, p, mesh)
        q = _copy(params)
        sq = init_lm_opt_state(cfg, q)
        for i in range(3):
            toks = _data(i + 1)
            tk, tg = shard_lm_batch(*toks, mesh)
            p, st, loss = step(p, st, tk, tg)
            jax.block_until_ready(loss)
            q, sq, ref = sd(q, sq, *toks)
            assert abs(float(loss) - float(ref)) < 1e-5
        assert _max_diff(p, q) < 1e-5

    def test_lamb_trains_on_dp_ep(self):
        cfg = OptimizerConfig(name="lamb", lr=1e-2,
                              update_sharding="sharded")
        _p, _st, losses = self._run(_dp_ep_mesh(), cfg, steps=4)
        assert all(np.isfinite(losses))


# --------------------------------------------------- guard × optimizer ----

class TestGuardWithOptimizer:
    def test_clean_batch_parity(self):
        """guard=True must be invisible on clean batches: the LOSS stays
        bit-identical to the unguarded adam step (the loss/grad graph is
        untouched), and params/moments agree to 1e-7. Unlike the SGD
        guard's bitwise pin, the adaptive update's sqrt/div chain gets
        re-fused differently by XLA once the guard's extra consumers
        (grad-norm reduction + selects) exist — a compiler fusion
        artifact, not a math change; the load-bearing BITWISE guarantee
        (a skipped step carries params+moments untouched) is pinned in
        test_skipped_step_leaves_moments_bitwise."""
        cfg = OptimizerConfig(name="adam", lr=1e-3)
        plain = make_single_device_train_step(H, attn_impl="dense",
                                              optimizer=cfg)
        guarded = make_single_device_train_step(H, attn_impl="dense",
                                                optimizer=cfg, guard=True)
        params = _params()
        tk, tg = _data()
        p0, s0 = _copy(params), init_lm_opt_state(cfg, params)
        p1, s1 = _copy(params), init_lm_opt_state(cfg, params)
        for i in range(2):
            p0, s0, l0 = plain(p0, s0, tk, tg)
            p1, s1, l1, gm = guarded(p1, s1, tk, tg)
            assert _bits_equal(l0, l1)
        assert _max_diff(p0, p1) <= 1e-7
        assert _max_diff(s0["m"], s1["m"]) <= 1e-7
        assert _max_diff(s0["v"], s1["v"]) <= 1e-7
        assert int(s1["count"]) == 2
        assert float(jax.device_get(gm)["nonfinite"]) == 0.0

    def test_skipped_step_leaves_moments_bitwise(self):
        """THE SATELLITE PIN: a non-finite step carries params AND the
        full optimizer state (m, v, count) bitwise — a NaN batch must not
        poison the Adam trajectory OR advance the bias correction."""
        cfg = OptimizerConfig(name="adam", lr=1e-3)
        guarded = make_single_device_train_step(H, attn_impl="dense",
                                                optimizer=cfg, guard=True)
        params = _params()
        tk, tg = _data()
        p, st = _copy(params), init_lm_opt_state(cfg, params)
        p, st, _, _ = guarded(p, st, tk, tg)  # one clean step: moments != 0
        # poison the params, step again: everything carried
        host = jax.device_get(p)
        arr = np.asarray(host["embed"]).copy()
        arr.flat[0] = np.nan
        host["embed"] = arr
        p = jax.tree_util.tree_map(jnp.asarray, host)
        pre_p, pre_st = _copy(p), _copy(st)
        p2, st2, loss, gm = guarded(p, st, tk, tg)
        assert not np.isfinite(float(loss))
        assert float(jax.device_get(gm)["nonfinite"]) == 1.0
        assert _tree_bits_equal(p2, pre_p)
        assert _tree_bits_equal(st2["m"], pre_st["m"])
        assert _tree_bits_equal(st2["v"], pre_st["v"])
        assert int(st2["count"]) == int(pre_st["count"])


# ------------------------------------------------------- pipeline dp×pp ----

class TestPipelineOptimizer:
    def _setup(self):
        from deeplearning4j_tpu.models.transformer_lm import make_pp_stages
        from deeplearning4j_tpu.parallel.pipeline import (
            shard_stage_params,
            stack_stage_params,
        )

        params = _params(n_layers=2)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("data", "pipe"))
        per_stage, stage_fn = make_pp_stages(params, H, n_stages=2,
                                             attn_impl="dense")
        stacked = shard_stage_params(stack_stage_params(per_stage), mesh,
                                     "pipe")
        n_micro, mb = 4, 2
        toks = jax.random.randint(jax.random.PRNGKey(3),
                                  (n_micro, mb, T + 1), 0, V)
        tk, tg = toks[..., :-1], toks[..., 1:]

        def pp_loss(y, tgt_mb):
            logits = y @ params["dec_w"] + params["dec_b"]
            logp = jax.nn.log_softmax(logits, axis=-1)
            return jnp.mean(
                -jnp.take_along_axis(logp, tgt_mb[..., None], -1)[..., 0])

        return params, mesh, stacked, stage_fn, pp_loss, tk, tg

    def test_sharded_vs_replicated_parity_and_placement(self):
        from deeplearning4j_tpu.parallel.pipeline import (
            init_pp_opt_state,
            make_pipeline_train_step,
        )

        params, mesh, stacked, stage_fn, pp_loss, tk, tg = self._setup()
        emb = params["embed"][tk]
        results = {}
        for mode in ("replicated", "sharded"):
            cfg = OptimizerConfig(name="adam", lr=1e-3,
                                  update_sharding=mode)
            step = make_pipeline_train_step(stage_fn, pp_loss, mesh, "pipe",
                                            batch_axis="data",
                                            optimizer=cfg)
            st = init_pp_opt_state(cfg, stacked, mesh, batch_axis="data")
            p = _copy(stacked)
            losses = []
            for _ in range(3):
                p, st, loss = step(p, st, emb, tg)
                losses.append(float(loss))
            results[mode] = (p, st, losses)
            assert losses[-1] < losses[0]  # adam actually trains
        p_r, _, l_r = results["replicated"]
        p_s, st_s, l_s = results["sharded"]
        np.testing.assert_allclose(l_r, l_s, atol=ATOL, rtol=0)
        assert _max_diff(p_r, p_s) <= ATOL
        # moments stage-sharded (pipe prefix kept) AND dp-sharded
        m_wq = st_s["m"]["wq"]
        assert m_wq.sharding.spec == jax.sharding.PartitionSpec(
            "pipe", "data")


# -------------------------------------------------- DP-sync trainer step ----

class TestSyncTrainerOptimizer:
    def _conf(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        return (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.01)
                .num_iterations(1).seed(0).list(2)
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax",
                          loss_function="MCXENT")
                .pretrain(False).backward(True).build())

    def test_sharded_vs_replicated_parity(self):
        from deeplearning4j_tpu.nn import functional as F
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
        from deeplearning4j_tpu.parallel.trainer import (
            init_sync_opt_state,
            make_sync_train_step,
        )

        conf = self._conf()
        mesh = data_parallel_mesh(8)
        params = F.init_params(conf, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(16, 4).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
        w = jnp.ones((16,), jnp.float32)
        key = jax.random.PRNGKey(7)
        out = {}
        for mode in ("replicated", "sharded"):
            cfg = OptimizerConfig(name="adam", lr=1e-3,
                                  update_sharding=mode)
            step = make_sync_train_step(conf, mesh, optimizer=cfg)
            st = init_sync_opt_state(cfg, params, mesh)
            p = _copy(params)
            for i in range(3):
                p, st, score = step(p, st, jnp.asarray(i), x, y, w, key)
            out[mode] = (jax.device_get(p), float(score), st)
        assert abs(out["replicated"][1] - out["sharded"][1]) <= ATOL
        assert _max_diff(out["replicated"][0], out["sharded"][0]) <= ATOL
        # the ZeRO moment leaves shard their leading dim over the dp axis
        m_leaf = out["sharded"][2]["m"][0]["W"]
        assert m_leaf.shape[0] == 8
        assert m_leaf.sharding.spec == jax.sharding.PartitionSpec("data")

    def test_metrics_block_carries_optimizer_health(self):
        from deeplearning4j_tpu.nn import functional as F
        from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
        from deeplearning4j_tpu.parallel.trainer import (
            init_sync_opt_state,
            make_sync_train_step,
        )

        conf = self._conf()
        mesh = data_parallel_mesh(8)
        params = F.init_params(conf, jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(16, 4).astype(np.float32))
        y = jnp.asarray(np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)])
        w = jnp.ones((16,), jnp.float32)
        cfg = OptimizerConfig(name="lamb", lr=1e-3)
        step = make_sync_train_step(conf, mesh, optimizer=cfg,
                                    with_metrics=True, guard=True)
        st = init_sync_opt_state(cfg, params, mesh)
        _, _, _, metrics = step(_copy(params), st, jnp.asarray(0), x, y, w,
                                jax.random.PRNGKey(7))
        m = jax.device_get(metrics)
        for k in ("loss", "grad_norm", "param_norm", "moment_norm_m",
                  "moment_norm_v", "update_ratio", "lamb_trust_ratio",
                  "nonfinite", "guard_grad_norm"):
            assert k in m, sorted(m)
        assert float(m["lamb_trust_ratio"]) > 0


# ----------------------------------------------------------- elastic path ----

class TestElasticOptimizer:
    def test_adam_trains_and_is_deterministic(self):
        from deeplearning4j_tpu.scaleout.elastic import (
            SyntheticRegressionModel,
        )

        def run():
            model = SyntheticRegressionModel(
                d_in=4, d_hidden=8, batch=8, lr=0.02, mesh_devices=2,
                optimizer=OptimizerConfig(name="adam", lr=1e-2,
                                          update_sharding="sharded"))
            p, loss = model.run_steps(model.init_params(), 0, 12,
                                      worker_seed=0)
            return p, loss, model.eval_loss(p)

        p1, l1, e1 = run()
        p2, l2, e2 = run()
        assert l1 == l2 and e1 == e2
        assert _max_diff(p1, p2) == 0.0
        sgd = SyntheticRegressionModel(d_in=4, d_hidden=8, batch=8,
                                       lr=0.02, mesh_devices=2)
        p0 = sgd.init_params()
        assert e1 < sgd.eval_loss(p0)  # actually learned

    def test_guarded_adam_skip_carries_moments(self):
        from deeplearning4j_tpu.scaleout.elastic import (
            SyntheticRegressionModel,
        )

        model = SyntheticRegressionModel(d_in=4, d_hidden=8, batch=8,
                                         lr=0.01, mesh_devices=1,
                                         guard=True, nan_at_step=2,
                                         optimizer="adam")
        p0, _ = model.run_steps(model.init_params(), 0, 2, worker_seed=0)
        m_before = _copy(jax.device_get(model._opt_state["m"]))
        count_before = int(jax.device_get(model._opt_state["count"]))
        p1, _ = model.run_steps(p0, 2, 1, worker_seed=0)  # the NaN step
        assert model.skipped_steps == 1
        assert _tree_bits_equal(p0, p1)
        assert _tree_bits_equal(m_before, model._opt_state["m"])
        assert int(jax.device_get(model._opt_state["count"])) == count_before


# ------------------------------------------------ checkpoint round trips ----

class TestOptStateCheckpoint:
    def test_partition_canonical_round_trip(self):
        mesh = _dp_ep_mesh()
        zero = lm_update_sharding(mesh)
        cfg = OptimizerConfig(name="adam", update_sharding="sharded")
        params = shard_lm_params(_params(), mesh)
        st = init_lm_opt_state(cfg, params, mesh)
        # make the moments non-trivial
        st = jax.tree_util.tree_map(
            lambda a: a + jnp.arange(a.size, dtype=a.dtype).reshape(a.shape)
            if a.ndim else a, st)
        can = canonical_opt_state(st, params, zero)
        back = partition_opt_state(can, zero)
        assert _tree_bits_equal(st["m"], back["m"])
        assert _tree_bits_equal(st["v"], back["v"])
        # canonical moments are param-shaped
        for (pa, pl), (_, cl) in zip(
                jax.tree_util.tree_leaves_with_path(jax.device_get(params)),
                jax.tree_util.tree_leaves_with_path(can["m"])):
            assert np.shape(pl) == np.shape(cl), jax.tree_util.keystr(pa)

    def test_ckpt_inspect_summarizes_and_diffs_moments(self, tmp_path):
        """The ckpt_inspect satellite: manifests carrying an ['opt']
        subtree render an optimizer-state block (leaf count, bytes,
        moment names, shardings), --json carries it structurally, and
        --diff covers moment trees (a moments-only change is exit 1 with
        the ['opt'] paths named)."""
        from deeplearning4j_tpu.scaleout.ckpt import Checkpointer
        from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

        mesh = _dp_ep_mesh()
        cfg = OptimizerConfig(name="adam", lr=1e-3,
                              update_sharding="sharded")
        cap = (B // 2) * T
        step = make_composed_train_step(mesh, H, cap, optimizer=cfg)
        p = shard_lm_params(_params(), mesh)
        st = init_lm_opt_state(cfg, p, mesh)
        zero = lm_update_sharding(mesh)
        ck = Checkpointer(str(tmp_path), registry=MetricsRegistry())
        tk, tg = shard_lm_batch(*_data(), mesh)
        p, st, _ = step(p, st, tk, tg)
        ck.save(1, {"params": p, "opt": canonical_opt_state(st, p, zero)},
                mesh=mesh)
        p, st, _ = step(p, st, tk, tg)
        ck.save(2, {"params": p, "opt": canonical_opt_state(st, p, zero)},
                mesh=mesh)

        tool = os.path.join(REPO, "tools", "ckpt_inspect.py")
        out = subprocess.run(
            [sys.executable, tool, str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert out.returncode == 0, out.stderr[-500:]
        summary = json.loads(out.stdout)
        opt = summary["optimizer_state"]
        n_param_leaves = len(jax.tree_util.tree_leaves(jax.device_get(p)))
        assert opt["leaves"] == 2 * n_param_leaves + 1  # m + v + count
        assert opt["moments"] == ["m", "v"]
        assert opt["has_step_count"] is True
        assert opt["bytes"] > 0
        # human rendering names the block too
        out_h = subprocess.run([sys.executable, tool, str(tmp_path)],
                               capture_output=True, text=True, timeout=120,
                               cwd=REPO)
        assert "optimizer state:" in out_h.stdout
        # --diff: the two steps differ in params AND moments; the moment
        # diffs are reported, not skipped
        from deeplearning4j_tpu.scaleout.ckpt.manifest import step_dir_name

        d1 = os.path.join(str(tmp_path), step_dir_name(1))
        d2 = os.path.join(str(tmp_path), step_dir_name(2))
        out_d = subprocess.run(
            [sys.executable, tool, d1, "--diff", d2, "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert out_d.returncode == 1  # they differ
        diff = json.loads(out_d.stdout)
        changed = {c["path"] for c in diff["changed"]}
        assert any(path.startswith("['opt']['m']") for path in changed)
        assert any(path.startswith("['opt']['v']") for path in changed)


# -------------------------------------------- telemetry / report rendering ----

class TestOptimizerTelemetry:
    def test_metrics_threaded_step_emits_optimizer_block(self):
        cfg = OptimizerConfig(name="lamb", lr=1e-3)
        step = make_single_device_train_step(H, attn_impl="dense",
                                             optimizer=cfg,
                                             with_metrics=True)
        params = _params()
        st = init_lm_opt_state(cfg, params)
        tk, tg = _data()
        _, _, _, metrics = step(_copy(params), st, tk, tg)
        m = jax.device_get(metrics)
        assert float(m["moment_norm_m"]) > 0
        assert float(m["moment_norm_v"]) > 0
        assert float(m["lamb_trust_ratio"]) > 0
        # the true ‖Δp‖/‖p‖ ratio, not the lr·‖g‖ SGD proxy
        assert float(m["update_ratio"]) > 0
        # adam (no trust ratio) omits the LAMB key
        cfg_a = OptimizerConfig(name="adam", lr=1e-3)
        step_a = make_single_device_train_step(H, attn_impl="dense",
                                               optimizer=cfg_a,
                                               with_metrics=True)
        _, _, _, ma = step_a(_copy(params),
                             init_lm_opt_state(cfg_a, params), tk, tg)
        assert "lamb_trust_ratio" not in ma

    def test_report_renders_moment_norms_silent_when_absent(self, tmp_path):
        """tools/telemetry_report.py renders the optimizer block when a
        step log carries it and stays byte-silent about it when absent —
        pinned both ways (the ISSUE 11/12 report discipline)."""
        from deeplearning4j_tpu.telemetry import (
            StepLogWriter,
            read_step_log,
            summarize_step_log,
        )

        with_opt = str(tmp_path / "opt.jsonl")
        writer = StepLogWriter(with_opt)
        for i in range(3):
            writer.write(i, wall_ms=1.0, loss=1.0 / (i + 1),
                         moment_norm_m=0.1 * (i + 1),
                         moment_norm_v=0.01 * (i + 1),
                         lamb_trust_ratio=1.5)
        writer.close()
        summary = summarize_step_log(read_step_log(with_opt))
        assert summary["moment_norm_m"]["last"] == 0.3
        assert summary["lamb_trust_ratio"]["first"] == 1.5
        tool = os.path.join(REPO, "tools", "telemetry_report.py")
        out = subprocess.run([sys.executable, tool, with_opt],
                             capture_output=True, text=True, timeout=120,
                             cwd=REPO)
        assert out.returncode == 0, out.stderr[-500:]
        for name in ("moment_norm_m", "moment_norm_v", "lamb_trust_ratio"):
            assert name in out.stdout
        # absent both ways
        without = str(tmp_path / "plain.jsonl")
        writer = StepLogWriter(without)
        for i in range(3):
            writer.write(i, wall_ms=1.0, loss=1.0 / (i + 1))
        writer.close()
        out2 = subprocess.run([sys.executable, tool, without],
                              capture_output=True, text=True, timeout=120,
                              cwd=REPO)
        assert out2.returncode == 0
        for name in ("moment_norm_m", "moment_norm_v", "lamb_trust_ratio"):
            assert name not in out2.stdout
