"""Distributed control-plane tests — the whole cluster in one process
(ref test model: TestDistributed / BaseTestDistributed in-JVM harness,
SURVEY.md §4)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.scaleout import InMemoryStateTracker, LocalDistributedRunner
from deeplearning4j_tpu.scaleout.aggregator import ParameterAveragingAggregator
from deeplearning4j_tpu.scaleout.job import CollectionJobIterator, DataSetJobIterator, Job
from deeplearning4j_tpu.scaleout.perform import MultiLayerNetworkWorkPerformer
from deeplearning4j_tpu.scaleout.workrouter import (
    HogWildWorkRouter,
    IterativeReduceWorkRouter,
)


def iris_conf_json(num_iterations=20):
    return (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).activation_function("tanh")
        .lr(0.1).momentum(0.9).num_iterations(num_iterations).seed(42)
        .list(2)
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True)
        .build()
        .to_json()
    )


def test_aggregator_averages():
    agg = ParameterAveragingAggregator()
    j1, j2 = Job(None), Job(None)
    j1.result = np.array([1.0, 2.0])
    j2.result = np.array([3.0, 4.0])
    agg.accumulate(j1)
    agg.accumulate(j2)
    np.testing.assert_allclose(agg.aggregate(), [2.0, 3.0])


def test_state_tracker_round_trip():
    t = InMemoryStateTracker()
    t.add_worker("w0")
    t.add_worker("w1")
    assert t.workers() == ["w0", "w1"]
    job = Job("work", "w0")
    t.add_job(job)
    assert t.job_for("w0") is job
    t.add_update("w0", job)
    assert "w0" in t.updates()
    t.set_current(np.zeros(3))
    t.add_replicate("w1")
    assert t.needs_replicate("w1") and not t.needs_replicate("w0")
    t.increment("n")
    assert t.count("n") == 1.0
    t.finish()
    assert t.is_done()


def test_routers_policy():
    t = InMemoryStateTracker()
    agg = ParameterAveragingAggregator()
    t.add_worker("w0")
    t.add_worker("w1")
    sync = IterativeReduceWorkRouter(t, agg)
    hog = HogWildWorkRouter(t, agg)
    assert not sync.send_work()  # no updates yet
    assert hog.send_work()       # always
    j = Job("x", "w0")
    j.result = np.ones(2)
    t.add_update("w0", j)
    assert not sync.send_work()  # only 1 of 2
    j2 = Job("x", "w1")
    j2.result = np.ones(2) * 3
    t.add_update("w1", j2)
    assert sync.send_work()
    sync.update()
    np.testing.assert_allclose(t.get_current(), [2.0, 2.0])
    assert t.needs_replicate("w0") and t.needs_replicate("w1")
    assert t.updates() == {}


def test_local_distributed_training_converges():
    """4 workers, IterativeReduce param averaging over Iris mini-batches —
    the in-process analogue of the reference's TestDistributed."""
    conf_json = iris_conf_json(num_iterations=15)
    it = IrisDataSetIterator(25, 150)  # 6 mini-batch jobs
    runner = LocalDistributedRunner(
        performer_factory=lambda: MultiLayerNetworkWorkPerformer(conf_json),
        job_iterator=DataSetJobIterator(it),
        num_workers=4,
    )
    final_params = runner.train()
    assert final_params is not None
    assert runner.tracker.count("jobs_done") == 6

    net = MultiLayerNetwork.from_json(conf_json)
    net.init()
    net.set_params(final_params)
    full = IrisDataSetIterator(150, 150).next()
    acc = (net.predict(full.features) == full.labels.argmax(-1)).mean()
    assert acc > 0.6, acc


def test_hogwild_router_runs():
    conf_json = iris_conf_json(num_iterations=5)
    it = IrisDataSetIterator(50, 150)
    tracker = InMemoryStateTracker()
    runner = LocalDistributedRunner(
        performer_factory=lambda: MultiLayerNetworkWorkPerformer(conf_json),
        job_iterator=DataSetJobIterator(it),
        num_workers=2,
        tracker=tracker,
        router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()),
    )
    assert runner.train() is not None


def test_hogwild_async_workers_make_unequal_progress():
    """The async path has NO per-round barrier: a slow worker must not gate
    a fast one (ref: HogWildWorkRouter.sendWork always true + WorkerActor's
    continuous pull loop, WorkerActor.java:168-206). With the old lockstep
    runner both workers would finish the same number of rounds."""
    import time as _time

    from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
    from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

    class PacedPerformer(WorkerPerformer):
        def __init__(self, delay_s):
            self.delay_s = delay_s

        def perform(self, job):
            _time.sleep(self.delay_s)
            job.result = np.asarray([float(job.work)])

        def update(self, *args):
            pass

    delays = iter([0.05, 0.001])  # worker-0 is 50x slower than worker-1
    tracker = InMemoryStateTracker()
    runner = LocalDistributedRunner(
        performer_factory=lambda: PacedPerformer(next(delays)),
        job_iterator=CollectionJobIterator(list(range(24))),
        num_workers=2,
        tracker=tracker,
        router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()),
    )
    runner.train()
    assert tracker.count("jobs_done") == 24
    slow = tracker.count("rounds.worker-0")
    fast = tracker.count("rounds.worker-1")
    assert fast >= 3 * max(slow, 1), (slow, fast)
    # the master aggregated on its own cadence while workers ran
    assert tracker.count("aggregations") >= 2


def test_hogwild_async_training_converges():
    """Async Hogwild with a deliberately slow straggler still converges on
    Iris — staleness-tolerant averaging (ref: HogWildWorkRouter semantics)."""
    import time as _time

    conf_json = iris_conf_json(num_iterations=15)

    class SlowFirstWorkerFactory:
        def __init__(self):
            self.n = 0

        def __call__(self):
            performer = MultiLayerNetworkWorkPerformer(conf_json)
            if self.n == 0:
                inner = performer.perform

                def slow_perform(job):
                    _time.sleep(0.05)
                    inner(job)

                performer.perform = slow_perform
            self.n += 1
            return performer

    tracker = InMemoryStateTracker()
    runner = LocalDistributedRunner(
        performer_factory=SlowFirstWorkerFactory(),
        job_iterator=DataSetJobIterator(IrisDataSetIterator(25, 150)),
        num_workers=2,
        tracker=tracker,
        router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()),
    )
    final_params = runner.train()
    assert final_params is not None
    assert tracker.count("jobs_done") == 6

    net = MultiLayerNetwork.from_json(conf_json)
    net.init()
    net.set_params(final_params)
    full = IrisDataSetIterator(150, 150).next()
    acc = (net.predict(full.features) == full.labels.argmax(-1)).mean()
    assert acc > 0.6, acc


def test_collection_job_iterator():
    it = CollectionJobIterator([1, 2, 3])
    seen = []
    while it.has_next():
        seen.append(it.next("w").work)
    assert seen == [1, 2, 3]
    it.reset()
    assert it.has_next()


def test_parallelization_map():
    from deeplearning4j_tpu.scaleout.parallelization import iterate, run_in_parallel

    assert iterate([1, 2, 3], lambda x: x * 2) == [2, 4, 6]
    assert run_in_parallel([lambda: 1, lambda: 2]) == [1, 2]


class TestFullStateCheckpoint:
    """Beyond-reference: params + updater state + iteration resume
    (ref only persists conf JSON + flat params, SURVEY.md §5)."""

    def _conf(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

        return (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .momentum(0.9).use_ada_grad(True).num_iterations(10).seed(42)
                .weight_init("VI").list(2)
                .override(0, layer_type="DENSE")
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax", loss_function="MCXENT")
                .pretrain(False).backward(True).build())

    def test_resume_is_bit_exact(self, tmp_path):
        import numpy as np

        from deeplearning4j_tpu.datasets.fetchers import iris_data
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.scaleout.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        x, y = iris_data()
        x = x.astype(np.float32)
        onehot = np.eye(3, dtype=np.float32)[y]

        # train 10 iters, checkpoint, train 10 more
        net_a = MultiLayerNetwork(self._conf()).init()
        net_a.fit(x, onehot)
        path = save_checkpoint(str(tmp_path / "ckpt"), net_a)
        net_a.fit(x, onehot)

        # resume from the checkpoint and train the same 10 more
        net_b, it = load_checkpoint(path)
        assert it == 10
        assert net_b._iteration == 10  # restored by load, not reassigned
        net_b.fit(x, onehot)

        np.testing.assert_allclose(
            np.asarray(net_a.params()), np.asarray(net_b.params()),
            atol=1e-6,
        )

    def test_checkpoint_restores_updater_state(self, tmp_path):
        import numpy as np

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.scaleout.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        rng = np.random.RandomState(0)
        x = rng.rand(12, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)]
        net = MultiLayerNetwork(self._conf()).init()
        net.fit(x, y)
        path = save_checkpoint(str(tmp_path / "c2"), net)
        import jax

        net2, _ = load_checkpoint(path)
        flat_a = [np.asarray(l) for l in jax.tree_util.tree_leaves(net._train_state)]
        flat_b = [np.asarray(l) for l in jax.tree_util.tree_leaves(net2._train_state)]
        assert len(flat_a) == len(flat_b) and len(flat_a) > 0
        for a, b in zip(flat_a, flat_b):
            np.testing.assert_allclose(a, b, atol=1e-7)

    def test_rng_stream_resumes_for_stochastic_conf(self, tmp_path):
        """With dropout in the conf, resumed training still matches the
        uninterrupted run — the host RNG stream position is checkpointed."""
        import numpy as np
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.scaleout.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .dropout(0.3).num_iterations(5).seed(11).weight_init("VI")
                .list(2)
                .override(0, layer_type="DENSE")
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax", loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        rng = np.random.RandomState(0)
        x = rng.rand(16, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]

        net_a = MultiLayerNetwork(conf).init()
        net_a.fit(x, y)
        path = save_checkpoint(str(tmp_path / "rng"), net_a)
        net_a.fit(x, y)

        net_b, _ = load_checkpoint(path)
        net_b.fit(x, y)
        np.testing.assert_allclose(np.asarray(net_a.params()),
                                   np.asarray(net_b.params()), atol=1e-6)

    def test_crash_mid_save_preserves_old_checkpoint(self, tmp_path,
                                                     monkeypatch):
        """A writer killed mid-save must leave the previous checkpoint at
        the path intact and loadable, and clean up its tmp file — the
        unique-tmp + os.replace discipline."""
        import numpy as np

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.scaleout.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        rng = np.random.RandomState(3)
        x = rng.rand(12, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)]
        net = MultiLayerNetwork(self._conf()).init()
        net.fit(x, y)
        path = save_checkpoint(str(tmp_path / "ck"), net)
        with open(path, "rb") as f:
            good_bytes = f.read()

        net.fit(x, y)

        def boom(f, **payload):
            f.write(b"half a checkpoint")  # partial write, then crash
            raise RuntimeError("disk died mid-save")

        monkeypatch.setattr(np, "savez", boom)
        with pytest.raises(RuntimeError, match="disk died"):
            save_checkpoint(path, net)
        monkeypatch.undo()

        with open(path, "rb") as f:
            assert f.read() == good_bytes, "old checkpoint was clobbered"
        leftovers = [p for p in os.listdir(tmp_path) if ".tmp-" in p]
        assert not leftovers, f"tmp files left behind: {leftovers}"
        net2, it = load_checkpoint(path)
        assert it == 10
        assert np.isfinite(np.asarray(net2.params())).all()

    def test_concurrent_saver_tmp_names_are_unique(self, tmp_path,
                                                   monkeypatch):
        """Two savers writing the same path must not collide on the tmp
        file (the old fixed ``path.tmp.npz`` name did)."""
        import numpy as np

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.scaleout.checkpoint import save_checkpoint

        net = MultiLayerNetwork(self._conf()).init()
        seen = []
        orig = np.savez

        def spy(f, **payload):
            seen.append(f.name)
            return orig(f, **payload)

        monkeypatch.setattr(np, "savez", spy)
        path = str(tmp_path / "ck")
        save_checkpoint(path, net)
        save_checkpoint(path, net)
        assert len(seen) == 2 and seen[0] != seen[1]
        assert all(".tmp-" in name for name in seen)

    def test_load_rejects_shape_mismatch_and_lossy_dtype(self, tmp_path):
        """Satellite: the loader must raise on a shape mismatch and on a
        lossy dtype narrowing instead of silently astype-ing into the
        template (safe widening still loads)."""
        import numpy as np

        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.scaleout.checkpoint import (
            load_checkpoint,
            save_checkpoint,
        )

        net = MultiLayerNetwork(self._conf()).init()
        path = save_checkpoint(str(tmp_path / "ck"), net)
        with np.load(path) as z:
            payload = {k: np.asarray(z[k]) for k in z.files}
        param_keys = [k for k in payload
                      if k.startswith("tree::['params']")
                      and payload[k].ndim == 2]
        key = param_keys[0]

        bad_shape = dict(payload)
        bad_shape[key] = payload[key][:-1]  # truncate one row
        p1 = str(tmp_path / "bad_shape.npz")
        np.savez(p1.removesuffix(".npz"), **bad_shape)
        with pytest.raises(ValueError, match="shape"):
            load_checkpoint(p1)

        bad_dtype = dict(payload)
        bad_dtype[key] = payload[key].astype(np.float64)
        p2 = str(tmp_path / "bad_dtype.npz")
        np.savez(p2.removesuffix(".npz"), **bad_dtype)
        with pytest.raises(TypeError, match="narrow"):
            load_checkpoint(p2)

        widened = dict(payload)
        widened[key] = payload[key].astype(np.float16)  # f16 → f32 is safe
        p3 = str(tmp_path / "widened.npz")
        np.savez(p3.removesuffix(".npz"), **widened)
        net3, _ = load_checkpoint(p3)
        assert np.isfinite(np.asarray(net3.params())).all()


class TestFaultTolerance:
    """Dead-worker recovery (ref: MasterActor stale-job GC + re-route,
    §5 failure detection)."""

    def _runner(self, fail_ids, fault_tolerant=True, num_workers=3):
        from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
        from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

        class FlakyPerformer(WorkerPerformer):
            def __init__(self, idx, fail_ids):
                self.idx = idx
                self.fail_ids = fail_ids

            def perform(self, job):
                if self.idx in self.fail_ids:
                    raise RuntimeError(f"worker {self.idx} crashed")
                job.result = np.asarray([float(job.work)])

            def update(self, *args):
                pass

        counter = iter(range(100))
        return LocalDistributedRunner(
            performer_factory=lambda: FlakyPerformer(next(counter), fail_ids),
            job_iterator=CollectionJobIterator(list(range(6))),
            num_workers=num_workers,
            fault_tolerant=fault_tolerant,
        )

    def test_failed_worker_job_rerouted(self):
        runner = self._runner(fail_ids={1})
        runner.train()
        # all 6 jobs completed despite worker 1 dying
        assert runner.tracker.count("jobs_done") == 6
        assert runner.tracker.count("worker_failures") == 1
        assert len(runner.tracker.workers()) == 2

    def test_not_fault_tolerant_raises(self):
        runner = self._runner(fail_ids={1}, fault_tolerant=False)
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="crashed"):
            runner.train()

    def test_all_workers_failed_raises(self):
        runner = self._runner(fail_ids={0, 1}, num_workers=2)
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="all workers failed"):
            runner.train()

    def test_job_timing_counter(self):
        runner = self._runner(fail_ids=set())
        runner.train()
        assert runner.tracker.count("job_ms_total") > 0


def test_timing_iteration_listener():
    from deeplearning4j_tpu.optimize.listeners import TimingIterationListener

    listener = TimingIterationListener(print_iterations=100)
    for i in range(5):
        listener(None, i, 1.0)
    # first callback only arms the clock (compile/setup excluded)
    assert len(listener.timings_ms) == 4
    assert listener.total_ms() >= 0
    assert listener.mean_ms() >= 0


def test_two_workers_fail_same_round_no_job_lost():
    """Regression: two reroutes in one round must not clobber each other or
    a survivor's in-flight job."""
    from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
    from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

    class Flaky(WorkerPerformer):
        def __init__(self, idx):
            self.idx = idx

        def perform(self, job):
            if self.idx in (0, 1):
                raise RuntimeError(f"worker {self.idx} crashed")
            job.result = np.asarray([float(job.work)])

        def update(self, *args):
            pass

    counter = iter(range(100))
    runner = LocalDistributedRunner(
        performer_factory=lambda: Flaky(next(counter)),
        job_iterator=CollectionJobIterator(list(range(6))),
        num_workers=3,
        fault_tolerant=True,
    )
    runner.train()
    assert runner.tracker.count("jobs_done") == 6
    assert runner.tracker.count("worker_failures") == 2


class TestEarlyStopping:
    """Master-side early stopping enforcing the tracker's earlyStop/bestLoss
    flags (ref: StateTracker.java exposes the flags; here the master trips
    and honors them)."""

    def _stuck_runner(self, n_jobs=12, patience=2, router=None, tracker=None):
        """Performer whose reported loss never improves."""
        from deeplearning4j_tpu.scaleout import EarlyStopping
        from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
        from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

        class StuckPerformer(WorkerPerformer):
            def perform(self, job):
                import time as _time

                _time.sleep(0.005)  # give the master heartbeat ticks to
                #                     observe several aggregation rounds
                job.result = np.asarray([1.0])
                job.score = 5.0  # constant: no improvement, ever

            def update(self, *args):
                pass

        tracker = tracker or InMemoryStateTracker()
        return LocalDistributedRunner(
            performer_factory=StuckPerformer,
            job_iterator=CollectionJobIterator(list(range(n_jobs))),
            num_workers=2,
            tracker=tracker,
            router=router,
            early_stopping=EarlyStopping(patience=patience),
        )

    def test_sync_stops_without_improvement(self):
        runner = self._stuck_runner()
        runner.train()
        t = runner.tracker
        assert t.is_early_stop()
        assert t.count("early_stopped") == 1
        # stopped well before the 12-job stream drained
        assert t.count("jobs_done") < 12
        assert t.best_loss() == 5.0  # first round set the best loss

    def test_async_stops_without_improvement(self):
        tracker = InMemoryStateTracker()
        runner = self._stuck_runner(
            n_jobs=200, patience=2, tracker=tracker,
            router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()))
        runner.train()
        assert tracker.is_early_stop()
        assert tracker.count("jobs_done") < 200

    def test_externally_set_flag_halts_sync_run(self):
        runner = self._stuck_runner(patience=10_000)
        runner.tracker.early_stop()  # e.g. an operator or another component
        runner.train()
        assert runner.tracker.count("jobs_done") == 0

    def test_improving_run_does_not_stop(self):
        from deeplearning4j_tpu.scaleout import EarlyStopping
        from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
        from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

        class ImprovingPerformer(WorkerPerformer):
            def __init__(self):
                self.loss = 10.0

            def perform(self, job):
                job.result = np.asarray([1.0])
                self.loss *= 0.9
                job.score = self.loss

            def update(self, *args):
                pass

        runner = LocalDistributedRunner(
            performer_factory=ImprovingPerformer,
            job_iterator=CollectionJobIterator(list(range(8))),
            num_workers=2,
            early_stopping=EarlyStopping(patience=2),
        )
        runner.train()
        assert not runner.tracker.is_early_stop()
        assert runner.tracker.count("jobs_done") == 8

    def test_performer_reports_score(self):
        from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
        from deeplearning4j_tpu.scaleout.job import Job
        from deeplearning4j_tpu.scaleout.perform import (
            MultiLayerNetworkWorkPerformer,
        )

        performer = MultiLayerNetworkWorkPerformer(iris_conf_json(5))
        job = Job(IrisDataSetIterator(30, 30).next(), "w0")
        performer.perform(job)
        assert job.score is not None and np.isfinite(job.score)

    def test_async_early_stop_with_orphaned_job_does_not_hang(self):
        """Regression: an early stop while a failed worker's job sits in the
        requeue must not spin the drain loop forever (drain workers exit
        immediately once the flag is set — orphans are abandoned). The flag
        is tripped externally mid-run, which both paths honor."""
        import threading
        import time as _time

        from deeplearning4j_tpu.scaleout import EarlyStopping
        from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
        from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

        class CrashOrSlow(WorkerPerformer):
            def __init__(self, idx):
                self.idx = idx

            def perform(self, job):
                if self.idx == 0:
                    raise RuntimeError("boom")  # its job lands in _requeued
                _time.sleep(0.005)
                job.result = np.asarray([1.0])
                job.score = 5.0

            def update(self, *args):
                pass

        counter = iter(range(10))
        tracker = InMemoryStateTracker()
        runner = LocalDistributedRunner(
            performer_factory=lambda: CrashOrSlow(next(counter)),
            job_iterator=CollectionJobIterator(list(range(500))),
            num_workers=2,
            tracker=tracker,
            fault_tolerant=True,
            router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()),
            early_stopping=EarlyStopping(patience=2),
        )
        t = threading.Thread(target=runner.train, daemon=True)
        t.start()
        _time.sleep(0.2)          # let worker-0 crash + worker-1 get going
        tracker.early_stop()      # external trip mid-run
        t.join(60)
        assert not t.is_alive(), "train() hung in the orphan drain loop"
        assert tracker.is_early_stop()
        assert tracker.count("jobs_done") < 500  # stopped early

    def test_async_fast_plateaued_worker_does_not_trip_patience(self):
        """A fast worker with flat loss must not trip early stopping while a
        slower worker is still improving: evaluation rounds require a fresh
        score from every reporting worker, so patience is judged on the
        round MEAN, not on whichever worker publishes most often."""
        import time as _time

        from deeplearning4j_tpu.scaleout import EarlyStopping
        from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
        from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

        class Paced(WorkerPerformer):
            def __init__(self, idx):
                self.idx = idx
                self.loss = 10.0

            def perform(self, job):
                if self.idx == 0:
                    _time.sleep(0.001)
                    job.score = 5.0          # fast, plateaued
                else:
                    _time.sleep(0.02)
                    self.loss *= 0.7         # slow, improving fast
                    job.score = self.loss
                job.result = np.asarray([1.0])

            def update(self, *args):
                pass

        counter = iter(range(10))
        tracker = InMemoryStateTracker()
        runner = LocalDistributedRunner(
            performer_factory=lambda: Paced(next(counter)),
            job_iterator=CollectionJobIterator(list(range(40))),
            num_workers=2,
            tracker=tracker,
            router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()),
            early_stopping=EarlyStopping(patience=3),
        )
        runner.train()
        assert not tracker.is_early_stop()
        assert tracker.count("jobs_done") == 40

    def test_async_crashed_worker_does_not_block_early_stopping(self):
        """A worker that crashes mid-run is deregistered by the async
        master's heartbeat (not after the loop), so the early-stopping
        coverage rule falls to the survivors and can still trip."""
        import time as _time

        from deeplearning4j_tpu.scaleout import EarlyStopping
        from deeplearning4j_tpu.scaleout.job import CollectionJobIterator
        from deeplearning4j_tpu.scaleout.perform import WorkerPerformer

        class CrashOrStuck(WorkerPerformer):
            def __init__(self, idx):
                self.idx = idx

            def perform(self, job):
                if self.idx == 0:
                    raise RuntimeError("boom")
                _time.sleep(0.005)
                job.result = np.asarray([1.0])
                job.score = 5.0  # survivor plateaus forever

            def update(self, *args):
                pass

        counter = iter(range(10))
        tracker = InMemoryStateTracker()
        runner = LocalDistributedRunner(
            performer_factory=lambda: CrashOrStuck(next(counter)),
            job_iterator=CollectionJobIterator(list(range(300))),
            num_workers=2,
            tracker=tracker,
            fault_tolerant=True,
            router=HogWildWorkRouter(tracker, ParameterAveragingAggregator()),
            early_stopping=EarlyStopping(patience=2),
        )
        runner.train()
        assert tracker.count("worker_failures") == 1
        assert tracker.is_early_stop()
        assert tracker.count("jobs_done") < 300
