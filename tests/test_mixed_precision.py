"""bf16 mixed-precision parity gates (ops/dtypes.py Policy).

The TPU bench runs with bf16 compute + fp32 master params; these tests gate
that policy against fp32: same conf, same data, same seeds — final loss and
accuracy must match within tolerance. (The reference is fp32-only through
ND4J; mixed precision is the TPU-idiomatic addition.)
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.fetchers import synthetic_mnist
from deeplearning4j_tpu.models.zoo import lenet, mnist_mlp
from deeplearning4j_tpu.nn import functional as F
from deeplearning4j_tpu.ops.dtypes import BF16_COMPUTE


def _train(conf, policy, x, y, steps):
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    epoch = F.make_train_epoch(conf, steps, donate=False, policy=policy)
    params, states, scores = epoch(
        params, states, jnp.asarray(0), x, y, jax.random.PRNGKey(1)
    )
    return params, np.asarray(scores)


def _accuracy(conf, params, x, y):
    out = F.output(conf, params, x.reshape(-1, x.shape[-1]))
    pred = np.argmax(np.asarray(out), axis=-1)
    truth = np.argmax(np.asarray(y.reshape(-1, y.shape[-1])), axis=-1)
    return float((pred == truth).mean())


class TestBF16Parity:
    def test_mlp_loss_and_accuracy_parity(self):
        steps, batch = 30, 128
        conf = mnist_mlp(64, 32)
        xs, ys = synthetic_mnist(batch * steps)
        x = jnp.asarray(xs).reshape(steps, batch, -1)
        y = jax.nn.one_hot(jnp.asarray(ys), 10, dtype=jnp.float32).reshape(
            steps, batch, -1
        )
        p32, s32 = _train(conf, None, x, y, steps)
        p16, s16 = _train(conf, BF16_COMPUTE, x, y, steps)
        # master params stay fp32 under the bf16 policy
        assert all(v.dtype == jnp.float32 for layer in p16 for v in layer.values())
        # loss curves track each other
        assert abs(s32[-1] - s16[-1]) < 0.08, (s32[-1], s16[-1])
        a32 = _accuracy(conf, p32, x, y)
        a16 = _accuracy(conf, p16, x, y)
        assert abs(a32 - a16) < 0.05, (a32, a16)
        assert a16 > 0.5, a16  # genuinely learned, not just matched

    def test_lenet_bf16_trains(self):
        steps, batch = 10, 64
        conf = lenet()
        xs, ys = synthetic_mnist(batch * steps)
        x = jnp.asarray(xs).reshape(steps, batch, -1)
        y = jax.nn.one_hot(jnp.asarray(ys), 10, dtype=jnp.float32).reshape(
            steps, batch, -1
        )
        p16, s16 = _train(conf, BF16_COMPUTE, x, y, steps)
        assert np.isfinite(s16).all()
        assert s16[-1] < s16[0], (s16[0], s16[-1])
