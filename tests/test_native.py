"""Native runtime tests: C++ CSV parser, prefetch loader, buffer pool,
async iterators. The native library is required in CI (toolchain baked in);
fallback paths are exercised explicitly."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.async_iterator import (
    AsyncDataSetIterator,
    NativeCSVDataSetIterator,
)
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.native import (
    BufferPool,
    NativeCSVLoader,
    load_csv,
    native_available,
)


@pytest.fixture
def csv_path(tmp_path):
    p = tmp_path / "d.csv"
    rows = [f"{i},{i*2},{i%3}" for i in range(20)]
    p.write_text("\n".join(rows) + "\n")
    return str(p)


def test_native_library_builds():
    assert native_available(), "g++ toolchain is baked in; native must build"


class TestLoadCSV:
    def test_parse(self, csv_path):
        arr = load_csv(csv_path)
        assert arr.shape == (20, 3)
        assert arr[3].tolist() == [3.0, 6.0, 0.0]

    def test_skip_lines(self, tmp_path):
        p = tmp_path / "h.csv"
        p.write_text("header,line\n1,2\n")
        assert load_csv(str(p), skip_lines=1).tolist() == [[1.0, 2.0]]

    def test_missing_file(self):
        with pytest.raises(ValueError):
            load_csv("/definitely/not/here.csv")

    def test_ragged_rows(self, tmp_path):
        p = tmp_path / "r.csv"
        p.write_text("1,2\n3\n")
        with pytest.raises(ValueError, match="ragged|parse"):
            load_csv(str(p))

    def test_matches_numpy(self, csv_path):
        native = load_csv(csv_path)
        ref = np.loadtxt(csv_path, delimiter=",", dtype=np.float32, ndmin=2)
        np.testing.assert_array_equal(native, ref)


class TestNativeLoader:
    def test_batches(self, csv_path):
        ld = NativeCSVLoader(csv_path, batch=8)
        assert ld.native
        sizes = [b.shape for b in ld]
        assert sizes == [(8, 3), (8, 3), (4, 3)]
        ld.close()

    def test_drop_last(self, csv_path):
        ld = NativeCSVLoader(csv_path, batch=8, drop_last=True)
        assert [b.shape[0] for b in ld] == [8, 8]
        ld.close()

    def test_shuffle_covers_epoch(self, csv_path):
        ld = NativeCSVLoader(csv_path, batch=6, shuffle_seed=9)
        first_col = sorted(int(v) for b in ld for v in b[:, 0])
        assert first_col == list(range(20))
        ld.close()

    def test_shuffle_deterministic(self, csv_path):
        def run():
            ld = NativeCSVLoader(csv_path, batch=20, shuffle_seed=7)
            out = next(iter(ld)).copy()
            ld.close()
            return out

        np.testing.assert_array_equal(run(), run())


class TestBufferPool:
    def test_acquire_release_cycle(self):
        pool = BufferPool(1024, 2)
        a, b = pool.acquire(), pool.acquire()
        assert a is not None and b is not None
        if pool.native:
            assert pool.acquire() is None
            assert pool.available() == 0
        pool.release(a)
        if pool.native:
            assert pool.available() == 1
        c = pool.acquire()
        assert c is not None and c.array.dtype == np.float32
        pool.close()

    def test_buffer_is_writable(self):
        pool = BufferPool(256, 1)
        buf = pool.acquire()
        buf.array[:] = 7.0
        assert buf.array.sum() == 7.0 * buf.array.size
        pool.release(buf)
        pool.close()


class TestAsyncIterator:
    def _backing(self, n=30, batch=7):
        rng = np.random.RandomState(0)
        ds = DataSet(rng.rand(n, 4).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, n)])
        return ListDataSetIterator(ds, batch)

    def test_same_batches_as_backing(self):
        sync = list(iter(self._backing()))
        async_it = AsyncDataSetIterator(self._backing(), capacity=2)
        got = []
        while async_it.has_next():
            got.append(async_it.next())
        assert len(got) == len(sync)
        for a, b in zip(got, sync):
            np.testing.assert_array_equal(a.features, b.features)

    def test_reset_mid_epoch(self):
        it = AsyncDataSetIterator(self._backing(), capacity=2)
        it.next()
        it.reset()
        total = 0
        while it.has_next():
            total += it.next().num_examples()
        assert total == 30

    def test_multiple_epochs(self):
        it = AsyncDataSetIterator(self._backing(), capacity=3)
        for _ in range(3):
            count = sum(b.num_examples() for b in iter(it))
            assert count == 30

    def test_producer_error_propagates(self):
        class Exploding(ListDataSetIterator):
            def next(self, num=None):
                if self._cursor >= 14:
                    raise RuntimeError("backing iterator died")
                return super().next(num)

        rng = np.random.RandomState(0)
        ds = DataSet(rng.rand(30, 4).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.randint(0, 2, 30)])
        it = AsyncDataSetIterator(Exploding(ds, 7), capacity=2)
        with pytest.raises(RuntimeError, match="backing iterator died"):
            while it.has_next():
                it.next()


class TestNativeCSVDataSetIterator:
    def test_one_hot_and_epoch(self, csv_path):
        it = NativeCSVDataSetIterator(csv_path, 8, num_possible_labels=3)
        assert it.native
        assert it.input_columns() == 2
        total = 0
        while it.has_next():
            ds = it.next()
            assert ds.features.shape[1] == 2
            assert ds.labels.shape[1] == 3
            total += ds.num_examples()
        assert total == 20
        it.reset()
        assert it.has_next()
        it.close()

    def test_trains_network(self, tmp_path):
        # native pipeline feeding a real fit() — the end-to-end infeed path
        from deeplearning4j_tpu.datasets.fetchers import iris_data
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        x, y = iris_data()
        p = tmp_path / "iris.csv"
        p.write_text("\n".join(
            ",".join(f"{v:.4f}" for v in row) + f",{int(lab)}"
            for row, lab in zip(x, y)) + "\n")
        it = NativeCSVDataSetIterator(str(p), 150, num_possible_labels=3,
                                      shuffle_seed=3)
        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .momentum(0.9).use_ada_grad(True).num_iterations(60).seed(42)
                .weight_init("VI").list(2)
                .override(0, layer_type="DENSE")
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax", loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it)
        preds = net.predict(x.astype(np.float32))
        assert (preds == y).mean() > 0.9
        it.close()


class TestNativeCorpusIndex:
    """native/text.cpp tokenize+count+index vs the Python path
    (ref host hot path: Word2Vec.java vocab phase + VocabActor)."""

    CORPUS = [
        "the quick brown fox jumps over the lazy dog",
        "the dog barks",
        "lonely",          # 1 kept token -> dropped from the index
        "quick quick fox the",
        "zebra apple apple the",
    ]

    def _python_reference(self, sentences, min_count):
        from deeplearning4j_tpu.models.word2vec import Word2Vec
        from deeplearning4j_tpu.text.sentence_iterator import (
            CollectionSentenceIterator,
        )

        w = Word2Vec(
            sentence_iterator=CollectionSentenceIterator(sentences),
            layer_size=8, min_word_frequency=min_count, seed=1,
        )
        # force the python path regardless of library availability
        w._native_path_possible = lambda: False
        w.build_vocab()
        return w

    def test_parity_with_python_path(self):
        import pytest as _pytest

        from deeplearning4j_tpu.native.lib import corpus_index, native_available

        if not native_available():
            _pytest.skip("native library unavailable")
        for min_count in (1, 2):
            ref = self._python_reference(self.CORPUS, min_count)
            text = "\n".join(self.CORPUS).encode()
            words, counts, flat, sids = corpus_index(text, min_count)
            ref_words = [vw.word for vw in ref.vocab.words()]
            ref_counts = [vw.count for vw in ref.vocab.words()]
            assert words == ref_words, (min_count, words, ref_words)
            assert counts.tolist() == ref_counts
            np.testing.assert_array_equal(flat, ref._flat)
            np.testing.assert_array_equal(sids, ref._sid)

    def test_word2vec_uses_native_path_equivalently(self):
        import pytest as _pytest

        from deeplearning4j_tpu.models.word2vec import Word2Vec
        from deeplearning4j_tpu.native.lib import native_available
        from deeplearning4j_tpu.text.sentence_iterator import (
            CollectionSentenceIterator,
        )

        if not native_available():
            _pytest.skip("native library unavailable")
        ref = self._python_reference(self.CORPUS, 1)
        nat = Word2Vec(
            sentence_iterator=CollectionSentenceIterator(self.CORPUS),
            layer_size=8, min_word_frequency=1, seed=1,
        )
        nat.build_vocab()
        assert [w.word for w in nat.vocab.words()] == [
            w.word for w in ref.vocab.words()]
        np.testing.assert_array_equal(nat._flat, ref._flat)
        np.testing.assert_array_equal(nat._sid, ref._sid)
        # huffman codes identical too (same counts -> same tree)
        for a, b in zip(nat.vocab.words(), ref.vocab.words()):
            assert a.code == b.code and a.points == b.points

    def test_non_ascii_falls_back(self):
        from deeplearning4j_tpu.models.word2vec import Word2Vec
        from deeplearning4j_tpu.text.sentence_iterator import (
            CollectionSentenceIterator,
        )

        sents = ["café au lait", "café noir s'il vous plaît"]
        w = Word2Vec(sentence_iterator=CollectionSentenceIterator(sents),
                     layer_size=8, seed=1)
        assert w._native_vocab_index() is None  # unicode -> python path
        w.build_vocab()  # iterator re-iterates fine after the probe
        assert w.vocab.contains("café")

    def test_preprocessor_falls_back(self):
        from deeplearning4j_tpu.models.word2vec import Word2Vec
        from deeplearning4j_tpu.text.sentence_iterator import (
            CollectionSentenceIterator,
        )
        from deeplearning4j_tpu.text.tokenization import (
            CommonPreprocessor,
            DefaultTokenizerFactory,
        )

        w = Word2Vec(
            sentence_iterator=CollectionSentenceIterator(["The DOG. barks!"]),
            tokenizer_factory=DefaultTokenizerFactory(CommonPreprocessor()),
            layer_size=8, seed=1,
        )
        assert w._native_vocab_index() is None
        w.build_vocab()
        assert w.vocab.contains("dog")  # lowercased + punctuation stripped
