"""ISSUE 7: span-based distributed tracing + crash flight recorder.

Unit-level pins for telemetry/trace.py (span model, JSONL begin/end
records, context propagation, flight-recorder dumps and their rate
limit), the tracker-frame propagation in remote_tracker.py, and the
tools/trace_report.py reconstruction — including the partial-round case
a kill -9 leaves behind (begin records with no end).
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from deeplearning4j_tpu.telemetry import trace as tr
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.trace_report import (  # noqa: E402
    build_timeline,
    chrome_trace,
    load_trace_dir,
)


@pytest.fixture
def no_global_tracer():
    """Isolate the process-global tracer; restore whatever was there."""
    prev = tr.set_tracer(None)
    yield
    tr.set_tracer(prev)


def _read_records(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


class TestSpans:
    def test_nesting_parents_and_jsonl_records(self, tmp_path,
                                               no_global_tracer):
        t = tr.Tracer("p0", trace_dir=str(tmp_path),
                      registry=MetricsRegistry())
        with t.span("outer", attrs={"k": 1}) as outer:
            assert t.current_span() is outer
            with t.span("inner") as inner:
                assert inner.parent_id == outer.span_id
                assert inner.trace_id == outer.trace_id
            assert t.current_span() is outer
        assert t.current_span() is None
        recs = _read_records(tmp_path / "spans_p0.jsonl")
        # begin records written eagerly (crash durability), ends after
        assert [r["ev"] for r in recs] == ["B", "B", "E", "E"]
        assert recs[0]["name"] == "outer" and recs[1]["name"] == "inner"
        assert recs[2]["name"] == "inner" and recs[2]["status"] == "ok"
        assert recs[2]["dur_ms"] >= 0

    def test_error_status_and_events(self, tmp_path, no_global_tracer):
        t = tr.Tracer("p0", trace_dir=str(tmp_path),
                      registry=MetricsRegistry())
        with pytest.raises(ValueError):
            with t.span("boom") as sp:
                sp.add_event("about_to_fail", detail="x")
                raise ValueError("synthetic")
        end = [r for r in _read_records(tmp_path / "spans_p0.jsonl")
               if r["ev"] == "E"][0]
        assert end["status"] == "error"
        assert "synthetic" in end["error"]
        assert end["events"][0]["name"] == "about_to_fail"
        assert t.registry.counter("trace_spans_error_total").value == 1

    def test_wire_context_parents_across_tracers(self, tmp_path,
                                                 no_global_tracer):
        """Two tracers = two processes: a context dict shipped over any
        transport parents the remote span under the local one."""
        master = tr.Tracer("master", trace_dir=str(tmp_path),
                           registry=MetricsRegistry())
        worker = tr.Tracer("worker", trace_dir=str(tmp_path),
                           registry=MetricsRegistry())
        root = master.start_span("round", attrs={"round": 0})
        ctx = root.context()  # JSON-safe wire dict
        ctx = json.loads(json.dumps(ctx))
        child = worker.start_span("work", parent=ctx)
        child.end()
        root.end()
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id

    def test_thread_local_current_span(self, tmp_path, no_global_tracer):
        t = tr.Tracer("p0", trace_dir=str(tmp_path),
                      registry=MetricsRegistry())
        seen = {}

        def other_thread():
            seen["current"] = t.current_span()

        with t.span("main-thread"):
            th = threading.Thread(target=other_thread)
            th.start()
            th.join()
        # another thread never silently parents under this thread's span
        assert seen["current"] is None

    def test_maybe_span_is_noop_without_tracer(self, no_global_tracer):
        assert tr.get_tracer() is None
        with tr.maybe_span("anything", attrs={"x": 1}) as sp:
            assert sp is None
        assert tr.current_trace_context() is None


class TestFlightRecorder:
    def test_dump_contents(self, tmp_path, no_global_tracer):
        reg = MetricsRegistry()
        reg.counter("workers_failed").inc(2)
        t = tr.Tracer("w0", trace_dir=str(tmp_path), registry=reg)
        with t.span("done-span"):
            pass
        open_span = t.start_span("stuck-span", attrs={"round": 3})
        path = t.dump("SIGTERM", error=RuntimeError("killed"),
                      extra={"note": "test"})
        assert path == str(tmp_path / "flightrec_w0.json")
        dump = json.load(open(path))
        assert dump["reason"] == "SIGTERM"
        assert "killed" in dump["error"]
        assert dump["extra"]["note"] == "test"
        assert [s["name"] for s in dump["open"]] == ["stuck-span"]
        assert dump["open"][0]["open"] is True
        assert dump["open"][0]["dur_ms"] >= 0
        assert any(r["name"] == "done-span" for r in dump["recent"])
        counters = {c["name"]: c["value"]
                    for c in dump["counters"]["counters"]}
        assert counters["workers_failed"] == 2
        assert "device_memory" in dump
        open_span.end()

    def test_checkpoint_rate_limit(self, tmp_path, no_global_tracer):
        t = tr.Tracer("w0", trace_dir=str(tmp_path),
                      registry=MetricsRegistry(),
                      min_checkpoint_interval_s=60.0)
        assert t.flight_checkpoint() is not None  # first always lands
        assert t.flight_checkpoint() is None      # inside the interval
        assert t.dump("crash") is not None        # explicit never limited

    def test_dump_never_raises(self, tmp_path, no_global_tracer):
        t = tr.Tracer("w0", trace_dir=str(tmp_path),
                      flight_path="/nonexistent-dir/cannot/write.json",
                      registry=MetricsRegistry())
        assert t.dump("crash") is None  # swallowed, not raised


class TestTrackerPropagation:
    def test_rpc_span_links_client_and_server(self, tmp_path,
                                              no_global_tracer, lockwatch):
        # armed lockwatch (ISSUE 11): tracer ring lock + tracker client
        # request lock + server state lock are all watched across the RPC
        from deeplearning4j_tpu.scaleout.remote_tracker import (
            StateTrackerClient,
            StateTrackerServer,
        )

        tracer = tr.Tracer("node", trace_dir=str(tmp_path),
                           registry=MetricsRegistry())
        tr.set_tracer(tracer)
        with StateTrackerServer() as server:
            client = StateTrackerClient(server.address,
                                        registry=MetricsRegistry())
            # outside any span: the 3-tuple untraced wire path
            client.add_worker("w-untraced")
            with tracer.span("op") as op:
                client.add_worker("w-traced")
                client.count("poll.key")  # poll method: never spanned
            client.close()
            time.sleep(0.1)  # server handler writes its span async
        spans = load_trace_dir(str(tmp_path))
        by_name = {}
        for sp in spans.values():
            by_name.setdefault(sp["name"], []).append(sp)
        assert len(by_name["tracker.rpc"]) == 1  # only the traced call
        rpc = by_name["tracker.rpc"][0]
        assert rpc["attrs"]["method"] == "add_worker"
        assert rpc["parent_id"] == op.span_id
        serve = by_name["tracker.serve"][0]
        assert serve["parent_id"] == rpc["span_id"]
        assert serve["trace_id"] == rpc["trace_id"] == op.trace_id
        watch = lockwatch.summary()
        assert watch["cycles"] == 0
        for name in ("telemetry.trace", "tracker.client", "tracker.state"):
            assert watch["locks"].get(name, {}).get("acquires", 0) > 0, \
                f"{name} lock was not watched across the RPC"

    def test_retry_recorded_as_event(self, tmp_path, no_global_tracer):
        import _dist_helpers
        from deeplearning4j_tpu.scaleout.remote_tracker import (
            StateTrackerClient,
            StateTrackerServer,
        )

        tracer = tr.Tracer("node", trace_dir=str(tmp_path),
                           registry=MetricsRegistry())
        tr.set_tracer(tracer)
        with StateTrackerServer() as server:
            with _dist_helpers.FaultyTrackerProxy(
                    server.address, cut_response_after=0) as proxy:
                client = StateTrackerClient(proxy.address,
                                            request_timeout_s=5, retries=3,
                                            backoff_s=0.01,
                                            registry=MetricsRegistry())
                with tracer.span("op"):
                    assert client.workers() == []  # cut → reconnect+retry
                client.close()
        spans = load_trace_dir(str(tmp_path))
        rpc = [s for s in spans.values() if s["name"] == "tracker.rpc"][0]
        names = [e["name"] for e in rpc["events"]]
        assert "retry" in names and "reconnect" in names


class TestTraceReport:
    def _fake_elastic_trace(self, d, kill_worker_mid_round=None):
        """Synthesize a master + two-worker trace the way elastic.py
        writes it; optionally leave w1's round-N spans unclosed (the
        kill -9 shape)."""
        reg = MetricsRegistry()
        master = tr.Tracer("master", trace_dir=str(d), registry=reg)
        workers = {w: tr.Tracer(w, trace_dir=str(d),
                                registry=MetricsRegistry())
                   for w in ("w0", "w1")}
        run = master.start_span("elastic.train", parent=False)
        for rnd in range(3):
            round_sp = master.start_span("elastic.round", parent=run,
                                         attrs={"round": rnd})
            barrier = master.start_span("elastic.barrier", parent=round_sp,
                                        attrs={"round": rnd})
            for i, (w, wt) in enumerate(sorted(workers.items())):
                killed = (kill_worker_mid_round is not None
                          and w == "w1" and rnd == kill_worker_mid_round)
                wr = wt.start_span("worker.round",
                                   parent=round_sp.context(),
                                   attrs={"round": rnd, "worker": w})
                steps = wt.start_span("worker.steps", parent=wr,
                                      attrs={"round": rnd})
                steps.end()
                if killed:
                    continue  # kill -9: round/publish spans never close
                pub = wt.start_span("worker.publish", parent=wr,
                                    attrs={"round": rnd, "worker": w})
                time.sleep(0.002 * (i + 1))  # staggered arrivals
                pub.end()
                barrier.add_event("contribution", worker=w)
                wr.end()
            if kill_worker_mid_round is not None \
                    and rnd >= kill_worker_mid_round:
                barrier.add_event("buried", worker="w1")
            barrier.end()
            if kill_worker_mid_round is not None \
                    and rnd == kill_worker_mid_round:
                # master still commits on the survivor set
                pass
            round_sp.end()
        run.end()
        return d

    def test_merged_timeline_and_attribution(self, tmp_path,
                                             no_global_tracer):
        self._fake_elastic_trace(tmp_path)
        spans = load_trace_dir(str(tmp_path))
        timeline = build_timeline(spans)
        assert timeline["processes"] == ["master", "w0", "w1"]
        rounds = timeline["rounds"]
        assert [r["round"] for r in rounds] == [0, 1, 2]
        for r in rounds:
            assert r["status"] == "committed"
            # w1's publish is staggered later → it is the straggler
            assert r["straggler"] == "w1"
            assert r["straggler_wait_ms"] > 0
            waited = {a["worker"]: a["waited_ms"] for a in r["contributors"]}
            assert waited["w1"] == 0.0 and waited["w0"] > 0

    def test_partial_round_from_kill(self, tmp_path, no_global_tracer):
        self._fake_elastic_trace(tmp_path, kill_worker_mid_round=1)
        spans = load_trace_dir(str(tmp_path))
        timeline = build_timeline(spans)
        r1 = [r for r in timeline["rounds"] if r["round"] == 1][0]
        # the survivor's contribution still committed the round, but the
        # victim's unclosed spans are visible on it
        assert "w1:worker.round" in r1["open_spans"]
        assert [a["worker"] for a in r1["contributors"]] == ["w0"]
        assert timeline["n_open"] >= 1

    def test_chrome_export_schema(self, tmp_path, no_global_tracer):
        self._fake_elastic_trace(tmp_path, kill_worker_mid_round=2)
        spans = load_trace_dir(str(tmp_path))
        out = chrome_trace(spans)
        events = out["traceEvents"]
        meta = [e for e in events if e["ph"] == "M"]
        assert {m["args"]["name"] for m in meta} == {"master", "w0", "w1"}
        xs = [e for e in events if e["ph"] == "X"]
        assert len(xs) == len(spans)
        for e in xs:
            assert e["dur"] >= 0 and e["ts"] > 0
            assert isinstance(e["pid"], int)
        # the victim's unclosed span is flagged open in its args
        assert any(e["args"].get("open") for e in xs)
        json.dumps(out)  # valid JSON end to end

    def test_cli(self, tmp_path, no_global_tracer):
        self._fake_elastic_trace(tmp_path)
        chrome_path = str(tmp_path / "chrome.json")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             str(tmp_path), "--chrome", chrome_path],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "committed" in out.stdout
        assert "waited on" in out.stdout
        assert os.path.exists(chrome_path)
        out2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        tl = json.loads(out2.stdout)
        assert len(tl["rounds"]) == 3

    def test_cli_missing_dir_exits_2(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
             str(tmp_path / "nope")],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 2
        assert "no such trace dir" in out.stderr

    def test_torn_tail_line_tolerated(self, tmp_path, no_global_tracer):
        t = tr.Tracer("p0", trace_dir=str(tmp_path),
                      registry=MetricsRegistry())
        with t.span("complete"):
            pass
        with open(tmp_path / "spans_p0.jsonl", "a") as fh:
            fh.write('{"ev": "B", "span_id": "torn')  # killed mid-write
        spans = load_trace_dir(str(tmp_path))
        assert len(spans) == 1  # the complete span survives, tail skipped


class TestBenchReport:
    def _write_round(self, d, n, value, detail=None, parsed=True, tail=""):
        rec = {"n": n, "cmd": "bench", "rc": 0, "tail": tail,
               "parsed": ({"metric": "m", "value": value, "unit": "x",
                           "detail": detail or {}} if parsed else None)}
        (d / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))

    def test_trajectory_and_regression_flag(self, tmp_path):
        self._write_round(tmp_path, 1, 100.0,
                          {"mlp_bf16_samples_per_sec": 1000.0})
        self._write_round(tmp_path, 2, 110.0,
                          {"mlp_bf16_samples_per_sec": 800.0,
                           "moe_tokens_per_sec": 50.0})
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "--dir", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        traj = json.loads(out.stdout)
        regs = {r["metric"] for r in traj["regressions"]}
        assert regs == {"mlp_bf16_samples_per_sec"}  # -20% flagged
        row = [r for r in traj["table"]
               if r["metric"] == "mlp_bf16_samples_per_sec"][0]
        assert row["delta_pct"] == -20.0 and row["regression"]
        # fail-on-regression turns the flag into exit 1
        out2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "--dir", str(tmp_path), "--fail-on-regression"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out2.returncode == 1
        assert "REGRESSION" in out2.stdout

    def test_unparsed_round_recovered_from_tail(self, tmp_path):
        self._write_round(tmp_path, 1, 100.0,
                          {"word2vec_words_per_sec": 500.0})
        self._write_round(
            tmp_path, 2, None, parsed=False,
            tail='...clipped... "word2vec_words_per_sec": 600.0, '
                 '"word2vec_host_device_split": {"host_pairgen_s": 0.0}}')
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "--dir", str(tmp_path), "--json"],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        traj = json.loads(out.stdout)
        assert traj["rounds"][1]["source"] == "partial"
        row = [r for r in traj["table"]
               if r["metric"] == "word2vec_words_per_sec"][0]
        assert dict((n, v) for n, v in row["series"])[2] == 600.0
        assert row["delta_pct"] == 20.0

    def test_runs_on_real_repo_artifacts(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py")],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "bench trajectory" in out.stdout

    def test_empty_dir_exits_2(self, tmp_path):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "bench_report.py"),
             "--dir", str(tmp_path)],
            capture_output=True, text=True, timeout=60, cwd=REPO)
        assert out.returncode == 2


# ---------------------------------------- W3C traceparent (ISSUE 12) ----

class TestTraceparent:
    """The HTTP-propagation half of the serve tracing: header format,
    parse tolerance (a malformed header is IGNORED per the W3C spec —
    the request must proceed as a fresh root), and that a caller-minted
    32-hex trace id flows through the span model unchanged."""

    def test_format_pads_internal_ids_to_w3c_width(self):
        hdr = tr.format_traceparent({"trace_id": "ab" * 8,
                                     "span_id": "cd" * 4})
        version, trace_id, span_id, flags = hdr.split("-")
        assert version == "00" and flags == "01"
        assert len(trace_id) == 32 and trace_id.endswith("ab" * 8)
        assert len(span_id) == 16 and span_id.endswith("cd" * 4)

    def test_parse_format_round_trip(self):
        ctx = {"trace_id": "a" * 32, "span_id": "b" * 16}
        assert tr.parse_traceparent(tr.format_traceparent(ctx)) == ctx

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-ffffffffffffffff-01",
        "00-" + "g" * 32 + "-" + "f" * 16 + "-01",   # non-hex
        "00-" + "0" * 32 + "-" + "f" * 16 + "-01",   # all-zero trace id
        "00-" + "f" * 32 + "-" + "0" * 16 + "-01",   # all-zero span id
        "ff-" + "f" * 32 + "-" + "f" * 16 + "-01",   # forbidden version
        "0-" + "f" * 32 + "-" + "f" * 16 + "-01",    # short version
        "00-" + "f" * 32 + "-" + "f" * 16,           # missing flags
    ])
    def test_malformed_headers_parse_to_none(self, bad):
        assert tr.parse_traceparent(bad) is None

    def test_future_version_with_extra_fields_accepted(self):
        # the spec: parse version 01+ headers by the 00 rules, ignoring
        # trailing fields
        hdr = "01-" + "a" * 32 + "-" + "b" * 16 + "-01-extra"
        assert tr.parse_traceparent(hdr) == {"trace_id": "a" * 32,
                                             "span_id": "b" * 16}

    def test_remote_trace_id_flows_through_spans(self, tmp_path,
                                                 no_global_tracer):
        tracer = tr.Tracer("srv", trace_dir=str(tmp_path))
        ctx = tr.parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-01")
        with tracer.span("http.request", parent=ctx) as sp:
            assert sp.trace_id == "a" * 32
            assert sp.parent_id == "b" * 16
            # the response header regenerates losslessly at full width
            assert tr.format_traceparent(sp.context()) == \
                f"00-{'a' * 32}-{sp.span_id}-01"
        tracer.close()
        recs = _read_records(str(tmp_path / "spans_srv.jsonl"))
        assert recs[0]["trace_id"] == "a" * 32
