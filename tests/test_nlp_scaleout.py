"""Distributed NLP training through the scaleout runner (ref test model:
DistributedWord2VecTest / DistributedGloveTest over the in-JVM Akka harness,
SURVEY.md §4)."""

import numpy as np

from deeplearning4j_tpu.models.word2vec import Word2Vec
from deeplearning4j_tpu.scaleout.nlp_perform import (
    NUM_PAIRS_SO_FAR,
    CoOccurrenceJobIterator,
    GloveWorkPerformer,
    SkipGramJobIterator,
    Word2VecWorkPerformer,
)
from deeplearning4j_tpu.scaleout.runner import LocalDistributedRunner
from deeplearning4j_tpu.scaleout.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.text.sentence_iterator import CollectionSentenceIterator


def _toy_corpus():
    fruit = "apple banana cherry fruit sweet juice"
    tech = "cpu gpu chip silicon compute memory"
    sents = []
    rng = np.random.default_rng(0)
    for _ in range(200):
        sents.append(" ".join(rng.permutation(fruit.split()).tolist()))
        sents.append(" ".join(rng.permutation(tech.split()).tolist()))
    return sents


def _cosine(a, b):
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


class TestDistributedWord2Vec:
    def test_runner_trains_embeddings(self):
        # build vocab + pair stream with the model's own pipeline
        w2v = Word2Vec(
            sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
            layer_size=16, window=3, negative=5, sample=0, seed=1,
        )
        w2v.build_vocab()
        rng = np.random.default_rng(1)
        all_c, all_t = [], []
        for _ in range(10):  # epochs of pairs, from the cached corpus index
            flat, sid = w2v._subsampled_flat(rng)
            c, t = w2v._pairs_from_flat(flat, sid, rng)
            perm = rng.permutation(c.shape[0])
            all_c.append(c[perm])
            all_t.append(t[perm])
        centers = np.concatenate(all_c)
        contexts = np.concatenate(all_t)

        tracker = InMemoryStateTracker()
        vocab = w2v.vocab
        runner = LocalDistributedRunner(
            performer_factory=lambda: Word2VecWorkPerformer(
                vocab, layer_size=16, negative=5, lr=0.1,
                total_pairs=len(centers), tracker=tracker, seed=1,
            ),
            job_iterator=SkipGramJobIterator(centers, contexts, 2048),
            num_workers=4,
            tracker=tracker,
        )
        flat = runner.train()
        assert flat is not None
        v, d = vocab.num_words(), 16
        syn0 = flat[: v * d].reshape(v, d)

        def vec(w):
            return syn0[vocab.index_of(w)]

        same = _cosine(vec("apple"), vec("banana"))
        cross = _cosine(vec("apple"), vec("gpu"))
        assert same > cross, (same, cross)
        # the shared lr-decay counter advanced across workers
        assert tracker.count(NUM_PAIRS_SO_FAR) == len(centers)


class TestDistributedGlove:
    def test_runner_trains_glove(self):
        from deeplearning4j_tpu.models.glove import Glove

        g = Glove(
            sentence_iterator=CollectionSentenceIterator(_toy_corpus()),
            layer_size=16, window=5, iterations=1, seed=1,
        )
        g.build_vocab_and_cooccurrences()
        rows, cols, vals = g.co.to_arrays()
        # several epochs of co-occurrence batches, shuffled
        rng = np.random.default_rng(2)
        order = np.concatenate(
            [rng.permutation(len(rows)) for _ in range(30)])

        runner = LocalDistributedRunner(
            performer_factory=lambda: GloveWorkPerformer(
                g.vocab.num_words(), layer_size=16, lr=0.05, seed=1),
            job_iterator=CoOccurrenceJobIterator(
                rows[order], cols[order], vals[order], batch_size=4096),
            num_workers=4,
        )
        flat = runner.train()
        assert flat is not None
        v, d = g.vocab.num_words(), 16
        w = flat[: v * d].reshape(v, d)

        def vec(word):
            return w[g.vocab.index_of(word)]

        same = _cosine(vec("apple"), vec("banana"))
        cross = _cosine(vec("apple"), vec("gpu"))
        assert same > cross, (same, cross)
