"""Cross-process control plane: master + REAL worker OS processes joined
only through the remote StateTracker (round-4 verdict missing #1 — the
in-memory tracker confined the whole master/worker protocol to one
process; ref: BaseHazelCastStateTracker.java:78-100 embedded-or-client)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deeplearning4j_tpu.scaleout.aggregator import ParameterAveragingAggregator
from deeplearning4j_tpu.scaleout.distributed_runner import DistributedMaster
from deeplearning4j_tpu.scaleout.job import (
    CollectionJobIterator,
    DataSetJobIterator,
    Job,
)
from deeplearning4j_tpu.scaleout.remote_tracker import (
    StateTrackerClient,
    StateTrackerServer,
)
from deeplearning4j_tpu.scaleout.workrouter import (
    HogWildWorkRouter,
    IterativeReduceWorkRouter,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")


def _spawn_worker(address, performer, kwargs=None, worker_id=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{REPO}{os.pathsep}{TESTS}{os.pathsep}" + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m",
           "deeplearning4j_tpu.scaleout.distributed_runner",
           "--connect", address, "--performer", performer]
    if kwargs:
        cmd += ["--kwargs-json", json.dumps(kwargs)]
    if worker_id:
        cmd += ["--worker-id", worker_id]
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _finish(procs, master, timeout=60):
    outs = []
    try:
        for p in procs:
            try:
                outs.append(p.communicate(timeout=timeout))
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate())
    finally:
        master.shutdown()
    return outs


# ---------------------------------------------------------------- tracker ----

def test_remote_tracker_contract_roundtrip():
    with StateTrackerServer() as server:
        client = StateTrackerClient(server.address)
        client.add_worker("w0")
        assert server.tracker.workers() == ["w0"]  # embedded side sees it
        job = Job(np.arange(3), "w0")
        job.result = np.ones(3)
        client.add_job(job)
        got = client.job_for("w0")
        np.testing.assert_array_equal(got.result, np.ones(3))
        client.increment("n", 2.5)
        assert client.count("n") == 2.5
        client.set_current(np.full(4, 7.0))
        np.testing.assert_array_equal(client.get_current(), np.full(4, 7.0))
        client.add_replicate("w0")
        assert client.needs_replicate("w0")
        client.done_replicating("w0")
        assert not client.needs_replicate("w0")
        client.set_best_loss(0.5)
        assert client.best_loss() == 0.5
        assert not client.is_early_stop()
        client.early_stop()
        assert client.is_early_stop()
        client.close()


def test_remote_clear_updates_never_drops_newer_snapshot():
    """The versioned cross-process replacement for the in-memory tracker's
    identity check: clearing an old snapshot must keep an update published
    after the snapshot was taken."""
    with StateTrackerServer() as server:
        client = StateTrackerClient(server.address)
        j1 = Job("a", "w0")
        j1.result = np.asarray([1.0])
        client.add_update("w0", j1)
        snap = client.updates()
        # a NEWER update lands between snapshot and clear
        j2 = Job("b", "w0")
        j2.result = np.asarray([2.0])
        client.add_update("w0", j2)
        client.clear_updates(snap)
        survivors = client.updates()
        assert "w0" in survivors, "newer unseen update was dropped"
        assert float(survivors["w0"].result[0]) == 2.0
        # clearing the fresh snapshot now empties the slot
        client.clear_updates(survivors)
        assert client.updates() == {}
        client.close()


def test_dead_client_cannot_pin_handler_thread():
    """ISSUE 18 satellite: a client that connects and goes silent must
    not hold its handler thread forever — the handler socket's explicit
    timeout bounds the blocking recv (the PR 10 lingering-handler
    class)."""
    import socket
    import threading

    server = StateTrackerServer(handler_timeout_s=0.3)
    try:
        baseline = threading.active_count()
        raw = socket.create_connection((server.host, server.port),
                                       timeout=5)
        raw.sendall(b"\x00")  # partial frame header, then silence
        deadline = time.time() + 5
        grew = False
        while time.time() < deadline:
            if threading.active_count() > baseline:
                grew = True
                break
            time.sleep(0.01)
        assert grew, "handler thread never started"
        # the dead client's handler must exit at its timeout, not linger
        deadline = time.time() + 10
        while threading.active_count() > baseline and time.time() < deadline:
            time.sleep(0.02)
        assert threading.active_count() <= baseline, (
            "dead client pinned its handler thread: "
            f"{[t.name for t in threading.enumerate()]}")
        raw.close()
        # the server still serves fresh clients afterwards
        client = StateTrackerClient(server.address)
        client.add_worker("alive")
        assert client.workers() == ["alive"]
        client.close()
    finally:
        server.shutdown()


def test_unclassified_rpc_method_is_rejected():
    """The idempotency contract is load-bearing at runtime too: a method
    in neither _IDEMPOTENT nor _NONIDEMPOTENT has no retry policy and
    must be rejected, not silently given one."""
    with StateTrackerServer() as server:
        client = StateTrackerClient(server.address)
        try:
            with pytest.raises(ValueError, match="idempotency"):
                client._call("definitely_not_classified")
        finally:
            client.close()


# ----------------------------------------------------- two-process runner ----

@pytest.mark.parametrize("router_cls", [IterativeReduceWorkRouter,
                                        HogWildWorkRouter])
def test_two_process_training_converges(router_cls):
    """Iris training across two real worker PROCESSES under BOTH routers:
    the master aggregates parameter averages published over TCP and the
    final model classifies Iris (the reference's TestDistributed posture,
    but with actual process isolation)."""
    from deeplearning4j_tpu.datasets.impl import IrisDataSetIterator
    from deeplearning4j_tpu.eval.evaluation import Evaluation
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf_json = (
        NeuralNetConfiguration.Builder()
        .n_in(4).n_out(8).activation_function("tanh")
        .lr(0.1).momentum(0.9).num_iterations(25).seed(42)
        .list(2)
        .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                  activation_function="softmax", loss_function="MCXENT")
        .pretrain(False).backward(True)
        .build()
        .to_json()
    )
    master = DistributedMaster(
        job_iterator=DataSetJobIterator(IrisDataSetIterator(30, 150)),
        min_workers=2, max_rounds=6, register_timeout_s=120,
    )
    master.router = router_cls(master.tracker, ParameterAveragingAggregator())
    procs = [
        _spawn_worker(master.address, "_dist_helpers:iris_performer",
                      {"conf_json": conf_json}, worker_id=f"w{i}")
        for i in range(2)
    ]
    try:
        params = master.train()
    finally:
        outs = _finish(procs, master)
    assert params is not None, [o[1][-500:] for o in outs]
    assert master.tracker.count("aggregations") >= 1
    assert master.tracker.count("jobs_done") >= 5
    # both processes actually performed work
    for i in range(2):
        assert master.tracker.count(f"rounds.w{i}") >= 1, (i, outs)

    net = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    net.init()
    net.set_params(params)
    it = IrisDataSetIterator(150, 150)
    ds = it.next()
    ev = Evaluation()
    ev.eval(ds.get_labels(), net.output(ds.get_feature_matrix()))
    assert ev.accuracy() > 0.6, ev.accuracy()


def test_early_stopping_trips_across_processes():
    """The SAME EarlyStopping policy the in-process runner uses stops a
    cross-process run: workers publish non-improving scores over TCP, the
    master's patience trips tracker.early_stop(), the run ends before the
    job iterator drains, and the workers' poll loops see the flag and
    exit cleanly (ref: StateTracker earlyStop/bestLoss flags,
    BaseHazelCastStateTracker)."""
    from deeplearning4j_tpu.scaleout.runner import EarlyStopping

    items = [7.0] * 400  # constant |work| -> constant scores, no improvement
    master = DistributedMaster(
        job_iterator=CollectionJobIterator(items),
        min_workers=2, max_rounds=200, register_timeout_s=120,
        early_stopping=EarlyStopping(patience=2),
    )
    master.router = HogWildWorkRouter(master.tracker,
                                      ParameterAveragingAggregator())
    procs = [
        _spawn_worker(master.address, "_dist_helpers:averaging_performer",
                      worker_id=f"w{i}")
        for i in range(2)
    ]
    try:
        params = master.train()
    finally:
        outs = _finish(procs, master)
    assert master.tracker.is_early_stop(), outs
    done = master.tracker.count("jobs_done")
    assert done < len(items), f"early stop never tripped ({done} jobs ran)"
    assert params is not None
    # workers exited on the flag, not by being killed
    for p in procs:
        assert p.returncode == 0, (p.returncode, outs)


def test_worker_process_crash_is_recovered():
    """One worker hard-crashes (os._exit mid-perform, no cleanup): the
    master's heartbeat watchdog requeues its job onto the survivor and the
    run completes every job."""
    master = DistributedMaster(
        job_iterator=CollectionJobIterator([1, 2, 3, 4, 5, 6]),
        min_workers=2, max_rounds=6, worker_timeout_s=3.0,
        register_timeout_s=120,
    )
    master.router = HogWildWorkRouter(master.tracker,
                                      ParameterAveragingAggregator())
    procs = [
        _spawn_worker(master.address, "_dist_helpers:crashing_performer",
                      worker_id="crasher"),
        _spawn_worker(master.address, "_dist_helpers:averaging_performer",
                      worker_id="survivor"),
    ]
    try:
        t0 = time.monotonic()
        params = master.train()
        wall = time.monotonic() - t0
    finally:
        outs = _finish(procs, master)
    assert params is not None
    assert master.tracker.count("workers_failed") == 1
    # crasher performed exactly 1 job and published none; all 6 items
    # completed, so the survivor did all of them (incl. the requeue)
    assert master.tracker.count("jobs_done") >= 6, (
        master.tracker.count("jobs_done"), wall, outs)
    assert master.tracker.count("rounds.survivor") >= 6
    assert procs[0].returncode == 17  # the os._exit marker
    assert "crasher" not in master.tracker.workers()
