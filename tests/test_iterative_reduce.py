"""IterativeReduce superstep tests (ref: IRUnitIrisDBNWorkerTests — master +
N workers in one process over row splits)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.scaleout.iterative_reduce import (
    ComputableMaster,
    ComputableWorker,
    IterativeReduceRunner,
    ParameterAveragingMaster,
    run_iterative_reduce,
)


class _CountingWorker(ComputableWorker):
    def __init__(self, value, steps):
        self.value = value
        self.steps = steps
        self.received = []

    def compute(self):
        if self.steps <= 0:
            return None
        self.steps -= 1
        return np.array([self.value], dtype=np.float64)

    def update(self, master_update):
        self.received.append(float(master_update[0]))


class TestRunner:
    def test_superstep_loop_and_barrier(self):
        workers = [_CountingWorker(v, steps=2) for v in (1.0, 3.0)]
        runner = IterativeReduceRunner(ParameterAveragingMaster(), workers)
        final = runner.run()
        assert runner.supersteps_run == 2
        assert final[0] == pytest.approx(2.0)
        # every worker received the averaged update each superstep
        assert workers[0].received == [2.0, 2.0]

    def test_stops_when_all_workers_done(self):
        workers = [_CountingWorker(1.0, steps=1), _CountingWorker(2.0, steps=3)]
        runner = IterativeReduceRunner(ParameterAveragingMaster(), workers,
                                       max_supersteps=10)
        runner.run()
        # continues while ANY worker still produces (ref: partial updates
        # still averaged); stops when all return None
        assert runner.supersteps_run == 3

    def test_worker_error_aborts(self):
        class Bad(ComputableWorker):
            def compute(self):
                raise RuntimeError("container failed")

            def update(self, mu):
                pass

        runner = IterativeReduceRunner(ParameterAveragingMaster(), [Bad()])
        with pytest.raises(RuntimeError, match="container failed"):
            runner.run()

    def test_requires_workers(self):
        with pytest.raises(ValueError):
            IterativeReduceRunner(ParameterAveragingMaster(), [])

    def test_master_complete_called(self):
        calls = []

        class M(ComputableMaster):
            def compute(self, ups, mu):
                return ups[0]

            def complete(self):
                calls.append(True)

        IterativeReduceRunner(M(), [_CountingWorker(1.0, 1)]).run()
        assert calls == [True]


class TestIrisIterativeReduce:
    def test_converges_on_iris(self):
        """ref IRUnitIrisDBNWorkerTests: split Iris over 3 workers, supersteps
        of local fit + averaging reach good accuracy."""
        from deeplearning4j_tpu.datasets.fetchers import iris_data

        x, y = iris_data()
        rng = np.random.RandomState(0)
        perm = rng.permutation(len(x))
        x, y = x[perm].astype(np.float32), y[perm]
        onehot = np.eye(3, dtype=np.float32)[y]
        conf = (NeuralNetConfiguration.Builder()
                .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
                .momentum(0.9).use_ada_grad(True).num_iterations(20).seed(42)
                .weight_init("VI").list(2)
                .override(0, layer_type="DENSE")
                .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                          activation_function="softmax", loss_function="MCXENT")
                .pretrain(False).backward(True).build())
        net, runner = run_iterative_reduce(conf, x, onehot,
                                           n_workers=3, supersteps=4)
        assert runner.supersteps_run == 4
        acc = (net.predict(x) == y).mean()
        assert acc > 0.9, acc
