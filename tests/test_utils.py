"""Utility tests (ref: MathUtilsTest, ViterbiTest, berkeley Counter usage)."""

import numpy as np
import pytest

from deeplearning4j_tpu.utils import (
    Counter,
    CounterMap,
    DiskBasedQueue,
    MovingWindowMatrix,
    Viterbi,
    clamp,
    entropy,
    information_gain,
    normalize_to_range,
    sum_of_squares,
)


class TestViterbi:
    def test_emission_only_argmax(self):
        em = np.log(np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]]))
        path, score = Viterbi(2).decode(em)
        assert path.tolist() == [0, 1, 0]
        assert score == pytest.approx(np.log(0.9) + np.log(0.8) + np.log(0.7))

    def test_transitions_enforce_smoothness(self):
        # sticky transitions flip the middle step despite its emission
        em = np.log(np.array([[0.9, 0.1], [0.45, 0.55], [0.9, 0.1]]))
        sticky = np.log(np.array([[0.95, 0.05], [0.05, 0.95]]))
        path, _ = Viterbi(2, transitions=sticky).decode(em)
        assert path.tolist() == [0, 0, 0]

    def test_shape_validation(self):
        with pytest.raises(ValueError):
            Viterbi(3).decode(np.zeros((4, 2)))
        with pytest.raises(ValueError):
            Viterbi(2, transitions=np.zeros((3, 3)))


class TestCounter:
    def test_basic_counts(self):
        c = Counter()
        for w in ["a", "b", "a", "c", "a"]:
            c.increment_count(w)
        assert c.get_count("a") == 3.0
        assert c.arg_max() == "a"
        assert c.total_count() == 5.0
        assert c.sorted_keys()[0] == "a"

    def test_normalize(self):
        c = Counter()
        c.increment_count("x", 3)
        c.increment_count("y", 1)
        c.normalize()
        assert c.get_count("x") == pytest.approx(0.75)
        assert c.total_count() == pytest.approx(1.0)

    def test_empty_argmax_raises(self):
        with pytest.raises(ValueError):
            Counter().arg_max()

    def test_counter_map(self):
        cm = CounterMap()
        cm.increment_count("the", "cat", 2)
        cm.increment_count("the", "dog", 1)
        cm.increment_count("a", "cat", 1)
        assert cm.get_count("the", "cat") == 2.0
        assert cm.get_count("nope", "cat") == 0.0
        assert cm.total_count() == 4.0
        assert cm.total_size() == 3
        assert cm.get_counter("the").arg_max() == "cat"


class TestMathUtils:
    def test_entropy(self):
        assert entropy([0.5, 0.5]) == pytest.approx(np.log(2))
        assert entropy([1.0, 0.0]) == 0.0

    def test_information_gain_perfect_split(self):
        gain = information_gain([5, 5], [[5, 0], [0, 5]])
        assert gain == pytest.approx(np.log(2))

    def test_normalize_to_range(self):
        out = normalize_to_range([0, 5, 10], 0, 1)
        assert out.tolist() == [0.0, 0.5, 1.0]
        assert normalize_to_range([3, 3]).tolist() == [0.0, 0.0]

    def test_clamp_and_sos(self):
        assert clamp(5, 0, 3) == 3
        assert clamp(-1, 0, 3) == 0
        assert sum_of_squares([3, 4]) == 25.0


class TestMovingWindowMatrix:
    def test_window_count_and_content(self):
        m = np.arange(16).reshape(4, 4)
        w = MovingWindowMatrix(m, 2, 2).windows()
        assert len(w) == 9
        np.testing.assert_array_equal(w[0], [[0, 1], [4, 5]])
        np.testing.assert_array_equal(w[-1], [[10, 11], [14, 15]])

    def test_rotations(self):
        m = np.arange(4).reshape(2, 2)
        w = MovingWindowMatrix(m, 2, 2, add_rotate=True).windows()
        assert len(w) == 4  # original + 3 rotations
        np.testing.assert_array_equal(w[1], np.rot90(m))

    def test_oversized_window_rejected(self):
        with pytest.raises(ValueError):
            MovingWindowMatrix(np.zeros((2, 2)), 3, 1)


class TestDiskBasedQueue:
    def test_fifo_round_trip(self, tmp_path):
        q = DiskBasedQueue(str(tmp_path / "spool"))
        q.add({"a": 1})
        q.add([1, 2, 3])
        assert len(q) == 2
        assert q.peek() == {"a": 1}
        assert q.poll() == {"a": 1}
        assert q.poll() == [1, 2, 3]
        assert q.poll() is None
        assert q.is_empty()

    def test_items_survive_on_disk(self, tmp_path):
        spool = str(tmp_path / "spool")
        q = DiskBasedQueue(spool)
        q.add(np.arange(5))
        import os
        assert len(os.listdir(spool)) == 1
        np.testing.assert_array_equal(q.poll(), np.arange(5))
        assert os.listdir(spool) == []

    def test_clear(self, tmp_path):
        q = DiskBasedQueue(str(tmp_path / "s"))
        for i in range(5):
            q.add(i)
        q.clear()
        assert q.is_empty()
