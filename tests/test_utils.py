"""Utility tests (ref: MathUtilsTest, ViterbiTest, berkeley Counter usage)."""

import numpy as np
import pytest

from deeplearning4j_tpu.utils import (
    Counter,
    CounterMap,
    DiskBasedQueue,
    MovingWindowMatrix,
    Viterbi,
    clamp,
    entropy,
    information_gain,
    normalize_to_range,
    sum_of_squares,
)


class TestViterbi:
    def test_emission_only_argmax(self):
        em = np.log(np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]]))
        path, score = Viterbi(2).decode(em)
        assert path.tolist() == [0, 1, 0]
        assert score == pytest.approx(np.log(0.9) + np.log(0.8) + np.log(0.7))

    def test_transitions_enforce_smoothness(self):
        # sticky transitions flip the middle step despite its emission
        em = np.log(np.array([[0.9, 0.1], [0.45, 0.55], [0.9, 0.1]]))
        sticky = np.log(np.array([[0.95, 0.05], [0.05, 0.95]]))
        path, _ = Viterbi(2, transitions=sticky).decode(em)
        assert path.tolist() == [0, 0, 0]

    def test_shape_validation(self):
        with pytest.raises(ValueError):
            Viterbi(3).decode(np.zeros((4, 2)))
        with pytest.raises(ValueError):
            Viterbi(2, transitions=np.zeros((3, 3)))


class TestCounter:
    def test_basic_counts(self):
        c = Counter()
        for w in ["a", "b", "a", "c", "a"]:
            c.increment_count(w)
        assert c.get_count("a") == 3.0
        assert c.arg_max() == "a"
        assert c.total_count() == 5.0
        assert c.sorted_keys()[0] == "a"

    def test_normalize(self):
        c = Counter()
        c.increment_count("x", 3)
        c.increment_count("y", 1)
        c.normalize()
        assert c.get_count("x") == pytest.approx(0.75)
        assert c.total_count() == pytest.approx(1.0)

    def test_empty_argmax_raises(self):
        with pytest.raises(ValueError):
            Counter().arg_max()

    def test_counter_map(self):
        cm = CounterMap()
        cm.increment_count("the", "cat", 2)
        cm.increment_count("the", "dog", 1)
        cm.increment_count("a", "cat", 1)
        assert cm.get_count("the", "cat") == 2.0
        assert cm.get_count("nope", "cat") == 0.0
        assert cm.total_count() == 4.0
        assert cm.total_size() == 3
        assert cm.get_counter("the").arg_max() == "cat"


class TestMathUtils:
    def test_entropy(self):
        assert entropy([0.5, 0.5]) == pytest.approx(np.log(2))
        assert entropy([1.0, 0.0]) == 0.0

    def test_information_gain_perfect_split(self):
        gain = information_gain([5, 5], [[5, 0], [0, 5]])
        assert gain == pytest.approx(np.log(2))

    def test_normalize_to_range(self):
        out = normalize_to_range([0, 5, 10], 0, 1)
        assert out.tolist() == [0.0, 0.5, 1.0]
        assert normalize_to_range([3, 3]).tolist() == [0.0, 0.0]

    def test_clamp_and_sos(self):
        assert clamp(5, 0, 3) == 3
        assert clamp(-1, 0, 3) == 0
        assert sum_of_squares([3, 4]) == 25.0


class TestMovingWindowMatrix:
    def test_window_count_and_content(self):
        m = np.arange(16).reshape(4, 4)
        w = MovingWindowMatrix(m, 2, 2).windows()
        assert len(w) == 9
        np.testing.assert_array_equal(w[0], [[0, 1], [4, 5]])
        np.testing.assert_array_equal(w[-1], [[10, 11], [14, 15]])

    def test_rotations(self):
        m = np.arange(4).reshape(2, 2)
        w = MovingWindowMatrix(m, 2, 2, add_rotate=True).windows()
        assert len(w) == 4  # original + 3 rotations
        np.testing.assert_array_equal(w[1], np.rot90(m))

    def test_oversized_window_rejected(self):
        with pytest.raises(ValueError):
            MovingWindowMatrix(np.zeros((2, 2)), 3, 1)


class TestDiskBasedQueue:
    def test_fifo_round_trip(self, tmp_path):
        q = DiskBasedQueue(str(tmp_path / "spool"))
        q.add({"a": 1})
        q.add([1, 2, 3])
        assert len(q) == 2
        assert q.peek() == {"a": 1}
        assert q.poll() == {"a": 1}
        assert q.poll() == [1, 2, 3]
        assert q.poll() is None
        assert q.is_empty()

    def test_items_survive_on_disk(self, tmp_path):
        spool = str(tmp_path / "spool")
        q = DiskBasedQueue(spool)
        q.add(np.arange(5))
        import os
        assert len(os.listdir(spool)) == 1
        np.testing.assert_array_equal(q.poll(), np.arange(5))
        assert os.listdir(spool) == []

    def test_clear(self, tmp_path):
        q = DiskBasedQueue(str(tmp_path / "s"))
        for i in range(5):
            q.add(i)
        q.clear()
        assert q.is_empty()


class TestConfigurationRegistry:
    def test_register_retrieve_round_trip(self, tmp_path):
        from deeplearning4j_tpu.scaleout.registry import ConfigurationRegistry

        reg = ConfigurationRegistry(str(tmp_path))
        conf = {"lr": 0.1, "layers": [4, 8, 3]}
        reg.register("cluster1", "net-a", conf)
        assert reg.retrieve("cluster1", "net-a") == conf
        assert reg.retrieve("cluster1", "missing") is None
        assert reg.list_ids("cluster1") == ["net-a"]
        assert reg.delete("cluster1", "net-a")
        assert not reg.delete("cluster1", "net-a")


class TestExtraIterators:
    def test_reconstruction_iterator(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import (
            ListDataSetIterator,
            ReconstructionDataSetIterator,
        )

        x = np.random.RandomState(0).rand(10, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[np.zeros(10, int)]
        it = ReconstructionDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 5)
        )
        it.reset()
        ds = it.next()
        np.testing.assert_array_equal(ds.features, ds.labels)

    def test_moving_window_iterator(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.iterator import MovingWindowDataSetIterator

        data = np.arange(16).reshape(4, 4)
        it = MovingWindowDataSetIterator(4, data, np.array([1.0]), 2, 2)
        it.reset()
        ds = it.next()
        assert ds.features.shape == (4, 4)  # 4 windows of 2x2 per batch
        total = 4 + sum(b.num_examples() for b in [it.next(), it.next()])
        assert total == 9

    def test_registry_rejects_traversal(self, tmp_path):
        from deeplearning4j_tpu.scaleout.registry import ConfigurationRegistry

        reg = ConfigurationRegistry(str(tmp_path / "root"))
        with pytest.raises(ValueError):
            reg.register("..", "x", {})
        with pytest.raises(ValueError):
            reg.delete("ns", "..")

    def test_moving_window_label_validation(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.iterator import MovingWindowDataSetIterator

        data = np.arange(16).reshape(4, 4)
        with pytest.raises(ValueError, match="labels"):
            MovingWindowDataSetIterator(4, data, np.ones((4, 1)), 2, 2)
        # one label per window (9) is accepted
        it = MovingWindowDataSetIterator(4, data, np.ones((9, 1)), 2, 2)
        assert it.total_examples() == 9

    def test_registry_list_ids_rejects_traversal(self, tmp_path):
        from deeplearning4j_tpu.scaleout.registry import ConfigurationRegistry

        reg = ConfigurationRegistry(str(tmp_path / "root"))
        with pytest.raises(ValueError):
            reg.list_ids("..")

    def test_moving_window_per_window_scalar_labels(self):
        import numpy as np
        from deeplearning4j_tpu.datasets.iterator import MovingWindowDataSetIterator

        data = np.arange(16).reshape(4, 4)
        it = MovingWindowDataSetIterator(9, data, np.arange(9, dtype=float), 2, 2)
        ds = it.next()
        assert ds.labels.shape == (9, 1)
        assert ds.labels[:, 0].tolist() == list(range(9))
