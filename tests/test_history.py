"""ISSUE 15: the metrics time-series history (telemetry/history.py).

Query semantics pinned with hand-built registries (range/rate/delta,
reset handling, label aggregation, windowed histogram-delta percentiles
and burn fractions), the write-ahead spill round-trip (incl. the torn
tail a kill leaves), bounded memory, and the PR 11 thread-lifecycle
discipline for the background sampler."""

import json
import os
import threading
import time

import pytest

from deeplearning4j_tpu.telemetry.history import (
    MetricsHistory,
    get_history,
    read_spill,
    replay_spill,
    set_history,
)
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry


def _hist(reg=None, **kw):
    return MetricsHistory(registry=reg or MetricsRegistry(), **kw)


class TestScalarQueries:
    def test_points_and_last_point(self):
        reg = MetricsRegistry()
        h = _hist(reg)
        reg.gauge("g").set(1.0)
        h.sample_once(now=100.0)
        reg.gauge("g").set(4.0)
        h.sample_once(now=110.0)
        assert h.points("g") == [(100.0, 1.0), (110.0, 4.0)]
        assert h.last_point("g") == (110.0, 4.0)
        assert h.points("g", window_s=5.0, now=112.0) == [(110.0, 4.0)]
        assert h.last_point("missing") is None

    def test_counter_rate_and_window(self):
        reg = MetricsRegistry()
        h = _hist(reg)
        c = reg.counter("c_total")
        for t, inc in ((100.0, 0), (110.0, 5), (120.0, 5)):
            c.inc(inc)
            h.sample_once(now=t)
        assert h.rate("c_total", window_s=60.0, now=120.0) == \
            pytest.approx(0.5)
        # a narrower window sees only the most recent increase
        assert h.rate("c_total", window_s=11.0, now=120.0) == \
            pytest.approx(0.5)
        assert h.rate("c_total", window_s=5.0, now=120.0) is None

    def test_rate_is_reset_safe(self):
        """A counter reset (process restart re-registering the name) must
        never produce a negative rate — the measurement restarts at the
        reset point."""
        h = _hist()
        with h._lock:
            h._ingest(100.0, {"counters": [
                {"name": "c", "labels": {}, "value": 90.0}]})
            h._ingest(110.0, {"counters": [
                {"name": "c", "labels": {}, "value": 2.0}]})
            h._ingest(120.0, {"counters": [
                {"name": "c", "labels": {}, "value": 7.0}]})
        assert h.rate("c", window_s=60.0, now=120.0) == pytest.approx(0.5)

    def test_labels_none_sums_label_sets(self):
        reg = MetricsRegistry()
        h = _hist(reg)
        reg.counter("c", {"w": "a"}).inc(1)
        reg.counter("c", {"w": "b"}).inc(2)
        h.sample_once(now=100.0)
        reg.counter("c", {"w": "a"}).inc(3)
        h.sample_once(now=110.0)
        assert h.points("c") == [(100.0, 3.0), (110.0, 6.0)]
        # explicit labels pin one series
        assert h.points("c", labels={"w": "b"}) == [(100.0, 2.0),
                                                    (110.0, 2.0)]

    def test_gauge_delta_signed(self):
        reg = MetricsRegistry()
        h = _hist(reg)
        reg.gauge("q").set(10.0)
        h.sample_once(now=100.0)
        reg.gauge("q").set(4.0)
        h.sample_once(now=130.0)
        assert h.delta("q", window_s=60.0, now=130.0) == pytest.approx(-6.0)
        assert h.delta("q", window_s=5.0, now=130.0) is None

    def test_last_points_by_label(self):
        reg = MetricsRegistry()
        h = _hist(reg)
        reg.gauge("hb_unix", {"worker": "w1"}).set(100.0)
        reg.gauge("hb_unix", {"worker": "w2"}).set(50.0)
        h.sample_once(now=200.0)
        rows = h.last_points_by_label("hb_unix")
        assert ({"worker": "w1"}, 200.0, 100.0) in rows
        assert ({"worker": "w2"}, 200.0, 50.0) in rows

    def test_ring_is_bounded(self):
        reg = MetricsRegistry()
        h = _hist(reg, window=4)
        for i in range(10):
            reg.gauge("g").set(float(i))
            h.sample_once(now=100.0 + i)
        pts = h.points("g")
        assert len(pts) == 4
        assert pts[0] == (106.0, 6.0)


class TestHistogramWindows:
    def _reg(self):
        reg = MetricsRegistry()
        hist = reg.histogram("lat_ms")
        return reg, hist

    def test_window_delta_percentile(self):
        """The windowed percentile reflects ONLY the window's
        observations — the old latency regime before the window cannot
        mask a fresh regression (the whole point vs all-time)."""
        reg, hist = self._reg()
        h = _hist(reg)
        for _ in range(100):
            hist.observe(3.0)  # an hour of fast requests…
        h.sample_once(now=100.0)
        for _ in range(10):
            hist.observe(2000.0)  # …then a regression
        h.sample_once(now=160.0)
        # all-time p50 is still fast; the window knows better
        assert hist.percentile(50) == 5.0
        assert h.percentile_over("lat_ms", 50, window_s=70.0,
                                 now=160.0) == 2500.0
        win = h.histogram_window("lat_ms", window_s=70.0, now=160.0)
        assert win["count"] == 10 and win["sum"] == pytest.approx(20000.0)

    def test_fraction_over_burn_numerator(self):
        reg, hist = self._reg()
        h = _hist(reg)
        h.sample_once(now=100.0)
        for v in (10.0, 40.0, 300.0, 900.0):
            hist.observe(v)
        h.sample_once(now=130.0)
        assert h.fraction_over("lat_ms", 250.0, window_s=60.0,
                               now=130.0) == pytest.approx(0.5)
        assert h.fraction_over("lat_ms", 250.0, window_s=5.0,
                               now=130.0) is None

    def test_empty_window_is_none(self):
        reg, hist = self._reg()
        h = _hist(reg)
        hist.observe(5.0)
        h.sample_once(now=100.0)
        h.sample_once(now=160.0)
        # no new observations inside the window → None, never 0-division
        assert h.percentile_over("lat_ms", 99, window_s=70.0,
                                 now=160.0) is None


class TestSpill:
    def test_write_ahead_round_trip(self, tmp_path):
        path = str(tmp_path / "spill.jsonl")
        reg = MetricsRegistry()
        h = _hist(reg, spill_path=path)
        reg.counter("c").inc(1)
        h.sample_once(now=100.0)
        reg.counter("c").inc(2)
        h.sample_once(now=110.0)
        h.close()
        recs = read_spill(path)
        assert [r["seq"] for r in recs] == [0, 1]
        replayed = replay_spill(path)
        assert replayed.points("c") == [(100.0, 1.0), (110.0, 3.0)]

    def test_torn_tail_tolerated(self, tmp_path):
        """A process killed mid-write leaves a torn final line; every
        earlier sample is complete by the write-ahead contract and must
        still load."""
        path = str(tmp_path / "spill.jsonl")
        reg = MetricsRegistry()
        h = _hist(reg, spill_path=path)
        reg.gauge("g").set(7.0)
        h.sample_once(now=100.0)
        h.close()
        with open(path, "a") as fh:
            fh.write('{"schema": "dl4j-tpu-history-v1", "ts": 110.0, "sn')
        recs = read_spill(path)
        assert len(recs) == 1 and recs[0]["ts"] == 100.0

    def test_corrupt_middle_line_raises(self, tmp_path):
        path = str(tmp_path / "spill.jsonl")
        with open(path, "w") as fh:
            fh.write("{not json\n")
            fh.write(json.dumps({"schema": "dl4j-tpu-history-v1",
                                 "ts": 1.0, "seq": 0,
                                 "snapshot": {}}) + "\n")
        with pytest.raises(ValueError, match="line 1"):
            read_spill(path)


class TestSamplerThread:
    def test_background_sampler_and_self_metrics(self):
        reg = MetricsRegistry()
        reg.gauge("g").set(1.0)
        h = _hist(reg, interval_s=0.01)
        with h:
            deadline = time.time() + 5.0
            while (reg.counter("history_samples_total").value < 3
                   and time.time() < deadline):
                time.sleep(0.01)
        assert reg.counter("history_samples_total").value >= 3
        assert reg.gauge("history_series").value >= 1
        assert len(h.points("g")) >= 3

    def test_thread_lifecycle_stable_under_repeated_start_stop(self):
        """ISSUE 15 satellite (the PR 11 regression-test pattern): the
        sampler neither leaks nor double-starts across repeated
        open/close, stop is idempotent, start-after-stop works."""
        before = threading.active_count()
        h = _hist(interval_s=0.005)
        for _ in range(4):
            h.start()
            h.start()  # idempotent
            time.sleep(0.02)
            h.stop()
            h.stop()  # idempotent
            assert threading.active_count() == before
        h.close()
        assert threading.active_count() == before

    def test_process_global_seam(self):
        prev = set_history(None)
        try:
            assert get_history() is None
            h = _hist()
            assert set_history(h) is None
            assert get_history() is h
        finally:
            set_history(prev)


def test_spill_dir_created(tmp_path):
    path = str(tmp_path / "nested" / "dir" / "spill.jsonl")
    h = _hist(spill_path=path)
    h.sample_once(now=1.0)
    h.close()
    assert os.path.isfile(path)
