"""ISSUE 11 runtime half: the utils.lockwatch lock-order watchdog, and the
thread-lifecycle audit — every server/loop shutdown path must join its
threads deterministically (the class of defect the PR 10 tracker flake
exposed; the graftlint ``unjoined-thread`` sweep found four more)."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.telemetry.registry import MetricsRegistry  # noqa: E402
from deeplearning4j_tpu.utils import lockwatch as lw  # noqa: E402


# ---------------------------------------------------------------- seam ----

def test_seam_hands_out_plain_primitives_when_off():
    assert not lw.enabled()
    lock = lw.make_lock("off.lock")
    assert type(lock) is type(threading.Lock())
    rlock = lw.make_rlock("off.rlock")
    assert type(rlock) is type(threading.RLock())
    cond = lw.make_condition(name="off.cond")
    assert isinstance(cond, threading.Condition)


def test_seam_hands_out_watched_primitives_when_armed(lockwatch):
    lock = lw.make_lock("on.lock")
    assert isinstance(lock, lw.WatchedLock)
    rlock = lw.make_rlock("on.rlock")
    assert isinstance(rlock, lw.WatchedRLock)


def test_env_var_arms_at_creation(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_LOCKWATCH", "1")
    try:
        lock = lw.make_lock("env.lock")
        assert isinstance(lock, lw.WatchedLock)
        assert lw.enabled()
    finally:
        lw.disable()
        lw.reset()


def test_disable_quiesces_existing_wrappers(lockwatch):
    lock = lw.make_lock("quiesce.lock")
    with lock:
        pass
    before = lw.summary()["locks"]["quiesce.lock"]["acquires"]
    lw.disable()
    with lock:  # still a working mutex, no recording
        pass
    lw.enable()
    assert lw.summary()["locks"]["quiesce.lock"]["acquires"] == before


# --------------------------------------------------------- order graph ----

def test_cycle_raises_before_deadlocking(lockwatch):
    a, b = lw.make_lock("order.a"), lw.make_lock("order.b")
    with a:
        with b:
            pass
    errs = []

    def reversed_order():
        try:
            with b:
                with a:
                    pass
        except lw.LockOrderViolation as exc:
            errs.append(exc)

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    assert len(errs) == 1 and "order.a" in str(errs[0])
    assert lw.summary()["cycles"] == 1
    assert lw.graph_snapshot()["order.a"] == ["order.b"]


def test_consistent_order_never_flags(lockwatch):
    a, b = lw.make_lock("ok.a"), lw.make_lock("ok.b")
    for _ in range(5):
        with a:
            with b:
                pass
    assert lw.summary()["cycles"] == 0


def test_cycle_counted_not_raised_when_disarmed():
    lw.reset()
    lw.enable(raise_on_cycle=False)
    try:
        a, b = lw.make_lock("soft.a"), lw.make_lock("soft.b")
        with a:
            with b:
                pass
        with b:
            with a:  # inversion: recorded, not raised
                pass
        assert lw.summary()["cycles"] == 1
    finally:
        lw.disable()
        lw.reset()


def test_rlock_reentry_is_not_an_edge(lockwatch):
    r = lw.make_rlock("re.lock")
    with r:
        with r:  # reentrant: no self-edge, no second acquire record
            pass
    assert "re.lock" not in lw.graph_snapshot()
    assert lw.summary()["locks"]["re.lock"]["acquires"] == 1


# ------------------------------------------------- condition integration ----

def test_condition_wait_hands_off_watched_lock(lockwatch):
    r = lw.make_rlock("cv.lock")
    cond = lw.make_condition(r, name="cv.lock")
    items = []

    def producer():
        with cond:
            items.append(1)
            cond.notify_all()

    got = []

    def consumer():
        with cond:
            while not items:
                cond.wait(0.05)
            got.append(items[0])

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    producer()
    t.join(timeout=10)
    assert got == [1]
    assert lw.summary()["cycles"] == 0


# ------------------------------------------------ metrics and watchdog ----

def test_metrics_flow_through_registry():
    reg = MetricsRegistry()
    lw.reset()
    lw.enable(registry=reg)
    try:
        lock = lw.make_lock("met.lock")
        hold = threading.Event()
        release = threading.Event()

        def holder():
            with lock:
                hold.set()
                release.wait(5)

        t = threading.Thread(target=holder)
        t.start()
        assert hold.wait(5)
        t2_done = []

        def contender():
            with lock:
                t2_done.append(1)

        t2 = threading.Thread(target=contender)
        t2.start()
        time.sleep(0.05)
        release.set()
        t.join(timeout=10)
        t2.join(timeout=10)
        assert t2_done == [1]
        labels = {"lock": "met.lock"}
        assert reg.counter("lockwatch_acquires_total", labels).value >= 2
        assert reg.counter("lockwatch_contended_total", labels).value >= 1
        assert reg.histogram("lockwatch_wait_ms", labels).count >= 2
        assert reg.histogram("lockwatch_hold_ms", labels).count >= 2
        rec = lw.metrics_record()
        assert rec["lockwatch_met_lock_acquires"] >= 2
        assert rec["lockwatch_met_lock_contended"] >= 1
        assert rec["lockwatch_met_lock_hold_ms_max"] > 0
    finally:
        lw.disable()
        lw.reset()


def test_timed_acquire_honors_timeout(lockwatch):
    lock = lw.make_lock("timeout.lock")
    release = threading.Event()

    def holder():
        with lock:
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.05)
    t0 = time.perf_counter()
    assert lock.acquire(timeout=0.2) is False
    assert time.perf_counter() - t0 < 2.0
    release.set()
    t.join(timeout=10)


def test_watchdog_dumps_thread_stacks_through_flight_recorder(tmp_path):
    from deeplearning4j_tpu.telemetry import trace as tr

    lw.reset()
    lw.enable(watchdog_s=0.2)
    tracer = tr.Tracer("lockwatch-test", trace_dir=str(tmp_path),
                       registry=MetricsRegistry())
    prev = tr.set_tracer(tracer)
    try:
        lock = lw.make_lock("stuck.lock")
        release = threading.Event()

        def holder():
            with lock:
                release.wait(5)

        t = threading.Thread(target=holder, name="the-holder")
        t.start()
        time.sleep(0.05)
        assert lock.acquire(timeout=0.6) is False  # blocked past watchdog
        release.set()
        t.join(timeout=10)
        assert lw.summary()["watchdog_dumps"] == 1
        dump_path = os.path.join(str(tmp_path),
                                 "flightrec_lockwatch-test.json")
        assert os.path.exists(dump_path)
        payload = json.load(open(dump_path))
        assert payload["reason"] == "lockwatch_blocked"
        extra = payload["extra"]
        assert extra["lockwatch"]["lock"] == "stuck.lock"
        stacks = extra["thread_stacks"]
        assert any("the-holder" in k for k in stacks), list(stacks)
    finally:
        tr.set_tracer(prev)
        lw.disable()
        lw.reset()


# ------------------------------------------- thread-lifecycle audit ----
# Satellite: every server/loop shutdown path joins its threads. The
# repeated open/close loops pin the fix for the graftlint sweep findings
# (UiServer + tracker server never joined; engine stop raced _thread) —
# a leaked thread shows up as a drifting active_count.

def _stable_thread_count(fn, cycles=4):
    """Run fn() (open+close one subsystem) repeatedly; the thread count
    after each cycle must return to the baseline."""
    fn()  # warm any lazily-started machinery
    baseline = threading.active_count()
    for _ in range(cycles):
        fn()
        deadline = time.time() + 5
        while threading.active_count() > baseline and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= baseline, (
            f"thread leak: {threading.active_count()} > {baseline} after "
            f"close ({[t.name for t in threading.enumerate()]})")


def test_ui_server_close_joins_its_thread():
    from deeplearning4j_tpu.ui.server import UiServer

    def cycle():
        srv = UiServer()
        srv.start(port=0)
        srv.stop()

    _stable_thread_count(cycle)


def test_tracker_server_shutdown_joins_its_thread():
    from deeplearning4j_tpu.scaleout.remote_tracker import (
        StateTrackerClient,
        StateTrackerServer,
    )

    def cycle():
        server = StateTrackerServer()
        client = StateTrackerClient(server.address,
                                    registry=MetricsRegistry())
        client.add_worker("w")
        client.close()
        server.shutdown()

    _stable_thread_count(cycle)


def test_memory_watermark_sampler_stop_joins():
    from deeplearning4j_tpu.telemetry.xprofile import MemoryWatermarkSampler

    def cycle():
        with MemoryWatermarkSampler(registry=MetricsRegistry(),
                                    interval_s=0.01):
            time.sleep(0.03)

    _stable_thread_count(cycle)


def test_async_checkpointer_close_joins(tmp_path):
    from deeplearning4j_tpu.scaleout.ckpt import (
        AsyncCheckpointer,
        Checkpointer,
    )

    idx = [0]

    def cycle():
        idx[0] += 1
        root = tmp_path / f"ck{idx[0]}"
        with AsyncCheckpointer(Checkpointer(str(root),
                                            registry=MetricsRegistry())):
            pass

    _stable_thread_count(cycle)


def test_engine_stop_is_idempotent_and_joins():
    from deeplearning4j_tpu.models.transformer_lm import init_lm_params
    from deeplearning4j_tpu.serve.engine import DecodeEngine

    import jax

    params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                            n_layers=1)
    engine = DecodeEngine(params, 2, n_slots=2, max_len=16,
                          serve_dtype=None, registry=MetricsRegistry())

    def cycle():
        engine.start()
        engine.generate([1, 2, 3], max_new_tokens=2)
        engine.stop()
        engine.stop()  # second stop: no-op, no AttributeError, no hang

    _stable_thread_count(cycle)
