"""Composed parallelism: ONE transformer LM (ATTENTION + top-2 MoE FFN)
trained on multi-axis meshes — dp×ep, dp×sp×ep, dp×pp — with every
composed step pinned against the identical dense single-device step
(round-4 verdict: the axes existed but were never composed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer_lm import (
    dense_loss_fn,
    init_lm_params,
    make_composed_train_step,
    make_pp_loss,
    make_pp_stages,
    make_single_device_train_step,
    shard_lm_batch,
    shard_lm_params,
)

V, D, H, E, DFF = 32, 16, 2, 4, 32
B, T = 4, 16


def _data(seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (B, T + 1), 0, V)
    return toks[:, :-1], toks[:, 1:]


def _params():
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, E, DFF)


def _assert_tree_close(a, b, atol, what):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree_util.tree_leaves_with_path(b)
    for (pa, la), (_, lb) in zip(fa, fb):
        err = float(jnp.max(jnp.abs(jnp.asarray(la, jnp.float32)
                                    - jnp.asarray(lb, jnp.float32))))
        assert err < atol, f"{what}: {jax.tree_util.keystr(pa)} diff {err}"


def _run_parity(mesh, capacity, atol, steps=3):
    params = _params()
    toks, tgts = _data()
    sharded = shard_lm_params(params, mesh)
    stoks, stgts = shard_lm_batch(toks, tgts, mesh)
    step = make_composed_train_step(mesh, H, capacity)
    ref_step = make_single_device_train_step(H)
    ref_params = params
    for i in range(steps):
        sharded, loss = step(sharded, stoks, stgts)
        jax.block_until_ready(loss)  # serialize: XLA CPU rendezvous quirk
        ref_params, ref_loss = ref_step(ref_params, toks, tgts)
        assert abs(float(loss) - float(ref_loss)) < atol, (
            i, float(loss), float(ref_loss))
    _assert_tree_close(jax.device_get(sharded), jax.device_get(ref_params),
                       atol, f"{mesh.axis_names} params after {steps} steps")
    return float(loss)


def test_dp_ep_parity():
    """dp2×ep4: batch over "data", experts over "expert" — scores and
    updated params equal the dense step to 1e-5 over 3 SGD steps."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    # ample capacity: tokens per token-shard row = (B/2)·T
    _run_parity(mesh, capacity=(B // 2) * T, atol=1e-5)


def test_dp_sp_ep_parity():
    """dp2×sp2×ep2: THREE strategies in one jitted step — batch sharding,
    ring attention over the sequence, expert-parallel MoE."""
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "sp", "expert"))
    params = _params()
    # E=2 experts on this mesh: rebuild router/experts for 2 experts
    p2 = init_lm_params(jax.random.PRNGKey(0), V, D, H, 2, DFF)
    toks, tgts = _data()
    sharded = shard_lm_params(p2, mesh)
    stoks, stgts = shard_lm_batch(toks, tgts, mesh)
    step = make_composed_train_step(mesh, H, capacity=(B // 2) * (T // 2))
    ref_step = make_single_device_train_step(H)
    ref_params = p2
    for i in range(3):
        sharded, loss = step(sharded, stoks, stgts)
        jax.block_until_ready(loss)
        ref_params, ref_loss = ref_step(ref_params, toks, tgts)
        # ring attention's online softmax reorders the reduction: 1e-4
        assert abs(float(loss) - float(ref_loss)) < 1e-4
    _assert_tree_close(jax.device_get(sharded), jax.device_get(ref_params),
                       1e-4, "dp×sp×ep params")
    del params


def test_dp_ep_capacity_overflow_still_trains():
    """With a tight capacity the composed step drops tokens (not parity
    with dense) but remains finite and learns."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "expert"))
    params = shard_lm_params(_params(), mesh)
    toks, tgts = _data()
    stoks, stgts = shard_lm_batch(toks, tgts, mesh)
    step = make_composed_train_step(mesh, H, capacity=4)
    first = None
    for _ in range(10):
        params, loss = step(params, stoks, stgts)
        jax.block_until_ready(loss)
        first = first if first is not None else float(loss)
    assert np.isfinite(float(loss))
    assert float(loss) < first


def test_dp_pp_trains_with_parity():
    """dp2×pp2: the SAME transformer split into [attention | MoE-FFN]
    stages on "pipe" with microbatches sharded over "data" — the SGD loss
    trajectory matches the unstaged dense model step-for-step."""
    from deeplearning4j_tpu.parallel.pipeline import (
        shard_stage_params,
        stack_stage_params,
    )

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "pipe"))
    params = _params()
    per_stage, stage_fn = make_pp_stages(params, H)
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh, "pipe")

    n_micro, mb = 4, 2
    toks = jax.random.randint(jax.random.PRNGKey(3),
                              (n_micro, mb, T + 1), 0, V)
    toks_mbs, tgt_mbs = toks[..., :-1], toks[..., 1:]

    pipe_loss = make_pp_loss(stage_fn, mesh, "pipe", batch_axis="data")

    # dense twin: identical math, no staging, no aux (the pp path's task
    # loss only — aux is a router-training regularizer, orthogonal here)
    seq_loss_fn = dense_loss_fn(H, aux_weight=0.0)

    def seq_loss(ps, toks_flat, tgt_flat):
        return seq_loss_fn(ps, toks_flat, tgt_flat)

    lr = 0.1
    trained = (stacked, params["embed"], params["dec_w"], params["dec_b"])
    seq_params = params
    toks_flat = toks_mbs.reshape(-1, T)
    tgt_flat = tgt_mbs.reshape(-1, T)
    jax.block_until_ready(pipe_loss(trained, toks_mbs, tgt_mbs))
    losses_p, losses_s = [], []
    for _ in range(4):
        lp, gp = jax.value_and_grad(pipe_loss)(trained, toks_mbs, tgt_mbs)
        trained = jax.tree_util.tree_map(lambda p, g: p - lr * g, trained, gp)
        jax.block_until_ready(lp)
        ls, gs = jax.value_and_grad(seq_loss)(seq_params, toks_flat, tgt_flat)
        seq_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, seq_params, gs)
        losses_p.append(float(lp))
        losses_s.append(float(ls))
    np.testing.assert_allclose(losses_p, losses_s, atol=1e-5, rtol=1e-5)
    assert losses_p[-1] < losses_p[0]
