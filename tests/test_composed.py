"""Composed parallelism: ONE transformer LM (ATTENTION + top-2 MoE FFN,
``n_layers`` scan-stacked decoder blocks) trained on multi-axis meshes —
dp×ep, dp×sp×ep, dp×pp — with every composed step pinned against the
identical dense single-device step (round-4 verdict: the axes existed but
were never composed; round-6: the BLOCKWISE flash core now runs inside
every composed path via the attn_impl seam, and the flagship is
multi-block)."""

import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.models.transformer_lm import (
    dense_loss_fn,
    init_lm_params,
    lm_n_layers,
    make_composed_train_step,
    make_pp_loss,
    make_pp_stages,
    make_single_device_train_step,
    shard_lm_batch,
    shard_lm_params,
)
from deeplearning4j_tpu.utils.retrace_guard import retrace_guard

V, D, H, E, DFF = 32, 16, 2, 4, 32
B, T = 4, 16


def _data(seed=1):
    toks = jax.random.randint(jax.random.PRNGKey(seed), (B, T + 1), 0, V)
    return toks[:, :-1], toks[:, 1:]


def _params(n_experts=E, n_layers=1):
    return init_lm_params(jax.random.PRNGKey(0), V, D, H, n_experts, DFF,
                          n_layers=n_layers)


def _assert_tree_close(a, b, atol, what):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree_util.tree_leaves_with_path(b)
    for (pa, la), (_, lb) in zip(fa, fb):
        err = float(jnp.max(jnp.abs(jnp.asarray(la, jnp.float32)
                                    - jnp.asarray(lb, jnp.float32))))
        assert err < atol, f"{what}: {jax.tree_util.keystr(pa)} diff {err}"


def _run_parity(mesh, capacity, atol, steps=3, n_experts=E, n_layers=1,
                attn_impl=None, moe_impl=None):
    """Composed step (optionally with forced attention core / MoE dispatch)
    vs the dense single-device oracle (materializing reference core), loss
    AND params."""
    params = _params(n_experts=n_experts, n_layers=n_layers)
    toks, tgts = _data()
    sharded = shard_lm_params(params, mesh)
    stoks, stgts = shard_lm_batch(toks, tgts, mesh)
    step = make_composed_train_step(mesh, H, capacity, attn_impl=attn_impl,
                                    moe_impl=moe_impl)
    ref_step = make_single_device_train_step(H, attn_impl="dense")
    ref_params = params
    for i in range(steps):
        # after the first (compiling) step, a warmed composed step must
        # never retrace — per-step recompiles are exactly the drift class
        # the retrace guard exists to catch (utils/retrace_guard.py)
        guard = (contextlib.nullcontext() if i == 0 else
                 retrace_guard(0, label=f"composed {mesh.axis_names} "
                                        f"step {i}"))
        with guard:
            sharded, loss = step(sharded, stoks, stgts)
            jax.block_until_ready(loss)  # serialize: XLA CPU rendezvous quirk
            ref_params, ref_loss = ref_step(ref_params, toks, tgts)
        assert abs(float(loss) - float(ref_loss)) < atol, (
            i, float(loss), float(ref_loss))
    _assert_tree_close(jax.device_get(sharded), jax.device_get(ref_params),
                       atol,
                       f"{mesh.axis_names} L={n_layers} impl={attn_impl} "
                       f"params after {steps} steps")
    return float(loss)


def _dp_ep_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "expert"))


def _dp_sp_ep_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("data", "sp", "expert"))


def test_dp_ep_parity():
    """dp2×ep4: batch over "data", experts over "expert" — scores and
    updated params equal the dense step to 1e-5 over 3 SGD steps."""
    # ample capacity: tokens per token-shard row = (B/2)·T
    _run_parity(_dp_ep_mesh(), capacity=(B // 2) * T, atol=1e-5)


def test_dp_ep_blockwise_core_parity():
    """dp2×ep4 with the BLOCKWISE flash core forced through the attn_impl
    seam — parity vs the dense-core oracle to 1e-5 (the flash custom VJP
    is exercised inside the composed grad)."""
    _run_parity(_dp_ep_mesh(), capacity=(B // 2) * T, atol=1e-5,
                attn_impl="blockwise")


def test_dp_sp_ep_parity():
    """dp2×sp2×ep2: THREE strategies in one jitted step — batch sharding,
    ring attention over the sequence, expert-parallel MoE."""
    _run_parity(_dp_sp_ep_mesh(), capacity=(B // 2) * (T // 2), atol=1e-4,
                n_experts=2)


def test_dp_sp_ep_blockwise_core_parity():
    """dp2×sp2×ep2 with the blockwise core inside the RING (each rotated
    K/V block goes through flash_attention's online-softmax tiles) — the
    tentpole path: dp×sp×ep × blockwise, parity to 1e-5."""
    _run_parity(_dp_sp_ep_mesh(), capacity=(B // 2) * (T // 2), atol=1e-5,
                n_experts=2, attn_impl="blockwise")


def test_dp_sp_ep_multiblock_blockwise_parity():
    """The multi-block flagship (n_layers=2, scan-stacked) on the full
    dp2×sp2×ep2 mesh with the blockwise core — depth × all three axes."""
    _run_parity(_dp_sp_ep_mesh(), capacity=(B // 2) * (T // 2), atol=1e-5,
                n_experts=2, n_layers=2, attn_impl="blockwise")


def test_dp_sp_ep_global_override_reaches_ring_core(monkeypatch):
    """The ACCEPTANCE path: set_attention_impl("blockwise") with NO
    per-call argument steers the ring's per-rotated-block core inside the
    composed dp2×sp2×ep2 step — get_attention_impl() observed as
    "blockwise" inside the block core while the parity run stays pinned to
    the dense oracle at 1e-5 (the oracle pins its core per-call, which
    outranks the global override)."""
    from deeplearning4j_tpu.ops import flash_attention as fa

    seen = {}
    orig = fa.blockwise_block_partials

    def spy(*args, **kwargs):
        seen["impl_inside_ring_core"] = fa.get_attention_impl()
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "blockwise_block_partials", spy)
    try:
        fa.set_attention_impl("blockwise")
        _run_parity(_dp_sp_ep_mesh(), capacity=(B // 2) * (T // 2),
                    atol=1e-5, n_experts=2)
    finally:
        fa.set_attention_impl(None)
    assert seen.get("impl_inside_ring_core") == "blockwise"


def test_dp_ep_multiblock_parity():
    """n_layers=3 on dp2×ep4: the lax.scan depth stacking composes with
    expert-parallel dispatch (3 layers of shard_map MoE inside one scan)."""
    _run_parity(_dp_ep_mesh(), capacity=(B // 2) * T, atol=1e-5, n_layers=3)


def test_dp_ep_grouped_alltoall_parity():
    """THE ACCEPTANCE PATH: n_experts=8 on a dp2×ep2 mesh — FOUR experts
    per device — trained through the all_to_all capacity exchange, parity
    vs the dense single-device oracle to 1e-5 (loss AND params). The old
    one-expert-per-device restriction is gone."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "expert"))
    from deeplearning4j_tpu.models.transformer_lm import selected_moe_impl

    # host-side metadata helper agrees with what the step will run: the
    # B·T token stream subdivides over dp2×ep2, so auto resolves alltoall
    assert selected_moe_impl(mesh, B * T) == "alltoall"
    _run_parity(mesh, capacity=(B // 2) * T, atol=1e-5, n_experts=8,
                n_layers=2, moe_impl="alltoall")


def test_dp_ep_grouped_replicated_parity():
    """The same grouped (G=4) flagship through the replicated-psum
    dispatch — the A/B twin the bench compares against stays correct."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "expert"))
    _run_parity(mesh, capacity=(B // 2) * T, atol=1e-5, n_experts=8,
                moe_impl="replicated")


def test_dp_sp_ep_grouped_alltoall_parity():
    """Grouped experts under ALL THREE axes: dp2×sp2×ep2 with n_experts=4
    (G=2), tokens sub-sharded over data×sp×expert for the exchange, ring
    attention rotating K/V inside each row."""
    _run_parity(_dp_sp_ep_mesh(), capacity=(B // 2) * (T // 2), atol=1e-4,
                n_experts=4, moe_impl="alltoall")


def test_dp_ep_capacity_overflow_still_trains():
    """With a tight capacity the composed step drops tokens (not parity
    with dense) but remains finite and learns."""
    mesh = _dp_ep_mesh()
    params = shard_lm_params(_params(), mesh)
    toks, tgts = _data()
    stoks, stgts = shard_lm_batch(toks, tgts, mesh)
    step = make_composed_train_step(mesh, H, capacity=4)
    first = None
    for _ in range(10):
        params, loss = step(params, stoks, stgts)
        jax.block_until_ready(loss)
        first = first if first is not None else float(loss)
    assert np.isfinite(float(loss))
    assert float(loss) < first


def _pp_parity(n_layers, n_stages, attn_impl=None, steps=4):
    """dp2×pp2: the multi-block transformer split at LAYER BOUNDARIES into
    ``n_stages`` stages on "pipe" with microbatches sharded over "data" —
    the SGD loss trajectory matches the unstaged dense model step-for-step.
    """
    from deeplearning4j_tpu.parallel.pipeline import (
        shard_stage_params,
        stack_stage_params,
    )

    devs = np.array(jax.devices()[:2 * n_stages]).reshape(2, n_stages)
    mesh = Mesh(devs, ("data", "pipe"))
    params = _params(n_layers=n_layers)
    assert lm_n_layers(params) == n_layers
    per_stage, stage_fn = make_pp_stages(params, H, n_stages=n_stages,
                                         attn_impl=attn_impl)
    stacked = shard_stage_params(stack_stage_params(per_stage), mesh, "pipe")

    n_micro, mb = 4, 2
    toks = jax.random.randint(jax.random.PRNGKey(3),
                              (n_micro, mb, T + 1), 0, V)
    toks_mbs, tgt_mbs = toks[..., :-1], toks[..., 1:]

    pipe_loss = make_pp_loss(stage_fn, mesh, "pipe", batch_axis="data")

    # dense twin: identical math, no staging, no aux (the pp path's task
    # loss only — aux is a router-training regularizer, orthogonal here);
    # the oracle always runs the materializing dense core
    seq_loss_fn = dense_loss_fn(H, aux_weight=0.0, attn_impl="dense")

    def seq_loss(ps, toks_flat, tgt_flat):
        return seq_loss_fn(ps, toks_flat, tgt_flat)

    lr = 0.1
    trained = (stacked, params["embed"], params["dec_w"], params["dec_b"])
    seq_params = params
    toks_flat = toks_mbs.reshape(-1, T)
    tgt_flat = tgt_mbs.reshape(-1, T)
    jax.block_until_ready(pipe_loss(trained, toks_mbs, tgt_mbs))
    # jit the grad steps ONCE: the retrace guard exposed that un-jitted
    # value_and_grad(pipe_loss) re-traced and re-compiled ~470 op-level
    # programs EVERY iteration (nothing cached across calls) — the exact
    # failure class the guard exists for
    pipe_vg = jax.jit(jax.value_and_grad(pipe_loss))
    seq_vg = jax.jit(jax.value_and_grad(seq_loss))
    losses_p, losses_s = [], []
    for i in range(steps):
        # iteration 0 compiles the grad programs; iteration 1 compiles once
        # more against the committed shardings the first update produced
        # (host-placed embed/decoder args became device-committed outputs).
        # From iteration 2 the staged step must be retrace-free (pinned:
        # shape drift through the pipeline schedule would recompile every
        # tick).
        guard = (contextlib.nullcontext() if i < 2 else
                 retrace_guard(0, label=f"dp×pp L={n_layers} step {i}"))
        with guard:
            lp, gp = pipe_vg(trained, toks_mbs, tgt_mbs)
            trained = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                             trained, gp)
            jax.block_until_ready(lp)
            ls, gs = seq_vg(seq_params, toks_flat, tgt_flat)
            seq_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, seq_params, gs)
        losses_p.append(float(lp))
        losses_s.append(float(ls))
    np.testing.assert_allclose(losses_p, losses_s, atol=1e-5, rtol=1e-5)
    assert losses_p[-1] < losses_p[0]
    # the staged stack's params must also track the unstaged model's blocks:
    # stage i's slice == layers [i·L/S, (i+1)·L/S) of the dense twin
    n_per = n_layers // n_stages
    stacked_new = jax.device_get(trained[0])
    seq_blocks = jax.device_get(seq_params["blocks"])
    restacked = jax.tree_util.tree_map(
        lambda a: a.reshape((n_layers,) + a.shape[2:]), stacked_new)
    _assert_tree_close(restacked, seq_blocks, 1e-5,
                       f"pp L={n_layers}/S={n_stages} stage params")


def test_dp_pp_trains_with_parity():
    """dp2×pp2, n_layers=2, one layer per stage."""
    _pp_parity(n_layers=2, n_stages=2)


def test_dp_pp_multilayer_blockwise_per_stage():
    """dp2×pp2, n_layers=4 → each stage scans TWO layers locally, every
    staged layer running the blockwise flash core (one compile covers both
    the depth-per-stage and the pp×blockwise dimensions)."""
    _pp_parity(n_layers=4, n_stages=2, attn_impl="blockwise", steps=3)


def test_pp_stages_rejects_indivisible_split():
    params = _params(n_layers=3)
    with pytest.raises(ValueError, match="layer-boundary"):
        make_pp_stages(params, H, n_stages=2)
