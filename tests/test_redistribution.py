"""In-graph redistribution plans (ISSUE 14; scaleout/ckpt/redistribution).

Pins: plan derivation (the slice/all_gather/all_to_all/ppermute step
kinds), plan execution parity vs the host-callback resharding loader
across the existing cross-mesh matrix (dp×ep ↔ dp×sp×ep ↔ dp×pp carry ↔
single-device), the compiled plan's collective inventory matching the
planned step kinds, the randomized round-trip identity property, and the
two live consumers (elastic param adoption, serve cold start)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import (
    Mesh,
    NamedSharding,
    PartitionSpec as P,
    SingleDeviceSharding,
)

from deeplearning4j_tpu.scaleout.ckpt.redistribution import (
    PlanStep,
    apply_plan,
    plan_cross_mesh,
    plan_redistribution,
    redistribute,
    redistribute_tree,
)


def _mesh_dp_ep():
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "expert"))


def _mesh_dp_sp_ep():
    return Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                ("data", "sp", "expert"))


def _mesh_dp_pp():
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "pipe"))


class TestPlanDerivation:
    def test_noop_move_gather_slice_kinds(self):
        mesh = _mesh_dp_ep()
        assert plan_redistribution(P("data", "expert"), P("data", "expert"),
                                   mesh).kinds() == []
        assert plan_redistribution(P(None, "expert"), P("expert", None),
                                   mesh).kinds() == ["all_to_all"]
        assert plan_redistribution(P("data", "expert"), P(None, "expert"),
                                   mesh).kinds() == ["all_gather"]
        assert plan_redistribution(P(None, None), P("data", "expert"),
                                   mesh).kinds() == ["slice"]

    def test_compound_plan_orders_gather_move_slice(self):
        mesh = _mesh_dp_ep()
        # "expert" leaves dim 0 entirely, "data" moves 0 -> 1: gather
        # then move, no trailing slice needed
        plan = plan_redistribution(P(("data", "expert"), None),
                                   P(None, "data"), mesh)
        assert plan.kinds() == ["all_gather", "all_to_all"]
        assert plan.steps[0].partition_spec() == P("data", None)
        # gather + slice composition
        plan2 = plan_redistribution(P("data", None), P(None, "expert"), mesh)
        assert plan2.kinds() == ["all_gather", "slice"]

    def test_unknown_axis_rejected(self):
        with pytest.raises(ValueError, match="not on the mesh"):
            plan_redistribution(P("bogus"), P(), _mesh_dp_ep())

    def test_cross_mesh_plan_kinds(self):
        a, b = _mesh_dp_ep(), _mesh_dp_sp_ep()
        src = NamedSharding(a, P(None, "expert"))
        # 4-way -> 2-way shard on dim 1: structure changes → all_to_all
        assert plan_cross_mesh(
            src, NamedSharding(b, P(None, "expert")), 2
        ).kinds() == ["all_to_all"]
        # same per-dim structure on a renamed mesh → pure device permute
        assert plan_cross_mesh(
            NamedSharding(a, P("data", None)),
            NamedSharding(_mesh_dp_pp(), P("data", None)), 2
        ).kinds() == ["ppermute"]


class TestPlanExecution:
    def _arr(self, mesh, spec, shape=(8, 8)):
        x = jnp.arange(int(np.prod(shape)), dtype=jnp.float32).reshape(shape)
        return x, jax.device_put(x, NamedSharding(mesh, spec))

    def test_apply_plan_values_and_placement(self):
        mesh = _mesh_dp_ep()
        x, xa = self._arr(mesh, P(None, "expert"))
        plan = plan_redistribution(P(None, "expert"), P("expert", None),
                                   mesh)
        y = apply_plan(plan, xa)
        assert y.sharding == NamedSharding(mesh, P("expert", None))
        assert jnp.array_equal(jax.device_get(y), x)

    def test_compiled_plan_inventory_matches_step_kinds(self):
        """The jitted plan's HLO contains exactly the planned collective
        kinds: an all_to_all move shows all-to-all, a gather shows
        all-gather, a slice program has NO comm at all."""
        from deeplearning4j_tpu.telemetry.xprofile import profile_lowered

        mesh = _mesh_dp_ep()
        _, xa = self._arr(mesh, P(None, "expert"))

        def inventory(src_spec, dst_spec, arr):
            plan = plan_redistribution(src_spec, dst_spec, mesh)
            dst = NamedSharding(mesh, plan.steps[-1].partition_spec())
            prof = profile_lowered(
                jax.jit(lambda v: v, out_shardings=dst).lower(arr),
                label="plan")
            return set(prof.collectives)

        assert inventory(P(None, "expert"), P("expert", None),
                         xa) == {"all-to-all"}
        assert inventory(P(None, "expert"), P(), xa) == {"all-gather"}
        _, xr = self._arr(mesh, P())
        assert inventory(P(), P("data", "expert"), xr) == set()

    @pytest.mark.parametrize("src_fn,dst_fn", [
        (lambda: (_mesh_dp_ep(), P(None, "expert")),
         lambda: (_mesh_dp_sp_ep(), P(None, "expert"))),
        (lambda: (_mesh_dp_sp_ep(), P("data", "sp")),
         lambda: (_mesh_dp_ep(), P("data", "expert"))),
        (lambda: (_mesh_dp_pp(), P("pipe", None)),
         lambda: (_mesh_dp_ep(), P(None, "expert"))),
    ])
    def test_cross_mesh_redistribute_values(self, src_fn, dst_fn):
        src_mesh, src_spec = src_fn()
        dst_mesh, dst_spec = dst_fn()
        x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
        xa = jax.device_put(x, NamedSharding(src_mesh, src_spec))
        y = redistribute(xa, NamedSharding(dst_mesh, dst_spec))
        assert y.sharding == NamedSharding(dst_mesh, dst_spec)
        assert jnp.array_equal(jax.device_get(y), x)


class TestCrossMeshMatrixParityVsHostRestore:
    """The acceptance pin: live in-graph redistribution of the flagship
    params lands BIT-identical state to the host-callback resharding
    loader (``restore_sharded``) restoring the same save, across the
    existing cross-mesh matrix."""

    def _params(self):
        from deeplearning4j_tpu.models.transformer_lm import init_lm_params

        return init_lm_params(jax.random.PRNGKey(0), vocab=32, d_model=16,
                              n_heads=2, n_experts=4, d_ff=32, n_layers=2)

    @pytest.mark.parametrize("src_fn,dst_fn", [
        (_mesh_dp_ep, _mesh_dp_sp_ep),
        (_mesh_dp_sp_ep, _mesh_dp_ep),
        (_mesh_dp_ep, None),   # -> single device
        (None, _mesh_dp_sp_ep),  # single device -> composed
    ])
    def test_live_matches_host_restore(self, tmp_path, src_fn, dst_fn):
        from deeplearning4j_tpu.models.transformer_lm import (
            lm_param_shardings,
            shard_lm_params,
        )
        from deeplearning4j_tpu.scaleout.ckpt.reshard import restore_sharded
        from deeplearning4j_tpu.scaleout.ckpt.sharded_io import save_sharded

        params = self._params()
        if src_fn is None:
            dev = jax.devices()[0]
            src = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, SingleDeviceSharding(dev)),
                params)
        else:
            src = shard_lm_params(params, src_fn())
        if dst_fn is None:
            dev = jax.devices()[0]
            dst_shardings = jax.tree_util.tree_map(
                lambda _: SingleDeviceSharding(dev), params)
        else:
            dst_shardings = lm_param_shardings(params, dst_fn())

        # host-callback oracle: save the SOURCE placement, restore onto dst
        step_dir = save_sharded(str(tmp_path), 0, src)
        # single-device targets restore unsharded through the host path
        oracle_shardings = None if dst_fn is None else dst_shardings
        oracle, _mf = restore_sharded(step_dir, params,
                                      shardings=oracle_shardings)

        live = redistribute_tree(src, dst_shardings)
        for (pa, a), (pb, b) in zip(
                jax.tree_util.tree_flatten_with_path(live)[0],
                jax.tree_util.tree_flatten_with_path(oracle)[0]):
            assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
            err = float(jnp.max(jnp.abs(jnp.asarray(a) - jnp.asarray(b))))
            assert err <= 1e-6, (jax.tree_util.keystr(pa), err)

    def test_live_lands_exact_dst_shardings(self):
        from deeplearning4j_tpu.models.transformer_lm import (
            lm_param_shardings,
            shard_lm_params,
        )

        params = self._params()
        src = shard_lm_params(params, _mesh_dp_ep())
        dst_shardings = lm_param_shardings(params, _mesh_dp_sp_ep())
        live = redistribute_tree(src, dst_shardings)
        for leaf, sh in zip(jax.tree_util.tree_leaves(live),
                            jax.tree_util.tree_leaves(dst_shardings)):
            assert leaf.sharding == sh


class TestRoundTripProperty:
    def test_randomized_round_trip_identity(self):
        """src→dst→src over randomized shardings is bitwise the identity
        (the plan property test: every derived program is invertible and
        lossless)."""
        mesh = _mesh_dp_sp_ep()
        axes = list(mesh.axis_names)
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(8, 8, 8)).astype(np.float32))

        def random_spec():
            remaining = list(axes)
            rng.shuffle(remaining)
            entries = []
            for _ in range(3):
                take = rng.integers(0, len(remaining) + 1)
                picked = tuple(remaining[:take])
                remaining = remaining[take:]
                entries.append(picked if picked else None)
            return P(*entries)

        for trial in range(8):
            src_spec, dst_spec = random_spec(), random_spec()
            src_sh = NamedSharding(mesh, src_spec)
            xa = jax.device_put(x, src_sh)
            there = redistribute(xa, NamedSharding(mesh, dst_spec))
            back = redistribute(there, src_sh)
            assert back.sharding == src_sh, (trial, src_spec, dst_spec)
            assert jnp.array_equal(jax.device_get(back), x), (
                trial, src_spec, dst_spec)


class TestLiveConsumers:
    def test_elastic_run_steps_device_params_match_host_params(self):
        """The elastic adoption fast path: run_steps fed the live
        device-committed tree must land bitwise the same trajectory as
        run_steps fed the same tree as host numpy."""
        from deeplearning4j_tpu.scaleout.elastic import (
            SyntheticRegressionModel,
        )

        model = SyntheticRegressionModel(d_in=8, d_hidden=16, batch=16,
                                         mesh_devices=2)
        p0 = model.init_params()
        host = jax.tree_util.tree_map(np.asarray, p0)
        p_host, l_host = model.run_steps(host, 0, 3, worker_seed=1)
        # device-committed twin (the carried-tree case)
        dev_tree = jax.tree_util.tree_map(jnp.asarray, host)
        p_dev, l_dev = model.run_steps(dev_tree, 0, 3, worker_seed=1)
        assert float(l_host) == float(l_dev)
        for a, b in zip(jax.tree_util.tree_leaves(p_host),
                        jax.tree_util.tree_leaves(p_dev)):
            assert jnp.array_equal(jnp.asarray(a), jnp.asarray(b))

    def test_engine_cold_start_from_live_sharded_params(self):
        """Serve any-mesh cold start: an engine adopted from a LIVE dp×ep
        sharded tree through the redistribution plans generates the same
        tokens as one built from the identical host tree."""
        from deeplearning4j_tpu.models.transformer_lm import (
            init_lm_params,
            shard_lm_params,
        )
        from deeplearning4j_tpu.serve.engine import DecodeEngine

        params = init_lm_params(jax.random.PRNGKey(3), vocab=32, d_model=16,
                                n_heads=2, n_experts=4, d_ff=32, n_layers=2)
        sharded = shard_lm_params(params, _mesh_dp_ep())
        kwargs = dict(n_slots=2, max_len=32, serve_dtype=None, seed=0)
        live = DecodeEngine.from_live_params(sharded, 2, **kwargs)
        host = DecodeEngine(params, 2, **kwargs)
        assert live.weight_version == "live-params"
        prompt = [1, 2, 3, 4]
        out_live = live.generate(prompt, max_new_tokens=6)
        out_host = host.generate(prompt, max_new_tokens=6)
        assert out_live == out_host and len(out_live) == 6
        # the adopted leaves really live on the serving device only
        dev = jax.devices()[0]
        for leaf in jax.tree_util.tree_leaves(live.params):
            assert leaf.sharding.device_set == {dev}
