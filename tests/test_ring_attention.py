"""Sequence-parallel attention tests on the 8-device CPU mesh
(conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
    sequence_sharding,
    ulysses_attention,
)


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("sp",))


def _qkv(b=2, h=4, t=64, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, t, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) * 0.5 for k in ks)


def test_ring_matches_dense(mesh):
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, "sp", causal=False)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_causal_matches_dense(mesh):
    q, k, v = _qkv(seed=1)
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_with_sharded_inputs(mesh):
    """Inputs already device_put with the sequence sharding: stays sharded."""
    q, k, v = _qkv(seed=2)
    sh = sequence_sharding(mesh, "sp")
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh, "sp", True))(
        qs, ks, vs
    )
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_grad_flows(mesh):
    q, k, v = _qkv(b=1, h=2, t=32, d=8, seed=3)

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, "sp", causal=True).sum()

    def ref_loss(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    g = jax.grad(loss)(q, k, v)
    gr = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["dense", "blockwise"])
def test_ring_prefetch_bit_identical_to_rotate_after(mesh, causal, impl):
    """ISSUE 14: rotate-then-attend on the double buffer (prefetch=True,
    the default) computes the IDENTICAL values as the historical
    rotate-after-attend body — output AND gradients, both cores, both
    mask modes. Only the trace order of the ppermute changes."""
    q, k, v = _qkv(b=1, h=2, t=64, d=8, seed=6)

    def run(prefetch):
        return ring_attention(q, k, v, mesh, "sp", causal=causal,
                              attn_impl=impl, prefetch=prefetch)

    out_pf, out_ra = run(True), run(False)
    assert jnp.array_equal(out_pf, out_ra)

    g_pf = jax.grad(lambda q, k, v: ring_attention(
        q, k, v, mesh, "sp", causal=causal, attn_impl=impl,
        prefetch=True).sum())(q, k, v)
    g_ra = jax.grad(lambda q, k, v: ring_attention(
        q, k, v, mesh, "sp", causal=causal, attn_impl=impl,
        prefetch=False).sum())(q, k, v)
    assert jnp.array_equal(g_pf, g_ra)


def test_composed_ring_prefetch_parity_dp_sp_ep():
    """The composed dp×sp×ep flagship step with the prefetch ring vs the
    rotate-after-attend oracle: loss AND updated params bit-identical
    (the ring_prefetch seam threading through make_composed_train_step)."""
    from deeplearning4j_tpu.models.transformer_lm import (
        init_lm_params,
        make_composed_train_step,
        shard_lm_batch,
        shard_lm_params,
    )

    cmesh = Mesh(np.array(jax.devices()).reshape(2, 2, 2),
                 ("data", "sp", "expert"))
    params = init_lm_params(jax.random.PRNGKey(0), vocab=32, d_model=16,
                            n_heads=2, n_experts=4, d_ff=32, n_layers=1)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, 32)
    tk, tg = toks[:, :-1], toks[:, 1:]

    def run(prefetch):
        p = shard_lm_params(
            jax.tree_util.tree_map(jnp.array, params), cmesh)
        stoks, stgts = shard_lm_batch(tk, tg, cmesh)
        step = make_composed_train_step(cmesh, 2, capacity=64,
                                        moe_impl="alltoall",
                                        ring_prefetch=prefetch)
        for _ in range(2):
            p, loss = step(p, stoks, stgts)
        return p, loss

    p_pf, l_pf = run(True)
    p_ra, l_ra = run(False)
    assert float(l_pf) == float(l_ra)
    for a, b in zip(jax.tree_util.tree_leaves(p_pf),
                    jax.tree_util.tree_leaves(p_ra)):
        assert jnp.array_equal(a, b)


def test_ulysses_matches_dense(mesh):
    q, k, v = _qkv(h=8, seed=4)  # 8 heads over 8 devices
    out = ulysses_attention(q, k, v, mesh, "sp", causal=False)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_causal_matches_dense(mesh):
    q, k, v = _qkv(h=8, seed=5)
    out = ulysses_attention(q, k, v, mesh, "sp", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(mesh):
    q, k, v = _qkv(h=4)  # 4 heads, 8 devices
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh, "sp")


def test_ring_blockwise_core_matches_dense(mesh):
    """The ring's per-rotated-block core forced to the blockwise
    online-softmax tiles (attn_impl seam) — same function as dense."""
    q, k, v = _qkv(seed=7)
    out = ring_attention(q, k, v, mesh, "sp", causal=True,
                         attn_impl="blockwise")
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    out_nc = ring_attention(q, k, v, mesh, "sp", causal=False,
                            attn_impl="blockwise")
    ref_nc = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out_nc), np.asarray(ref_nc),
                               atol=2e-5, rtol=2e-5)


def test_ring_blockwise_grad_flows(mesh):
    q, k, v = _qkv(b=1, h=2, t=32, d=8, seed=8)

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, "sp", causal=True,
                              attn_impl="blockwise").sum()

    def ref_loss(q, k, v):
        return reference_attention(q, k, v, causal=True).sum()

    g = jax.grad(loss)(q, k, v)
    gr = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               atol=5e-5, rtol=5e-5)


def test_ring_block_core_follows_global_override(mesh, monkeypatch):
    """set_attention_impl("blockwise") steers the RING's inner core (not
    just the dense dispatcher): the blockwise partials run with the global
    override visible as "blockwise" inside the block core — the composed
    dp×sp×ep acceptance assertion."""
    from deeplearning4j_tpu.ops import flash_attention as fa

    seen = {}
    orig = fa.blockwise_block_partials

    def spy(*args, **kwargs):
        seen["impl_inside_core"] = fa.get_attention_impl()
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "blockwise_block_partials", spy)
    q, k, v = _qkv(seed=9)
    try:
        fa.set_attention_impl("blockwise")
        out = ring_attention(q, k, v, mesh, "sp", causal=True)
    finally:
        fa.set_attention_impl(None)
    assert seen.get("impl_inside_core") == "blockwise"
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_block_core_follows_env_var(mesh, monkeypatch):
    """DL4J_TPU_ATTN_IMPL=blockwise reaches the ring core too — the no-code
    -edit switch the bench twins and dryrun_multichip rely on."""
    from deeplearning4j_tpu.ops import flash_attention as fa

    called = []
    orig = fa.blockwise_block_partials
    monkeypatch.setattr(fa, "blockwise_block_partials",
                        lambda *a, **k: (called.append(1), orig(*a, **k))[1])
    monkeypatch.setenv(fa.ATTN_IMPL_ENV, "blockwise")
    q, k, v = _qkv(seed=10)
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    assert called, "env var did not reach the ring block core"
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_blockwise_core_matches_dense(mesh):
    """ulysses' post-AllToAll attention through the core seam (the one sp
    variant outside the ring path)."""
    q, k, v = _qkv(h=8, seed=11)
    out = ulysses_attention(q, k, v, mesh, "sp", causal=True,
                            attn_impl="blockwise")
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_long_sequence_memory_shape(mesh):
    """T=1024 over 8 devices: per-device block is 128 — just verify it runs
    and matches on a slice (full dense ref is still fine at this size)."""
    q, k, v = _qkv(b=1, h=2, t=1024, d=8, seed=6)
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out)[0, 0, ::101],
                               np.asarray(ref)[0, 0, ::101],
                               atol=5e-5, rtol=5e-5)
