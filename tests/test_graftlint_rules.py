"""Golden fixtures for every graftlint rule: one known-bad and one
known-clean snippet each, pinned by rule id. These are the rule-level
contract; tests/test_graftlint_repo.py is the repo-level gate."""

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.graftlint import lint_source  # noqa: E402


def _rules_hit(src: str, path: str = "fixture.py"):
    return {f.rule for f in lint_source(textwrap.dedent(src), path)}


# ------------------------------------------------------------ jit-host-sync ----

def test_jit_host_sync_bad_inside_jit():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def step(params, x):
        y = params @ x
        norm = float(y.sum())          # host sync inside traced code
        host = np.asarray(y)           # materializes inside traced code
        return y / norm, host
    """
    assert "jit-host-sync" in _rules_hit(src)


def test_jit_host_sync_bad_scan_body():
    src = """
    import jax

    def epoch(params, xs):
        def body(carry, x):
            s = carry + x.sum().item()   # .item() in a lax.scan body
            return s, s
        return jax.lax.scan(body, params, xs)
    """
    assert "jit-host-sync" in _rules_hit(src)


def test_jit_host_sync_bad_host_loop_fetch():
    src = """
    import jax

    @jax.jit
    def train_step(params, x):
        return params - 0.1 * x, (params * x).sum()

    def fit(params, batches):
        total = 0.0
        for x in batches:
            params, loss = train_step(params, x)
            total += float(loss)       # per-step fetch serializes dispatch
        return params, total
    """
    assert "jit-host-sync" in _rules_hit(src)


def test_jit_host_sync_clean():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(params, x):
        y = params @ x
        return y / jnp.sum(y)

    def fit(params, batches):
        losses = []
        for x in batches:
            params, loss = step(params, x)
            losses.append(loss)        # stays on device
        return params, [float(l) for l in jax.device_get(losses)]
    """
    assert "jit-host-sync" not in _rules_hit(src)


# --------------------------------------------------------- untimed-dispatch ----

def test_untimed_dispatch_bad():
    src = """
    import time

    def bench(step, params, x):
        t0 = time.perf_counter()
        for _ in range(10):
            params, loss = step(params, x)
        return time.perf_counter() - t0   # clock stops at enqueue
    """
    assert "untimed-dispatch" in _rules_hit(src)


def test_untimed_dispatch_clean_block_until_ready():
    src = """
    import time
    import jax

    def bench(step, params, x):
        t0 = time.perf_counter()
        for _ in range(10):
            params, loss = step(params, x)
        jax.block_until_ready(params)
        return time.perf_counter() - t0
    """
    assert "untimed-dispatch" not in _rules_hit(src)


def test_untimed_dispatch_clean_scalar_fetch():
    src = """
    import time

    def bench(step, params, x):
        t0 = time.perf_counter()
        for _ in range(10):
            params, loss = step(params, x)
        last = float(loss)            # a device->host fetch is a true sync
        return time.perf_counter() - t0
    """
    assert "untimed-dispatch" not in _rules_hit(src)


# --------------------------------------------------------------- prng-reuse ----

def test_prng_reuse_bad_double_draw():
    src = """
    import jax

    def init(key):
        w1 = jax.random.normal(key, (4, 4))
        w2 = jax.random.normal(key, (4, 4))   # same key, same weights
        return w1, w2
    """
    assert "prng-reuse" in _rules_hit(src)


def test_prng_reuse_bad_loop_without_advance():
    src = """
    import jax

    def fit(step, params, key):
        key = jax.random.fold_in(key, 0)
        for i in range(10):
            params = step(params, key)   # identical randomness every step
        return params
    """
    assert "prng-reuse" in _rules_hit(src)


def test_prng_reuse_clean_split_and_branches():
    src = """
    import jax

    def fit(step, params, key):
        for i in range(10):
            key, sub = jax.random.split(key)
            params = step(params, sub)
        return params

    def init(key, kind):
        if kind == "normal":
            return jax.random.normal(key, (4,))
        return jax.random.uniform(key, (4,))   # other arm: exclusive
    """
    assert "prng-reuse" not in _rules_hit(src)


# -------------------------------------------------------------- stray-debug ----

def test_stray_debug_bad():
    src = """
    import jax

    @jax.jit
    def step(params, x):
        loss = (params * x).sum()
        print("loss", loss)            # fires at trace time only
        jax.debug.print("loss {}", loss)
        return loss
    """
    assert "stray-debug" in _rules_hit(src)


def test_stray_debug_clean_host_side():
    src = """
    import jax

    @jax.jit
    def step(params, x):
        return (params * x).sum()

    def fit(params, x):
        loss = step(params, x)
        print("loss", float(loss))     # host-side logging is fine
        return loss
    """
    assert "stray-debug" not in _rules_hit(src)


# ------------------------------------------------------------ nondet-pytree ----

def test_nondet_pytree_bad():
    src = """
    def build_params(names, init):
        return {n: init(n) for n in set(names)}   # nondeterministic order
    """
    assert "nondet-pytree" in _rules_hit(src)


def test_nondet_pytree_clean_sorted():
    src = """
    def build_params(names, init):
        return {n: init(n) for n in sorted(set(names))}
    """
    assert "nondet-pytree" not in _rules_hit(src)


# -------------------------------------------------------- env-read-in-trace ----

def test_env_read_bad():
    src = """
    import os

    def configure():
        return os.environ.get("MY_RANDOM_KNOB", "0") == "1"
    """
    assert "env-read-in-trace" in _rules_hit(src)


def test_env_read_clean_blessed():
    src = """
    import os

    ATTN_ENV = "DL4J_TPU_ATTN_IMPL"

    def configure():
        a = os.environ.get("DL4J_TPU_FOO")     # blessed namespace literal
        b = os.environ.get(ATTN_ENV)           # blessed via in-file constant
        return a, b
    """
    assert "env-read-in-trace" not in _rules_hit(src)


def test_env_read_clean_in_compat():
    src = """
    import os

    def bridge():
        return os.environ.get("ANYTHING_GOES")
    """
    assert "env-read-in-trace" not in _rules_hit(src, path="compat.py")


# ------------------------------------------------------------ missing-donate ----

def test_missing_donate_bad():
    src = """
    import jax

    @jax.jit
    def train_step(params, x):
        return params - 0.1 * x
    """
    assert "missing-donate" in _rules_hit(src)


def test_missing_donate_clean_donated_and_explicit_decline():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(params, x):
        return params - 0.1 * x

    @partial(jax.jit, donate_argnums=())   # considered, declined
    def oracle_step(params, x):
        return params - 0.1 * x
    """
    assert "missing-donate" not in _rules_hit(src)


# ------------------------------------------------------------- suppression ----

def test_inline_allow_requires_reason():
    bad = """
    import os

    def configure():
        return os.environ.get("KNOB")  # graftlint: allow[env-read-in-trace]
    """
    assert "env-read-in-trace" in _rules_hit(bad), \
        "a reason-less allow must NOT suppress"
    good = """
    import os

    def configure():
        return os.environ.get("KNOB")  # graftlint: allow[env-read-in-trace] deliberate seam because reasons
    """
    assert "env-read-in-trace" not in _rules_hit(good)


def test_parse_error_is_a_finding_not_a_crash():
    assert _rules_hit("def broken(:\n") == {"parse-error"}
