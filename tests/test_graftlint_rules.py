"""Golden fixtures for every graftlint rule: one known-bad and one
known-clean snippet each, pinned by rule id. These are the rule-level
contract; tests/test_graftlint_repo.py is the repo-level gate."""

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.graftlint import lint_source  # noqa: E402


def _rules_hit(src: str, path: str = "fixture.py"):
    return {f.rule for f in lint_source(textwrap.dedent(src), path)}


# ------------------------------------------------------------ jit-host-sync ----

def test_jit_host_sync_bad_inside_jit():
    src = """
    import jax
    import numpy as np

    @jax.jit
    def step(params, x):
        y = params @ x
        norm = float(y.sum())          # host sync inside traced code
        host = np.asarray(y)           # materializes inside traced code
        return y / norm, host
    """
    assert "jit-host-sync" in _rules_hit(src)


def test_jit_host_sync_bad_scan_body():
    src = """
    import jax

    def epoch(params, xs):
        def body(carry, x):
            s = carry + x.sum().item()   # .item() in a lax.scan body
            return s, s
        return jax.lax.scan(body, params, xs)
    """
    assert "jit-host-sync" in _rules_hit(src)


def test_jit_host_sync_bad_host_loop_fetch():
    src = """
    import jax

    @jax.jit
    def train_step(params, x):
        return params - 0.1 * x, (params * x).sum()

    def fit(params, batches):
        total = 0.0
        for x in batches:
            params, loss = train_step(params, x)
            total += float(loss)       # per-step fetch serializes dispatch
        return params, total
    """
    assert "jit-host-sync" in _rules_hit(src)


def test_jit_host_sync_clean():
    src = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(params, x):
        y = params @ x
        return y / jnp.sum(y)

    def fit(params, batches):
        losses = []
        for x in batches:
            params, loss = step(params, x)
            losses.append(loss)        # stays on device
        return params, [float(l) for l in jax.device_get(losses)]
    """
    assert "jit-host-sync" not in _rules_hit(src)


# --------------------------------------------------------- untimed-dispatch ----

def test_untimed_dispatch_bad():
    src = """
    import time

    def bench(step, params, x):
        t0 = time.perf_counter()
        for _ in range(10):
            params, loss = step(params, x)
        return time.perf_counter() - t0   # clock stops at enqueue
    """
    assert "untimed-dispatch" in _rules_hit(src)


def test_untimed_dispatch_clean_block_until_ready():
    src = """
    import time
    import jax

    def bench(step, params, x):
        t0 = time.perf_counter()
        for _ in range(10):
            params, loss = step(params, x)
        jax.block_until_ready(params)
        return time.perf_counter() - t0
    """
    assert "untimed-dispatch" not in _rules_hit(src)


def test_untimed_dispatch_clean_scalar_fetch():
    src = """
    import time

    def bench(step, params, x):
        t0 = time.perf_counter()
        for _ in range(10):
            params, loss = step(params, x)
        last = float(loss)            # a device->host fetch is a true sync
        return time.perf_counter() - t0
    """
    assert "untimed-dispatch" not in _rules_hit(src)


# --------------------------------------------------------------- prng-reuse ----

def test_prng_reuse_bad_double_draw():
    src = """
    import jax

    def init(key):
        w1 = jax.random.normal(key, (4, 4))
        w2 = jax.random.normal(key, (4, 4))   # same key, same weights
        return w1, w2
    """
    assert "prng-reuse" in _rules_hit(src)


def test_prng_reuse_bad_loop_without_advance():
    src = """
    import jax

    def fit(step, params, key):
        key = jax.random.fold_in(key, 0)
        for i in range(10):
            params = step(params, key)   # identical randomness every step
        return params
    """
    assert "prng-reuse" in _rules_hit(src)


def test_prng_reuse_clean_split_and_branches():
    src = """
    import jax

    def fit(step, params, key):
        for i in range(10):
            key, sub = jax.random.split(key)
            params = step(params, sub)
        return params

    def init(key, kind):
        if kind == "normal":
            return jax.random.normal(key, (4,))
        return jax.random.uniform(key, (4,))   # other arm: exclusive
    """
    assert "prng-reuse" not in _rules_hit(src)


# -------------------------------------------------------------- stray-debug ----

def test_stray_debug_bad():
    src = """
    import jax

    @jax.jit
    def step(params, x):
        loss = (params * x).sum()
        print("loss", loss)            # fires at trace time only
        jax.debug.print("loss {}", loss)
        return loss
    """
    assert "stray-debug" in _rules_hit(src)


def test_stray_debug_clean_host_side():
    src = """
    import jax

    @jax.jit
    def step(params, x):
        return (params * x).sum()

    def fit(params, x):
        loss = step(params, x)
        print("loss", float(loss))     # host-side logging is fine
        return loss
    """
    assert "stray-debug" not in _rules_hit(src)


# ------------------------------------------------------------ nondet-pytree ----

def test_nondet_pytree_bad():
    src = """
    def build_params(names, init):
        return {n: init(n) for n in set(names)}   # nondeterministic order
    """
    assert "nondet-pytree" in _rules_hit(src)


def test_nondet_pytree_clean_sorted():
    src = """
    def build_params(names, init):
        return {n: init(n) for n in sorted(set(names))}
    """
    assert "nondet-pytree" not in _rules_hit(src)


# -------------------------------------------------------- env-read-in-trace ----

def test_env_read_bad():
    src = """
    import os

    def configure():
        return os.environ.get("MY_RANDOM_KNOB", "0") == "1"
    """
    assert "env-read-in-trace" in _rules_hit(src)


def test_env_read_clean_blessed():
    src = """
    import os

    ATTN_ENV = "DL4J_TPU_ATTN_IMPL"

    def configure():
        a = os.environ.get("DL4J_TPU_FOO")     # blessed namespace literal
        b = os.environ.get(ATTN_ENV)           # blessed via in-file constant
        return a, b
    """
    assert "env-read-in-trace" not in _rules_hit(src)


def test_env_read_clean_in_compat():
    src = """
    import os

    def bridge():
        return os.environ.get("ANYTHING_GOES")
    """
    assert "env-read-in-trace" not in _rules_hit(src, path="compat.py")


# ------------------------------------------------------------ missing-donate ----

def test_missing_donate_bad():
    src = """
    import jax

    @jax.jit
    def train_step(params, x):
        return params - 0.1 * x
    """
    assert "missing-donate" in _rules_hit(src)


def test_missing_donate_clean_donated_and_explicit_decline():
    src = """
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(params, x):
        return params - 0.1 * x

    @partial(jax.jit, donate_argnums=())   # considered, declined
    def oracle_step(params, x):
        return params - 0.1 * x
    """
    assert "missing-donate" not in _rules_hit(src)


# ------------------------------------------------------------- suppression ----

def test_inline_allow_requires_reason():
    bad = """
    import os

    def configure():
        return os.environ.get("KNOB")  # graftlint: allow[env-read-in-trace]
    """
    assert "env-read-in-trace" in _rules_hit(bad), \
        "a reason-less allow must NOT suppress"
    good = """
    import os

    def configure():
        return os.environ.get("KNOB")  # graftlint: allow[env-read-in-trace] deliberate seam because reasons
    """
    assert "env-read-in-trace" not in _rules_hit(good)


def test_parse_error_is_a_finding_not_a_crash():
    assert _rules_hit("def broken(:\n") == {"parse-error"}


# ============================================================================
# Concurrency rules (ISSUE 11) — bad+clean golden fixtures per rule, kept in
# module-level dicts so the meta-test below can pin that EVERY registered
# rule ships fixtures (a future rule cannot land unpinned).

BAD_FIXTURES = {
    "jit-host-sync": """
        import jax

        @jax.jit
        def step(params, x):
            return float((params * x).sum())
    """,
    "untimed-dispatch": """
        import time

        def bench(step, params, x):
            t0 = time.perf_counter()
            params, loss = step(params, x)
            return time.perf_counter() - t0
    """,
    "prng-reuse": """
        import jax

        def init(key):
            w1 = jax.random.normal(key, (4, 4))
            w2 = jax.random.normal(key, (4, 4))
            return w1, w2
    """,
    "stray-debug": """
        import jax

        @jax.jit
        def step(x):
            print("x", x)
            return x
    """,
    "nondet-pytree": """
        def build(names, init):
            return {n: init(n) for n in set(names)}
    """,
    "env-read-in-trace": """
        import os

        def configure():
            return os.environ.get("SOME_RANDOM_KNOB")
    """,
    "missing-donate": """
        import jax

        @jax.jit
        def train_step(params, x):
            return params - 0.1 * x
    """,
    "unguarded-shared-state": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._thread = threading.Thread(target=self._loop)

            def start(self):
                self._thread.start()

            def _loop(self):
                while True:
                    self.count += 1       # thread-side write, no lock

            def snapshot(self):
                with self._lock:
                    return self.count     # a lock the writer never takes

            def stop(self):
                self._thread.join()
    """,
    "lock-order": """
        import threading

        a = threading.Lock()
        b = threading.Lock()

        def one():
            with a:
                with b:
                    pass

        def two():
            with b:
                with a:                   # reversed: deadlock risk
                    pass
    """,
    "blocking-under-lock": """
        import threading
        import time

        class Poller:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self._sock = sock

            def poll(self):
                with self._lock:
                    return self._sock.recv(1024)   # blocks all contenders

            def backoff(self):
                with self._lock:
                    time.sleep(1.0)
    """,
    "unjoined-thread": """
        import threading

        class Sampler:
            def start(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                pass

            def stop(self):
                pass                       # no join: teardown races _run
    """,
    "condition-wait-no-predicate": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._ready = threading.Event()
                self.item = None

            def get(self):
                with self._cond:
                    self._cond.wait(1.0)   # spurious wakeup -> None
                    return self.item

            def get_event(self):
                self._ready.wait(0.5)      # result discarded
                return self.item
    """,
    "socket-no-timeout": """
        import socket
        import threading

        class Poller:
            def start(self):
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

            def _loop(self):
                sock = socket.socket()
                sock.connect(("127.0.0.1", 9000))  # no timeout anywhere
                return sock.recv(1024)

            def stop(self):
                self._thread.join(timeout=10)
    """,
    "unbounded-retry": """
        def fetch(sock):
            while True:
                try:
                    return sock.recv(1024)
                except ConnectionError:
                    continue              # dead peer -> infinite spin
    """,
    "retry-no-backoff": """
        def fetch(sock):
            for attempt in range(5):
                try:
                    return sock.recv(1024)
                except ConnectionError:
                    continue              # re-enters at CPU speed
            raise ConnectionError("gave up")
    """,
    "swallowed-thread-exception": """
        import threading

        class Pusher:
            def start(self):
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

            def _loop(self):
                try:
                    self._push()
                except Exception:
                    pass                  # the pusher dies invisibly

            def _push(self):
                pass

            def stop(self):
                self._thread.join(timeout=10)
    """,
    "nonidempotent-retry": """
        _IDEMPOTENT = frozenset({"get_kv", "put_kv"})
        _NONIDEMPOTENT = frozenset({"increment"})

        class Client:
            def _call(self, method, *args):
                return method, args

            def get_kv(self, key):
                return self._call("get_kv", key)

            def clear_all(self):
                return self._call("clear_all")  # classified by nobody
    """,
}

CLEAN_FIXTURES = {
    "jit-host-sync": """
        import jax

        @jax.jit
        def step(params, x):
            return (params * x).sum()
    """,
    "untimed-dispatch": """
        import time
        import jax

        def bench(step, params, x):
            t0 = time.perf_counter()
            params, loss = step(params, x)
            jax.block_until_ready(loss)
            return time.perf_counter() - t0
    """,
    "prng-reuse": """
        import jax

        def init(key):
            k1, k2 = jax.random.split(key)
            return jax.random.normal(k1, (4, 4)), jax.random.normal(k2, (4, 4))
    """,
    "stray-debug": """
        import jax

        @jax.jit
        def step(x):
            return x

        def fit(x):
            y = step(x)
            print("y", float(y))
            return y
    """,
    "nondet-pytree": """
        def build(names, init):
            return {n: init(n) for n in sorted(set(names))}
    """,
    "env-read-in-trace": """
        import os

        def configure():
            return os.environ.get("DL4J_TPU_SOME_KNOB")
    """,
    "missing-donate": """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def train_step(params, x):
            return params - 0.1 * x
    """,
    "unguarded-shared-state": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._thread = threading.Thread(target=self._loop)

            def start(self):
                self._thread.start()

            def _loop(self):
                while True:
                    with self._lock:
                        self.count += 1

            def snapshot(self):
                with self._lock:
                    return self.count

            def stop(self):
                self._thread.join()
    """,
    "lock-order": """
        import threading

        a = threading.Lock()
        b = threading.Lock()

        def one():
            with a:
                with b:
                    pass

        def two():
            with a:                        # same global order everywhere
                with b:
                    pass
    """,
    "blocking-under-lock": """
        import threading

        class Poller:
            def __init__(self, sock):
                self._lock = threading.Lock()
                self._sock = sock
                self._last = None

            def poll(self):
                data = self._sock.recv(1024)   # blocks OUTSIDE the lock
                with self._lock:
                    self._last = data
                return data
    """,
    "unjoined-thread": """
        import threading

        class Sampler:
            def start(self):
                self._stop = threading.Event()
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()

            def _run(self):
                while not self._stop.wait(0.1):
                    pass

            def stop(self):
                self._stop.set()
                self._thread.join(timeout=10)
    """,
    "condition-wait-no-predicate": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._ready = threading.Event()
                self.item = None

            def get(self):
                with self._cond:
                    while self.item is None:   # predicate re-checked
                        self._cond.wait(1.0)
                    return self.item

            def get_event(self):
                if not self._ready.wait(0.5):  # result checked
                    raise TimeoutError
                return self.item
    """,
    "socket-no-timeout": """
        import socket
        import threading

        from deeplearning4j_tpu.utils import netwatch

        class Poller:
            def start(self):
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

            def _loop(self):
                sock = socket.create_connection(("127.0.0.1", 9000),
                                                timeout=5.0)
                sock.settimeout(5.0)
                data = sock.recv(1024)
                watched = netwatch.make_socket("poller.peer")
                watched.connect(("127.0.0.1", 9001))  # seam: default timed
                return data + watched.recv(1024)

            def stop(self):
                self._thread.join(timeout=10)
    """,
    "unbounded-retry": """
        import time

        def fetch(sock):
            for attempt in range(3):           # attempt budget
                try:
                    return sock.recv(1024)
                except ConnectionError:
                    time.sleep(0.1 * (attempt + 1))
            raise ConnectionError("gave up")

        def poll(sock, deadline):
            while True:
                if time.monotonic() > deadline:  # deadline guard
                    raise TimeoutError("poll deadline")
                try:
                    return sock.recv(1024)
                except ConnectionError:
                    time.sleep(0.05)
    """,
    "retry-no-backoff": """
        import random
        import time

        def fetch(sock):
            for attempt in range(5):
                try:
                    return sock.recv(1024)
                except ConnectionError:
                    time.sleep(0.05 * (2 ** attempt)
                               * (0.5 + random.random() / 2))
            raise ConnectionError("gave up")
    """,
    "swallowed-thread-exception": """
        import logging
        import threading

        log = logging.getLogger(__name__)

        class Pusher:
            def start(self):
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

            def _loop(self):
                try:
                    self._push()
                except Exception as exc:
                    log.warning("pusher died: %r", exc)

            def _push(self):
                pass

            def stop(self):
                self._thread.join(timeout=10)
    """,
    "nonidempotent-retry": """
        _IDEMPOTENT = frozenset({"get_kv", "put_kv"})
        _NONIDEMPOTENT = frozenset({"increment"})

        class Client:
            def _call(self, method, *args):
                return method, args

            def get_kv(self, key):
                return self._call("get_kv", key)

            def increment(self, key):
                return self._call("increment", key)
    """,
}


def _rule_params():
    import pytest as _pytest

    from tools.graftlint import RULES

    return _pytest.mark.parametrize("rule", sorted(RULES))


@_rule_params()
def test_bad_fixture_trips_its_rule(rule):
    assert rule in BAD_FIXTURES, f"no bad golden fixture for rule {rule!r}"
    assert rule in _rules_hit(BAD_FIXTURES[rule]), (
        f"the bad fixture for {rule!r} no longer trips it")


@_rule_params()
def test_clean_fixture_passes_its_rule(rule):
    assert rule in CLEAN_FIXTURES, f"no clean golden fixture for {rule!r}"
    assert rule not in _rules_hit(CLEAN_FIXTURES[rule]), (
        f"the clean fixture for {rule!r} falsely trips it")


def test_every_registered_rule_has_fixtures():
    """The meta-pin: a rule cannot register without shipping bad+clean
    goldens here — future rules land pinned or not at all."""
    from tools.graftlint import RULES

    assert set(BAD_FIXTURES) == set(RULES), (
        f"BAD_FIXTURES out of sync with the registry: "
        f"missing={set(RULES) - set(BAD_FIXTURES)}, "
        f"orphaned={set(BAD_FIXTURES) - set(RULES)}")
    assert set(CLEAN_FIXTURES) == set(RULES), (
        f"CLEAN_FIXTURES out of sync with the registry: "
        f"missing={set(RULES) - set(CLEAN_FIXTURES)}, "
        f"orphaned={set(CLEAN_FIXTURES) - set(RULES)}")


# ----------------------------------------- concurrency rule edge behavior ----

def test_condition_alias_guards_shared_state():
    """`Condition(self._lock)` IS the lock: guarding via the condition on
    one side and the lock on the other shares one underlying mutex."""
    src = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.RLock()
            self._work = threading.Condition(self._lock)
            self.queue = []
            self._thread = threading.Thread(target=self._loop)

        def start(self):
            self._thread.start()

        def submit(self, item):
            with self._work:
                self.queue.append(item)
                self._work.notify_all()

        def _loop(self):
            with self._lock:
                if self.queue:
                    self.queue.pop(0)

        def stop(self):
            self._thread.join()
    """
    assert "unguarded-shared-state" not in _rules_hit(src)


def test_lock_propagates_through_private_helpers():
    """A helper only ever called under the lock inherits the guard — the
    DecodeEngine._accept_token shape must not false-positive."""
    src = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.total = 0
            self._thread = threading.Thread(target=self._loop)

        def start(self):
            self._thread.start()

        def _bump(self):
            self.total += 1            # guarded at every call site

        def _loop(self):
            with self._lock:
                self._bump()

        def read(self):
            with self._lock:
                return self.total

        def stop(self):
            self._thread.join()
    """
    assert "unguarded-shared-state" not in _rules_hit(src)


def test_blocking_under_lock_allows_condition_wait():
    src = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition(self._lock)
            self.item = None

        def get(self):
            with self._cond:
                while self.item is None:
                    self._cond.wait(0.1)   # releases while waiting: fine
                return self.item
    """
    assert "blocking-under-lock" not in _rules_hit(src)


def test_unjoined_thread_join_via_local_swap():
    """`t, self._thread = self._thread, None` then `t.join()` counts as a
    join path (the DecodeEngine.stop shape)."""
    src = """
    import threading

    class Engine:
        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            pass

        def stop(self):
            t, self._thread = self._thread, None
            if t is not None:
                t.join(timeout=10)
    """
    assert "unjoined-thread" not in _rules_hit(src)


def test_unjoined_thread_joined_via_list_loop():
    src = """
    import threading

    def fan_out(work):
        threads = [threading.Thread(target=w) for w in work]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    """
    assert "unjoined-thread" not in _rules_hit(src)


# ------------------------------------- net rule edge behavior (ISSUE 18) ----

def test_socket_timeout_propagates_through_alias():
    """`t = s; t.settimeout(5)` times the ONE underlying OS socket —
    reads through either name are clean."""
    src = """
    import socket
    import threading

    class Poller:
        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            raw = socket.socket()
            sock = raw
            sock.settimeout(5.0)
            return raw.recv(1024)      # timed through the alias

        def stop(self):
            self._thread.join(timeout=10)
    """
    assert "socket-no-timeout" not in _rules_hit(src)


def test_socket_timeout_propagates_through_call_params():
    """A module helper's socket parameter inherits timed-ness from its
    call sites: untimed at any site -> the helper's reads fire; timed at
    every site -> clean (the _recv_frame/_recv_exact chain shape)."""
    bad = """
    import socket
    import threading

    def _read(sock):
        return sock.recv(1024)

    class Poller:
        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            sock = socket.socket()
            return _read(sock)

        def stop(self):
            self._thread.join(timeout=10)
    """
    assert "socket-no-timeout" in _rules_hit(bad)
    good = """
    import socket
    import threading

    def _read(sock):
        return sock.recv(1024)

    class Poller:
        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            sock = socket.socket()
            sock.settimeout(5.0)
            return _read(sock)

        def stop(self):
            self._thread.join(timeout=10)
    """
    assert "socket-no-timeout" not in _rules_hit(good)


def test_netwatch_seam_is_timed_by_construction():
    """A socket adopted through utils.netwatch.wrap_socket carries the
    watch's enforced default — timed without a visible settimeout."""
    src = """
    import threading

    from deeplearning4j_tpu.utils import netwatch

    class Client:
        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            self._sock = netwatch.wrap_socket(self._dial(), "client")
            return self._sock.recv(1024)

        def _dial(self):
            return None

        def stop(self):
            self._thread.join(timeout=10)
    """
    assert "socket-no-timeout" not in _rules_hit(src)


def test_setdefaulttimeout_clears_the_module():
    src = """
    import socket
    import threading

    socket.setdefaulttimeout(10.0)

    def _loop():
        sock = socket.socket()
        return sock.recv(1024)

    def start():
        threading.Thread(target=_loop, daemon=True).start()
    """
    assert "socket-no-timeout" not in _rules_hit(src)


def test_handler_request_socket_needs_timeout():
    """socketserver handler: self.request IS the accepted socket; a
    `timeout` class attribute (or an explicit settimeout) times it."""
    bad = """
    import socketserver

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                data = self.request.recv(1024)
                if not data:
                    return
                self.request.sendall(data)
    """
    assert "socket-no-timeout" in _rules_hit(bad)
    good = """
    import socketserver

    class Handler(socketserver.BaseRequestHandler):
        timeout = 300

        def handle(self):
            while True:
                data = self.request.recv(1024)
                if not data:
                    return
                self.request.sendall(data)
    """
    assert "socket-no-timeout" not in _rules_hit(good)


def test_foreach_skip_scan_is_not_a_retry():
    """`except ... : continue` over a collection ADVANCES to the next
    item — only range()/count() loops (attempt budgets) and while loops
    are retry-shaped."""
    src = """
    def sweep(socks):
        out = []
        for sock in socks:
            try:
                out.append(sock.recv(1024))
            except ConnectionError:
                continue               # next peer, not a re-issue
        return out
    """
    hits = _rules_hit(src)
    assert "unbounded-retry" not in hits
    assert "retry-no-backoff" not in hits


def test_nonidempotent_contract_only_binds_declaring_modules():
    src = """
    class Client:
        def _call(self, method):
            return method

        def anything(self):
            return self._call("anything")   # no contract declared: free
    """
    assert "nonidempotent-retry" not in _rules_hit(src)
