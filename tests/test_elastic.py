"""ISSUE 6 fault matrix: elastic parameter-averaging training under
injected failures, plus the hardened tracker transport.

The multi-process tests spawn REAL worker OS processes through the elastic
worker CLI and compare the master's final averaged params against
``simulate_elastic`` — an in-process oracle that replays the identical
round protocol (same adoption, same local-step indexing, same
``average_trees`` float64 math), so survivor-set parity bounds are
checkpoint-grade (1e-6), not statistical.

Split: one fast kill/recover smoke stays in tier-1; the wider matrix
(post-contribution kill, rejoin, staleness run-ahead) is ``slow``. Every
subprocess wait carries an explicit timeout so a wedged cluster fails the
test instead of hanging CI.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import _dist_helpers
from deeplearning4j_tpu.scaleout.elastic import (
    VERSION_KEY,
    ElasticMaster,
    ElasticWorker,
    _contrib_key,
    simulate_elastic,
)
from deeplearning4j_tpu.scaleout.remote_tracker import (
    StateTrackerClient,
    StateTrackerServer,
    TrackerUnavailable,
)
from deeplearning4j_tpu.telemetry import trace as trace_mod
from deeplearning4j_tpu.telemetry.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")
if REPO not in sys.path:
    sys.path.insert(0, REPO)  # tools.trace_report import below

from tools.trace_report import build_timeline, load_trace_dir  # noqa: E402

SYNC = 3


def _model(**kw):
    return _dist_helpers.elastic_toy_model(**kw)


def _spawn_worker(address, blob_uri, worker_id, seed, sync_every=SYNC,
                  extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = (
        f"{REPO}{os.pathsep}{TESTS}{os.pathsep}" + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, "-m", "deeplearning4j_tpu.scaleout.elastic",
           "--connect", address, "--blob", blob_uri,
           "--model", "_dist_helpers:elastic_toy_model",
           "--worker-id", worker_id, "--worker-seed", str(seed),
           "--sync-every", str(sync_every), "--round-timeout-s", "90",
           *extra]
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _finish(procs, master, timeout=120):
    outs = []
    try:
        for p in procs:
            try:
                outs.append(p.communicate(timeout=timeout))
            except subprocess.TimeoutExpired:
                p.kill()
                outs.append(p.communicate())
    finally:
        master.shutdown()
    return outs


def _assert_tree_close(a, b, atol, what):
    import jax

    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        err = float(np.max(np.abs(np.asarray(la) - np.asarray(lb))))
        assert err <= atol, f"{what}: leaf diff {err}"


def _worker_summary(out: str, worker_id: str) -> dict:
    lines = [ln for ln in out.splitlines()
             if ln.startswith("ELASTIC_WORKER_DONE")]
    assert lines, f"no completion line from {worker_id}: {out[-500:]}"
    return json.loads(lines[-1].split(None, 1)[1])


# ---------------------------------------------------- kill -9 mid-round ----

def test_elastic_kill_recover_smoke(tmp_path, lockwatch):
    """Tier-1 smoke for acceptance (a): one of two REAL worker processes
    hard-exits mid-round (before publishing — its delta is unsynced), the
    master deregisters it on heartbeat staleness and commits every round
    on the survivor set. Final averaged params match the survivor-set
    oracle to 1e-6 and ``workers_failed`` is incremented.

    ISSUE 7 rides the same run: every process traces into a shared dir,
    and the kill -9 must leave forensics, not silence — the victim's
    flight-recorder dump (written ahead at registration), its UNCLOSED
    round-0 spans on disk, and a trace_report timeline that merges all
    three processes with barrier-wait attribution.

    ISSUE 11 rides it too (armed ``lockwatch``): the master-side control
    plane — embedded tracker state lock, registry, tracer — runs on
    watched primitives with cycle detection raising, so a lock-order
    inversion between the master's heartbeat scan and a handler thread
    fails loudly here instead of deadlocking a fleet."""
    blob = f"file://{tmp_path / 'blob'}"
    trace_dir = str(tmp_path / "trace")
    prev = trace_mod.set_tracer(trace_mod.Tracer(
        "master", trace_dir=trace_dir, registry=MetricsRegistry()))
    try:
        master = ElasticMaster(_model(), blob, sync_every=SYNC,
                               min_workers=1, worker_timeout_s=2.0,
                               register_timeout_s=120, round_timeout_s=120)
        procs = [
            _spawn_worker(master.address, blob, "survivor", seed=1,
                          extra=["--trace-dir", trace_dir]),
            _spawn_worker(master.address, blob, "victim", seed=2,
                          extra=["--crash-at-round", "0",
                                 "--crash-after-steps", "1",
                                 "--trace-dir", trace_dir]),
        ]
        try:
            master.wait_for_workers(2)  # both registered before the kill
            final = master.train(rounds=3)
        finally:
            outs = _finish(procs, master)
    finally:
        trace_mod.set_tracer(prev)
    watch = lockwatch.summary()
    assert watch["cycles"] == 0 and watch["watchdog_dumps"] == 0
    assert watch["locks"].get("tracker.state", {}).get("acquires", 0) > 0, \
        "the embedded tracker's state lock was not watched"
    assert procs[1].returncode == 23, outs[1][1][-500:]  # the os._exit mark
    assert master.tracker.count("workers_failed") == 1
    assert "victim" not in master.tracker.workers()
    assert int(master.tracker.count(VERSION_KEY)) == 3
    ref, _ = simulate_elastic(_model(), [1], sync_every=SYNC, rounds=3)
    _assert_tree_close(final, ref, 1e-6, "survivor-set parity")
    # the survivor exited cleanly on the done flag, not by being killed
    assert procs[0].returncode == 0, outs[0][1][-500:]

    # ---- forensics (ISSUE 7 acceptance) ----
    for proc_name in ("master", "survivor", "victim"):
        assert os.path.exists(
            os.path.join(trace_dir, f"spans_{proc_name}.jsonl")), proc_name
    # the kill -9 victim cannot run hooks; its write-ahead dump (from
    # registration) must exist anyway
    victim_dump = os.path.join(trace_dir, "flightrec_victim.json")
    assert os.path.exists(victim_dump)
    assert json.load(open(victim_dump))["reason"] == "checkpoint"
    spans = load_trace_dir(trace_dir)
    victim_open = [sp for sp in spans.values()
                   if sp.get("process") == "victim"
                   and sp.get("status") == "open"]
    assert any(sp["name"] == "worker.round" for sp in victim_open), (
        "victim died mid-round: its round span must be reconstructed as "
        f"open, got {[s['name'] for s in victim_open]}")
    timeline = build_timeline(spans)
    committed = [r for r in timeline["rounds"]
                 if r["status"] == "committed"]
    assert [r["round"] for r in committed] == [0, 1, 2]
    r0 = committed[0]
    # round 0's merged view: the survivor contributed, the victim's
    # unclosed spans are attributed to the round it died in
    assert [a["worker"] for a in r0["contributors"]] == ["survivor"]
    assert "victim:worker.round" in r0["open_spans"]
    assert r0["straggler"] == "survivor"
    # cross-process link: a survivor round span parents under a master
    # round span (the ctx rode the published blob meta)
    master_rounds = {sp["span_id"] for sp in spans.values()
                     if sp["name"] == "elastic.round"}
    worker_rounds = [sp for sp in spans.values()
                     if sp["name"] == "worker.round"
                     and sp.get("process") == "survivor"]
    assert worker_rounds and all(
        sp.get("parent_id") in master_rounds for sp in worker_rounds)
    # the CLI renders the same reconstruction
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_report.py"),
         trace_dir, "--chrome", str(tmp_path / "chrome.json")],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert "victim:worker.round" in out.stdout
    chrome = json.load(open(tmp_path / "chrome.json"))
    assert len(chrome["traceEvents"]) > 10


@pytest.mark.slow
def test_elastic_kill_after_contributing_keeps_synced_work(tmp_path):
    """The DeepSpark cost model, pinned: a worker that dies in round 1
    loses ONLY its unsynced round-1 delta — its round-0 contribution stays
    in the average. Oracle: both workers contribute round 0, survivor only
    from round 1 on."""
    blob = f"file://{tmp_path / 'blob'}"
    master = ElasticMaster(_model(), blob, sync_every=SYNC, min_workers=1,
                           worker_timeout_s=2.0, register_timeout_s=120,
                           round_timeout_s=120)
    procs = [
        _spawn_worker(master.address, blob, "survivor", seed=1),
        _spawn_worker(master.address, blob, "victim", seed=2,
                      extra=["--crash-at-round", "1",
                             "--crash-after-steps", "2"]),
    ]
    try:
        master.wait_for_workers(2)
        final = master.train(rounds=4)
    finally:
        outs = _finish(procs, master)
    assert procs[1].returncode == 23, outs[1][1][-500:]
    assert master.tracker.count("workers_failed") == 1
    # seeds [survivor=1, victim=2]; round 0 both, then survivor alone
    ref, _ = simulate_elastic(
        _model(), [1, 2], sync_every=SYNC, rounds=4,
        schedule={0: [0, 1], 1: [0], 2: [0], 3: [0]})
    _assert_tree_close(final, ref, 1e-6, "synced-work-kept parity")


# ------------------------------------------------------------- rejoin ----

@pytest.mark.slow
def test_elastic_rejoin_readmitted_at_current_step(tmp_path):
    """Acceptance (b): a replacement worker that connects mid-run pulls
    the current averaged params + step and is admitted from the current
    round — barriers for earlier rounds never waited for it, and its local
    step counter continues from ``version * sync_every``. Phase 1 loses
    the victim to a kill -9; the replacement joins before phase 2, which
    then cannot commit a single round without its contributions."""
    blob = f"file://{tmp_path / 'blob'}"
    master = ElasticMaster(_model(), blob, sync_every=SYNC, min_workers=1,
                           worker_timeout_s=2.0, register_timeout_s=120,
                           round_timeout_s=120)
    procs = [
        _spawn_worker(master.address, blob, "original", seed=1),
        _spawn_worker(master.address, blob, "victim", seed=2,
                      extra=["--crash-at-round", "1"]),
    ]
    try:
        master.wait_for_workers(2)
        master.train(rounds=3, finish=False)  # phase 1: victim dies here
        assert master.tracker.count("workers_failed") == 1
        # mid-run join: the replacement adopts version 3's params + step
        procs.append(_spawn_worker(master.address, blob, "replacement",
                                   seed=3))
        deadline = time.monotonic() + 90
        while "replacement" not in master.tracker.workers():
            assert time.monotonic() < deadline, "replacement never joined"
            time.sleep(0.05)
        master.train(rounds=3)  # phase 2: barriers now REQUIRE it
    finally:
        outs = _finish(procs, master)
    assert int(master.tracker.count(VERSION_KEY)) == 6
    summary = _worker_summary(outs[-1][0], "replacement")
    admit = int(master.tracker.count("admit.replacement"))
    assert admit == 3, admit  # admitted at the version it adopted
    assert summary["round"] >= admit
    assert summary["step"] == summary["round"] * SYNC  # step taken over
    assert master.tracker.count("elastic.joined") >= 1
    assert procs[-1].returncode == 0, outs[-1][1][-500:]
    # every phase-2 round carries a replacement contribution
    for rnd in range(3, 6):
        assert master.tracker.count(f"contrib.{rnd}.replacement") > 0, rnd


# -------------------------------------------------- staleness run-ahead ----

@pytest.mark.slow
def test_elastic_staleness_runs_ahead_of_commits(tmp_path):
    """DeepSpark staleness knob: with ``max_staleness=2`` a worker keeps
    training on its local chain while the master is NOT committing at all,
    publishing contributions up to two rounds ahead; with the default
    bulk-synchronous setting it parks after one. Then the master starts
    committing and the run completes."""
    blob = f"file://{tmp_path / 'blob'}"
    master = ElasticMaster(_model(), blob, sync_every=2, min_workers=1,
                           worker_timeout_s=30.0, register_timeout_s=60,
                           round_timeout_s=90)
    worker = ElasticWorker(master.address, blob, _model(),
                           worker_id="stale", worker_seed=5, sync_every=2,
                           max_staleness=2, round_timeout_s=90)
    t = threading.Thread(target=worker.run, daemon=True)
    t.start()
    try:
        master.wait_for_workers(1)
        # master commits NOTHING yet; the worker still publishes rounds
        # 0..2 (a 2-round lead past adopted version 0), then blocks
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(master.blob.try_get(_contrib_key(r, "stale")) is not None
                   for r in range(3)):
                break
            time.sleep(0.05)
        for r in range(3):
            assert master.blob.try_get(_contrib_key(r, "stale")) is not None
        # lead is capped: round 3 must NOT be published while version is 0
        time.sleep(0.5)
        assert master.blob.try_get(_contrib_key(3, "stale")) is None
        final = master.train(rounds=4)
        assert final is not None
    finally:
        master.shutdown()
    t.join(timeout=60)
    assert not t.is_alive(), "stale worker failed to finish"


# ------------------------------------------------- numerical faults (#8) ----

def test_elastic_guarded_worker_skips_nan_batch(tmp_path):
    """ISSUE 8 fault matrix, worker side: a guarded worker hit by a NaN
    batch SKIPS the step in-graph (params carried, publish stays finite)
    and the run commits every round with full parity against the
    simulate_elastic oracle running the identical guarded model — the
    poison never reaches the averaging at all."""
    from deeplearning4j_tpu.scaleout.elastic import SyntheticRegressionModel

    def model():
        # NaN batch at global step 3 (= round 1 under sync_every=2) for
        # worker_seed=2 only — deterministic, so the oracle reproduces it
        return SyntheticRegressionModel(
            d_in=4, d_hidden=8, batch=8, lr=0.05, mesh_devices=1,
            guard=True, nan_at_step=3, nan_worker_seed=2)

    blob = f"file://{tmp_path / 'blob'}"
    master = ElasticMaster(model(), blob, sync_every=2, min_workers=2,
                           worker_timeout_s=30.0, register_timeout_s=60,
                           round_timeout_s=90)
    workers = [
        ElasticWorker(master.address, blob, model(), worker_id=f"w{s}",
                      worker_seed=s, sync_every=2, round_timeout_s=90)
        for s in (1, 2)
    ]
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for t in threads:
        t.start()
    try:
        master.wait_for_workers(2)
        final = master.train(rounds=3)
    finally:
        master.shutdown()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive()
    # the guard fired exactly once, on the poisoned worker's model
    assert workers[1].model.skipped_steps == 1
    assert workers[0].model.skipped_steps == 0
    # nobody was quarantined: the worker-side skip kept its publish finite
    assert master.tracker.count("workers_quarantined") == 0
    assert int(master.tracker.count(VERSION_KEY)) == 3
    ref, _ = simulate_elastic(model(), [1, 2], sync_every=2, rounds=3)
    _assert_tree_close(final, ref, 1e-6, "guarded-skip parity")


def test_elastic_quarantine_poisoned_contribution(tmp_path):
    """ISSUE 8 fault matrix, master side: an UNGUARDED worker publishes a
    NaN-poisoned contribution — the master quarantines it through the bury
    path BEFORE averaging (the survivors' params match the oracle that
    never saw the poison, 1e-6), the round barrier stops waiting for it,
    and the forensic trail lands end to end: ``workers_quarantined``
    counter, the barrier span's ``nonfinite`` event naming the worker, and
    a flight-recorder dump with the poisoned-leaf report."""
    from deeplearning4j_tpu.scaleout.elastic import SyntheticRegressionModel

    def model(**kw):
        d = dict(d_in=4, d_hidden=8, batch=8, lr=0.05, mesh_devices=1)
        d.update(kw)
        return SyntheticRegressionModel(**d)

    blob = f"file://{tmp_path / 'blob'}"
    trace_dir = str(tmp_path / "trace")
    # a long checkpoint interval keeps the round-commit write-ahead dumps
    # from overwriting the quarantine's "nonfinite" dump on a slow box
    # (explicit dump() calls are never rate-limited)
    prev = trace_mod.set_tracer(trace_mod.Tracer(
        "master", trace_dir=trace_dir, registry=MetricsRegistry(),
        min_checkpoint_interval_s=3600.0))
    try:
        master = ElasticMaster(model(), blob, sync_every=2, min_workers=1,
                               worker_timeout_s=30.0, register_timeout_s=60,
                               round_timeout_s=90)
        clean = ElasticWorker(master.address, blob, model(),
                              worker_id="clean", worker_seed=1,
                              sync_every=2, round_timeout_s=90)
        # unguarded + NaN at global step 2 (round 1): trains THROUGH the
        # NaN, so its round-1 publish carries non-finite params
        poison = ElasticWorker(master.address, blob,
                               model(nan_at_step=2, nan_worker_seed=2),
                               worker_id="poison", worker_seed=2,
                               sync_every=2, round_timeout_s=90)
        threads = [threading.Thread(target=w.run, daemon=True)
                   for w in (clean, poison)]
        for t in threads:
            t.start()
        try:
            master.wait_for_workers(2)
            final = master.train(rounds=3)
        finally:
            master.shutdown()
        for t in threads:
            t.join(timeout=60)
    finally:
        trace_mod.set_tracer(prev)
    assert master.tracker.count("workers_quarantined") == 1
    assert "poison" in master._quarantined
    assert "poison" not in master.tracker.workers()
    assert int(master.tracker.count(VERSION_KEY)) == 3
    # averaging never ingested the poisoned delta: round 0 both, round 1+
    # survivor only (the quarantine is sticky for the run)
    ref, _ = simulate_elastic(model(), [1, 2], sync_every=2, rounds=3,
                              schedule={0: [0, 1], 1: [0], 2: [0]})
    _assert_tree_close(final, ref, 1e-6, "quarantine survivor parity")
    from deeplearning4j_tpu.optimize.guardrails import tree_all_finite

    assert tree_all_finite(final)
    # forensics: the barrier span carries the nonfinite event...
    spans = load_trace_dir(trace_dir)
    events = [ev for sp in spans.values()
              if sp["name"] == "elastic.barrier"
              for ev in sp.get("events", [])
              if ev.get("name") == "nonfinite"]
    assert any(ev.get("worker") == "poison" for ev in events), events
    # ...and the flight dump names the worker + the poisoned leaves
    dump = json.load(open(os.path.join(trace_dir,
                                       "flightrec_master.json")))
    assert dump["reason"] == "nonfinite"
    assert dump["extra"]["worker"] == "poison"
    assert dump["extra"]["poisoned_leaves"], dump["extra"]


# ----------------------------------------------------- min_workers halt ----

def test_elastic_min_workers_halts_below_quorum(tmp_path):
    """Degrade-vs-halt: with ``min_workers=2`` the loss of one of two
    workers is a loud ElasticTrainingError, not silent degraded training —
    and (ISSUE 7) the master's flight recorder dumps on the error, with
    the failed barrier span recording the burial."""
    from deeplearning4j_tpu.scaleout.elastic import ElasticTrainingError

    blob = f"file://{tmp_path / 'blob'}"
    trace_dir = str(tmp_path / "trace")
    prev = trace_mod.set_tracer(trace_mod.Tracer(
        "master", trace_dir=trace_dir, registry=MetricsRegistry()))
    try:
        master = ElasticMaster(_model(), blob, sync_every=SYNC,
                               min_workers=2, worker_timeout_s=1.5,
                               register_timeout_s=120, round_timeout_s=60)
        procs = [
            _spawn_worker(master.address, blob, "w0", seed=1),
            _spawn_worker(master.address, blob, "crash", seed=2,
                          extra=["--crash-at-round", "0"]),
        ]
        try:
            master.wait_for_workers(2)
            with pytest.raises(ElasticTrainingError, match="min_workers"):
                master.train(rounds=4)
        finally:
            _finish(procs, master)
    finally:
        trace_mod.set_tracer(prev)
    assert master.tracker.count("workers_failed") == 1
    # the halt left a forensic artifact naming the error
    dump_path = os.path.join(trace_dir, "flightrec_master.json")
    assert os.path.exists(dump_path)
    dump = json.load(open(dump_path))
    assert dump["reason"] == "ElasticTrainingError"
    assert "min_workers" in dump["error"]
    # the barrier span carries the burial event and the error status
    spans = load_trace_dir(trace_dir)
    barriers = [sp for sp in spans.values()
                if sp["name"] == "elastic.barrier"]
    assert any(sp.get("status") == "error" for sp in barriers)
    assert any(ev.get("name") == "buried" and ev.get("worker") == "crash"
               for sp in barriers for ev in sp.get("events", []))


def test_master_crash_mid_merge_leaves_flight_dump(tmp_path):
    """ISSUE 7's master-crash-mid-merge forensics: a coordinator stuck in
    the ``merge_save`` barrier (one of two part manifests missing) is
    SIGTERMed — the crash hook dumps the flight recorder with the OPEN
    ``ckpt.merge_save`` span, and trace_report reconstructs the partial
    merge from the begin-record the kill left on disk. (The durability
    half — no committed manifest, clean resume — is pinned in
    test_ckpt_resume.)"""
    import signal

    root = str(tmp_path / "ckpt")
    trace_dir = str(tmp_path / "trace")
    child_code = (
        "import sys\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "from deeplearning4j_tpu.telemetry import trace as tr\n"
        "from deeplearning4j_tpu.scaleout.ckpt import Checkpointer\n"
        "root, trace_dir = sys.argv[1], sys.argv[2]\n"
        "tr.configure('merge-master', trace_dir)\n"
        "ck = Checkpointer(root)\n"
        "ck.save_process(1, {'w': jnp.arange(8.0)}, process_index=0)\n"
        "ck.merge_save(1, n_processes=2, timeout_s=120)\n"  # blocks
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-c", child_code, root, trace_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # wait until the merge span's begin-record is on disk (the child
        # is then parked in the part-manifest barrier), then SIGTERM it
        span_file = os.path.join(trace_dir, "spans_merge-master.jsonl")
        deadline = time.monotonic() + 60
        while True:
            if os.path.exists(span_file) and \
                    "ckpt.merge_save" in open(span_file).read():
                break
            assert time.monotonic() < deadline, "merge span never started"
            assert proc.poll() is None, proc.communicate()[1][-800:]
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    dump_path = os.path.join(trace_dir, "flightrec_merge-master.json")
    assert os.path.exists(dump_path)
    dump = json.load(open(dump_path))
    assert dump["reason"] == "SIGTERM"
    assert any(sp["name"] == "ckpt.merge_save" and sp.get("open")
               for sp in dump["open"])
    # trace_report reconstructs the partial merge from the torn span file
    spans = load_trace_dir(trace_dir)
    merge = [sp for sp in spans.values()
             if sp["name"] == "ckpt.merge_save"][0]
    assert merge["status"] == "open"
    assert merge["attrs"]["n_processes"] == 2
    # the kill landed before the commit: no manifest, nothing to resume
    from deeplearning4j_tpu.scaleout.ckpt import Checkpointer

    assert Checkpointer(root).latest_step() is None


# ------------------------------------------------------------ transport ----

def test_tracker_blackhole_times_out_as_unavailable():
    """A master that accepts but never answers used to hang the worker
    thread forever in ``recv``; now the request timeout surfaces
    TrackerUnavailable after the bounded retry budget."""
    with StateTrackerServer() as server:
        with _dist_helpers.FaultyTrackerProxy(server.address,
                                              blackhole=True) as proxy:
            client = StateTrackerClient(proxy.address,
                                        request_timeout_s=0.3, retries=1,
                                        backoff_s=0.01,
                                        registry=MetricsRegistry())
            t0 = time.monotonic()
            with pytest.raises(TrackerUnavailable):
                client.workers()
            assert time.monotonic() - t0 < 5.0  # bounded, not forever
            client.close()


def test_tracker_reconnects_through_cut_frame():
    """A response frame cut in half mid-stream (master restart / dropped
    proxy) is absorbed: the client reconnects and transparently retries
    the idempotent call; the reconnect is visible in telemetry."""
    reg = MetricsRegistry()
    with StateTrackerServer() as server:
        with _dist_helpers.FaultyTrackerProxy(
                server.address, cut_response_after=2) as proxy:
            client = StateTrackerClient(proxy.address, request_timeout_s=5,
                                        retries=3, backoff_s=0.01,
                                        registry=reg)
            client.add_worker("w0")                 # exchange 1
            assert client.workers() == ["w0"]       # exchange 2
            # exchange 3's response is cut mid-frame → reconnect + retry
            assert client.workers() == ["w0"]
            assert proxy.cuts == 1
            assert reg.counter("tracker_reconnects_total").value >= 1
            assert reg.counter("tracker_retries_total").value >= 1
            client.close()


def test_tracker_delay_within_timeout_is_just_latency():
    with StateTrackerServer() as server:
        with _dist_helpers.FaultyTrackerProxy(server.address,
                                              delay_s=0.05) as proxy:
            client = StateTrackerClient(proxy.address, request_timeout_s=2,
                                        registry=MetricsRegistry())
            client.increment("k", 2.0)
            assert client.count("k") == 2.0
            client.close()


def test_tracker_non_idempotent_fails_fast_without_retry():
    """``increment`` through a dead connection must raise rather than
    silently retry: re-applying after an ambiguous failure could double
    count. (Idempotent calls on the same dead client DO retry and fail
    only after the budget.)"""
    reg = MetricsRegistry()
    server = StateTrackerServer()
    client = StateTrackerClient(server.address, request_timeout_s=0.5,
                                retries=2, backoff_s=0.01, registry=reg)
    server.shutdown()
    # shutdown() stops the ACCEPT loop, but the handler thread already
    # serving this client's established socket may live on briefly — drop
    # the socket so the call must reconnect against the closed listener
    # (deterministic refusal; the scenario the test is about)
    client._drop_socket()
    with pytest.raises(TrackerUnavailable):
        client.increment("jobs_done")
    assert reg.counter("tracker_retries_total").value == 0
    with pytest.raises(TrackerUnavailable):
        client.workers()
    assert reg.counter("tracker_retries_total").value >= 1
    client.close()


@pytest.mark.slow
def test_elastic_worker_survives_tracker_frame_cut(tmp_path):
    """End to end through the fault proxy: a mid-run cut connection is a
    stall for the elastic worker (reconnect + idempotent retry inside the
    client), not a crash — training completes with full parity."""
    blob = f"file://{tmp_path / 'blob'}"
    master = ElasticMaster(_model(), blob, sync_every=SYNC, min_workers=1,
                           worker_timeout_s=30.0, register_timeout_s=60,
                           round_timeout_s=90)
    with _dist_helpers.FaultyTrackerProxy(master.address,
                                          cut_response_after=10) as proxy:
        worker = ElasticWorker(proxy.address, blob, _model(),
                               worker_id="wobbly", worker_seed=4,
                               sync_every=SYNC, round_timeout_s=90)
        t = threading.Thread(target=worker.run, daemon=True)
        t.start()
        try:
            master.wait_for_workers(1)
            final = master.train(rounds=4)
        finally:
            master.shutdown()
        t.join(timeout=60)
        assert not t.is_alive()
        assert proxy.cuts == 1  # the fault actually fired
    ref, _ = simulate_elastic(_model(), [4], sync_every=SYNC, rounds=4)
    _assert_tree_close(final, ref, 1e-6, "parity through frame cut")


# -------------------------------------------------- checkpoint the run ----

def test_elastic_master_checkpoints_and_resumes(tmp_path):
    """The master snapshots averaged params through the (async) ckpt
    subsystem and a FRESH master resumes at the committed version — the
    elastic analogue of kill/resume parity."""
    from deeplearning4j_tpu.scaleout.ckpt import (
        AsyncCheckpointer,
        Checkpointer,
    )

    blob = f"file://{tmp_path / 'blob'}"
    reg = MetricsRegistry()
    ck = AsyncCheckpointer(Checkpointer(str(tmp_path / "ckpt"), keep_last=3,
                                        registry=reg))
    master = ElasticMaster(_model(), blob, sync_every=SYNC, min_workers=1,
                           worker_timeout_s=30.0, register_timeout_s=60,
                           round_timeout_s=90, checkpointer=ck,
                           checkpoint_every=2)
    worker = ElasticWorker(master.address, blob, _model(), worker_id="w",
                           worker_seed=9, sync_every=SYNC, round_timeout_s=90)
    t = threading.Thread(target=worker.run, daemon=True)
    t.start()
    try:
        master.wait_for_workers(1)
        final = master.train(rounds=4)
    finally:
        master.shutdown()  # flushes pending async saves
    t.join(timeout=60)
    assert reg.counter("ckpt_async_saves_total").value >= 2
    assert reg.counter("ckpt_async_failures_total").value == 0

    blob2 = f"file://{tmp_path / 'blob2'}"
    master2 = ElasticMaster(_model(), blob2, sync_every=SYNC,
                            checkpointer=Checkpointer(
                                str(tmp_path / "ckpt"), registry=reg))
    try:
        resumed = master2.resume()
        assert resumed == 4
        _assert_tree_close(master2.params(), final, 1e-7,
                           "resumed elastic params")
    finally:
        master2.shutdown()
