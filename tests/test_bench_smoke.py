"""CI gate that `python bench.py` completes within its stage budgets on the
CPU backend and always lands a parseable summary line — so a driver timeout
like round 2's rc=124 can never recur silently (VERDICT r02 next-steps #1/#10).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_completes_on_cpu():
    env = dict(os.environ)
    # JAX_PLATFORMS env does not stick (sitecustomize pins the TPU);
    # BENCH_FORCE_CPU makes every stage child flip jax.config to CPU
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "240"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "mnist_mlp_train_samples_per_sec_per_chip"
    assert rec["value"] and rec["value"] > 0
    det = rec["detail"]
    # CPU baseline ran first and loudly: either a number or an explicit
    # failed status — never a silent 0.0.
    assert det.get("cpu_mlp_fp32_samples_per_sec") or \
        "failed" in str(det.get("cpu_mlp_fp32_status", ""))
    # MFU recorded for every completed TPU-model stage
    for stage in ("mlp_bf16", "mlp_fp32", "lenet_bf16", "lenet_fp32"):
        if det.get(f"{stage}_samples_per_sec"):
            assert f"{stage}_mfu" in det
    # the partial file was flushed incrementally
    assert os.path.exists(os.path.join(REPO, "bench_partial.json"))


def test_bench_skips_stages_past_deadline():
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "1"  # already expired: every stage must skip
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0.0 and rec["vs_baseline"] is None
    assert all(
        v == "skipped_budget"
        for k, v in rec["detail"].items() if k.endswith("_status")
    )
