"""CI gate that `python bench.py` completes within its stage budgets on the
CPU backend and always lands a parseable summary line — so a driver timeout
like round 2's rc=124 can never recur silently (VERDICT r02 next-steps #1/#10).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_completes_on_cpu():
    env = dict(os.environ)
    # JAX_PLATFORMS env does not stick (sitecustomize pins the TPU);
    # BENCH_FORCE_CPU makes every stage child flip jax.config to CPU
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "240"
    # scope to the stages the assertions below actually read (summary
    # metric, CPU baseline, MFU keys) — the full sweep is `python bench.py`
    # on the chip; per-stage plumbing for the newer stages is guarded by
    # test_bench_lm_composed_stage_on_cpu and the skip test keeps every
    # stage's budget discipline honest
    env["BENCH_ONLY"] = ("cpu_mlp_fp32,mlp_bf16,mlp_bf16_nofused,"
                         "mlp_fp32,lenet_bf16")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "mnist_mlp_train_samples_per_sec_per_chip"
    assert rec["value"] and rec["value"] > 0
    det = rec["detail"]
    # CPU baseline ran first and loudly: either a number or an explicit
    # failed status — never a silent 0.0.
    assert det.get("cpu_mlp_fp32_samples_per_sec") or \
        "failed" in str(det.get("cpu_mlp_fp32_status", ""))
    # MFU recorded for every completed TPU-model stage
    for stage in ("mlp_bf16", "mlp_fp32", "lenet_bf16", "lenet_fp32"):
        if det.get(f"{stage}_samples_per_sec"):
            assert f"{stage}_mfu" in det
    # the partial file was flushed incrementally
    assert os.path.exists(os.path.join(REPO, "bench_partial.json"))


def test_bench_lm_composed_stage_on_cpu():
    """The composed-flagship LM stage (round 6) runs END TO END on the CPU
    backend at tiny shapes: rate key present, forced-dense A/B twin key
    present, forced-CPU baseline key present, A/B ratio computed, and the
    env-seam core choice recorded in the stage detail — so tier-1 guards
    the stage plumbing without a chip."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "420"
    env["BENCH_ONLY"] = "cpu_lm_composed,lm_composed,lm_composed_densecore"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=480, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    assert det.get("lm_composed_samples_per_sec"), det.get(
        "lm_composed_status")
    assert "lm_composed_densecore_samples_per_sec" in det
    assert "cpu_lm_composed_samples_per_sec" in det
    assert det.get("lm_composed_mfu") is not None
    if det.get("lm_composed_densecore_samples_per_sec"):
        assert "lm_composed_vs_densecore" in det
    stage_detail = det.get("lm_composed_detail", {})
    assert stage_detail.get("attn_impl") == "blockwise"
    assert stage_detail.get("tokens_per_sec", 0) > 0
    dense_detail = det.get("lm_composed_densecore_detail", {})
    assert dense_detail.get("attn_impl") == "dense"
    # telemetry block (ISSUE 2): the stage A/Bs the metrics-threaded step,
    # runs a logged window through the JSONL pipeline, and must stay under
    # the 5% overhead budget at the default fetch interval
    telemetry = stage_detail.get("telemetry", {})
    assert telemetry, "lm_composed detail lost its telemetry block"
    assert telemetry["steps_logged"] > 0
    summary = telemetry["step_log_summary"]
    assert "loss" in summary and "grad_norm" in summary
    assert summary["tokens_per_sec_mean"] > 0
    assert len(summary["router_load_mean"]) >= 2
    assert telemetry["overhead_pct"] < 5.0, telemetry
    # profile blob (ISSUE 9): every lm_composed round embeds the compiled
    # step's StepProfile + attribution so profile_report/bench_report can
    # diff footprint across rounds
    blob = stage_detail.get("profile", {})
    assert blob, "lm_composed detail lost its profile blob"
    assert blob["flops"] > 0 and blob["label"] == "lm_composed"
    assert blob["donated_args"] >= 1  # the bench step donates params
    assert "xla_vs_analytic_flops" in blob
    att = stage_detail.get("profile_attribution", {})
    assert att.get("bound") in ("compute", "memory", "comm")


def test_bench_ckpt_stage_on_cpu():
    """The sharded-checkpoint stage runs end to end on the CPU backend:
    save MB/s as the headline rate plus restore timing, bytes, and
    chunk/file counts in the stage detail — tier-1 guards the stage
    plumbing (Checkpointer → manifest → resharding restore) without a
    chip."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "150"
    env["BENCH_ONLY"] = "ckpt"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=200, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    assert det.get("ckpt_save_mb_per_sec"), det.get("ckpt_status")
    stage_detail = det.get("ckpt_detail", {})
    assert stage_detail.get("save_ms", 0) > 0
    assert stage_detail.get("restore_ms", 0) > 0
    assert stage_detail.get("mb", 0) > 0
    assert stage_detail.get("chunks", 0) > 0
    assert stage_detail.get("shard_files", 0) >= 1
    assert stage_detail.get("restore_mb_per_sec", 0) > 0


def test_bench_moe_and_word2vec_sharded_stages_on_cpu():
    """The grouped-MoE dispatch A/B stage and the mesh-sharded word2vec
    stage run end to end on the CPU backend (8 faked devices): the moe
    detail blob carries every (impl, G) config with tokens/s + estimated
    comm bytes + capacity + drop fraction and the A/B ratios, and the
    sharded word2vec stage lands a words/s number — tier-1 guards the new
    stage plumbing without a chip."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "240"
    env["BENCH_ONLY"] = "moe,word2vec_sharded"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    assert det.get("moe_tokens_per_sec"), det.get("moe_status")
    blob = det.get("moe_detail", {})
    assert blob.get("mesh", {}).get("expert", 0) >= 2
    assert blob.get("top_k") == 2
    for group in (1, 4):
        for impl in ("alltoall", "replicated"):
            cfg = blob.get(f"{impl}_g{group}", {})
            assert cfg.get("tokens_per_sec", 0) > 0, (impl, group, blob)
            assert cfg.get("est_fwd_comm_bytes_per_dev", 0) > 0
            assert cfg.get("capacity", 0) > 0
            assert cfg.get("dropped_frac") is not None
        # G experts per device actually materialized: E = G × ep
        assert blob[f"alltoall_g{group}"]["n_experts"] == group * \
            blob["mesh"]["expert"]
        assert f"alltoall_vs_replicated_g{group}" in blob
    assert "comm_model" in blob
    # the headline value is the alltoall G=4 rate
    assert det["moe_tokens_per_sec"] == blob["alltoall_g4"]["tokens_per_sec"]
    assert det.get("word2vec_sharded_words_per_sec"), det.get(
        "word2vec_sharded_status")


def test_bench_skips_stages_past_deadline():
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "1"  # already expired: every stage must skip
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["value"] == 0.0 and rec["vs_baseline"] is None
    assert all(
        v == "skipped_budget"
        for k, v in rec["detail"].items() if k.endswith("_status")
    )


def test_bench_fault_tolerance_stages_on_cpu():
    """The ISSUE-6 robustness stages run end to end on the CPU backend:
    ``ckpt_async`` reports save-step jitter for blocking vs background
    snapshots (background overhead must not exceed blocking — the whole
    point of the writer thread), and ``elastic_sync`` reports the SparkNet
    sync-period A/B (held-out loss + steps/s for sync_every ∈ {1,8,32})."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "300"
    env["BENCH_ONLY"] = "ckpt_async,elastic_sync"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=360, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]

    assert det.get("ckpt_async_blocking_vs_background"), det.get(
        "ckpt_async_status")
    ca = det.get("ckpt_async_detail", {})
    assert ca["blocking"]["save_step_ms"] > 0
    assert ca["background"]["plain_step_ms"] > 0
    # the background writer must take (at least) no MORE off the training
    # thread than a blocking save; on any real disk it takes far less
    assert (ca["background"]["save_overhead_ms"]
            <= ca["blocking"]["save_overhead_ms"] + 1.0), ca

    assert det.get("elastic_sync_steps_per_sec"), det.get(
        "elastic_sync_status")
    es = det.get("elastic_sync_detail", {})
    per = es["per_sync_every"]
    assert set(per) == {"1", "8", "32"}
    for cfg in per.values():
        assert cfg["final_eval_loss"] > 0
        assert cfg["steps_per_sec"] > 0
    # infrequent sync is faster wall-clock (fewer averaging barriers)
    assert per["32"]["steps_per_sec"] >= per["1"]["steps_per_sec"], per


def test_bench_elastic_trace_stage_on_cpu():
    """ISSUE 7 acceptance: the traced elastic round stays under the <5%
    overhead budget vs untraced (round-alternating paired estimator, same
    discipline as the PR 2 metrics budget), and the stage's forensic
    chain lands: spans on disk, a trace_report timeline with every round
    committed, a Chrome export, and a flight dump.

    The estimator's documented noise floor on a shared-CPU box is ~±1.5%
    (trimmed mean of 20 paired deltas; see measure_elastic_trace), so a
    single reading can graze the budget on a bad scheduler day — one
    retry keeps the gate honest (a REAL regression, like per-poll spans
    or uncapped dumps, measures 10-20% and fails both runs)."""

    def run_stage():
        env = dict(os.environ)
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_FAST"] = "1"
        env["BENCH_BUDGET_SEC"] = "200"
        env["BENCH_ONLY"] = "elastic_trace"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=260, cwd=REPO, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
        assert det.get("elastic_trace_overhead_pct") is not None, det.get(
            "elastic_trace_status")
        return det

    det = run_stage()
    sd = det["elastic_trace_detail"]
    # forensic chain (stable, no retry needed)
    assert sd["spans"] > 10
    assert sd["rounds_committed_in_report"] == 4
    assert sd["chrome_events"] > sd["spans"]  # spans + process metadata
    assert sd["flight_dump"] is True
    assert sd["plain_round_ms"] > 0 and sd["traced_round_ms"] > 0
    if sd["overhead_pct"] >= 5.0:  # noise-floor retry, see docstring
        sd = run_stage()["elastic_trace_detail"]
    assert sd["overhead_pct"] < 5.0, sd


def test_bench_guardrails_stage_on_cpu():
    """ISSUE 8 acceptance: the guarded composed-LM step costs <5% vs the
    identical unguarded step (paired-median estimator, same discipline as
    the telemetry/trace budgets), and the stage's recovery demo lands end
    to end — an injected NaN batch skipped in-graph (skipped_steps==1,
    params carried bitwise and finite), the faulting step dumped as a
    replay bundle, and tools/step_replay.py reproducing the non-finite
    result from it.

    The overhead estimator shares the shared-CPU noise floor of the other
    A/B stages (~±2% on a bad scheduler day) — one retry keeps the gate
    honest; a real regression (e.g. a host sync inside the guard) measures
    far above 5% on both runs."""

    def run_stage():
        env = dict(os.environ)
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_FAST"] = "1"
        env["BENCH_BUDGET_SEC"] = "300"
        env["BENCH_ONLY"] = "guardrails"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=360, cwd=REPO, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
        assert det.get("guardrails_overhead_pct") is not None, det.get(
            "guardrails_status")
        return det

    det = run_stage()
    sd = det["guardrails_detail"]
    # recovery demo (stable, no retry needed)
    rec = sd["recovery"]
    assert rec["skipped_steps"] == 1
    assert rec["params_carried_bitwise"] is True
    assert rec["params_finite_after_skip"] is True
    assert rec["replay_rc"] == 0
    assert rec["replay_reproduced"] is True
    assert rec["poisoned_leaves"] == ["['batch']['x']"]
    import math
    assert math.isfinite(rec["post_recovery_loss"])
    if sd["overhead_pct"] >= 5.0:  # noise-floor retry, see docstring
        sd = run_stage()["guardrails_detail"]
    assert sd["overhead_pct"] < 5.0, sd


def test_bench_profile_stage_on_cpu():
    """ISSUE 9 acceptance: the ``profile=`` seam is COMPILE-TIME-ONLY —
    the profiled composed-LM step (AOT lower/compile once, then the same
    executable every call) must cost <5% vs the identical plain jitted
    step in steady state, and the stage's StepProfile blob must land with
    non-null FLOPs, the analytic-vs-XLA cross-check inside the documented
    band, a roofline attribution, and an explicit (empty-on-CPU)
    watermark block.

    Same shared-CPU noise floor as the other A/B budget stages (~±2% on
    a bad scheduler day) — one retry keeps the gate honest; a real
    regression (e.g. re-profiling per call) measures far above 5% on
    both runs."""

    def run_stage():
        env = dict(os.environ)
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_FAST"] = "1"
        env["BENCH_BUDGET_SEC"] = "240"
        env["BENCH_ONLY"] = "profile"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
        assert det.get("profile_overhead_pct") is not None, det.get(
            "profile_status")
        return det

    det = run_stage()
    sd = det["profile_detail"]
    blob = sd["profile"]
    assert blob["label"] == "lm_single_device" and blob["platform"] == "cpu"
    assert blob["flops"] > 0 and blob["bytes_accessed"] > 0
    assert blob["donated_args"] >= 1
    assert blob["compile_seconds"] > 0
    assert blob["collectives"] == {}  # single device: no comm
    # the analytic cross-check: the scan-adjusted XLA expectation holds
    # (the full-table ratio is also recorded for context)
    assert 0.85 <= sd["xla_vs_analytic_flops"] <= 1.25, sd
    assert sd["analytic_train_flops"] > 0
    assert sd["attribution"]["bound"] in ("compute", "memory", "comm")
    assert sd["signature_fallbacks"] == 0
    # the watermark sampler ran; CPU reports no per-device stats, and the
    # stage says so explicitly instead of inventing numbers
    assert sd["memory_watermarks"]["samples"] > 0
    assert sd["memory_watermarks"]["devices"] == {}
    if sd["overhead_pct"] >= 5.0:  # noise-floor retry, see docstring
        sd = run_stage()["profile_detail"]
    assert sd["overhead_pct"] < 5.0, sd


def test_bench_serve_stage_on_cpu():
    """ISSUE 10 acceptance: the serve stage runs end to end on the CPU
    backend — the continuous-batching decode engine beats the naive
    recompute-per-token baseline on tokens/s (same bf16 weights, so the
    ratio isolates the KV cache + batching), p50/p95 latency lands under
    the open-loop traffic generator, every request completes, and the
    int8 weight-only twin reports its smaller at-rest footprint.

    The throughput ratio shares the shared-CPU noise floor of the other
    A/B stages — one retry keeps the gate honest (the measured margin is
    ~2x; a real regression, like a retrace per occupancy change, lands
    well under 1.0 on both runs)."""

    def run_stage():
        env = dict(os.environ)
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_FAST"] = "1"
        env["BENCH_BUDGET_SEC"] = "360"  # watch twins: 12 paired runs
        env["BENCH_ONLY"] = "serve"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
        assert det.get("serve_tokens_per_sec"), det.get("serve_status")
        return det

    det = run_stage()
    sd = det["serve_detail"]
    # stable structure (no retry needed)
    assert det["serve_tokens_per_sec"] == sd["tokens_per_sec"]
    assert sd["completed"] == sd["n_requests"]
    lat = sd["latency"]
    assert lat["p99_ms"] >= lat["p95_ms"] >= lat["p50_ms"] > 0
    assert lat["mean_ms"] > 0
    assert lat["first_token_p99_ms"] >= lat["first_token_p50_ms"] > 0
    assert sd["naive_tokens_per_sec"] > 0
    assert sd["occupancy_mean"] > 0
    assert sd["serve_dtype"] == "bf16"
    # goodput under SLO (ISSUE 15 satellite): reported alongside the
    # percentiles and coherent with them — attainment is a fraction and
    # goodput can never exceed completed/duration
    gp = sd["goodput"]
    assert gp["slo_ms"] > 0
    assert 0.0 <= gp["slo_attainment"] <= 1.0
    assert gp["goodput_rps"] >= 0.0
    assert gp["goodput_rps"] <= sd["completed"] / max(
        sd["latency"]["p50_ms"] / 1000.0, 1e-9)
    # lockwatch twin (ISSUE 11): the watched run stays cycle-free and
    # inside the <5% tokens/s budget (shared-CPU noise: one retry below
    # rides the serve_vs_naive retry)
    watch = sd["lockwatch"]
    assert watch["cycles"] == 0 and watch["watchdog_dumps"] == 0
    assert watch["engine_lock"].get("acquires", 0) > 0
    assert watch["metrics"].get("lockwatch_serve_engine_acquires", 0) > 0
    # int8 A/B twin: decodes, and the at-rest weights really shrank
    assert sd["int8"]["tokens_per_sec"] > 0
    assert sd["int8"]["weight_bytes"] < sd["weight_bytes"]
    assert sd["int8"]["weight_bytes_vs_bf16"] < 1.0
    # tracing twin (ISSUE 12): every open-loop request reconstructed by
    # the REAL tools/trace_report.py attribution with queue+prefill+
    # decode+gap summing to the request latency within 1ms (stable
    # structure; the overhead budget shares the noise retry below)
    tw = sd["tracing"]
    assert tw["requests_traced"] >= sd["n_requests"]
    assert tw["open_requests"] == 0
    assert tw["attribution_max_err_ms"] is not None
    assert tw["attribution_max_err_ms"] <= 1.0, tw
    assert tw["sample_attribution"]["status"] == "ok"
    # netwatch twin (ISSUE 18): arming the socket watchdog around the
    # same open-loop run is free for the decode hot path (budget shares
    # the noise retry below), and the in-window tracker RPC roundtrip
    # exercised the seam end to end — both the client socket and the
    # server handler socket show live per-endpoint counters, with no
    # stall dumps on a healthy run
    nw = sd["netwatch"]
    assert nw["stall_dumps"] == 0, nw
    assert nw["default_timeout_s"] > 0
    assert nw["endpoints"].get("tracker.client", {}).get("ops", 0) > 0, nw
    assert nw["endpoints"].get(
        "tracker.server.handler", {}).get("ops", 0) > 0, nw
    assert nw["endpoints"]["tracker.client"]["timeouts"] == 0, nw
    assert nw["metrics"].get("netwatch_tracker_client_ops", 0) > 0, nw
    # the acceptance ratios: continuous batching beats recompute-per-token
    # AND the armed watchdog AND the armed socket watch each cost <5%
    # tokens/s; the armed tracer gets a 10% fast-mode budget — its eager
    # line-buffered JSONL sink is a real fixed per-span cost that these
    # ~0.1s micro-runs can't amortize (the same-engine paired estimator
    # in bench.py measures it at ~5%, reliably, where the old
    # single-shot estimator hid it in ±10% run noise). One shared noise
    # retry.
    if (sd["serve_vs_naive"] <= 1.0
            or sd["lockwatch"]["overhead_pct"] >= 5.0
            or sd["tracing"]["overhead_pct"] >= 10.0
            or sd["netwatch"]["overhead_pct"] >= 5.0):
        sd = run_stage()["serve_detail"]
    assert sd["serve_vs_naive"] > 1.0, sd
    assert sd["lockwatch"]["overhead_pct"] < 5.0, sd["lockwatch"]
    assert sd["tracing"]["overhead_pct"] < 10.0, sd["tracing"]
    assert sd["netwatch"]["overhead_pct"] < 5.0, sd["netwatch"]


def test_bench_fleet_stage_on_cpu():
    """ISSUE 19 acceptance: the fleet stage runs end to end on the CPU
    backend — two real FleetReplica serve/heartbeat loops over the TCP
    tracker, open-loop traffic routed with session affinity (latency +
    goodput blocks land for the fleet_* bench_report rows), then a
    mid-stream replica kill: the router detects the death off heartbeat
    staleness, requeues every in-flight request, cold-starts a
    replacement from live params, and every accepted request completes
    token-identical to the single-engine oracle. The requeue block
    carries the recovery-latency number the LOWER-IS-BETTER
    fleet_requeue_to_first_token_ms row tracks."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "300"
    env["BENCH_ONLY"] = "fleet"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=360, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    assert det.get("fleet_tokens_per_sec"), det.get("fleet_status")
    sd = det["fleet_detail"]
    assert det["fleet_tokens_per_sec"] == sd["tokens_per_sec"]
    # healthy phase: full membership, every request completed, latency/
    # goodput coherent (these blocks feed the bench_report extractors)
    assert sd["completed"] == sd["n_requests"]
    assert sd["replicas"] == 2
    lat = sd["latency"]
    assert lat["p99_ms"] >= lat["p95_ms"] >= lat["p50_ms"] > 0
    assert lat["first_token_p99_ms"] >= lat["first_token_p50_ms"] > 0
    gp = sd["goodput"]
    assert gp["slo_ms"] > 0
    assert 0.0 <= gp["slo_attainment"] <= 1.0
    assert gp["goodput_rps"] >= 0.0
    healthy = sd["healthy"]
    assert healthy["alive"] == 2
    assert healthy["affinity_sessions"] >= 1
    assert sum(healthy["dispatches"].values()) >= sd["n_requests"]
    # chaos phase: the kill really fired mid-stream, every accepted
    # request still completed, outputs pinned to the oracle, the dead
    # replica buried and its replacement alive in the final snapshot
    chaos = sd["chaos"]
    assert chaos["kill_fired"] is True
    assert chaos["completed"] == chaos["n_requests"]
    assert chaos["token_identical"] is True
    assert chaos["failed_replicas"] == ["r1"]
    assert chaos["replacement_joined"] is True
    assert chaos["requeued_requests"] >= 1
    rq = sd["requeue"]
    assert rq["requeued_requests"] >= 1
    assert rq["requeue_to_first_token_ms"] > 0
    assert rq["requeue_to_first_token_max_ms"] >= \
        rq["requeue_to_first_token_ms"]


def test_bench_observability_stage_on_cpu():
    """ISSUE 15 acceptance: the observability stage runs end to end on
    the CPU backend — the SAME open-loop serve run with the watch layer
    armed (history sampler at 20Hz + alert engine on the default pack at
    10Hz) costs <5% tokens/s (the shared noise retry keeps the gate
    honest on a loaded box), the quiet run fires NOTHING, the armed
    run's history answers live rate/percentile queries, and the
    deterministic injected-fault demo drives nonfinite_step_rate AND
    serve_latency_slo_burn to firing with the transitions rendered
    through the REAL tools/alert_report.py."""

    def run_stage():
        env = dict(os.environ)
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_FAST"] = "1"
        env["BENCH_BUDGET_SEC"] = "240"
        env["BENCH_ONLY"] = "observability"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
        assert det.get("observability_overhead_pct") is not None, det.get(
            "observability_status")
        return det["observability_detail"]

    sd = run_stage()
    # stable structure (no retry needed)
    assert sd["tokens_per_sec"] > 0
    assert sd["tokens_per_sec_watched"] > 0
    hist = sd["history"]
    assert hist["samples"] >= 2          # sampler really ran
    assert hist["series"] > 0
    assert hist["serve_tokens_rate_per_s"] > 0   # live rate query worked
    al = sd["alerts"]
    assert al["rules"] == 16  # default pack incl. ISSUE 16 serve rules
    # + the ISSUE 17 runprof rules + the ISSUE 19 fleet rules
    # + the ISSUE 20 tune_cache_stale rule
    # a healthy run pages nobody
    assert al["quiet_run_firing"] == []
    # the injected-fault demo fired BOTH demo rules deterministically...
    assert al["demo_states"] == {"nonfinite_step_rate": "firing",
                                 "serve_latency_slo_burn": "firing"}
    # ...and the real alert_report rendered the transitions
    assert al["report_transitions"] >= 2
    assert al["report_fired"] == ["nonfinite_step_rate",
                                  "serve_latency_slo_burn"]
    # the armed-watch overhead budget, with the shared noise retry
    if sd["overhead_pct"] >= 5.0:  # noise-floor retry, see docstring
        sd = run_stage()
    assert sd["overhead_pct"] < 5.0, sd


def test_bench_runprof_stage_on_cpu():
    """ISSUE 17 acceptance: the runprof stage runs end to end on the CPU
    backend — the SAME open-loop serve run with the runprof seam timing
    every scheduler tick costs <5% tokens/s (shared noise retry), the
    armed run's streaming gauges carry real values, the composed-LM
    measured-MFU cross-check holds (runprof_measured_mfu — fenced device
    seconds — is >= the wall-clock MFU, within the documented band the
    tier-1 test pins), and the N-step capture session round-trips
    through load_session + the profile_report runtime renderer."""

    def run_stage():
        env = dict(os.environ)
        env["BENCH_FORCE_CPU"] = "1"
        env["BENCH_FAST"] = "1"
        env["BENCH_BUDGET_SEC"] = "240"
        env["BENCH_ONLY"] = "runprof"
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
        assert det.get("runprof_overhead_pct") is not None, det.get(
            "runprof_status")
        # the cross-check MFU is lifted to its own tracked row
        assert det.get("runprof_measured_mfu") is not None
        return det["runprof_detail"]

    sd = run_stage()
    # stable structure (no retry needed)
    assert sd["tokens_per_sec"] > 0
    assert sd["tokens_per_sec_runprof"] > 0
    g = sd["serve_gauges"]
    assert g["runprof_steps_total"] > 0      # ticks really flushed
    assert g["runprof_step_ms"] > 0
    assert g["runprof_steps_per_s"] > 0
    # the measured-MFU cross-check: fenced device wall <= wall clock,
    # so measured >= wall; and both are real nonzero numbers
    assert sd["measured_mfu"] > 0
    assert sd["wall_mfu"] > 0
    assert sd["measured_vs_wall_mfu"] >= 1.0, sd
    # session -> report chain
    sess = sd["session"]
    assert sess["steps"] == sd["lm_steps"]
    assert sess["partial"] is False
    assert sess["chrome_events"] > 0
    assert sess["session_mfu"] > 0
    assert sess["report_rendered"] is True
    # the armed-seam overhead budget, with the shared noise retry
    if sd["overhead_pct"] >= 5.0:  # noise-floor retry, see docstring
        sd = run_stage()
    assert sd["overhead_pct"] < 5.0, sd


def test_bench_autotune_stage_on_cpu():
    """ISSUE 20 acceptance: the autotune stage runs the REAL two-phase
    roofline search end to end on the CPU backend — the LM seam's
    candidates flow through make_single_device_train_step(tuned=cfg),
    the serve seam through profiled prefill/KV shapes + a live engine —
    and the headline tuned-vs-default ratio lands >= 1.0 (the default is
    always a candidate, so the stage can never report a regression;
    within-noise margins are informational-marked, never claimed)."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "420"
    env["BENCH_ONLY"] = "autotune"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=480, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    ratio = det.get("autotune_tuned_vs_default")
    assert ratio is not None, det.get("autotune_status")
    assert ratio >= 1.0, ratio  # default always a candidate
    sd = det["autotune_detail"]
    # both searched seams landed with a full count ledger
    for seam in ("flash_attention", "serve"):
        s = sd["seams"][seam]
        assert s["tuned_vs_default"] >= 1.0, (seam, s)
        c = s["counts"]
        assert c["total"] == c["invalid"] + c["profiled"]  # all accounted
        assert c["measured"] >= 1                    # frontier executed
        assert c["pruned"] <= c["profiled"]          # pruning from phase 1
        assert s["winner"] is not None and s["default"] is not None
    # the serve seam's ratio is lifted to its own tracked row
    assert det.get("autotune_serve_tuned_vs_default") == \
        sd["seams"]["serve"]["tuned_vs_default"]
    # the informational noise marker is present either way
    assert "headline_within_noise" in sd


def test_bench_comm_overlap_stage_on_cpu():
    """ISSUE 14 acceptance: the comm_overlap stage runs end to end on the
    CPU backend (8 faked devices) — the 2D-factorized MoE dispatch lands
    with twice the all_to_all definitions at half the group size and loss
    parity vs flat, the overlapped pipeline and prefetch-ring twins are
    BIT-identical to their strict oracles, every config carries a measured
    comm fraction, and the counted-configs gate is honest (CPU collectives
    are memcpys, so the stage must MARK configs informational rather than
    claim wins). No timing-ratio assertion: the schedules' wall-clock win
    needs real ICI; the correctness+shape+gating chain is what tier-1
    pins."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "300"
    env["BENCH_ONLY"] = "comm_overlap"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=360, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    assert det.get("comm_overlap_overlap_vs_strict"), det.get(
        "comm_overlap_status")
    sd = det["comm_overlap_detail"]

    # (1) the 2D factorization: two group-factorized a2a definitions per
    # flat one, strictly smaller replica groups, exact loss parity
    a2a = sd["a2a"]
    assert a2a["grid"] == [2, 2]
    assert a2a["alltoall"]["a2a_group_sizes"] == [4]
    assert a2a["alltoall_2d"]["a2a_group_sizes"] == [2]
    assert a2a["alltoall_2d"]["a2a_count"] == 2 * a2a["alltoall"]["a2a_count"]
    assert a2a["parity_loss_abs_diff"] <= 1e-5
    assert a2a["alltoall"]["step_ms"] > 0
    assert a2a["alltoall_2d"]["step_ms"] > 0
    assert "2d_vs_flat" in a2a

    # (2) overlapped pipeline: bit-identical to strict
    pp = sd["pipeline"]
    assert pp["bit_identical"] is True
    assert pp["strict"]["collective_permute_count"] >= 1
    assert pp["overlap_vs_strict"] > 0

    # (3) prefetch ring: bit-identical to rotate-after-attend
    ring = sd["ring"]
    assert ring["bit_identical"] is True
    assert ring["prefetch_vs_rotate_after"] > 0

    # comm-fraction gating present and honest on CPU
    for cfg, key in (("a2a", "alltoall"), ("pipeline", "strict"),
                     ("ring", "rotate_after")):
        assert sd[cfg][key]["comm_fraction"] >= 0
    assert isinstance(sd["counted_configs"], list)
    assert isinstance(sd["headline_counted"], bool)

    # tracked blob + wire row: the 2D dispatch profile embeds
    blob = sd["profile"]
    assert blob["label"] == "comm_overlap_alltoall_2d"
    assert blob["collectives"]["all-to-all"]["group_sizes"] == [2]
    assert sd["collective_wire_bytes"] == blob["collective_wire_bytes"]
    # lifted ratio rows for bench_report tracking
    assert det["comm_overlap_a2a_2d_vs_flat"] == a2a["2d_vs_flat"]
    assert det["comm_overlap_ring_prefetch_vs_rotate_after"] == \
        ring["prefetch_vs_rotate_after"]


def test_bench_optimizer_stage_on_cpu():
    """ISSUE 13 acceptance: the in-graph optimizer A/B stage runs end to
    end on the CPU backend (8 faked devices, dp×ep mesh) — SGD vs
    Adam(replicated) vs Adam/LAMB(update-sharded) all land steps/s plus
    compiled StepProfile footprints, the headline replicated/sharded
    peak-bytes ratio is STRICTLY > 1 (the ZeRO-sharded update's compiled
    footprint is smaller — this is the profiler-provable claim, not a
    timing race, so no noise retry is needed), the measured per-replica
    moment bytes shrink by exactly the dp factor, the sharded Adam blob
    (the bench_report ``optimizer_profile_peak_bytes`` LOWER-IS-BETTER
    row) embeds as the stage profile with the params all-gather in its
    collective inventory, and the sharded-vs-replicated parity check at
    identical math stays ≤1e-5 at bench shapes."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "300"
    env["BENCH_ONLY"] = "optimizer"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=360, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    ratio = det.get("optimizer_peak_bytes_ratio")
    assert ratio, det.get("optimizer_status")
    assert ratio > 1.0, det
    sd = det["optimizer_detail"]
    dp = sd["mesh"]["data"]
    assert dp >= 2 and sd["mesh"]["expert"] >= 2
    for cfg in ("sgd", "adam_replicated", "adam_sharded", "lamb_sharded"):
        blob = sd[cfg]
        assert blob["steps_per_sec"] > 0, (cfg, blob)
        assert blob["profile_peak_bytes"] > 0
        assert blob["profile_flops"] > 0
    # the footprint claim, per config: sharded < replicated on BOTH the
    # compiled peak and the at-rest per-replica moment bytes (the latter
    # by exactly the dp factor — no padding slack at bench shapes)
    assert (sd["adam_sharded"]["profile_peak_bytes"]
            < sd["adam_replicated"]["profile_peak_bytes"])
    assert (sd["adam_sharded"]["moment_bytes_per_replica"]
            < sd["adam_replicated"]["moment_bytes_per_replica"])
    assert sd["moment_bytes_ratio"] == float(dp)
    # the redundant-update FLOPs drop (per-replica program)
    assert (sd["adam_sharded"]["profile_flops"]
            < sd["adam_replicated"]["profile_flops"])
    # the tracked blob is the sharded Adam step, all-gather present
    assert sd["profile"]["label"] == "optimizer_adam_sharded"
    assert "all-gather" in sd["profile"]["collectives"]
    assert "all-gather" in sd["adam_sharded"]["collectives"]
    # identical math: sharded and replicated agree after 3 steps
    assert sd["adam_sharded_vs_replicated_parity_max_abs_diff"] <= 1e-5
    assert sd["adam_loss_delta"] <= 1e-5


def test_bench_ref_micro_stage_on_cpu():
    """ISSUE 16: the machine-noise reference stage runs end to end on the
    CPU backend and reports a positive rate under the standard
    samples_per_sec key — tools/bench_report.py keys its round-over-round
    normalization off this row, so the stage silently dying would turn
    every future delta back into raw (unnormalized) noise."""
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    env["BENCH_FAST"] = "1"
    env["BENCH_BUDGET_SEC"] = "60"
    env["BENCH_ONLY"] = "ref_micro"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    det = json.loads(out.stdout.strip().splitlines()[-1])["detail"]
    assert det.get("ref_micro_samples_per_sec", 0) > 0, det.get(
        "ref_micro_status")


# ------------------------------------------------ stage-coverage meta-test ----

# Stages that predate this meta-test and whose plumbing is the ONE shared
# measure()/measure_word2vec() code path — it is exercised by the
# mlp/lenet smokes above (same _conf/_make_data/measure machinery, only
# the model/precision params differ), and the skip test runs every stage
# through the budget discipline. A NEW stage with new plumbing must NOT
# be added here: give it a BENCH_ONLY smoke like the ones above.
_LEGACY_MEASURE_STAGES = {
    "mlp_fp32_true", "conv_wide_bf16", "conv_wide_bf16_im2col",
    "lstm_bf16", "lstm_fp32", "lstm_wide_bf16", "lstm_wide_bf16_nokernels",
    "attn_bf16", "attn_long_bf16", "attn_long_bf16_densecore",
    "cpu_word2vec", "word2vec", "cpu_word2vec_large", "word2vec_large",
}


def _smoked_stages():
    """Every stage named in a BENCH_ONLY assignment in THIS file — the
    stages with a dedicated end-to-end smoke."""
    import re

    src = open(os.path.abspath(__file__)).read()
    covered = set()
    for m in re.finditer(r'env\["BENCH_ONLY"\]\s*=\s*\(?([^\n]+)', src):
        # the assignment may be a parenthesized multi-line string concat
        chunk = src[m.start():m.start() + 400]
        for lit in re.findall(r'"([^"]+)"', chunk.split("out = ")[0]):
            if lit == "BENCH_ONLY":
                continue
            covered.update(s.strip() for s in lit.split(",") if s.strip())
    return covered


def test_every_bench_stage_has_smoke():
    """ISSUE 8 satellite: every bench.py stage is either smoked by a
    BENCH_ONLY test in this file or explicitly allowlisted as a legacy
    measure()-family stage — a future stage cannot land without tier-1
    coverage of its plumbing. The allowlist itself is pinned against the
    live STAGES list so it can only ever shrink honestly."""
    sys.path.insert(0, REPO)
    import bench

    stages = {name for name, _cap in bench.STAGES}
    covered = _smoked_stages()
    missing = sorted(stages - covered - _LEGACY_MEASURE_STAGES)
    assert not missing, (
        f"bench stages without a smoke test: {missing} — add a BENCH_ONLY "
        "smoke in tests/test_bench_smoke.py (see the guardrails stage's) "
        "or, ONLY for a measure()-family variant, extend "
        "_LEGACY_MEASURE_STAGES with a why")
    stale = sorted(_LEGACY_MEASURE_STAGES - stages)
    assert not stale, f"allowlisted stages no longer exist: {stale}"
    # the newer stages really are covered by dedicated smokes
    assert "guardrails" in covered
    assert "profile" in covered
