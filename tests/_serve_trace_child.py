"""Child process for the serve-tracing kill -9 test (ISSUE 12).

Configures a process tracer, builds a tiny decode engine, submits a
request with a large token budget onto the background scheduler, prints
``READY`` once the request is mid-decode, then idles until the parent
SIGKILLs it. The tracer writes span begin records eagerly, so the death
leaves an open ``serve.request`` (plus its ``serve.decode`` child and
open ``engine.step`` spans) that tools/trace_report.py must reconstruct
— the same write-ahead forensic posture the elastic rounds pinned in
PR 7.

Run: ``python tests/_serve_trace_child.py TRACE_DIR`` (CPU platform is
forced here, mirroring tests/conftest.py, since this child has no
conftest).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    trace_dir = sys.argv[1]
    import jax

    jax.config.update("jax_platforms", "cpu")

    from deeplearning4j_tpu.models.transformer_lm import init_lm_params
    from deeplearning4j_tpu.serve import DecodeEngine
    from deeplearning4j_tpu.telemetry import trace as tr

    tr.configure("serve-victim", trace_dir, crash_hooks=False)
    params = init_lm_params(jax.random.PRNGKey(0), 31, 8, 2, 2, 16,
                            n_layers=1)
    # max_len 2048 → thousands of decode steps: the request is still
    # mid-stream whenever the parent's SIGKILL lands after READY
    engine = DecodeEngine(params, 2, n_slots=1, max_len=2048,
                          serve_dtype=None)
    engine.start()
    req = engine.submit([1, 2, 3], max_new_tokens=1_000_000)
    # wait until the request is genuinely mid-decode before signalling
    while not req.generated:
        time.sleep(0.01)
    print("READY", flush=True)
    # idle; the parent kill -9s us mid-request (no hook will run — only
    # the eagerly-written begin records survive)
    time.sleep(120)
    engine.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
