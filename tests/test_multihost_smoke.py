"""Two-process multihost smoke test.

Spawns two REAL processes wired through multihost.initialize() (env-var
path, the same wiring scaleout/provision.py launch commands emit), builds
the global mesh spanning both processes' CPU devices, and runs a psum over
DCN-style collectives (Gloo transport here). This is the closest offline
analogue to the reference's multi-JVM distributed tests
(testsupport/BaseTestDistributed.java)."""

import os
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
import os as _os
_os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # 0.4.x: the XLA flag above already did it
sys.path.insert(0, os.environ["DL4J_REPO"])

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()  # env-var path: DL4J_COORDINATOR / NUM_PROCESSES / PROCESS_ID
pid, n = multihost.process_info()
assert n == 2, f"expected 2 processes, got {n}"

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = multihost.global_mesh(("data",))
assert len(mesh.devices.ravel()) == 4  # 2 procs x 2 local cpu devices

# every process contributes its rank+1; the cross-process gather must see both
local = jnp.ones((2, 1), jnp.float32) * (pid + 1)
from jax.experimental import multihost_utils
global_sum = multihost_utils.process_allgather(local).sum()
assert float(global_sum) == 2 * 1.0 + 2 * 2.0, global_sum

is_coord = multihost.is_coordinator()
assert is_coord == (pid == 0)
print(f"MHOK {pid}", flush=True)
"""


_TRAIN_CHILD = r"""
import os, sys
import os as _os
_os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # 0.4.x: the XLA flag above already did it
sys.path.insert(0, os.environ["DL4J_REPO"])

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()
pid, n = multihost.process_info()
assert n == 2, f"expected 2 processes, got {n}"

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.nn import functional as F
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

conf = (
    NeuralNetConfiguration.Builder()
    .n_in(4).n_out(8).activation_function("tanh")
    .lr(0.1).momentum(0.9).num_iterations(1).seed(42)
    .list(2)
    .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
              activation_function="softmax", loss_function="MCXENT")
    .pretrain(False).backward(True)
    .build()
)

# identical deterministic data + init on every process
params = F.init_params(conf, jax.random.PRNGKey(0))
states = F.init_train_state(conf, params)
key = jax.random.PRNGKey(7)
xk, yk = jax.random.split(key)
BATCH = 16
x_np = np.asarray(jax.random.uniform(xk, (BATCH, 4), jnp.float32))
y_np = np.asarray(jax.nn.one_hot(
    jax.random.randint(yk, (BATCH,), 0, 3), 3, dtype=jnp.float32))
w_np = np.ones((BATCH,), np.float32)
STEPS = 3

# ---- single-process reference: same step on a 1-local-device mesh ----
local_mesh = Mesh(np.array(jax.local_devices()[:1]), ("data",))
local_step = make_sync_train_step(conf, local_mesh)
lp = jax.tree_util.tree_map(jnp.array, params)
ls = jax.tree_util.tree_map(jnp.array, states)
ref_scores = []
for i in range(STEPS):
    lp, ls, s = local_step(lp, ls, jnp.asarray(i),
                           jnp.asarray(x_np), jnp.asarray(y_np),
                           jnp.asarray(w_np), key)
    ref_scores.append(float(s))

# ---- the SAME training step over the 2-process global mesh ----
gmesh = multihost.global_mesh(("data",))
assert len(gmesh.devices.ravel()) == 4
rep = NamedSharding(gmesh, P())
shard = NamedSharding(gmesh, P("data"))

def place(a, sharding):
    a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])

gp = jax.tree_util.tree_map(lambda a: place(a, rep), params)
gs = jax.tree_util.tree_map(lambda a: place(a, rep), states)
gx, gy, gw = place(x_np, shard), place(y_np, shard), place(w_np, shard)
gkey = place(key, rep)

gstep = make_sync_train_step(conf, gmesh)
dp_scores = []
for i in range(STEPS):
    gp, gs, s = gstep(gp, gs, jnp.asarray(i), gx, gy, gw, gkey)
    dp_scores.append(float(np.asarray(s.addressable_data(0))))

# ---- parity: the cross-process DP step must reproduce local training ----
for i, (a, b) in enumerate(zip(ref_scores, dp_scores)):
    assert abs(a - b) < 1e-5, f"step {i}: local {a} vs dp {b}"
for layer_ref, layer_dp in zip(lp, gp):
    for a, b in zip(jax.tree_util.tree_leaves(layer_ref),
                    jax.tree_util.tree_leaves(layer_dp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b.addressable_data(0)), atol=1e-5)
print(f"MHTRAIN {pid} " + " ".join(f"{s:.6f}" for s in dp_scores), flush=True)
"""


_RING_CHILD = r"""
import os, sys
import os as _os
_os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # 0.4.x: the XLA flag above already did it
sys.path.insert(0, os.environ["DL4J_REPO"])

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()
pid, n = multihost.process_info()
assert n == 2

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)

# sequence axis spans BOTH processes' devices: the K/V ring crosses the
# process boundary over the Gloo transport
mesh = multihost.global_mesh(("sp",))
assert len(mesh.devices.ravel()) == 4

B, H, T, D = 1, 2, 32, 8  # T sharded 4-way: 2 shards per process
ks = jax.random.split(jax.random.PRNGKey(5), 3)
q_np, k_np, v_np = (np.asarray(jax.random.normal(k2, (B, H, T, D)))
                    for k2 in ks)
spec = P(None, None, "sp", None)

def place(a):
    sh = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(a.shape, sh, lambda idx: a[idx])

out = ring_attention(place(q_np), place(k_np), place(v_np), mesh, "sp",
                     causal=True)
ref = reference_attention(jnp.asarray(q_np), jnp.asarray(k_np),
                          jnp.asarray(v_np), causal=True)
# compare this process's addressable sequence shards against the dense ref
ref_np = np.asarray(ref)
for shard in out.addressable_shards:
    t0 = shard.index[2].start or 0
    t1 = shard.index[2].stop or T
    got = np.asarray(shard.data)
    want = ref_np[:, :, t0:t1]
    assert np.allclose(got, want, atol=1e-4), (
        pid, t0, t1, float(np.max(np.abs(got - want))))
print(f"RINGOK {pid}", flush=True)
"""


_CKPT_CHILD = r"""
import os, sys
import os as _os
_os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # 0.4.x: the XLA flag above already did it
sys.path.insert(0, os.environ["DL4J_REPO"])

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()
pid, n = multihost.process_info()
assert n == 2

import time
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.scaleout.ckpt import (
    restore_sharded,
    save_process_shards,
    merge_process_manifests,
    latest_step,
)
from deeplearning4j_tpu.scaleout.ckpt.manifest import read_manifest

root = os.environ["DL4J_CKPT_ROOT"]
mesh = multihost.global_mesh(("data",))
assert len(mesh.devices.ravel()) == 4

# a global array sharded across BOTH processes' devices + a replicated one
x_np = np.arange(32.0, dtype=np.float32).reshape(8, 4)
shard = NamedSharding(mesh, P("data"))
rep = NamedSharding(mesh, P())
b_np = np.arange(4, dtype=np.float32)
x = jax.make_array_from_callback(x_np.shape, shard, lambda idx: x_np[idx])
b = jax.make_array_from_callback(b_np.shape, rep, lambda idx: b_np[idx])
state = {"x": x, "b": b}

# EVERY process writes only its addressable shards
step_dir = save_process_shards(root, 11, state)
if multihost.is_coordinator():
    # the directory is not a checkpoint until the coordinator merges
    assert latest_step(root) is None
    # merge_process_manifests IS the barrier: it waits for both parts
    merge_process_manifests(root, 11, n_processes=2,
                            meta={"src": "mh-child"}, mesh=mesh)
else:
    # non-coordinators wait for the committed manifest on the shared root
    # (this jax build has no multiprocess CPU collectives to sync with)
    deadline = time.monotonic() + 120
    while latest_step(root) != 11:
        assert time.monotonic() < deadline, "manifest never committed"
        time.sleep(0.05)
assert latest_step(root) == 11

m = read_manifest(step_dir)
n_chunks = sum(len(e.chunks) for e in m.leaves)
assert n_chunks == 4 + 1, n_chunks  # 4 data shards + 1 deduped replica

template = {"x": np.zeros((8, 4), np.float32), "b": np.zeros(4, np.float32)}
shardings = {"x": shard, "b": rep}
got, manifest = restore_sharded(step_dir, template, shardings)
assert manifest.meta["src"] == "mh-child"
for s in got["x"].addressable_shards:
    np.testing.assert_array_equal(np.asarray(s.data), x_np[s.index])
for s in got["b"].addressable_shards:
    np.testing.assert_array_equal(np.asarray(s.data),
                                  np.arange(4, dtype=np.float32))
print(f"MHCKPT {pid}", flush=True)
"""


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_initialize_and_allgather(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            DL4J_REPO=repo,
            DL4J_COORDINATOR=f"127.0.0.1:{port}",
            DL4J_NUM_PROCESSES="2",
            DL4J_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        outs.append((p.returncode, out, err))
    for pid, (code, out, err) in enumerate(outs):
        assert code == 0, f"proc {pid} failed:\n{err[-2000:]}"
        assert f"MHOK {pid}" in out


@pytest.mark.slow
def test_two_process_dp_training_matches_single_process(tmp_path):
    """The sync DP train step over a 2-process global mesh reproduces
    single-device training on the same data to 1e-5 — the end-to-end
    multi-host analogue of the reference's multi-JVM distributed tests
    (testsupport/BaseTestDistributed.java). Each child asserts score AND
    updated-param parity internally; the parent checks both children agree."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            DL4J_REPO=repo,
            DL4J_COORDINATOR=f"127.0.0.1:{port}",
            DL4J_NUM_PROCESSES="2",
            DL4J_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _TRAIN_CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        outs.append((p.returncode, out, err))
    lines = []
    for pid, (code, out, err) in enumerate(outs):
        assert code == 0, f"proc {pid} failed:\n{err[-2000:]}"
        line = [ln for ln in out.splitlines() if ln.startswith(f"MHTRAIN {pid}")]
        assert line, out
        lines.append(line[0].split(None, 2)[2])
    # both controllers observed identical global scores
    assert lines[0] == lines[1], lines


@pytest.mark.slow
def test_two_process_per_host_checkpoint_write_and_merge(tmp_path):
    """ISSUE 6 tentpole persistence layer, on a REAL two-process mesh:
    each host writes only its addressable shards (lowest-global-device-id
    dedup for replicas), the coordinator merges the part manifests behind
    the barrier and commits LAST, and both hosts restore the committed
    step without ever materializing global state on one host."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    root = str(tmp_path / "ckpt")
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            DL4J_REPO=repo,
            DL4J_CKPT_ROOT=root,
            DL4J_COORDINATOR=f"127.0.0.1:{port}",
            DL4J_NUM_PROCESSES="2",
            DL4J_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CKPT_CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"proc {pid} failed:\n{err[-2000:]}"
        assert f"MHCKPT {pid}" in out


@pytest.mark.slow
def test_two_process_ring_attention_matches_dense(tmp_path):
    """Ring attention with the SEQUENCE axis spanning two processes: the
    K/V ring's ppermute hops cross the process boundary (Gloo here; DCN on
    a real multi-host pod) and must reproduce dense attention — the
    long-context story at the reference's multi-JVM scale posture."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            DL4J_REPO=repo,
            DL4J_COORDINATOR=f"127.0.0.1:{port}",
            DL4J_NUM_PROCESSES="2",
            DL4J_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _RING_CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"proc {pid} failed:\n{err[-2000:]}"
        assert f"RINGOK {pid}" in out
