"""Two-process multihost smoke test.

Spawns two REAL processes wired through multihost.initialize() (env-var
path, the same wiring scaleout/provision.py launch commands emit), builds
the global mesh spanning both processes' CPU devices, and runs a psum over
DCN-style collectives (Gloo transport here). This is the closest offline
analogue to the reference's multi-JVM distributed tests
(testsupport/BaseTestDistributed.java)."""

import os
import subprocess
import sys

import pytest

_CHILD = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
sys.path.insert(0, os.environ["DL4J_REPO"])

from deeplearning4j_tpu.parallel import multihost

multihost.initialize()  # env-var path: DL4J_COORDINATOR / NUM_PROCESSES / PROCESS_ID
pid, n = multihost.process_info()
assert n == 2, f"expected 2 processes, got {n}"

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = multihost.global_mesh(("data",))
assert len(mesh.devices.ravel()) == 4  # 2 procs x 2 local cpu devices

# every process contributes its rank+1; the cross-process gather must see both
local = jnp.ones((2, 1), jnp.float32) * (pid + 1)
from jax.experimental import multihost_utils
global_sum = multihost_utils.process_allgather(local).sum()
assert float(global_sum) == 2 * 1.0 + 2 * 2.0, global_sum

is_coord = multihost.is_coordinator()
assert is_coord == (pid == 0)
print(f"MHOK {pid}", flush=True)
"""


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_initialize_and_allgather(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(
            os.environ,
            DL4J_REPO=repo,
            DL4J_COORDINATOR=f"127.0.0.1:{port}",
            DL4J_NUM_PROCESSES="2",
            DL4J_PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        outs.append((p.returncode, out, err))
    for pid, (code, out, err) in enumerate(outs):
        assert code == 0, f"proc {pid} failed:\n{err[-2000:]}"
        assert f"MHOK {pid}" in out
