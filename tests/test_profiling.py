"""Tracing/profiling tests (SURVEY.md §5 — XLA-profiler upgrade over the
reference's StopWatch/heartbeat-ms timing)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.utils.profiling import (
    ProfilerIterationListener,
    annotate,
    device_memory_stats,
    save_device_memory_profile,
    trace,
)


def _dir_has_files(root):
    for _, _, files in os.walk(root):
        if files:
            return True
    return False


def test_trace_context_writes_artifacts(tmp_path):
    log_dir = str(tmp_path / "trace")
    with trace(log_dir):
        with annotate("test-block"):
            jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    assert _dir_has_files(log_dir), "no trace artifacts written"


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert len(stats) == len(jax.devices())
    assert all("device" in s for s in stats)


def test_save_device_memory_profile(tmp_path):
    path = save_device_memory_profile(str(tmp_path / "mem.pprof"))
    assert os.path.getsize(path) > 0


def test_profiler_iteration_listener(tmp_path):
    log_dir = str(tmp_path / "iters")
    listener = ProfilerIterationListener(log_dir, start=2, steps=2)
    for i in range(6):
        jax.block_until_ready(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
        listener(None, i, 0.0)
    listener.close()
    assert _dir_has_files(log_dir)


def test_listener_in_real_training(tmp_path):
    """The listener rides the MultiLayerNetwork listener chain during an
    actual fit (ref: IterationListener hook)."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.Builder()
            .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
            .num_iterations(5).seed(0).list(2)
            .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build())
    net = MultiLayerNetwork(conf).init()
    log_dir = str(tmp_path / "fit-trace")
    listener = ProfilerIterationListener(log_dir, start=1, steps=2)
    net.listeners.append(listener)
    rng = np.random.RandomState(0)
    net.fit(rng.rand(12, 4).astype(np.float32),
            np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)])
    listener.close()
    assert _dir_has_files(log_dir)


class _TraceSpy:
    """Records jax.profiler start/stop calls without arming the real XLA
    profiler (a still-armed profiler would poison later tests — exactly
    the failure mode close() exists to prevent)."""

    def __init__(self, monkeypatch):
        self.events = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda log_dir, **kw: self.events.append(("start", log_dir)))
        monkeypatch.setattr(
            jax.profiler, "stop_trace",
            lambda: self.events.append(("stop", None)))

    @property
    def starts(self):
        return [e for e in self.events if e[0] == "start"]

    @property
    def stops(self):
        return [e for e in self.events if e[0] == "stop"]


class TestProfilerListenerWindowSemantics:
    """ISSUE 9 satellite: the exact window contract — listeners fire
    AFTER each iteration, so a window opened at the ``start``-th callback
    traces callbacks start+1 … start+steps — plus close() releasing a
    still-open trace, idempotently."""

    def test_window_opens_at_start_and_spans_steps(self, monkeypatch,
                                                   tmp_path):
        spy = _TraceSpy(monkeypatch)
        listener = ProfilerIterationListener(str(tmp_path), start=2, steps=3)
        opened_at, closed_at = None, None
        for i in range(8):
            listener(None, i, 0.0)
            if spy.starts and opened_at is None:
                opened_at = i
            if spy.stops and closed_at is None:
                closed_at = i
        # start=2: the trace opens once the 2nd callback has fired...
        assert opened_at == 1  # 2nd callback = loop index 1
        # ...and spans the NEXT 3 iterations (callbacks 3, 4, 5)
        assert closed_at == 4  # 5th callback = loop index 4
        assert len(spy.starts) == 1 and len(spy.stops) == 1
        # the window is one-shot: later iterations never rearm it
        listener(None, 99, 0.0)
        assert len(spy.starts) == 1

    def test_start_zero_opens_at_first_callback(self, monkeypatch,
                                                tmp_path):
        spy = _TraceSpy(monkeypatch)
        listener = ProfilerIterationListener(str(tmp_path), start=0, steps=1)
        listener(None, 0, 0.0)
        assert len(spy.starts) == 1
        listener(None, 1, 0.0)
        assert len(spy.stops) == 1

    def test_close_releases_still_open_trace(self, monkeypatch, tmp_path):
        spy = _TraceSpy(monkeypatch)
        listener = ProfilerIterationListener(str(tmp_path), start=1, steps=5)
        for i in range(2):  # training ends INSIDE the window
            listener(None, i, 0.0)
        assert len(spy.starts) == 1 and len(spy.stops) == 0
        listener.close()
        assert len(spy.stops) == 1
        # idempotent: a second close (finally-block double call) is a no-op
        listener.close()
        assert len(spy.stops) == 1
        # and the closed listener never reopens a window
        listener(None, 5, 0.0)
        assert len(spy.starts) == 1

    def test_close_before_window_opens_is_noop(self, monkeypatch, tmp_path):
        spy = _TraceSpy(monkeypatch)
        listener = ProfilerIterationListener(str(tmp_path), start=5, steps=2)
        listener.close()  # nothing armed yet
        assert spy.events == []


def test_cli_train_profile_flag(tmp_path):
    """--profile DIR on the train subcommand captures a trace around fit."""
    from deeplearning4j_tpu.cli.driver import main
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

    conf = (NeuralNetConfiguration.Builder()
            .n_in(4).n_out(8).activation_function("tanh").lr(0.1)
            .num_iterations(3).seed(0).list(2)
            .override(1, layer_type="OUTPUT", n_in=8, n_out=3,
                      activation_function="softmax", loss_function="MCXENT")
            .pretrain(False).backward(True).build())
    conf_path = tmp_path / "model.json"
    conf_path.write_text(conf.to_json())
    rng = np.random.RandomState(1)
    rows = np.hstack([rng.rand(30, 4), rng.randint(0, 3, (30, 1))])
    csv = tmp_path / "data.csv"
    csv.write_text("\n".join(",".join(f"{v:.4f}" for v in r) for r in rows))
    prof_dir = tmp_path / "prof"
    rc = main(["train", "--conf", str(conf_path), "--input", str(csv),
               "--model", str(tmp_path / "out.npz"), "--labels", "3",
               "--profile", str(prof_dir)])
    assert rc == 0
    assert _dir_has_files(str(prof_dir))
    assert (tmp_path / "out.npz").exists()
