"""Scaling-efficiency benchmark (BASELINE config #5 analogue).

Measures the synchronous data-parallel training step (in-graph gradient
AllReduce — the XLA-native rewrite of the reference's per-iteration
ParameterAveraging loop, ref: spark/impl/multilayer/SparkDl4jMultiLayer.java:183-203)
at 1/2/4/8 virtual CPU devices, fixed per-device batch (weak scaling).

Virtual CPU "devices" share one socket's cores, so wall-clock does NOT scale
the way chips over ICI do (n=1 gets every core to itself; n=8 contend).
The honest metric on this host is **DP overhead**: the sharded step at n
devices vs the SAME global batch on a single device — identical total FLOPs
on identical silicon, so any gap is sharding + collective overhead. Ideal is
1.0; on real chips over ICI the same code's overhead is one gradient-pytree
AllReduce per step (see parallel/trainer.py). This is the reference's own
test posture (Spark local[8] — also one socket).

Run:  python scaling_bench.py  →  prints JSON and writes SCALING_r02.json
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

PER_DEVICE_BATCH = 256
STEPS = 30
WARMUP = 5

_CHILD = r"""
import sys, time, json
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", int(sys.argv[1]))
import jax.numpy as jnp
sys.path.insert(0, {repo!r})

from deeplearning4j_tpu.models.zoo import mnist_mlp
from deeplearning4j_tpu.nn import functional as F
from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

n = int(sys.argv[1])
batch = int(sys.argv[2])
conf = mnist_mlp(256, 128)
params = F.init_params(conf, jax.random.PRNGKey(0))
states = F.init_train_state(conf, params)
mesh = data_parallel_mesh(n)
step = make_sync_train_step(conf, mesh)

key = jax.random.PRNGKey(1)
x = jax.random.uniform(key, (batch, 784), jnp.float32)
y = jax.nn.one_hot(jax.random.randint(key, (batch,), 0, 10), 10, dtype=jnp.float32)
w = jnp.ones((batch,), jnp.float32)

for i in range({warmup}):
    params, states, score = step(params, states, jnp.asarray(i), x, y, w, key)
jax.block_until_ready(params)
t0 = time.perf_counter()
for i in range({steps}):
    params, states, score = step(params, states, jnp.asarray(i), x, y, w, key)
jax.block_until_ready(params)
dt = time.perf_counter() - t0
assert bool(jnp.isfinite(score)), "non-finite score"
print("MS", dt / {steps} * 1000.0)
"""


def measure(n_devices: int, global_batch: int) -> float:
    """Per-step milliseconds at n virtual CPU devices (fresh subprocess — the
    device count is fixed at backend init)."""
    code = _CHILD.format(repo=os.path.dirname(os.path.abspath(__file__)),
                         warmup=WARMUP, steps=STEPS)
    out = subprocess.run(
        [sys.executable, "-c", code, str(n_devices), str(global_batch)],
        capture_output=True, text=True, timeout=600)
    for line in out.stdout.splitlines():
        if line.startswith("MS "):
            return float(line.split()[1])
    raise RuntimeError(f"scaling child failed (n={n_devices}):\n{out.stderr[-2000:]}")


def main() -> None:
    rows = []
    for n in (1, 2, 4, 8):
        gb = PER_DEVICE_BATCH * n
        dp_ms = measure(n, gb)
        single_ms = dp_ms if n == 1 else measure(1, gb)
        rows.append({
            "devices": n,
            "per_device_batch": PER_DEVICE_BATCH,
            "global_batch": gb,
            "dp_step_ms": round(dp_ms, 2),
            "single_device_same_batch_ms": round(single_ms, 2),
            "dp_overhead_efficiency": round(single_ms / dp_ms, 3),
            "global_samples_per_sec": round(gb / (dp_ms / 1000.0), 1),
        })
    out = {
        "protocol": "sync DP (in-graph gradient AllReduce), MLP "
                    "784-256-128-10 fp32, virtual CPU mesh. "
                    "dp_overhead_efficiency = same-global-batch single-device "
                    "step time / sharded step time (cores are shared across "
                    "virtual devices, so this isolates sharding+collective "
                    "overhead; ideal 1.0). Ref posture: Spark local[8], "
                    "SparkDl4jMultiLayer.java:183-203",
        "scaling": rows,
    }
    with open("SCALING_r02.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
