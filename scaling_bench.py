"""Scaling-efficiency benchmark with collective-vs-compute breakdown
(BASELINE config #5 analogue).

Measures the synchronous data-parallel training step (in-graph gradient
AllReduce — the XLA-native rewrite of the reference's per-iteration
ParameterAveraging loop, ref: spark/impl/multilayer/SparkDl4jMultiLayer.java:183-203)
at 1/2/4/8 virtual CPU devices, fixed per-device batch (weak scaling).

Three timings per device count n (global batch = 256·n):
  dp_ms      — the real sharded step (compute + sharding machinery + psum)
  ablated_ms — the SAME sharded step with the psum replaced by identity
               (trainer.make_sync_train_step(ablate_collectives=True)):
               identical compute and sharding machinery, no collective
  single_ms  — the same global batch as ONE un-sharded step on 1 device:
               identical total FLOPs on identical silicon

Decomposition:
  collective_ms    = dp_ms − ablated_ms     (the AllReduce itself)
  mesh_overhead_ms = ablated_ms − single_ms (virtual-mesh artifact: n
                     per-shard executions dispatched onto the SAME host
                     core(s), losing the one-big-matmul batching the single
                     -device run gets — this term does not exist on real
                     chips, where each shard owns its silicon)
  dp_overhead_efficiency   = single_ms / dp_ms   (the honest virtual-mesh
                             number; ideal 1.0)
  collective_only_efficiency = single_ms / (single_ms + collective_ms)
                             (what remains once each shard owns its compute
                             — the framework-attributable share)

Virtual CPU "devices" share the host's core(s) (`nproc` is recorded in the
artifact), so wall-clock cannot weak-scale here; the reference's own test
posture has the same property (Spark local[8] on one socket).

Run:  python scaling_bench.py  →  prints JSON and writes SCALING_r05.json
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

PER_DEVICE_BATCH = 256
STEPS = 30
WARMUP = 5
REPEATS = 3
OUT = "SCALING_r05.json"

REPO = os.path.dirname(os.path.abspath(__file__))


def _child_main(n: int, batch: int, mode: str, warmup: int = WARMUP,
                steps: int = STEPS, repeats: int = REPEATS) -> None:
    """One measurement child: runs in a FRESH subprocess (the virtual CPU
    device count is fixed at backend init) and prints one RES json line.

    A real function rather than a ``python -c`` template string so the
    graftlint untimed-dispatch rule can SEE the timed loops and keep the
    block_until_ready-before-clock-stop discipline enforced (the round-2
    enqueue-rate bug class)."""
    import json as _json
    import statistics
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.compat import set_host_device_count

    set_host_device_count(n)
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo import mnist_mlp
    from deeplearning4j_tpu.nn import functional as F
    from deeplearning4j_tpu.parallel.mesh import data_parallel_mesh
    from deeplearning4j_tpu.parallel.trainer import make_sync_train_step

    ablate = mode == "ablate"
    conf = mnist_mlp(256, 128)
    params = F.init_params(conf, jax.random.PRNGKey(0))
    states = F.init_train_state(conf, params)
    mesh = data_parallel_mesh(n)
    step = make_sync_train_step(conf, mesh, ablate_collectives=ablate)

    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.uniform(kx, (batch, 784), jnp.float32)
    y = jax.nn.one_hot(jax.random.randint(ky, (batch,), 0, 10), 10,
                       dtype=jnp.float32)
    w = jnp.ones((batch,), jnp.float32)
    key = jax.random.PRNGKey(1)

    # collective accounting via the shared compiled-step profiler (ISSUE 9;
    # replaces the ad-hoc as_text() scrape): one AOT compile, the inventory
    # counts sync AND async (-start) all-reduces with their analytic wire
    # bytes under the documented ring convention
    from deeplearning4j_tpu.telemetry.xprofile import profile_lowered

    prof = profile_lowered(
        step.lower(params, states, jnp.asarray(0), x, y, w, key),
        label=f"dp_sync[{n}]")
    allreduce = prof.collectives.get("all-reduce", {})
    n_allreduce = allreduce.get("count", 0)
    # ISSUE 14: also surface the all_to_all traffic so ep-axis scaling
    # runs capture the MoE dispatch cost (0 on the pure-dp step here)
    alltoall = prof.collectives.get("all-to-all", {})
    param_bytes = sum(int(jnp.size(leaf)) * 4 for layer in params
                      for leaf in jax.tree_util.tree_leaves(layer))

    # the same step key every iteration is deliberate: identical per-step
    # work across repeats is what makes the min/median spread meaningful
    for i in range(warmup):
        # graftlint: allow[prng-reuse] identical per-step randomness keeps repeat timings comparable
        params, states, score = step(params, states, jnp.asarray(i), x, y, w,
                                     key)
    jax.block_until_ready(params)
    # R repeats, ALL reported: a 1-core host makes single timings noisy under
    # transient background load. The minimum is the uncontended step time; the
    # parent records the min/median spread so subtraction-based attribution
    # can be flagged when it sits inside the repeat noise instead of silently
    # clamped (advisor r04).
    reps = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for i in range(steps):
            # graftlint: allow[prng-reuse] see the warmup loop above
            params, states, score = step(params, states, jnp.asarray(i), x, y,
                                         w, key)
        jax.block_until_ready(params)
        reps.append(time.perf_counter() - t0)
    assert bool(jnp.isfinite(score)), "non-finite score"
    print("RES", _json.dumps({
        "ms": min(reps) / steps * 1000.0,
        "ms_median": statistics.median(reps) / steps * 1000.0,
        "ms_repeats": [r / steps * 1000.0 for r in reps],
        "all_reduce_ops": n_allreduce,
        "all_reduce_wire_bytes": allreduce.get("wire_bytes", 0.0),
        "all_to_all_ops": alltoall.get("count", 0),
        "all_to_all_wire_bytes": alltoall.get("wire_bytes", 0.0),
        "xla_flops": prof.flops,
        "param_bytes": param_bytes,
    }), flush=True)


def measure(n_devices: int, global_batch: int, mode: str = "dp") -> dict:
    """Per-step stats at n virtual CPU devices (fresh subprocess — the
    device count is fixed at backend init). mode: dp | ablate."""
    code = (f"import sys; sys.path.insert(0, {REPO!r}); "
            f"from scaling_bench import _child_main; "
            f"_child_main({n_devices}, {global_batch}, {mode!r})")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    for line in out.stdout.splitlines():
        if line.startswith("RES "):
            return json.loads(line[4:])
    raise RuntimeError(f"scaling child failed (n={n_devices}):\n{out.stderr[-2000:]}")


def main() -> None:
    nproc = os.cpu_count()
    rows = []
    param_bytes = None
    for n in (1, 2, 4, 8):
        gb = PER_DEVICE_BATCH * n
        dp = measure(n, gb, "dp")
        param_bytes = dp["param_bytes"]
        dp_ms = dp["ms"]
        if n == 1:
            abl = dp
            abl_ms = dp_ms
            single_ms = dp_ms
        else:
            abl = measure(n, gb, "ablate")
            abl_ms = abl["ms"]
            single_ms = measure(1, gb, "dp")["ms"]
        # collective_ms subtracts minima from two subprocesses; on a noisy
        # shared host the two minima can come from different contention
        # regimes. Record the raw (possibly negative) difference plus each
        # side's min→median spread, and flag the row when |diff| sits inside
        # that spread — never silently clamp (advisor r04).
        raw_diff = dp_ms - abl_ms
        spread = ((dp["ms_median"] - dp_ms) + (abl["ms_median"] - abl_ms))
        coll_ms = max(raw_diff, 0.0)
        rows.append({
            "devices": n,
            "per_device_batch": PER_DEVICE_BATCH,
            "global_batch": gb,
            "dp_step_ms": round(dp_ms, 3),
            "dp_step_ms_median": round(dp["ms_median"], 3),
            "ablated_step_ms": round(abl_ms, 3),
            "ablated_step_ms_median": round(abl["ms_median"], 3),
            "single_device_same_batch_ms": round(single_ms, 3),
            "collective_ms": round(coll_ms, 3),
            "collective_ms_raw_diff": round(raw_diff, 3),
            "collective_within_noise": bool(abs(raw_diff) <= spread),
            "repeat_spread_ms": round(spread, 3),
            "dp_step_ms_repeats": [round(r, 3) for r in dp["ms_repeats"]],
            "ablated_step_ms_repeats": [round(r, 3) for r in abl["ms_repeats"]],
            "mesh_overhead_ms": round(abl_ms - single_ms, 3),
            "dp_overhead_efficiency": round(single_ms / dp_ms, 3),
            "collective_only_efficiency": round(
                single_ms / (single_ms + coll_ms), 3),
            "all_reduce_ops_per_step": dp["all_reduce_ops"],
            "all_to_all_ops_per_step": dp["all_to_all_ops"],
            "all_to_all_wire_bytes_per_step": dp["all_to_all_wire_bytes"],
            "global_samples_per_sec": round(gb / (dp_ms / 1000.0), 1),
        })
    r8 = rows[-1]
    # ICI projection: one fused all-reduce of the grad pytree per step.
    # Ring all-reduce moves 2·(n−1)/n·payload per link; v5e ICI ≈ 45 GB/s
    # per direction per link, so the wire time at n=8 is ~tens of µs
    # against a per-shard compute of single_ms(256) — the measured
    # collective_ms here instead rides host memcpy on nproc core(s).
    ici_bw = 45e9
    wire_s = 2 * (8 - 1) / 8 * param_bytes / ici_bw
    shard_compute_ms = rows[0]["dp_step_ms"]  # batch 256 on one device
    out = {
        "protocol": "sync DP (ONE fused in-graph gradient AllReduce/step), "
                    "MLP 784-256-128-10 fp32, virtual CPU mesh, weak scaling "
                    "at 256 samples/device. dp_overhead_efficiency = "
                    "same-global-batch single-device step / sharded step "
                    "(identical FLOPs on identical silicon; ideal 1.0). "
                    "ablated_step_ms re-runs the identical sharded program "
                    "with psum ablated, so collective_ms = dp − ablated and "
                    "mesh_overhead_ms = ablated − single isolate the "
                    "AllReduce from the virtual-mesh artifact. Ref posture: "
                    "Spark local[8], SparkDl4jMultiLayer.java:183-203",
        "host": {"nproc": nproc, "platform": "cpu (virtual devices)"},
        "grad_allreduce_payload_bytes": param_bytes,
        "scaling": rows,
        "analysis": {
            "binding_constraint": (
                f"This host exposes nproc={nproc} core(s); all {rows[-1]['devices']} "
                "virtual devices time-share it. mesh_overhead_ms (ablated − "
                "single) is therefore serialization of n per-shard programs "
                "on shared core(s) + the loss of single-kernel batching — an "
                "artifact with no analogue on a real pod, where each chip "
                "owns its MXU. The framework-attributable cost is "
                "collective_ms only: the single fused AllReduce the step "
                "issues (all_reduce_ops_per_step confirms the count from "
                "compiled HLO)."),
            "two_device_real_vs_ideal": (
                f"n=2: dp={rows[1]['dp_step_ms']}ms vs ideal(single, same "
                f"batch)={rows[1]['single_device_same_batch_ms']}ms; the gap "
                f"splits into mesh_overhead={rows[1]['mesh_overhead_ms']}ms "
                f"(virtual-mesh serialization, vanishes on 2 real chips) + "
                f"collective={rows[1]['collective_ms']}ms (the AllReduce)."),
            "ici_projection": {
                "payload_mb": round(param_bytes / 1e6, 3),
                "ring_allreduce_wire_us_at_8x45GBps": round(wire_s * 1e6, 1),
                "per_shard_compute_ms_b256": shard_compute_ms,
                "projected_efficiency_8_chips": round(
                    shard_compute_ms
                    / (shard_compute_ms + wire_s * 1e3), 4),
                "note": "on real v5e ICI the fused grad AllReduce wire time "
                        "is ~2 orders below per-shard compute; the measured "
                        "collective_ms here is host-memcpy-bound and is an "
                        "upper bound on the framework's collective cost",
            },
            "collective_only_efficiency_8": r8["collective_only_efficiency"],
        },
    }
    with open(OUT, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
